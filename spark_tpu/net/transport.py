"""gRPC network transport: the control and block planes.

Role of the reference's Netty transport stack
(common/network-common/src/main/java/org/apache/spark/network/TransportContext.java:62,
core/rpc/netty/NettyRpcEnv.scala): one message-framed, authenticated
transport serving (1) control RPC (executor registration, heartbeats,
task launch) and (2) bulk block transfer (shuffle blocks as chunked
streams — the ManagedBuffer/ChunkFetch role).

TPU-first design departure: the reference hand-rolls framing, zero-copy
file regions, and SASL over Netty. Here gRPC/HTTP2 supplies framing,
flow-control, and multiplexing; payloads are opaque bytes (cloudpickle
for control, Arrow IPC for blocks) registered on a GenericRpcHandler so
no protoc codegen step is needed; auth is a per-cluster shared secret
carried in call metadata and enforced by a server interceptor (the
SecretKeyHolder/SASL bootstrap role). Large blocks stream in 4 MiB
chunks (HTTP/2 flow control replaces maxChunksBeingTransferred).
"""

from __future__ import annotations

import random
import time
from concurrent import futures
from typing import Callable, Iterator

import grpc

from ..utils import faults
from ..utils.counters import LockedCounterMap

SERVICE = "sparktpu.Transport"
CHUNK_BYTES = 4 << 20
_AUTH_KEY = "sparktpu-auth"

# process-wide retry bookkeeping (tests and the chaos gate read these):
# absorbed = transient UNAVAILABLE errors a retry recovered from;
# gave_up = logical calls that exhausted their retry budget.
# RPC clients retry concurrently from heartbeat, fetch, and serve
# threads — a bare dict += here is a read-modify-write race (lost
# updates), so the tallies live behind the locked-counter helper;
# reads (stats["absorbed"]) still return plain ints.
RETRY_STATS = LockedCounterMap("net.transport.RETRY_STATS",
                               ("absorbed", "gave_up"))


class RetryPolicy:
    """Bounded retry for transient RpcUnavailableError on IDEMPOTENT
    control-plane calls: exponential backoff with full jitter, capped
    per-sleep, under a wall-clock deadline (role of the reference's
    RpcUtils.numRetries/retryWaitMs + shuffle.io.maxRetries discipline).
    Application errors (RemoteRpcError) never retry — the same call
    would fail the same way anywhere."""

    __slots__ = ("attempts", "base_ms", "max_ms", "deadline_s")

    def __init__(self, attempts: int = 3, base_ms: float = 50.0,
                 max_ms: float = 2000.0, deadline_s: float = 10.0):
        self.attempts = max(int(attempts), 0)
        self.base_ms = float(base_ms)
        self.max_ms = float(max_ms)
        self.deadline_s = float(deadline_s)

    def backoff_s(self, attempt: int) -> float:
        """Sleep before retry `attempt` (1-based): exp growth with full
        jitter so a thundering herd of retries decorrelates."""
        span = min(self.base_ms * (2 ** (attempt - 1)), self.max_ms)
        return random.uniform(span / 2, span) / 1000.0

    @classmethod
    def from_conf(cls, conf) -> "RetryPolicy":
        from ..config import (
            RPC_MAX_RETRIES, RPC_RETRY_BACKOFF_MS, RPC_RETRY_DEADLINE,
        )

        return cls(
            attempts=int(conf.get(RPC_MAX_RETRIES)),
            base_ms=float(conf.get(RPC_RETRY_BACKOFF_MS)),
            deadline_s=float(conf.get(RPC_RETRY_DEADLINE)))


# small best-effort default for fire-and-forget cleanup RPCs
#  (free_shuffle and friends): absorb one flap, never stall shutdown
BEST_EFFORT_RETRY = RetryPolicy(attempts=2, base_ms=25.0, max_ms=200.0,
                                deadline_s=2.0)


class RpcUnavailableError(ConnectionError):
    """The peer is unreachable or died mid-call (connection-plane failure,
    distinct from an application error raised by the handler). Only
    UNAVAILABLE maps here — it is the one status that means 'the process
    behind this channel is gone', which callers use as executor death."""


class RemoteRpcError(RuntimeError):
    """The call failed for a non-liveness reason: the handler raised
    (carries its traceback), the payload broke a transport limit
    (RESOURCE_EXHAUSTED), auth failed, or the method is unknown.
    Retrying the same call elsewhere will fail the same way."""


def _ident(b: bytes) -> bytes:
    return b


class _AuthInterceptor(grpc.ServerInterceptor):
    def __init__(self, token: str):
        self._token = token

        def deny(request, context):
            context.abort(grpc.StatusCode.UNAUTHENTICATED, "bad auth token")

        self._deny = grpc.unary_unary_rpc_method_handler(
            deny, request_deserializer=_ident, response_serializer=_ident)

    def intercept_service(self, continuation, handler_call_details):
        meta = dict(handler_call_details.invocation_metadata or ())
        if meta.get(_AUTH_KEY) != self._token:
            return self._deny
        return continuation(handler_call_details)


class _Handler(grpc.GenericRpcHandler):
    def __init__(self, unary: dict, stream: dict):
        self._unary = unary
        self._stream = stream

    def service(self, handler_call_details):
        name = handler_call_details.method.rsplit("/", 1)[-1]
        if name in self._unary:
            fn = self._unary[name]

            def run(request, context):
                return fn(request)

            return grpc.unary_unary_rpc_method_handler(
                run, request_deserializer=_ident,
                response_serializer=_ident)
        if name in self._stream:
            fn = self._stream[name]

            def run_stream(request, context):
                yield from fn(request)

            return grpc.unary_stream_rpc_method_handler(
                run_stream, request_deserializer=_ident,
                response_serializer=_ident)
        return None


class RpcServer:
    """Byte-payload RPC endpoint (the TransportServer + Dispatcher role).

    Handlers run on a thread pool; a unary handler is bytes→bytes, a
    stream handler is bytes→Iterator[bytes]. Exceptions raised by a
    handler surface to the caller as RemoteRpcError with the traceback.
    """

    def __init__(self, token: str, host: str = "127.0.0.1",
                 max_workers: int = 16):
        self._token = token
        self._host = host
        self._max_workers = max_workers
        self._unary: dict[str, Callable[[bytes], bytes]] = {}
        self._stream: dict[str, Callable[[bytes], Iterator[bytes]]] = {}
        self._server: grpc.Server | None = None
        self.address: str = ""

    def register(self, method: str, fn: Callable[[bytes], bytes]) -> None:
        self._unary[method] = _wrap_errors(fn)

    def register_stream(self, method: str,
                        fn: Callable[[bytes], Iterator[bytes]]) -> None:
        self._stream[method] = fn

    def start(self) -> str:
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self._max_workers),
            interceptors=[_AuthInterceptor(self._token)],
            options=[("grpc.max_receive_message_length", 256 << 20),
                     ("grpc.max_send_message_length", 256 << 20)])
        self._server.add_generic_rpc_handlers(
            [_Handler(self._unary, self._stream)])
        port = self._server.add_insecure_port(f"{self._host}:0")
        self._server.start()
        self.address = f"{self._host}:{port}"
        return self.address

    def stop(self, grace: float = 0.5) -> None:
        if self._server is not None:
            self._server.stop(grace)
            self._server = None


_ERR_PREFIX = b"\x00SPARKTPU_RPC_ERR\x00"


def _wrap_errors(fn):
    def run(payload: bytes) -> bytes:
        import traceback

        try:
            return b"\x00OK\x00" + fn(payload)
        except Exception:
            return _ERR_PREFIX + traceback.format_exc().encode()

    return run


class RpcClient:
    """One authenticated channel to a peer, reused across calls (the
    TransportClientFactory connection-pool role — per-call reconnect
    would pay TCP+HTTP/2 setup per message)."""

    def __init__(self, addr: str, token: str,
                 connect_timeout: float = 10.0):
        self.addr = addr
        self._meta = ((_AUTH_KEY, token),)
        self._channel = grpc.insecure_channel(
            addr,
            options=[("grpc.max_receive_message_length", 256 << 20),
                     ("grpc.max_send_message_length", 256 << 20)])
        self._connect_timeout = connect_timeout

    def wait_ready(self, timeout: float | None = None) -> None:
        try:
            grpc.channel_ready_future(self._channel).result(
                timeout=timeout or self._connect_timeout)
        except grpc.FutureTimeoutError:
            raise RpcUnavailableError(
                f"{self.addr} not reachable") from None

    def _classify(self, method: str, e: grpc.RpcError) -> Exception:
        msg = f"{method}@{self.addr}: {e.code()}: {e.details()}"
        if e.code() in (grpc.StatusCode.UNAVAILABLE,
                        grpc.StatusCode.DEADLINE_EXCEEDED):
            return RpcUnavailableError(msg)
        return RemoteRpcError(msg)

    def call(self, method: str, payload: bytes = b"",
             timeout: float | None = None,
             compress: bool = False,
             retry: RetryPolicy | None = None) -> bytes:
        """One unary call. `compress=True` gzips the request on the wire
        (per-call grpc compression) — used for span-heavy telemetry
        payloads riding the heartbeat channel, where text-shaped pickle
        shrinks well and the frame budget should stay reserved for
        shuffle blocks.

        `retry` opts an IDEMPOTENT call into bounded retry of transient
        RpcUnavailableError (exp backoff + jitter, deadline-bounded).
        RemoteRpcError (the handler raised / payload too big / bad
        auth) never retries, and callers that treat UNAVAILABLE as
        executor death (the task launch path) must NOT pass a policy —
        absorbing the loss signal there would mask dead executors."""
        fn = self._channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=_ident, response_deserializer=_ident)
        deadline = (time.monotonic() + retry.deadline_s
                    if retry is not None else None)
        attempt = 0
        while True:
            try:
                if faults.ENABLED:
                    faults.maybe_fail("rpc.call",
                                      detail=f"{method}@{self.addr}",
                                      exc=RpcUnavailableError)
                try:
                    raw = fn(payload, metadata=self._meta, timeout=timeout,
                             compression=grpc.Compression.Gzip if compress
                             else None)
                except grpc.RpcError as e:
                    raise self._classify(method, e) from None
                if attempt:
                    RETRY_STATS.bump("absorbed")
                break
            except RpcUnavailableError:
                attempt += 1
                if retry is None or attempt > retry.attempts:
                    if retry is not None:
                        RETRY_STATS.bump("gave_up")
                    raise
                wait = retry.backoff_s(attempt)
                if deadline is not None and \
                        time.monotonic() + wait >= deadline:
                    RETRY_STATS.bump("gave_up")
                    raise
                time.sleep(wait)
        if raw.startswith(_ERR_PREFIX):
            raise RemoteRpcError(raw[len(_ERR_PREFIX):].decode())
        return raw[len(b"\x00OK\x00"):]

    def stream(self, method: str, payload: bytes = b"",
               timeout: float | None = None) -> Iterator[bytes]:
        fn = self._channel.unary_stream(
            f"/{SERVICE}/{method}",
            request_serializer=_ident, response_deserializer=_ident)
        try:
            yield from fn(payload, metadata=self._meta, timeout=timeout)
        except grpc.RpcError as e:
            raise self._classify(method, e) from None

    def close(self) -> None:
        try:
            self._channel.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
