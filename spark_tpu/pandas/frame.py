"""pandas API on spark_tpu.

Role of the reference's pandas-on-Spark layer (python/pyspark/pandas/ —
pandas DataFrame semantics compiled to engine plans). This shim covers the
working core: column access/assignment, boolean filtering, arithmetic,
groupby aggregation, sort/merge/head/describe — every operation stays lazy
in the engine until materialization (`to_pandas`, len, repr).
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

import spark_tpu.api.functions as F
from ..api.column import Column as EngineColumn
from ..api.dataframe import DataFrame as EngineFrame


def _session():
    from ..api.session import TpuSession

    s = TpuSession._active
    if s is None:
        s = TpuSession("pandas-api")
    return s


def read_parquet(path: str) -> "DataFrame":
    return DataFrame(_session().read.parquet(path))


def read_csv(path: str, **kw) -> "DataFrame":
    return DataFrame(_session().read.csv(path, **kw))


def from_pandas(pdf) -> "DataFrame":
    return DataFrame(_session().createDataFrame(pdf))


class Series:
    """A lazy column bound to its frame."""

    def __init__(self, frame: "DataFrame", col: EngineColumn, name: str):
        self._frame = frame
        self._col = col
        self.name = name

    # arithmetic / comparison return new Series
    def _wrap(self, col: EngineColumn) -> "Series":
        return Series(self._frame, col, self.name)

    def __add__(self, o):
        return self._wrap(self._col + _unwrap(o))

    def __sub__(self, o):
        return self._wrap(self._col - _unwrap(o))

    def __mul__(self, o):
        return self._wrap(self._col * _unwrap(o))

    def __truediv__(self, o):
        return self._wrap(self._col / _unwrap(o))

    def __eq__(self, o):  # type: ignore[override]
        return self._wrap(self._col == _unwrap(o))

    def __ne__(self, o):  # type: ignore[override]
        return self._wrap(self._col != _unwrap(o))

    def __lt__(self, o):
        return self._wrap(self._col < _unwrap(o))

    def __le__(self, o):
        return self._wrap(self._col <= _unwrap(o))

    def __gt__(self, o):
        return self._wrap(self._col > _unwrap(o))

    def __ge__(self, o):
        return self._wrap(self._col >= _unwrap(o))

    def __and__(self, o):
        return self._wrap(self._col & _unwrap(o))

    def __or__(self, o):
        return self._wrap(self._col | _unwrap(o))

    def __invert__(self):
        return self._wrap(~self._col)

    def isin(self, values):
        return self._wrap(self._col.isin(list(values)))

    def isna(self):
        return self._wrap(self._col.isNull())

    def fillna(self, v):
        return self._wrap(F.coalesce(self._col, F.lit(v)))

    def str_upper(self):
        return self._wrap(F.upper(self._col))

    # reductions materialize
    def _agg(self, fn):
        out = self._frame._df.agg(fn(self._col).alias("v")).collect()
        return out[0]["v"]

    def sum(self):  # noqa: A003
        return self._agg(F.sum)

    def mean(self):
        return self._agg(F.avg)

    def min(self):  # noqa: A003
        return self._agg(F.min)

    def max(self):  # noqa: A003
        return self._agg(F.max)

    def count(self):
        return self._agg(F.count)

    def nunique(self):
        return self._agg(F.countDistinct)

    def to_pandas(self):
        import pandas as pd

        t = self._frame._df.select(self._col.alias(self.name)).toArrow()
        return t.to_pandas()[self.name]

    def __repr__(self):
        return repr(self.to_pandas())


def _unwrap(o):
    if isinstance(o, Series):
        return o._col
    return o


class GroupBy:
    def __init__(self, frame: "DataFrame", keys: list[str]):
        self._frame = frame
        self._keys = keys

    def agg(self, spec: dict) -> "DataFrame":
        fns = {"sum": F.sum, "mean": F.avg, "avg": F.avg, "min": F.min,
               "max": F.max, "count": F.count, "nunique": F.countDistinct,
               "std": F.stddev}
        aggs = []
        for col, how in spec.items():
            hows = how if isinstance(how, (list, tuple)) else [how]
            for h in hows:
                name = col if len(hows) == 1 else f"{col}_{h}"
                aggs.append(fns[h](col).alias(name))
        return DataFrame(self._frame._df.groupBy(*self._keys).agg(*aggs))

    def sum(self):  # noqa: A003
        cols = [c for c in self._frame.columns if c not in self._keys
                and self._frame._numeric(c)]
        return self.agg({c: "sum" for c in cols})

    def mean(self):
        cols = [c for c in self._frame.columns if c not in self._keys
                and self._frame._numeric(c)]
        return self.agg({c: "mean" for c in cols})

    def count(self):
        return DataFrame(self._frame._df.groupBy(*self._keys).count())

    def size(self):
        return self.count()


class DataFrame:
    def __init__(self, df: EngineFrame):
        self._df = df

    # --- metadata ------------------------------------------------------
    @property
    def columns(self) -> list[str]:
        return self._df.columns

    @property
    def shape(self):
        return (len(self), len(self.columns))

    def _numeric(self, name: str) -> bool:
        from ..types import NumericType

        for f in self._df.schema:
            if f.name == name:
                return isinstance(f.dataType, NumericType)
        return False

    def __len__(self):
        return self._df.count()

    # --- selection -----------------------------------------------------
    def __getitem__(self, key):
        if isinstance(key, str):
            return Series(self, F.col(key), key)
        if isinstance(key, list):
            return DataFrame(self._df.select(*key))
        if isinstance(key, Series):  # boolean mask
            return DataFrame(self._df.filter(key._col))
        raise KeyError(key)

    def __setitem__(self, name: str, value):
        if isinstance(value, Series):
            self._df = self._df.withColumn(name, value._col)
        else:
            self._df = self._df.withColumn(name, F.lit(value))

    def assign(self, **kw) -> "DataFrame":
        df = self._df
        for name, v in kw.items():
            df = df.withColumn(name, v._col if isinstance(v, Series)
                               else F.lit(v))
        return DataFrame(df)

    def drop(self, columns) -> "DataFrame":
        cols = [columns] if isinstance(columns, str) else list(columns)
        return DataFrame(self._df.drop(*cols))

    def rename(self, columns: dict) -> "DataFrame":
        df = self._df
        for old, new in columns.items():
            df = df.withColumnRenamed(old, new)
        return DataFrame(df)

    def dropna(self, subset=None) -> "DataFrame":
        cols = subset or self.columns
        df = self._df
        for c in cols:
            df = df.filter(F.col(c).isNotNull())
        return DataFrame(df)

    def drop_duplicates(self, subset=None) -> "DataFrame":
        return DataFrame(self._df.dropDuplicates(subset))

    # --- compute -------------------------------------------------------
    def groupby(self, by) -> GroupBy:
        keys = [by] if isinstance(by, str) else list(by)
        return GroupBy(self, keys)

    def sort_values(self, by, ascending=True) -> "DataFrame":
        keys = [by] if isinstance(by, str) else list(by)
        return DataFrame(self._df.orderBy(*keys, ascending=ascending))

    def merge(self, other: "DataFrame", on=None, how: str = "inner"
              ) -> "DataFrame":
        return DataFrame(self._df.join(other._df, on=on, how=how))

    def head(self, n: int = 5):
        return self._df.limit(n).toPandas()

    def describe(self):
        return self._df.describe().toPandas()

    def value_counts(self, col: str):
        return (self._df.groupBy(col).count()
                .orderBy(F.col("count").desc()).toPandas())

    # --- materialization ----------------------------------------------
    def to_pandas(self):
        return self._df.toPandas()

    def to_spark(self) -> EngineFrame:
        return self._df

    def __repr__(self):
        return repr(self._df.limit(20).toPandas())


# ---------------------------------------------------------------------------
# r4 breadth (reference: python/pyspark/pandas — Series.str accessor,
# apply-as-UDF, query, pivot_table, IO writers)
# ---------------------------------------------------------------------------

class _StrAccessor:
    """Series.str namespace (pyspark.pandas strings.py role)."""

    def __init__(self, s: "Series"):
        self._s = s

    def _wrap(self, col):
        return self._s._wrap(col)

    def upper(self):
        return self._wrap(F.upper(self._s._col))

    def lower(self):
        return self._wrap(F.lower(self._s._col))

    def len(self):  # noqa: A003
        return self._wrap(F.length(self._s._col))

    def contains(self, pat: str):
        return self._wrap(self._s._col.contains(pat))

    def startswith(self, pat: str):
        return self._wrap(self._s._col.startswith(pat))

    def endswith(self, pat: str):
        return self._wrap(self._s._col.endswith(pat))

    def replace(self, pat: str, repl: str):
        return self._wrap(F.regexp_replace(self._s._col, pat, repl))

    def strip(self):
        return self._wrap(F.trim(self._s._col))


def _extend_series():
    """Attach the r4 Series surface (kept out-of-line so the core class
    above stays readable)."""

    Series.str = property(_StrAccessor)

    def astype(self, t):
        name = {int: "bigint", float: "double", str: "string",
                bool: "boolean"}.get(t, str(t))
        return self._wrap(self._col.cast(name))

    def _abs(self):
        return self._wrap(F.abs(self._col))

    def _round(self, ndigits: int = 0):
        return self._wrap(F.round(self._col, ndigits))

    def clip(self, lower=None, upper=None):
        c = self._col
        if lower is not None:
            c = F.greatest(c, F.lit(lower))
        if upper is not None:
            c = F.least(c, F.lit(upper))
        return self._wrap(c)

    def between(self, lo, hi):
        return self._wrap(self._col.between(lo, hi))

    def std(self):
        return self._agg(F.stddev)

    def var(self):
        return self._agg(F.variance)

    def median(self):
        return self._agg(F.median)

    def unique(self):
        t = self._frame._df.select(
            self._col.alias(self.name)).distinct().toArrow()
        return t.column(0).to_pylist()

    def value_counts(self):
        return (self._frame._df.groupBy(self._col.alias(self.name))
                .count().orderBy(F.col("count").desc()).toPandas())

    def apply(self, fn):
        """Element-wise python function as a vectorized host UDF
        (pyspark.pandas apply → ArrowEvalPython role)."""
        u = F.udf(fn)
        return self._wrap(u(self._col))

    map = apply  # noqa: A003

    Series.astype = astype
    Series.abs = _abs
    Series.round = _round
    Series.clip = clip
    Series.between = between
    Series.std = std
    Series.var = var
    Series.median = median
    Series.unique = unique
    Series.value_counts = value_counts
    Series.apply = apply
    Series.map = apply


_extend_series()


def _extend_frame():
    def fillna(self, value) -> "DataFrame":
        return DataFrame(self._df.na.fill(value))

    def query(self, expr: str) -> "DataFrame":
        return DataFrame(self._df.filter(expr))

    def nlargest(self, n: int, columns) -> "DataFrame":
        keys = [columns] if isinstance(columns, str) else list(columns)
        return DataFrame(self._df.orderBy(
            *[F.col(k).desc() for k in keys]).limit(n))

    def nsmallest(self, n: int, columns) -> "DataFrame":
        keys = [columns] if isinstance(columns, str) else list(columns)
        return DataFrame(self._df.orderBy(*keys).limit(n))

    def pivot_table(self, values: str, index: str, columns: str,
                    aggfunc: str = "mean"):
        agg = {"mean": F.avg, "sum": F.sum, "count": F.count,
               "min": F.min, "max": F.max}[aggfunc]
        return DataFrame(self._df.groupBy(index).pivot(columns)
                         .agg(agg(values)))

    def nunique(self):
        import pandas as pd

        # one query per column: the engine rejects several DISTINCT
        # aggregates over different expressions in one Aggregate
        out = {}
        for c in self.columns:
            row = self._df.agg(F.countDistinct(c).alias("n")).toPandas()
            out[c] = int(row["n"][0])
        return pd.Series(out)

    def to_parquet(self, path: str) -> None:
        self._df.write.mode("overwrite").parquet(path)

    def to_csv(self, path: str) -> None:
        self._df.write.mode("overwrite").csv(path)

    DataFrame.fillna = fillna
    DataFrame.query = query
    DataFrame.nlargest = nlargest
    DataFrame.nsmallest = nsmallest
    DataFrame.pivot_table = pivot_table
    DataFrame.nunique = nunique
    DataFrame.to_parquet = to_parquet
    DataFrame.to_csv = to_csv


_extend_frame()


# ---------------------------------------------------------------------------
# r5 breadth (reference: python/pyspark/pandas — rolling/expanding
# windows, groupby.apply, datetimes.py dt accessor, to_datetime,
# MultiIndex through set_index/groupby keys)
# ---------------------------------------------------------------------------

class _Rolling:
    """Positional rolling window (pyspark.pandas window.py Rolling).
    Window semantics are row-positional, so the series materializes to
    the host once and the reductions run as VECTORIZED numpy over a
    sliding_window_view — no per-row Python loop."""

    def __init__(self, s: "Series", window: int, min_periods=None):
        self._s = s
        self.window = int(window)
        self.min_periods = self.window if min_periods is None \
            else int(min_periods)

    def _values(self):
        return self._s.to_pandas().to_numpy(dtype=float, na_value=np.nan)

    def _windows(self):
        """[n, w] view: row i = the window ending at i (NaN-padded)."""
        v = self._values()
        w = min(self.window, max(len(v), 1))
        padded = np.concatenate([np.full(w - 1, np.nan), v])
        return v, np.lib.stride_tricks.sliding_window_view(padded, w)

    def _gate(self, res, cnt):
        return np.where(cnt >= self.min_periods, res, np.nan)

    def _reduce(self, nanfn):
        import warnings

        v, win = self._windows()
        cnt = (~np.isnan(win)).sum(axis=1)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)  # all-NaN
            res = nanfn(win)
        return self._host_series(self._gate(res, cnt))

    def _host_series(self, values):
        import pandas as pd

        return pd.Series(values, name=self._s.name)

    def sum(self):  # noqa: A003
        return self._reduce(lambda w: np.nansum(w, axis=1))

    def mean(self):
        return self._reduce(lambda w: np.nanmean(w, axis=1))

    def min(self):  # noqa: A003
        return self._reduce(lambda w: np.nanmin(w, axis=1))

    def max(self):  # noqa: A003
        return self._reduce(lambda w: np.nanmax(w, axis=1))

    def std(self):
        return self._reduce(lambda w: np.nanstd(w, axis=1, ddof=1))

    def count(self):
        # pandas: count gates min_periods on window POSITIONS (NaN rows
        # included), then counts the non-null ones
        v, win = self._windows()
        n = len(v)
        cnt = (~np.isnan(win)).sum(axis=1).astype(float)
        positions = np.minimum(np.arange(n) + 1, self.window)
        gate = positions >= min(self.min_periods, self.window)
        return self._host_series(np.where(gate, cnt, np.nan))


class _Expanding(_Rolling):
    """Expanding window: cumulative formulations (accumulate/cumsum),
    never a materialized n×n window."""

    def __init__(self, s: "Series", min_periods: int = 1):
        super().__init__(s, 1 << 31, min_periods=min_periods)

    def _cum(self):
        v = self._values()
        valid = ~np.isnan(v)
        return v, valid, np.cumsum(valid)

    def sum(self):  # noqa: A003
        v, valid, cnt = self._cum()
        return self._host_series(
            self._gate(np.nancumsum(v), cnt))

    def mean(self):
        v, valid, cnt = self._cum()
        with np.errstate(invalid="ignore", divide="ignore"):
            res = np.nancumsum(v) / cnt
        return self._host_series(self._gate(res, cnt))

    def min(self):  # noqa: A003
        v, valid, cnt = self._cum()
        res = np.minimum.accumulate(np.where(valid, v, np.inf))
        return self._host_series(self._gate(res, cnt))

    def max(self):  # noqa: A003
        v, valid, cnt = self._cum()
        res = np.maximum.accumulate(np.where(valid, v, -np.inf))
        return self._host_series(self._gate(res, cnt))

    def std(self):
        v, valid, cnt = self._cum()
        s1 = np.nancumsum(v)
        s2 = np.nancumsum(v * v)
        with np.errstate(invalid="ignore", divide="ignore"):
            var = (s2 - s1 * s1 / cnt) / (cnt - 1)
        res = np.sqrt(np.maximum(var, 0))
        return self._host_series(
            np.where(cnt >= max(self.min_periods, 2), res, np.nan))

    def count(self):
        v, valid, cnt = self._cum()
        positions = np.arange(len(v)) + 1
        return self._host_series(
            np.where(positions >= self.min_periods,
                     cnt.astype(float), np.nan))


class _DtAccessor:
    """Series.dt namespace (pyspark.pandas datetimes.py role)."""

    def __init__(self, s: "Series"):
        self._s = s

    def _wrap(self, col):
        return self._s._wrap(col)

    @property
    def year(self):
        return self._wrap(F.year(self._s._col))

    @property
    def month(self):
        return self._wrap(F.month(self._s._col))

    @property
    def day(self):
        return self._wrap(F.dayofmonth(self._s._col))

    @property
    def hour(self):
        return self._wrap(F.hour(self._s._col))

    @property
    def minute(self):
        return self._wrap(F.minute(self._s._col))

    @property
    def second(self):
        return self._wrap(F.second(self._s._col))

    @property
    def dayofweek(self):
        # pandas: Monday=0; engine dayofweek: Sunday=1
        return self._wrap((F.dayofweek(self._s._col) + F.lit(5)) % F.lit(7))

    @property
    def quarter(self):
        return self._wrap(F.quarter(self._s._col))

    @property
    def date(self):
        return self._wrap(self._s._col.cast("date"))


def to_datetime(arg, format=None):  # noqa: A002
    """ps.to_datetime: Series → timestamp column; anything else defers
    to real pandas (host values)."""
    import pandas as pd

    if isinstance(arg, Series):
        if format is None:
            return arg._wrap(arg._col.cast("timestamp"))
        # explicit format: host-parse via pandas, re-enter as a column
        parsed = pd.to_datetime(arg.to_pandas(), format=format)
        name = arg.name
        frame = arg._frame
        pdf = frame.to_pandas()
        pdf[name + "__dt"] = parsed.to_numpy()
        out = DataFrame(_session().createDataFrame(pdf))
        return out[name + "__dt"]
    return pd.to_datetime(arg, format=format)


def _extend_frame_r5():
    def set_index(self, keys) -> "DataFrame":
        keys = [keys] if isinstance(keys, str) else list(keys)
        out = DataFrame(self._df)
        out._index_cols = keys
        return out

    def reset_index(self, drop: bool = False) -> "DataFrame":
        idx = getattr(self, "_index_cols", None)
        if drop and idx:
            # pandas drops the former index entirely
            keep = [c for c in self.columns if c not in idx]
            out = DataFrame(self._df.select(*keep))
        else:
            out = DataFrame(self._df)
        out._index_cols = None
        return out

    _orig_to_pandas = DataFrame.to_pandas

    def to_pandas(self):
        pdf = _orig_to_pandas(self)
        idx = getattr(self, "_index_cols", None)
        if idx:
            pdf = pdf.set_index(idx if len(idx) > 1 else idx[0])
        return pdf

    DataFrame.set_index = set_index
    DataFrame.reset_index = reset_index
    DataFrame.to_pandas = to_pandas

    def g_apply(self, fn):
        """groupby(...).apply(fn): fn receives each group as a REAL
        pandas DataFrame; results concat into a new frame
        (pyspark.pandas groupby.apply → the grouped-map UDF shape)."""
        import pandas as pd

        pdf = self._frame._df.toPandas()
        pieces = []
        for key, grp in pdf.groupby(
                self._keys if len(self._keys) > 1 else self._keys[0]):
            r = fn(grp)
            if isinstance(r, pd.DataFrame):
                r = r.copy()
                # re-attach grouping keys fn's result dropped (pandas
                # carries them in the result index; columns here)
                for k, v in zip(self._keys,
                                key if isinstance(key, tuple) else (key,)):
                    if k not in r.columns:
                        r[k] = v
                pieces.append(r)
            elif isinstance(r, pd.Series):
                row = r.to_frame().T
                for k, v in zip(self._keys,
                                key if isinstance(key, tuple) else (key,)):
                    row[k] = v
                pieces.append(row)
            else:
                row = {k: v for k, v in zip(
                    self._keys,
                    key if isinstance(key, tuple) else (key,))}
                row["value"] = r
                pieces.append(pd.DataFrame([row]))
        merged = pd.concat(pieces, ignore_index=True)
        return DataFrame(_session().createDataFrame(merged))

    GroupBy.apply = g_apply

    _orig_g_agg = GroupBy.agg

    def g_agg(self, spec: dict) -> "DataFrame":
        out = _orig_g_agg(self, spec)
        # grouping keys become the (Multi)Index, like pandas
        out._index_cols = list(self._keys)
        return out

    GroupBy.agg = g_agg

    def rolling(self, window: int, min_periods=None):
        return _Rolling(self, window, min_periods)

    def expanding(self, min_periods: int = 1):
        return _Expanding(self, min_periods)

    Series.rolling = rolling
    Series.expanding = expanding
    Series.dt = property(_DtAccessor)


_extend_frame_r5()


def concat(frames) -> "DataFrame":
    """Row-wise union (pd.concat axis=0 over same-schema frames)."""
    frames = list(frames)
    df = frames[0]._df
    for f in frames[1:]:
        df = df.union(f._df)
    return DataFrame(df)


def read_json(path: str) -> "DataFrame":
    return DataFrame(_session().read.json(path))


def read_orc(path: str) -> "DataFrame":
    return DataFrame(_session().read.orc(path))
