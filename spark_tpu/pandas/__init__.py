from .frame import (  # noqa: F401
    DataFrame, Series, from_pandas, read_csv, read_parquet,
)
from .frame import concat, read_json, read_orc, to_datetime  # noqa: F401
