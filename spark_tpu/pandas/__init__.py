from .frame import (  # noqa: F401
    DataFrame, Series, from_pandas, read_csv, read_parquet,
)
