"""sparktpu-sqlserver entry point (HiveThriftServer2.main role)."""

from __future__ import annotations

import argparse
import json
import threading


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="sparktpu-sqlserver")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=10000)
    p.add_argument("--conf", action="append", default=[], metavar="K=V")
    args = p.parse_args(argv)

    from ..api.session import TpuSession
    from .sql_endpoint import SQLEndpoint

    conf = dict(kv.split("=", 1) for kv in args.conf if "=" in kv)
    session = TpuSession("sqlserver", conf)
    ep = SQLEndpoint(session, host=args.host, port=args.port).start()
    print(json.dumps({"host": ep.host, "port": ep.port}), flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    ep.stop()
    session.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
