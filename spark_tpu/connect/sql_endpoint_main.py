"""sparktpu-sqlserver entry point (HiveThriftServer2.main role).

Serves SQL over the JSON-lines endpoint with the full serving stack:
session-per-connection isolation, fair-scheduler pools, and graceful
drain — SIGTERM (and Ctrl-C) stop accepting statements immediately
(typed SERVER_DRAINING errors on the wire), let in-flight queries
finish and flush their query profiles, then exit.
"""

from __future__ import annotations

import argparse
import json
import signal
import threading


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="sparktpu-sqlserver")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=10000)
    p.add_argument("--conf", action="append", default=[], metavar="K=V")
    p.add_argument("--cache-dir", default=None, metavar="DIR",
                   help="enable the persistent caches rooted here "
                        "(spark.tpu.cache.dir): the XLA compile cache "
                        "makes a server RESTART warm — known plans pay "
                        "no cold compiles — and the result cache answers "
                        "repeated identical queries with zero kernel "
                        "launches, shared across all connections")
    p.add_argument("--pools", default=None, metavar="DECLS",
                   help="fair-scheduler pool declarations "
                        "'name[:weight],...' (spark.tpu.scheduler.pools); "
                        "connections pick a pool with "
                        "SET spark.tpu.scheduler.pool=<name>")
    p.add_argument("--session-mode", choices=("isolated", "shared"),
                   default=None,
                   help="session model (spark.tpu.serve.sessionMode): "
                        "'isolated' (default) clones one session per "
                        "connection; 'shared' keeps the legacy "
                        "one-session-for-all behavior")
    args = p.parse_args(argv)

    from ..api.session import TpuSession
    from .sql_endpoint import SQLEndpoint

    conf = dict(kv.split("=", 1) for kv in args.conf if "=" in kv)
    if args.cache_dir:
        conf.setdefault("spark.tpu.cache.dir", args.cache_dir)
    if args.pools:
        conf.setdefault("spark.tpu.scheduler.pools", args.pools)
    if args.session_mode:
        conf.setdefault("spark.tpu.serve.sessionMode", args.session_mode)
    session = TpuSession("sqlserver", conf)
    ep = SQLEndpoint(session, host=args.host, port=args.port).start()
    print(json.dumps({"host": ep.host, "port": ep.port}), flush=True)

    stop_evt = threading.Event()

    def _on_term(signum, frame):  # graceful drain on SIGTERM
        stop_evt.set()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass  # non-main thread / restricted platform: Ctrl-C still works
    try:
        stop_evt.wait()
    except KeyboardInterrupt:
        pass
    drained = ep.stop()  # reject new, finish in-flight, flush profiles
    print(json.dumps({"stopped": True, "drained": bool(drained),
                      "status": ep.service.status()}), flush=True)
    session.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
