"""sparktpu-sqlserver entry point (HiveThriftServer2.main role)."""

from __future__ import annotations

import argparse
import json
import threading


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="sparktpu-sqlserver")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=10000)
    p.add_argument("--conf", action="append", default=[], metavar="K=V")
    p.add_argument("--cache-dir", default=None, metavar="DIR",
                   help="enable the persistent caches rooted here "
                        "(spark.tpu.cache.dir): the XLA compile cache "
                        "makes a server RESTART warm — known plans pay "
                        "no cold compiles — and the result cache answers "
                        "repeated identical queries with zero kernel "
                        "launches, shared across all connections")
    args = p.parse_args(argv)

    from ..api.session import TpuSession
    from .sql_endpoint import SQLEndpoint

    conf = dict(kv.split("=", 1) for kv in args.conf if "=" in kv)
    if args.cache_dir:
        conf.setdefault("spark.tpu.cache.dir", args.cache_dir)
    session = TpuSession("sqlserver", conf)
    ep = SQLEndpoint(session, host=args.host, port=args.port).start()
    print(json.dumps({"host": ep.host, "port": ep.port}), flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    ep.stop()
    session.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
