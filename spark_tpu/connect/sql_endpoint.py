"""SQL endpoint for external tools + DB-API client.

Role of the reference's HiveThriftServer2
(sql/hive-thriftserver/.../HiveThriftServer2.scala:149 + the
SparkSQLOperationManager): a long-running server external tools connect
to with plain SQL and get tabular results back — the JDBC/ODBC
endpoint role. The wire protocol is newline-delimited JSON over TCP
(one request object per line, one response object per line) instead of
Thrift, and `spark_tpu.connect.sql_endpoint.connect()` provides a
DB-API 2.0 connection/cursor so Python tools (and anything that speaks
DB-API) can query the engine like any database:

    conn = connect("127.0.0.1", port)
    cur = conn.cursor()
    cur.execute("select k, sum(v) from t group by k")
    cur.fetchall()

All connections share the ONE server session — SET commands and temp
views are visible across clients, the same shared-SparkContext model
the reference's thriftserver uses by default (per-connection config
isolation would need session cloning; not implemented)."""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from typing import Any


def _json_cell(v) -> Any:
    import datetime
    import decimal

    if isinstance(v, (datetime.datetime, datetime.date)):
        return v.isoformat()
    if isinstance(v, decimal.Decimal):
        return str(v)
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return v


class SQLEndpoint:
    """JSON-lines SQL server over one engine session."""

    def __init__(self, session, host: str = "127.0.0.1", port: int = 0):
        self.session = session
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        req = json.loads(line)
                        resp = outer._run(req)
                    except Exception as e:  # protocol-level failure
                        resp = {"error": f"{type(e).__name__}: {e}"}
                    self.wfile.write(
                        (json.dumps(resp) + "\n").encode())
                    self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: threading.Thread | None = None

    def _run(self, req: dict) -> dict:
        sql = req.get("sql")
        if not sql:
            return {"error": "request must carry a 'sql' field"}
        try:
            out = self.session.sql(sql)
            if out is None or not hasattr(out, "toArrow"):
                return {"columns": [], "types": [], "rows": []}
            t = out.toArrow()
            cols = t.column_names
            types = [str(c.type) for c in t.columns]
            pylists = [c.to_pylist() for c in t.columns]
            rows = [[_json_cell(v) for v in row]
                    for row in zip(*pylists)] if cols else []
            return {"columns": cols, "types": types, "rows": rows}
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}

    def start(self) -> "SQLEndpoint":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="sql-endpoint")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


# -- DB-API 2.0 client ------------------------------------------------------

apilevel = "2.0"
threadsafety = 1
paramstyle = "format"


class Error(Exception):
    pass


class Cursor:
    def __init__(self, conn: "Connection"):
        self._conn = conn
        self.description = None
        self.rowcount = -1
        self._rows: list = []
        self._pos = 0
        self.arraysize = 1

    def execute(self, sql: str, params=None) -> "Cursor":
        if params:
            # substitute ONLY %s placeholders — a literal % elsewhere in
            # the SQL (LIKE 'a%') must not be treated as a format spec
            parts = sql.split("%s")
            if len(parts) - 1 != len(params):
                raise Error(
                    f"{len(params)} parameters for "
                    f"{len(parts) - 1} %s placeholders")
            out = [parts[0]]
            for p, tail in zip(params, parts[1:]):
                out.append(_sql_quote(p))
                out.append(tail)
            sql = "".join(out)
        resp = self._conn._request({"sql": sql})
        if resp.get("error"):
            raise Error(resp["error"])
        cols = resp.get("columns", [])
        types = resp.get("types", [])
        self.description = [(c, t, None, None, None, None, None)
                            for c, t in zip(cols, types)] or None
        self._rows = [tuple(r) for r in resp.get("rows", [])]
        self.rowcount = len(self._rows)
        self._pos = 0
        return self

    def fetchone(self):
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size=None):
        size = size or self.arraysize
        out = self._rows[self._pos:self._pos + size]
        self._pos += len(out)
        return out

    def fetchall(self):
        out = self._rows[self._pos:]
        self._pos = len(self._rows)
        return out

    def close(self):
        self._rows = []

    def __iter__(self):
        while True:
            r = self.fetchone()
            if r is None:
                return
            yield r


def _sql_quote(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, str):
        return "'" + v.replace("'", "''") + "'"
    return str(v)


class Connection:
    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)
        self._file = self._sock.makefile("rwb")
        self._lock = threading.Lock()

    def _request(self, req: dict) -> dict:
        with self._lock:
            self._file.write((json.dumps(req) + "\n").encode())
            self._file.flush()
            line = self._file.readline()
        if not line:
            raise Error("server closed the connection")
        return json.loads(line)

    def cursor(self) -> Cursor:
        return Cursor(self)

    def commit(self) -> None:
        pass        # autocommit semantics

    def rollback(self) -> None:
        raise Error("transactions are not supported")

    def close(self) -> None:
        try:
            self._file.close()
        finally:
            self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def connect(host: str = "127.0.0.1", port: int = 10000,
            timeout: float = 60.0) -> Connection:
    return Connection(host, port, timeout)
