"""SQL endpoint for external tools + DB-API client.

Role of the reference's HiveThriftServer2
(sql/hive-thriftserver/.../HiveThriftServer2.scala:149 + the
SparkSQLOperationManager): a long-running server external tools connect
to with plain SQL and get tabular results back — the JDBC/ODBC
endpoint role. The wire protocol is newline-delimited JSON over TCP
(one request object per line, one response object per line) instead of
Thrift, and `spark_tpu.connect.sql_endpoint.connect()` provides a
DB-API 2.0 connection/cursor so Python tools (and anything that speaks
DB-API) can query the engine like any database:

    conn = connect("127.0.0.1", port)
    cur = conn.cursor()
    cur.execute("select k, sum(v) from t group by k")
    cur.fetchall()

Session model (spark_tpu/serve/): each connection gets its OWN cloned
session (TpuSession.newSession) — SET and temp views are
connection-local while the KernelCache, warehouse catalog and
persistent caches stay shared, the reference ThriftServer's
session-per-connection model. Temp views registered on the server
session read through to every connection. The legacy
all-connections-share-one-session behavior is an opt-in: start the
server with spark.tpu.serve.sessionMode=shared, or send
{"session": "shared"} on a connection before its first statement.

Queries are admitted through weighted fair-scheduler pools
(spark.tpu.scheduler.pools; a connection picks its pool with
`SET spark.tpu.scheduler.pool=<name>`), with bounded queues,
queue-timeout rejection, and plan-time HBM admission. A
{"status": true} request returns the per-pool live serving status
(queued/running/rejected, latency percentiles, SLO findings).
stop() drains gracefully: new statements are rejected with a typed
SERVER_DRAINING error while in-flight queries finish and flush their
query profiles."""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from typing import Any


def _json_cell(v) -> Any:
    import datetime
    import decimal

    if isinstance(v, (datetime.datetime, datetime.date)):
        return v.isoformat()
    if isinstance(v, decimal.Decimal):
        return str(v)
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return v


class SQLEndpoint:
    """JSON-lines SQL server over a serving session pool (see module
    docstring: session-per-connection, fair-scheduler pool admission,
    graceful drain)."""

    def __init__(self, session, host: str = "127.0.0.1", port: int = 0,
                 service=None):
        from ..serve.service import QueryService

        self.session = session
        self.service = service if service is not None \
            else QueryService(session)
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                # per-connection session, cloned lazily on the first
                # statement so a {"session": "shared"} opt-in sent
                # first binds the connection to the server session
                state = {"session": None}
                for line in self.rfile:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        req = json.loads(line)
                        resp = outer._run(req, state)
                    except Exception as e:  # protocol-level failure
                        resp = _error_resp(e)
                    self.wfile.write(
                        (json.dumps(resp) + "\n").encode())
                    self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: threading.Thread | None = None

    def _conn_session(self, state: dict, req: dict):
        if req.get("session") == "shared":
            # explicit opt-in rebinds the connection (legacy behavior)
            state["session"] = self.service.open_session("shared")
        if state["session"] is None:
            state["session"] = self.service.open_session()
        return state["session"]

    def _run(self, req: dict, state: dict) -> dict:
        if req.get("status"):
            return {"status": self.service.status()}
        if req.get("metrics"):
            # Prometheus text scrape over the SQL wire — same payload
            # the history server's /metrics serves; "" while the export
            # switch is off so tools can distinguish disabled from empty
            from ..obs import export as _export

            return {"metrics": _export.render_prometheus()
                    if _export.ENABLED else "",
                    "enabled": _export.ENABLED}
        if req.get("bundles"):
            # black-box bundle index over the SQL wire (obs/blackbox):
            # recent anomaly-captured bundles, newest first — empty
            # list with the capture layer unarmed
            from ..config import OBS_BUNDLE_DIR
            from ..obs import blackbox

            bdir = str(self.service.session.conf.get(
                OBS_BUNDLE_DIR) or "")
            return {"bundles": blackbox.list_bundles(bdir)[:16]
                    if bdir else [],
                    "enabled": blackbox.ENABLED}
        sql = req.get("sql")
        if not sql:
            if req.get("session"):
                # session-mode-only request: bind and acknowledge
                try:
                    self._conn_session(state, req)
                    return {"ok": True, "session": req.get("session")}
                except Exception as e:
                    return _error_resp(e)
            return {"error": "request must carry a 'sql' field"}
        try:
            sess = self._conn_session(state, req)
            t = self.service.execute_sql(sess, sql)
            if t is None or not hasattr(t, "column_names"):
                return {"columns": [], "types": [], "rows": []}
            cols = t.column_names
            types = [str(c.type) for c in t.columns]
            pylists = [c.to_pylist() for c in t.columns]
            rows = [[_json_cell(v) for v in row]
                    for row in zip(*pylists)] if cols else []
            return {"columns": cols, "types": types, "rows": rows}
        except Exception as e:
            return _error_resp(e)

    def start(self) -> "SQLEndpoint":
        # race-lint: ignore[bare-submit] — HTTP accept loop for the whole
        # endpoint; per-request queries enter their own scope downstream
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="sql-endpoint")
        self._thread.start()
        return self

    def stop(self, drain_timeout: float | None = None) -> bool:
        """Graceful drain then socket close: new statements are
        rejected with SERVER_DRAINING the moment this is called;
        in-flight and already-queued queries get the drain budget
        (spark.tpu.serve.drainTimeout) to finish — and flush their
        query profiles — before the listener closes. Returns True when
        everything quiesced inside the budget."""
        try:
            drained = self.service.drain(drain_timeout)
        except Exception:
            drained = False
        self._server.shutdown()
        self._server.server_close()
        return drained


def _error_resp(e: Exception) -> dict:
    resp = {"error": f"{type(e).__name__}: {e}"}
    ec = getattr(e, "error_class", None)
    if ec:
        resp["error_class"] = ec
    return resp


# -- DB-API 2.0 client ------------------------------------------------------

apilevel = "2.0"
threadsafety = 1
paramstyle = "format"


class Error(Exception):
    """DB-API error; `error_class` carries the server's stable error
    condition (e.g. SERVER_DRAINING, ADMISSION_TIMEOUT) when one rode
    the wire."""

    def __init__(self, message: str, error_class: str | None = None):
        super().__init__(message)
        self.error_class = error_class


class Cursor:
    def __init__(self, conn: "Connection"):
        self._conn = conn
        self.description = None
        self.rowcount = -1
        self._rows: list = []
        self._pos = 0
        self.arraysize = 1

    def execute(self, sql: str, params=None) -> "Cursor":
        if params:
            # substitute ONLY %s placeholders — a literal % elsewhere in
            # the SQL (LIKE 'a%') must not be treated as a format spec
            parts = sql.split("%s")
            if len(parts) - 1 != len(params):
                raise Error(
                    f"{len(params)} parameters for "
                    f"{len(parts) - 1} %s placeholders")
            out = [parts[0]]
            for p, tail in zip(params, parts[1:]):
                out.append(_sql_quote(p))
                out.append(tail)
            sql = "".join(out)
        resp = self._conn._request({"sql": sql})
        if resp.get("error"):
            raise Error(resp["error"], resp.get("error_class"))
        cols = resp.get("columns", [])
        types = resp.get("types", [])
        self.description = [(c, t, None, None, None, None, None)
                            for c, t in zip(cols, types)] or None
        self._rows = [tuple(r) for r in resp.get("rows", [])]
        self.rowcount = len(self._rows)
        self._pos = 0
        return self

    def fetchone(self):
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size=None):
        size = size or self.arraysize
        out = self._rows[self._pos:self._pos + size]
        self._pos += len(out)
        return out

    def fetchall(self):
        out = self._rows[self._pos:]
        self._pos = len(self._rows)
        return out

    def close(self):
        self._rows = []

    def __iter__(self):
        while True:
            r = self.fetchone()
            if r is None:
                return
            yield r


def _sql_quote(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, str):
        return "'" + v.replace("'", "''") + "'"
    return str(v)


class Connection:
    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)
        self._file = self._sock.makefile("rwb")
        self._lock = threading.Lock()

    def _request(self, req: dict) -> dict:
        with self._lock:
            self._file.write((json.dumps(req) + "\n").encode())
            self._file.flush()
            line = self._file.readline()
        if not line:
            raise Error("server closed the connection")
        return json.loads(line)

    def cursor(self) -> Cursor:
        return Cursor(self)

    def use_shared_session(self) -> None:
        """Opt this connection into the legacy shared server session
        (SET / temp views visible across connections)."""
        resp = self._request({"session": "shared"})
        if resp.get("error"):
            raise Error(resp["error"], resp.get("error_class"))

    def server_status(self) -> dict:
        """Per-pool live serving status (queued/running/rejected,
        latency percentiles, SLO findings)."""
        resp = self._request({"status": True})
        if resp.get("error"):
            raise Error(resp["error"], resp.get("error_class"))
        return resp.get("status", {})

    def server_metrics(self) -> str:
        """Prometheus text scrape of the server's metrics registry
        ("" when spark.tpu.metrics.export is off server-side)."""
        resp = self._request({"metrics": True})
        if resp.get("error"):
            raise Error(resp["error"], resp.get("error_class"))
        return resp.get("metrics", "")

    def commit(self) -> None:
        pass        # autocommit semantics

    def rollback(self) -> None:
        raise Error("transactions are not supported")

    def close(self) -> None:
        try:
            self._file.close()
        finally:
            self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def connect(host: str = "127.0.0.1", port: int = 10000,
            timeout: float = 60.0) -> Connection:
    return Connection(host, port, timeout)
