"""Connect server: remote SQL execution over the gRPC transport.

Role of the reference's Spark Connect service
(sql/connect/server/src/main/scala/org/apache/spark/sql/connect/service/SparkConnectService.scala:59
executePlan, and SparkConnectPlanner converting proto plans to Catalyst
trees): a long-lived server process owns the engine; thin clients ship a
declarative PLAN — never code — and receive Arrow IPC result batches
streamed back. Departures from the reference, deliberate and TPU-first:

* Plan wire format is JSON (relations.proto role) with SQL-text
  expressions: the engine's own parser plays the role of the proto
  expression tree decoder, so the client needs zero engine code and the
  schema stays readable. An upload carries Arrow IPC bytes after the
  JSON header (the LocalRelation / artifact-upload path).
* One engine TpuSession per (user-supplied) remote session id, created
  on first use and closed on release — SessionHolder semantics. All
  sessions share the server process's device runtime, which is exactly
  the TPU deployment shape: the chip belongs to the server.

Wire protocol (over spark_tpu.net.transport, auth token per cluster):
  execute_plan   stream: req = json(plan);  frames = b"ok", ipc chunks…
                 or a single b"\\x00ERR\\x00" + traceback frame
  command        unary:  req = json + optional binary tail; resp = json
"""

from __future__ import annotations

import json
import threading
import uuid

from ..net.transport import CHUNK_BYTES, RpcServer

_HDR = b"\x00JSON\x00"  # separates json header from binary tail
_ERR = b"\x00ERR\x00"


def pack(obj: dict, tail: bytes = b"") -> bytes:
    return json.dumps(obj).encode() + _HDR + tail


def unpack(payload: bytes) -> tuple[dict, bytes]:
    head, _, tail = payload.partition(_HDR)
    return json.loads(head.decode()), tail


class ConnectServer:
    """Plans and executes client plans against per-session engines."""

    def __init__(self, conf: dict | None = None, token: str | None = None,
                 host: str = "127.0.0.1"):
        self.token = token or uuid.uuid4().hex
        self.conf = dict(conf or {})
        self._sessions: dict = {}
        self._lock = threading.Lock()
        self._server = RpcServer(self.token, host=host)
        self._server.register("command", self._on_command)
        self._server.register_stream("execute_plan", self._on_execute)
        self.address = ""

    def start(self) -> str:
        self.address = self._server.start()
        return self.address

    def stop(self) -> None:
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for s in sessions:
            try:
                s.stop()
            except Exception:
                pass
        self._server.stop()

    # ------------------------------------------------------------------
    def _session(self, session_id: str):
        from ..api.session import TpuSession

        with self._lock:
            s = self._sessions.get(session_id)
            if s is None:
                s = TpuSession(f"connect-{session_id[:8]}", dict(self.conf))
                self._sessions[session_id] = s
        return s

    def _plan_to_df(self, session, plan: dict):
        """JSON relation tree → engine DataFrame (SparkConnectPlanner
        role). Expression payloads are SQL text resolved by the engine's
        own parser."""
        op = plan["op"]
        if op == "sql":
            return session.sql(plan["query"])
        if op == "table":
            return session.table(plan["name"])
        if op == "project":
            return self._plan_to_df(session, plan["child"]) \
                .selectExpr(*plan["exprs"])
        if op == "filter":
            return self._plan_to_df(session, plan["child"]) \
                .filter(plan["condition"])
        if op == "limit":
            return self._plan_to_df(session, plan["child"]) \
                .limit(int(plan["n"]))
        raise ValueError(f"unknown relation op {op!r}")

    # ------------------------------------------------------------------
    def _on_execute(self, payload: bytes):
        import traceback

        try:
            req, _ = unpack(payload)
            session = self._session(req["session_id"])
            table = self._plan_to_df(session, req["plan"]).toArrow()
            import pyarrow as pa

            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, table.schema) as w:
                w.write_table(table)
            raw = sink.getvalue().to_pybytes()
        except Exception:
            yield _ERR + traceback.format_exc().encode()
            return
        yield b"ok"
        for off in range(0, len(raw), CHUNK_BYTES):
            yield raw[off:off + CHUNK_BYTES]

    def _on_command(self, payload: bytes) -> bytes:
        req, tail = unpack(payload)
        op = req["op"]
        if op == "ping":
            return pack({"status": "ok"})
        session = self._session(req["session_id"])
        if op == "upload":
            import pyarrow as pa

            table = pa.ipc.open_stream(pa.BufferReader(tail)).read_all()
            name = req.get("name") or f"upload_{uuid.uuid4().hex[:8]}"
            session.createDataFrame(table).createOrReplaceTempView(name)
            return pack({"status": "ok", "name": name})
        if op == "create_view":
            df = self._plan_to_df(session, req["plan"])
            df.createOrReplaceTempView(req["name"])
            return pack({"status": "ok"})
        if op == "sql_command":
            # DDL/DML path: execute for effect, return row count only
            out = session.sql(req["query"])
            try:
                n = out.toArrow().num_rows
            except Exception:
                n = 0
            return pack({"status": "ok", "rows": n})
        if op == "explain":
            df = self._plan_to_df(session, req["plan"])
            mode = "extended" if req.get("extended") else "formatted"
            text = df.query_execution.explain_string(mode)
            return pack({"status": "ok", "plan": text})
        if op == "schema":
            df = self._plan_to_df(session, req["plan"])
            return pack({"status": "ok",
                         "fields": [(a.name, str(a.dtype)) for a in
                                    df.query_execution.analyzed.output]})
        if op == "close_session":
            with self._lock:
                s = self._sessions.pop(req["session_id"], None)
            if s is not None:
                s.stop()
            return pack({"status": "ok"})
        raise ValueError(f"unknown command {op!r}")


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="spark_tpu Connect server (Spark Connect role)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--token", default=None,
                   help="cluster secret; generated if omitted")
    p.add_argument("--conf", action="append", default=[],
                   metavar="K=V", help="engine conf entries")
    args = p.parse_args(argv)
    conf = dict(kv.split("=", 1) for kv in args.conf)
    server = ConnectServer(conf, token=args.token, host=args.host)
    addr = server.start()
    print(json.dumps({"address": addr, "token": server.token}), flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
