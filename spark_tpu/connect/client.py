"""Connect thin client: Spark-Connect-style remote DataFrame API.

Role of the reference's pure-Python Connect client
(python/pyspark/sql/connect/ — a gRPC client mirroring the DataFrame
API with no JVM/engine dependency): this module imports ONLY stdlib,
pyarrow, and the engine-free gRPC transport. Plans are declarative JSON
relation trees with SQL-text expressions; results stream back as Arrow
IPC batches. A process using this client never imports jax or the
engine — `tests/test_connect.py` pins that property.

    from spark_tpu.connect.client import ConnectSession
    spark = ConnectSession("127.0.0.1:15002", token)
    spark.createDataFrame(arrow_table, "t")
    rows = spark.sql("SELECT k, sum(v) FROM t GROUP BY k").collect()
"""

from __future__ import annotations

import json
import uuid

from ..net.transport import RpcClient

_HDR = b"\x00JSON\x00"
_ERR = b"\x00ERR\x00"


class ConnectError(RuntimeError):
    """Server-side failure executing a remote plan (carries the server
    traceback so analysis errors read the same as in-process)."""


class ConnectSession:
    """Remote session handle (SparkSession surface, Connect flavor)."""

    def __init__(self, address: str, token: str,
                 session_id: str | None = None):
        self._client = RpcClient(address, token)
        self._client.wait_ready()
        self.session_id = session_id or uuid.uuid4().hex

    # -- plumbing ------------------------------------------------------
    def _command(self, op: str, tail: bytes = b"", **kw) -> dict:
        req = {"op": op, "session_id": self.session_id, **kw}
        raw = self._client.call(
            "command", json.dumps(req).encode() + _HDR + tail, timeout=600)
        head, _, _ = raw.partition(_HDR)
        return json.loads(head.decode())

    def _execute(self, plan: dict):
        import pyarrow as pa

        req = {"session_id": self.session_id, "plan": plan}
        frames = self._client.stream(
            "execute_plan", json.dumps(req).encode(), timeout=600)
        head = next(frames, None)
        if head != b"ok":
            detail = (head or b"")[len(_ERR):].decode(errors="replace")
            raise ConnectError(detail or "empty response")
        raw = b"".join(frames)
        return pa.ipc.open_stream(pa.BufferReader(raw)).read_all()

    # -- session surface -----------------------------------------------
    def sql(self, query: str) -> "ConnectDataFrame":
        return ConnectDataFrame(self, {"op": "sql", "query": query})

    def table(self, name: str) -> "ConnectDataFrame":
        return ConnectDataFrame(self, {"op": "table", "name": name})

    def createDataFrame(self, arrow_table,
                        view_name: str | None = None) -> "ConnectDataFrame":
        """Upload a pyarrow table; registered server-side as a temp view
        (the LocalRelation/artifact-upload path)."""
        import pyarrow as pa

        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, arrow_table.schema) as w:
            w.write_table(arrow_table)
        out = self._command("upload", tail=sink.getvalue().to_pybytes(),
                            name=view_name)
        return self.table(out["name"])

    def close(self) -> None:
        try:
            self._command("close_session")
        finally:
            self._client.close()

    stop = close

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class ConnectDataFrame:
    """Lazy remote plan (Dataset surface, Connect flavor)."""

    def __init__(self, session: ConnectSession, plan: dict):
        self._session = session
        self._plan = plan

    # -- transformations (build the plan client-side) -------------------
    def selectExpr(self, *exprs: str) -> "ConnectDataFrame":
        return ConnectDataFrame(self._session, {
            "op": "project", "exprs": list(exprs), "child": self._plan})

    select = selectExpr  # SQL-text expressions are the client's Column

    def filter(self, condition: str) -> "ConnectDataFrame":
        return ConnectDataFrame(self._session, {
            "op": "filter", "condition": condition, "child": self._plan})

    where = filter

    def limit(self, n: int) -> "ConnectDataFrame":
        return ConnectDataFrame(self._session, {
            "op": "limit", "n": n, "child": self._plan})

    # -- actions --------------------------------------------------------
    def toArrow(self):
        return self._session._execute(self._plan)

    def collect(self) -> list[dict]:
        return self.toArrow().to_pylist()

    def count(self) -> int:
        out = ConnectDataFrame(self._session, {
            "op": "project", "exprs": ["count(*) AS count"],
            "child": self._plan}).toArrow()
        return out["count"][0].as_py()

    def schema(self) -> list[tuple]:
        out = self._session._command("schema", plan=self._plan)
        return [tuple(f) for f in out["fields"]]

    def explain(self, extended: bool = False) -> None:
        out = self._session._command("explain", plan=self._plan,
                                     extended=extended)
        print(out["plan"])

    def createOrReplaceTempView(self, name: str) -> None:
        self._session._command("create_view", plan=self._plan, name=name)
