"""pyspark.sql.functions-compatible function namespace.

Role of the reference's sql/api functions.scala / python/pyspark/sql/functions.
"""

from __future__ import annotations

from typing import Any

from ..expr import expressions as E
from .column import Column, _expr


def col(name: str) -> Column:
    if name == "*":
        return Column(E.UnresolvedStar())
    return Column(E.UnresolvedAttribute(name.split(".")))


column = col


def lit(v: Any) -> Column:
    if isinstance(v, Column):
        return v
    return Column(E.Literal(v))


def expr(sql_text: str) -> Column:
    from ..sql.parser import parse_expression

    return Column(parse_expression(sql_text))


def _c(v) -> E.Expression:
    if isinstance(v, str):
        return E.UnresolvedAttribute(v.split("."))
    return _expr(v)


# --- aggregates -------------------------------------------------------------

def sum(c) -> Column:  # noqa: A001
    return Column(E.Sum(_c(c)))


def count(c) -> Column:
    e = _c(c)
    if isinstance(e, E.UnresolvedAttribute) and e.name == "*":
        e = None
    if isinstance(e, E.UnresolvedStar):
        e = None
    return Column(E.Count(e))


def countDistinct(c) -> Column:
    return Column(E.Count(_c(c), distinct=True))


count_distinct = countDistinct


def avg(c) -> Column:
    return Column(E.Average(_c(c)))


mean = avg


def min(c) -> Column:  # noqa: A001
    return Column(E.Min(_c(c)))


def max(c) -> Column:  # noqa: A001
    return Column(E.Max(_c(c)))


def first(c, ignorenulls: bool = True) -> Column:
    return Column(E.First(_c(c), ignorenulls))


def any_value(c) -> Column:
    return Column(E.AnyValue(_c(c)))


def median(c) -> Column:
    return Column(E.Median(_c(c)))


def percentile_approx(c, q, accuracy=None) -> Column:
    return Column(E.Percentile(_c(c), float(q)))


def stddev(c) -> Column:
    return Column(E.StddevSamp(_c(c)))


stddev_samp = stddev


def stddev_pop(c) -> Column:
    return Column(E.StddevPop(_c(c)))


def variance(c) -> Column:
    return Column(E.VarianceSamp(_c(c)))


var_samp = variance


def var_pop(c) -> Column:
    return Column(E.VariancePop(_c(c)))


def corr(a, b) -> Column:
    from ..expr import agg_compound as AC

    return Column(AC.corr(_c(a), _c(b)))


def covar_samp(a, b) -> Column:
    from ..expr import agg_compound as AC

    return Column(AC.covar_samp(_c(a), _c(b)))


def covar_pop(a, b) -> Column:
    from ..expr import agg_compound as AC

    return Column(AC.covar_pop(_c(a), _c(b)))


def skewness(c) -> Column:
    from ..expr import agg_compound as AC

    return Column(AC.skewness(_c(c)))


def kurtosis(c) -> Column:
    from ..expr import agg_compound as AC

    return Column(AC.kurtosis(_c(c)))


def approx_count_distinct(c, rsd=None) -> Column:
    return Column(E.Count(_c(c), distinct=True))


def sum_distinct(c) -> Column:
    e = E.Sum(_c(c))
    e.distinct = True
    return Column(e)


sumDistinct = sum_distinct


# --- conditionals -----------------------------------------------------------

def when(cond: Column, value) -> Column:
    return Column(E.CaseWhen([(cond.expr, _expr(value))], None))


def coalesce(*cols) -> Column:
    return Column(E.Coalesce([_c(c) for c in cols]))


def isnull(c) -> Column:
    return Column(E.IsNull(_c(c)))


def isnan(c) -> Column:
    return Column(E.IsNaN(_c(c)))


def greatest(*cols) -> Column:
    return Column(E.Greatest([_c(c) for c in cols]))


def least(*cols) -> Column:
    return Column(E.Least([_c(c) for c in cols]))


def nanvl(a, b) -> Column:
    return Column(E.If(E.IsNaN(_c(a)), _c(b), _c(a)))


# --- math -------------------------------------------------------------------

def abs(c) -> Column:  # noqa: A001
    return Column(E.Abs(_c(c)))


def sqrt(c) -> Column:
    return Column(E.Sqrt(_c(c)))


def exp(c) -> Column:
    return Column(E.Exp(_c(c)))


def log(c) -> Column:
    return Column(E.Log(_c(c)))


def log10(c) -> Column:
    return Column(E.Log10(_c(c)))


def floor(c) -> Column:
    return Column(E.Floor(_c(c)))


def ceil(c) -> Column:
    return Column(E.Ceil(_c(c)))


def round(c, scale: int = 0) -> Column:  # noqa: A001
    return Column(E.Round(_c(c), E.Literal(scale)))


def pow(a, b) -> Column:  # noqa: A001
    return Column(E.Pow(_c(a), _c(b)))


def negative(c) -> Column:
    return Column(E.UnaryMinus(_c(c)))


# --- strings ----------------------------------------------------------------

def upper(c) -> Column:
    return Column(E.Upper(_c(c)))


def lower(c) -> Column:
    return Column(E.Lower(_c(c)))


def trim(c) -> Column:
    return Column(E.Trim(_c(c)))


def ltrim(c) -> Column:
    return Column(E.LTrim(_c(c)))


def rtrim(c) -> Column:
    return Column(E.RTrim(_c(c)))


def length(c) -> Column:
    return Column(E.Length(_c(c)))


def substring(c, pos: int, length: int) -> Column:
    return Column(E.Substring(_c(c), E.Literal(pos), E.Literal(length)))


def concat(*cols) -> Column:
    return Column(E.Concat([_c(c) for c in cols]))


def split(c, pattern: str) -> Column:
    return Column(E.Split(_c(c), E.Literal(pattern)))


def explode(c) -> Column:
    return Column(E.Explode(_c(c)))


def grouping(c) -> Column:
    return Column(E.Grouping(_c(c)))


def grouping_id(*cols) -> Column:
    return Column(E.GroupingID([_c(c) for c in cols]))


def collect_list(c) -> Column:
    return Column(E.CollectList(_c(c)))


def collect_set(c) -> Column:
    return Column(E.CollectSet(_c(c)))


def array_agg(c) -> Column:
    return Column(E.CollectList(_c(c)))


def size(c) -> Column:
    return Column(E.Size(_c(c)))


def array_contains(c, value) -> Column:
    return Column(E.ArrayContains(_c(c), E.Literal(value)))


def array_min(c) -> Column:
    return Column(E.ArrayMin(_c(c)))


def array_max(c) -> Column:
    return Column(E.ArrayMax(_c(c)))


def sort_array(c, asc: bool = True) -> Column:
    return Column(E.SortArray(_c(c), E.Literal(asc)))


def array_distinct(c) -> Column:
    return Column(E.ArrayDistinct(_c(c)))


def element_at(c, idx: int) -> Column:
    # element_at dispatches on the (resolved) element type; defer via
    # UnresolvedFunction so the analyzer builds it post-resolution
    return Column(E.UnresolvedFunction(
        "element_at", [_c(c), E.Literal(idx)]))


def regexp_extract(c, pattern: str, idx: int = 1) -> Column:
    return Column(E.RegexpExtract(_c(c), E.Literal(pattern), E.Literal(idx)))


def lpad(c, length: int, pad: str = " ") -> Column:
    return Column(E.Lpad(_c(c), E.Literal(length), E.Literal(pad)))


def rpad(c, length: int, pad: str = " ") -> Column:
    return Column(E.Rpad(_c(c), E.Literal(length), E.Literal(pad)))


def regexp_replace(c, pattern: str, replacement: str) -> Column:
    import re as _re

    class _RR(E._DictTransform):
        def transform(self, s, _p=pattern, _r=replacement):
            return _re.sub(_p, _r, s)

    return Column(_RR(_c(c)))


# --- datetime ---------------------------------------------------------------

def year(c) -> Column:
    return Column(E.Year(_c(c)))


def month(c) -> Column:
    return Column(E.Month(_c(c)))


def dayofmonth(c) -> Column:
    return Column(E.DayOfMonth(_c(c)))


def quarter(c) -> Column:
    return Column(E.Quarter(_c(c)))


def dayofweek(c) -> Column:
    return Column(E.DayOfWeek(_c(c)))


def dayofyear(c) -> Column:
    return Column(E.DayOfYear(_c(c)))


def hour(c) -> Column:
    return Column(E.Hour(_c(c)))


def minute(c) -> Column:
    return Column(E.Minute(_c(c)))


def second(c) -> Column:
    return Column(E.Second(_c(c)))


def weekofyear(c) -> Column:
    return Column(E.WeekOfYear(_c(c)))


def date_add(c, days) -> Column:
    return Column(E.DateAdd(_c(c), _c(days)))


def date_sub(c, days) -> Column:
    return Column(E.DateSub(_c(c), _c(days)))


def datediff(end, start) -> Column:
    return Column(E.DateDiff(_c(end), _c(start)))


def trunc(c, fmt: str) -> Column:
    return Column(E.TruncDate(_c(c), fmt))


def make_date(y, m, d) -> Column:
    return Column(E.MakeDate(_c(y), _c(m), _c(d)))


def to_date(c, fmt: str | None = None) -> Column:
    from ..types import date as _date

    return Column(E.Cast(_c(c), _date))


# --- window functions -------------------------------------------------------

def row_number() -> Column:
    from ..expr.window import RowNumber

    return Column(RowNumber())


def rank() -> Column:
    from ..expr.window import Rank

    return Column(Rank())


def dense_rank() -> Column:
    from ..expr.window import DenseRank

    return Column(DenseRank())


def percent_rank() -> Column:
    from ..expr.window import PercentRank

    return Column(PercentRank())


def cume_dist() -> Column:
    from ..expr.window import CumeDist

    return Column(CumeDist())


def ntile(n: int) -> Column:
    from ..expr.window import NTile

    return Column(NTile(E.Literal(n)))


def lag(c, offset: int = 1, default=None) -> Column:
    from ..expr.window import Lag

    return Column(Lag(_c(c), offset,
                      None if default is None else E.Literal(default)))


def lead(c, offset: int = 1, default=None) -> Column:
    from ..expr.window import Lead

    return Column(Lead(_c(c), offset,
                       None if default is None else E.Literal(default)))


# --- python UDFs ------------------------------------------------------------

def udf(f=None, returnType=None, deterministic: bool = True):
    """Vectorized Python UDF (Arrow-UDF analog): the function receives numpy
    arrays (falls back to row-at-a-time when that fails).

    `deterministic=False` (the asNondeterministic analog) opts out of
    value-level optimizations — in particular the dictionary-domain lane
    that evaluates a deterministic UDF once per DISTINCT value of a
    dictionary-encoded string argument (physical/python_eval.py); a
    non-deterministic UDF must run per row."""
    from ..expr.pyudf import PythonUDF
    from ..types import DataType, float64

    rt = returnType or float64
    if isinstance(rt, str):
        from ..sql.parser import parse_data_type

        rt = parse_data_type(rt)

    def wrap(fn):
        def call(*cols):
            return Column(PythonUDF(fn, [_c(c) for c in cols], rt,
                                    name=getattr(fn, "__name__", "udf"),
                                    deterministic=deterministic))

        call.__name__ = getattr(fn, "__name__", "udf")
        return call

    if f is not None:
        return wrap(f)
    return wrap


pandas_udf = udf


# --- sort helpers -----------------------------------------------------------

def asc(c) -> Column:
    return Column(E.SortOrder(_c(c), True))


def desc(c) -> Column:
    return Column(E.SortOrder(_c(c), False))
