"""DataFrame statistic functions (df.stat).

Role of the reference's DataFrameStatFunctions (sql/core/.../
DataFrameStatFunctions.scala backed by StatFunctions.scala): correlation,
covariance, quantiles, contingency tables, frequent items, stratified
sampling — all expressed as engine queries.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

import spark_tpu.api.functions as F


class DataFrameStatFunctions:
    def __init__(self, df):
        self.df = df

    def corr(self, col1: str, col2: str) -> float:
        out = self.df.agg(F.corr(col1, col2).alias("c")).collect()
        return float(out[0]["c"])

    def cov(self, col1: str, col2: str) -> float:
        out = self.df.agg(F.covar_samp(col1, col2).alias("c")).collect()
        return float(out[0]["c"])

    def approxQuantile(self, col, probabilities: Sequence[float],
                       relativeError: float = 0.0):
        """Exact quantiles via the device sort (the reference's
        Greenwald-Khanna sketch trades accuracy for one pass; our sort is
        already the aggregation substrate, so exact is the cheap option)."""
        cols = [col] if isinstance(col, str) else list(col)
        sorted_df = self.df.select(*cols)
        table = sorted_df.toArrow()
        out = []
        for c in cols:
            vals = np.sort(np.asarray(
                table.column(c).drop_null().to_numpy(zero_copy_only=False),
                dtype=np.float64))
            if len(vals) == 0:
                out.append([float("nan")] * len(probabilities))
                continue
            qs = []
            for p in probabilities:
                idx = min(int(p * len(vals)), len(vals) - 1)
                qs.append(float(vals[idx]))
            out.append(qs)
        return out[0] if isinstance(col, str) else out

    def freqItems(self, cols: Sequence[str], support: float = 0.01):
        """Frequent items per column (reference: StatFunctions.freqItems)."""
        n = self.df.count()
        threshold = max(int(n * support), 1)
        result = {}
        for c in cols:
            counts = (self.df.groupBy(c).agg(F.count("*").alias("cnt"))
                      .filter(F.col("cnt") >= threshold)
                      .toArrow().to_pydict())
            result[c + "_freqItems"] = counts[c]
        return result

    def crosstab(self, col1: str, col2: str):
        """Contingency table as a DataFrame."""
        import pyarrow as pa

        counts = (self.df.groupBy(col1, col2)
                  .agg(F.count("*").alias("cnt")).toArrow().to_pydict())
        rows = sorted(set(map(str, counts[col1])))
        cols = sorted(set(map(str, counts[col2])))
        grid = {r: {c: 0 for c in cols} for r in rows}
        for r, c, n in zip(counts[col1], counts[col2], counts["cnt"]):
            grid[str(r)][str(c)] = n
        data = {f"{col1}_{col2}": rows}
        for c in cols:
            data[c] = [grid[r][c] for r in rows]
        return self.df.session.createDataFrame(pa.table(data))

    def sampleBy(self, col: str, fractions: dict, seed: int = 42):
        """Stratified sampling: per-stratum Bernoulli fractions."""
        out = None
        for value, frac in fractions.items():
            stratum = self.df.filter(F.col(col) == value).sample(frac, seed)
            out = stratum if out is None else out.union(stratum)
        return out if out is not None else self.df.limit(0)
