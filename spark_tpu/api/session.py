"""TpuSession — the SparkSession equivalent.

Role of the reference's SparkSession (sql/api .../SparkSession.scala; classic
impl sql/core/.../classic/SparkSession.scala) + the SparkContext/SparkEnv
bootstrap (core/SparkContext.scala, core/SparkEnv.scala:587): wires conf,
catalog, analyzer, optimizer, planner, and the JAX device runtime.
"""

from __future__ import annotations

import os
import re
import threading
from typing import Any, Iterable, Sequence

import numpy as np
import pyarrow as pa

from ..config import SQLConf
from ..exec.context import Metrics
from ..plan.analyzer import Analyzer
from ..plan.catalog import Catalog
from ..plan.logical import LocalRelation, RangeRelation
from ..plan.optimizer import Optimizer
from ..expr.expressions import AttributeReference
from ..types import StructType, from_arrow_type, int64

_jax_initialized = False
_init_lock = threading.Lock()

# per-statement fair-scheduler pool hint: /*+ POOL(x) */ anywhere in the
# statement text (the reference's ResolveHints COALESCE/REPARTITION hint
# comment syntax, applied to serving admission)
_POOL_HINT_RE = re.compile(
    r"/\*\+\s*POOL\s*\(\s*([A-Za-z0-9_.\-]+)\s*\)\s*\*/", re.IGNORECASE)


def _init_jax():
    """Enable x64 (int64 sums/hashes; XLA emulates on TPU with int32 pairs —
    SURVEY.md §7 'Hard parts' (6)) exactly once, before any tracing. Also
    raises the recursion limit — expression-tree recursion uses several
    frames per node (the reference raises JVM stack size for Catalyst for
    the same reason)."""
    global _jax_initialized
    import sys

    if sys.getrecursionlimit() < 20000:
        sys.setrecursionlimit(20000)
    with _init_lock:
        if _jax_initialized:
            return
        import jax

        jax.config.update("jax_enable_x64", True)
        _jax_initialized = True


class SessionBuilder:
    def __init__(self):
        self._conf: dict[str, Any] = {}
        self._name = "spark-tpu"

    def appName(self, name: str) -> "SessionBuilder":
        self._name = name
        return self

    def master(self, master: str) -> "SessionBuilder":
        # accepted for API compatibility; local[n] sets default parallelism
        if master.startswith("local[") and master.endswith("]"):
            n = master[6:-1]
            if n != "*":
                self._conf["spark.default.parallelism"] = int(n)
        return self

    def config(self, key=None, value=None, **kw) -> "SessionBuilder":
        if key is not None:
            self._conf[key] = value
        self._conf.update(kw)
        return self

    def getOrCreate(self) -> "TpuSession":
        if TpuSession._active is not None:
            for k, v in self._conf.items():
                TpuSession._active.conf.set(k, v)
            return TpuSession._active
        return TpuSession(self._name, self._conf)


class TpuSession:
    _active: "TpuSession | None" = None

    builder = None  # replaced below by property-like helper

    def __init__(self, name: str = "spark-tpu",
                 conf: dict[str, Any] | None = None):
        _init_jax()
        self.name = name
        self.conf = SQLConf(conf)
        self.catalog_ = Catalog(self.conf.case_sensitive)
        wh_dir = self.conf.get("spark.sql.warehouse.dir")
        if wh_dir:
            from ..exec import persist_cache as _pc
            from ..plan.warehouse import Warehouse

            # every catalog write (save/append/overwrite/drop) drops the
            # persistent result-cache entries depending on the table —
            # a no-op while spark.tpu.cache.dir is unset
            self.catalog_.external = Warehouse(
                str(wh_dir),
                on_write=lambda p, _c=self.conf:
                _pc.invalidate_path(_c, p))
        self._analyzer = Analyzer(self.catalog_, self.conf.case_sensitive)
        self._optimizer = Optimizer()
        self._metrics = Metrics()
        self._table_stats: dict[str, Any] = {}  # ANALYZE TABLE output
        self._cached: dict[int, Any] = {}
        self._streams: list = []
        from ..exec.listener import EventLoggingListener, ListenerBus
        from ..obs.tracing import Tracer

        # always-on span tracing (spark.tpu.trace.enabled flips it live);
        # pure host bookkeeping — see obs/tracing.py
        self.tracer = Tracer(conf=self.conf)
        from ..obs import resources as _resources

        # device-resource ledger + kernel cost capture switches
        # (spark.tpu.memory.ledger / spark.tpu.metrics.kernelCost) —
        # process-global like the KernelCache, configured per session
        _resources.configure(self.conf)
        from ..columnar import encoding as _encoding

        # compressed-execution ingest harvest (spark.tpu.encoding.enabled)
        _encoding.configure(self.conf)
        from ..utils import faults as _faults

        # deterministic fault injection (spark.tpu.faults.*) — off by
        # default; chaos runs flip it per session and the rules ship to
        # workers with the rest of the conf
        _faults.configure(self.conf)
        from ..utils import lockwatch as _lockwatch

        # runtime lock-discipline watching (spark.tpu.lockwatch.enabled)
        # — off by default: raw unwrapped locks, zero overhead; the
        # --race gate enables it per session / via SPARK_TPU_LOCKWATCH=1
        _lockwatch.configure(self.conf)
        from ..exec import persist_cache as _persist

        # persistent compile/result caches (spark.tpu.cache.*) — off by
        # default (cache dir empty); with a dir configured this points
        # jax's XLA persistent compilation cache at <dir>/xla and
        # installs the disk-hit/miss event counters. Conf ships to
        # workers, whose begin_stage_obs makes the same call.
        _persist.configure(self.conf)
        from ..obs import export as _export

        # service metrics plane (spark.tpu.metrics.export) — off by
        # default: no registry sampling, no ticker thread, Prometheus
        # endpoints report disabled. QueryService wires the scrape
        # sources; here the switch itself is applied session-wide.
        _export.configure(self.conf)
        from ..obs.live import LiveObs

        # live telemetry store: heartbeat-streamed worker obs partials,
        # in-flight stage progress, straggler findings (obs/live.py) —
        # created BEFORE the conf-driven cluster attach so the cluster's
        # heartbeat handler has a sink from its first beat
        self.live_obs = LiveObs(conf=self.conf)
        from ..obs import blackbox as _blackbox

        # query black box (spark.tpu.obs.bundles): anomaly-triggered
        # diagnostic bundle capture. Off by default — configure() leaves
        # the module bool False and every call site stays one attribute
        # read. The live store's finding sink routes POST-CLOSE trigger
        # findings (the SLO verdict lands on ticket release) into the
        # capture layer; the sink itself no-ops unless armed.
        _blackbox.configure(self.conf)
        self.live_obs.finding_sink = (
            lambda qid, f, _s=self: _blackbox.on_finding(_s, qid, f))
        self._progress_reporter = None
        self.listener_bus = ListenerBus()
        if str(self.conf.get("spark.eventLog.enabled", "false")).lower() \
                == "true":
            log_dir = self.conf.get("spark.eventLog.dir", "/tmp/spark-events")
            self.listener_bus.register(EventLoggingListener(log_dir))
        self._maybe_attach_conf_cluster()
        TpuSession._active = self

    def _maybe_attach_conf_cluster(self) -> None:
        """Conf-driven cluster attach (the spark-submit --master flow):
        spark.tpu.master=grpc://host:port joins a standalone master
        (deploy/standalone.py); spark.tpu.cluster.enabled=true spawns a
        local process cluster (the reference's local-cluster mode)."""
        import os

        master = str(self.conf.get("spark.tpu.master", "") or "")
        push = str(self.conf.get("spark.tpu.shuffle.push",
                                 "false")).lower() == "true"
        if master.startswith(("grpc://", "spark://")):
            from ..deploy.standalone import StandaloneCluster

            secret = (self.conf.get("spark.tpu.master.secret")
                      or os.environ.get("SPARK_TPU_MASTER_SECRET"))
            if not secret:
                raise ValueError(
                    "spark.tpu.master set but no secret: provide "
                    "spark.tpu.master.secret or SPARK_TPU_MASTER_SECRET")
            from ..config import HEARTBEAT_INTERVAL

            self._sql_cluster = StandaloneCluster(
                master, str(secret),
                int(self.conf.get("spark.executor.instances", 2)),
                app_name=self.name, push_shuffle=push,
                heartbeat_interval=float(self.conf.get(
                    HEARTBEAT_INTERVAL)))
        elif str(self.conf.get("spark.tpu.cluster.enabled",
                               "false")).lower() == "true":
            from ..config import HEARTBEAT_INTERVAL
            from ..exec.cluster import LocalCluster

            self._sql_cluster = LocalCluster(
                num_workers=int(self.conf.get("spark.tpu.cluster.workers",
                                              2)),
                push_shuffle=push,
                heartbeat_interval=float(self.conf.get(
                    HEARTBEAT_INTERVAL)))
        if getattr(self, "_sql_cluster", None) is not None:
            self._wire_cluster_obs(self._sql_cluster)

    def _wire_cluster_obs(self, cluster) -> None:
        """Point the cluster's heartbeat telemetry at this session's
        live store (executor heartbeats carry per-task obs partials)."""
        if hasattr(cluster, "obs_sink"):
            cluster.obs_sink = self.live_obs.on_heartbeat

    def newSession(self) -> "TpuSession":
        """Per-connection session clone (reference: SparkSession
        .newSession + the thriftserver's session-per-connection model).

        The clone gets its OWN conf (seeded from this session's current
        overrides — SET stays connection-local), its own temp-view
        catalog and SQL variables (reading THROUGH to this session's:
        views registered on the server session stay visible, views the
        clone registers stay local), and its own metrics/tracer/
        listener bus. It SHARES everything expensive and process-wide:
        the KernelCache (module-global), the warehouse catalog with its
        result-cache invalidation hook, the persistent caches under
        spark.tpu.cache.dir, the live-obs store, the block manager, and
        any attached cluster. stop() on a clone never tears the shared
        services down."""
        import collections

        from ..exec.listener import ListenerBus
        from ..obs.tracing import Tracer

        clone = object.__new__(TpuSession)
        clone.name = self.name
        clone.conf = SQLConf(self.conf.overrides())
        clone.catalog_ = Catalog(clone.conf.case_sensitive)
        clone.catalog_.external = self.catalog_.external
        # read-through temp views/variables: clone registrations land in
        # the first map (connection-local), parent registrations stay
        # visible; dropping a parent view from a clone is a no-op
        clone.catalog_._tables = collections.ChainMap(
            {}, self.catalog_._tables)
        clone.catalog_.variables = collections.ChainMap(
            {}, self.catalog_.variables)
        clone._analyzer = Analyzer(clone.catalog_,
                                   clone.conf.case_sensitive)
        clone._optimizer = Optimizer()
        clone._metrics = Metrics()
        clone._table_stats = self._table_stats      # shared ANALYZE stats
        clone._cached = self._cached                # shared cached plans
        clone._streams = []
        clone.tracer = Tracer(conf=clone.conf)
        clone.live_obs = self.live_obs              # one live store
        clone._progress_reporter = None
        clone.listener_bus = ListenerBus()
        cl = getattr(self, "_sql_cluster", None)
        if cl is not None:
            clone._sql_cluster = cl
        clone._block_manager = self.block_manager   # shared pin budgets
        clone._shared_services = True
        return clone

    @property
    def listenerManager(self):
        return self.listener_bus

    # ------------------------------------------------------------------
    def _planner(self):
        from ..physical.planner import Planner

        return Planner(
            self.conf,
            cluster=getattr(self, "_sql_cluster", None) is not None)

    # ------------------------------------------------------------------
    @property
    def read(self):
        from .readwriter import DataFrameReader

        return DataFrameReader(self)

    def table(self, name: str):
        from .dataframe import DataFrame
        from ..plan.logical import UnresolvedRelation

        return DataFrame(self, UnresolvedRelation(name.split(".")))

    def sql(self, query: str, **kwargs):
        from ..plan.commands import Command, run_command
        from ..plan.logical import WithCTE
        from ..sql.parser import parse_sql
        from .dataframe import DataFrame

        from ..sql.scripting import execute_script, is_script

        if is_script(query):
            return execute_script(self, query)
        # per-statement pool hint: /*+ POOL(x) */ routes THIS statement
        # to the named fair-scheduler pool (serve/pools.py). Validated
        # here — an unknown pool is a typed error naming the declared
        # pools, not a silent fallback to 'default'. The hint is
        # stripped before parse and stamped on the DataFrame for the
        # serving layer's admission call.
        pool_hint = None
        m = _POOL_HINT_RE.search(query)
        if m is not None:
            pool_hint = m.group(1)
            query = query[:m.start()] + query[m.end():]
            from ..errors import UnknownPoolError
            from ..serve.pools import pool_configs

            valid = list(pool_configs(self.conf))
            if pool_hint not in valid:
                raise UnknownPoolError(pool_hint, valid)
        import uuid as _uuid

        from ..obs.tracing import pop_query, push_query

        # parse predates the collect's query id — tag its spans with a
        # private scope so concurrent sql() calls on a shared session
        # can't capture each other's parse work (the old mark()/since()
        # buffer slice could)
        pqid = f"parse-{_uuid.uuid4().hex[:8]}"
        qtoken = push_query(pqid)
        try:
            with self.tracer.span("parse", cat="phase"):  # no-op when off
                plan = parse_sql(query)
        finally:
            pop_query(qtoken)
        if isinstance(plan, Command):
            return run_command(self, plan)
        if isinstance(plan, WithCTE):
            plan = self._materialize_ctes(plan)
        # the parse span predates the QueryExecution — ride it on the
        # parsed plan so to_arrow's event includes the full lifecycle
        parse_spans = self.tracer.spans_for(pqid)
        if parse_spans:
            try:
                plan._parse_spans = parse_spans
            except Exception:
                pass
        df = DataFrame(self, plan)
        if pool_hint is not None:
            df._pool_hint = pool_hint
        return df

    def _materialize_ctes(self, wplan):
        """Execute each multiply-referenced CTE once and splice the
        result into every call site as an in-memory relation (WithCTE /
        CTERelationRef role — see plan/logical.py WithCTE). Every splice
        site gets FRESH attribute ids over the SHARED source: a
        correlated subquery referencing the same CTE as its outer query
        (q1/q30's ctr1/ctr2) must see distinct ids or decorrelation
        cannot tell inner from outer."""
        from .dataframe import DataFrame

        mapping = {}
        for uniq, body in wplan.materializations:
            body = self._splice_relations(body, mapping)
            table = DataFrame(self, body).toArrow()
            rel = self.createDataFrame(table).plan
            mapping[uniq.lower()] = rel
        return self._splice_relations(wplan.child, mapping)

    def _splice_relations(self, plan, mapping):
        from ..expr.expressions import AttributeReference
        from ..plan import logical as L
        from ..plan.subquery import SubqueryExpression

        def fresh(rel):
            attrs = [AttributeReference(a.name, a.dtype, a.nullable)
                     for a in rel.output]
            if isinstance(rel, L.LocalRelation):
                return L.LocalRelation(attrs, rel.table)
            return L.LogicalRelation(rel.source, attrs, rel.name)

        def fix_expr(ex):
            if isinstance(ex, SubqueryExpression):
                return ex.copy(plan=self._splice_relations(ex.plan, mapping))
            return ex

        def rule(node):
            if isinstance(node, L.UnresolvedRelation):
                rel = mapping.get(node.name.lower())
                if rel is not None:
                    return fresh(rel)
            return node.map_expressions(lambda e: e.transform_up(fix_expr))

        return plan.transform_up(rule)

    def range(self, start: int, end: int | None = None, step: int = 1,
              numPartitions: int | None = None):
        from .dataframe import DataFrame

        if end is None:
            start, end = 0, start
        n = numPartitions or int(self.conf.get("spark.default.parallelism", 8))
        return DataFrame(self, RangeRelation(start, end, step, n))

    def createDataFrame(self, data, schema=None):
        from .dataframe import DataFrame

        table = _to_arrow_table(data, schema)
        attrs = [AttributeReference(f.name, from_arrow_type(f.type),
                                    f.nullable)
                 for f in table.schema]
        return DataFrame(self, LocalRelation(attrs, table))

    # ------------------------------------------------------------------
    @property
    def readStream(self):
        from ..streaming.api import DataStreamReader

        return DataStreamReader(self)

    @property
    def streams(self):
        return _StreamsApi(self)

    def memory_stream(self, schema=None):
        """Create a MemoryStream + its DataFrame (test helper; reference:
        MemoryStream[T].toDF)."""
        from ..streaming.query import StreamingRelation
        from ..streaming.sources import MemoryStream
        from .dataframe import DataFrame

        src = MemoryStream(schema)
        if schema is None:
            raise ValueError("memory_stream requires a pyarrow schema")
        return src, DataFrame(self, StreamingRelation(src))

    # ------------------------------------------------------------------
    @property
    def catalog(self):
        return _CatalogApi(self)

    def startUI(self, port: int = 0):
        """Start the live web UI (core/ui/SparkUI.scala role); returns
        the SparkUI with `.url`."""
        from ..exec.ui import SparkUI

        self._ui = SparkUI(self, port=port).start()
        return self._ui

    def attachSqlCluster(self, cluster) -> "TpuSession":
        """Route non-result SQL stages to a process cluster
        (exec/cluster_sql.py — the multi-host stage execution contract)."""
        self._sql_cluster = cluster
        self._wire_cluster_obs(cluster)
        return self

    def _ensure_progress_reporter(self):
        """Start the console progress reporter on first use
        (spark.tpu.progress.console — ConsoleProgressBar role); lives
        until session stop."""
        if self._progress_reporter is None:
            from ..obs.live import ConsoleProgressReporter

            self._progress_reporter = ConsoleProgressReporter(
                self.live_obs, conf=self.conf).start()
        return self._progress_reporter

    def detachSqlCluster(self) -> "TpuSession":
        self._sql_cluster = None
        return self

    def capture_diagnostics(self, df=None) -> str | None:
        """Explicitly capture a diagnostic bundle (obs/blackbox.py) —
        the operator's on-demand black-box pull. With a DataFrame, the
        bundle covers its last execution (plan reports, recorded
        metrics, profile + history); without one, the most recently
        closed query if the capture layer is armed, else a
        session-level bundle (serving/metrics/fleet state only).
        Requires spark.tpu.obs.bundleDir; works with the anomaly
        trigger (spark.tpu.obs.bundles) off. Returns the bundle id, or
        None when no bundle dir is configured."""
        from ..obs import blackbox

        qe = ctx = None
        if df is not None:
            qe = df.query_execution
            ctx = getattr(qe, "_last_ctx", None)
        else:
            recent = blackbox.most_recent()
            if recent is not None:
                qe, ctx = recent
        return blackbox.capture(self, qe=qe, ctx=ctx, reason="manual")

    def stop(self) -> None:
        # a newSession() clone shares the cluster/block manager with its
        # parent — stopping the clone must not tear those down
        shared = getattr(self, "_shared_services", False)
        pr = getattr(self, "_progress_reporter", None)
        if pr is not None:
            try:
                pr.stop()
            except Exception:
                pass
            self._progress_reporter = None
        for q in self._streams:
            try:
                q.stop()
            except Exception:
                pass
        self._streams.clear()
        rc = getattr(self, "_rdd_context", None)
        if rc is not None:
            rc.stop()
        ui = getattr(self, "_ui", None)
        if ui is not None:
            try:
                ui.stop()
            except Exception:
                pass
            self._ui = None
        cl = getattr(self, "_sql_cluster", None)
        if cl is not None:
            if not shared:
                try:
                    cl.stop()
                except Exception:
                    pass
            self._sql_cluster = None
        bm = getattr(self, "_block_manager", None)
        if bm is not None:
            if not shared:
                try:
                    bm.clear()
                except Exception:
                    pass
            self._block_manager = None
        if TpuSession._active is self:
            TpuSession._active = None

    @property
    def block_manager(self):
        """Session block store: cached tables live here under tiered
        budgets (device pins / host RAM / disk) with LRU eviction —
        role of core/storage/BlockManager.scala + MemoryStore/DiskStore."""
        bm = getattr(self, "_block_manager", None)
        if bm is None:
            from ..exec.block_store import BlockManager

            spill = str(self.conf.get("spark.local.dir", "") or "") or None
            bm = self._block_manager = BlockManager(
                self.conf, spill_dir=spill, metrics=self._metrics)
        return bm

    @staticmethod
    def _table_to_ipc(table) -> bytes:
        import pyarrow as pa

        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as w:
            w.write_table(table)
        return sink.getvalue().to_pybytes()

    def _cache_df(self, df):
        """Materialize once and register (analyzed plan → block id): ANY
        later query containing a semantically equal subtree is rewritten
        to scan the cached block (role of CacheManager.useCachedData,
        sqlx/columnar/CacheManager.scala + QueryExecution
        withCachedData). The bytes live in the tiered block store, so a
        cache bigger than the memory budget degrades to disk and then to
        recompute-from-lineage — it never pins unbounded RAM."""
        import uuid

        analyzed = df.query_execution.analyzed
        for plan, _attrs, _bid in self._cached.values():
            if plan.fast_equals(analyzed):
                return df
        table = df.toArrow()
        block_id = f"cache-{uuid.uuid4().hex[:12]}"
        self.block_manager.put(block_id, self._table_to_ipc(table))
        # unique token key (id(df) recycles after GC and would silently
        # evict an unrelated entry)
        self._cached[object()] = (analyzed, list(analyzed.output), block_id)
        return df

    def _uncache_df(self, df):
        analyzed = df.query_execution.analyzed
        for k, (plan, _attrs, bid) in list(self._cached.items()):
            if plan.fast_equals(analyzed):
                self.block_manager.remove(bid)
                del self._cached[k]
        return df

    def _cached_relation(self, analyzed, attrs, block_id):
        """Block bytes → LocalRelation; a dropped block re-materializes
        from lineage (the RDD recompute-on-miss contract,
        BlockManager.getOrElseUpdate role) and re-enters the store."""
        import pyarrow as pa

        from .dataframe import DataFrame

        data = self.block_manager.get(block_id)
        if data is None:
            guard = getattr(self, "_recomputing", None)
            if guard is None:
                guard = self._recomputing = set()
            if block_id in guard:
                return None     # already rebuilding below us — compute raw
            guard.add(block_id)
            try:
                table = DataFrame(self, analyzed).toArrow()
            finally:
                guard.discard(block_id)
            self._metrics.add("cache.recomputed_from_lineage")
            self.block_manager.put(block_id, self._table_to_ipc(table))
        else:
            table = pa.ipc.open_stream(pa.BufferReader(data)).read_all()
        return LocalRelation(attrs, table)

    def _use_cached(self, plan):
        """Substitute cached fragments into an analyzed plan. One
        relation per block per call (memo): a self-join of a cached
        frame shares a single deserialized table instead of two."""
        if not self._cached:
            return plan
        entries = list(self._cached.values())
        memo: dict = {}

        def rule(node):
            for cached_plan, attrs, block_id in entries:
                if node.fast_equals(cached_plan):
                    if block_id not in memo:
                        memo[block_id] = self._cached_relation(
                            cached_plan, attrs, block_id)
                    if memo[block_id] is not None:
                        return memo[block_id]
            return node

        return plan.transform_up(rule)

    def version(self) -> str:
        from .. import __version__

        return __version__


class _StreamsApi:
    def __init__(self, session):
        self.s = session

    @property
    def active(self):
        return [q for q in self.s._streams if q.isActive]

    def awaitAnyTermination(self, timeout=None):
        for q in list(self.s._streams):
            q.awaitTermination(timeout)


class _CatalogApi:
    def __init__(self, session: TpuSession):
        self.s = session

    def listTables(self):
        return self.s.catalog_.list_tables()

    def dropTempView(self, name: str) -> bool:
        return self.s.catalog_.drop(name)

    def tableExists(self, name: str) -> bool:
        try:
            self.s.catalog_.lookup(name.split("."))
            return True
        except Exception:
            return False

    def listColumns(self, table: str):
        """Column name/type/nullable rows for a table (pyspark
        Catalog.listColumns shape)."""
        plan = self.s.catalog_.lookup(table.split("."))
        from ..exec.query_execution import QueryExecution

        analyzed = QueryExecution(self.s, plan).analyzed
        return [{"name": a.name, "dataType": str(a.dtype),
                 "nullable": bool(a.nullable)} for a in analyzed.output]

    def listFunctions(self, pattern: str | None = None):
        """Registered SQL function names (Catalog.listFunctions role)."""
        from ..expr.registry import filter_names

        return filter_names(pattern)

    def functionExists(self, name: str) -> bool:
        from ..expr.registry import function_exists

        return function_exists(name)

    def cacheTable(self, name: str) -> None:
        # command layer directly: an f-string SQL round trip would break
        # on names that aren't lexable identifiers
        from ..plan.commands import CacheTableCommand, run_command

        run_command(self.s, CacheTableCommand(name))

    def uncacheTable(self, name: str) -> None:
        from ..plan.commands import CacheTableCommand, run_command

        run_command(self.s, CacheTableCommand(name, uncache=True))


def _to_arrow_table(data, schema) -> pa.Table:
    from ..types import StructType as ST, to_arrow_type

    if isinstance(data, pa.Table):
        return data
    try:
        import pandas as pd

        if isinstance(data, pd.DataFrame):
            return pa.Table.from_pandas(data, preserve_index=False)
    except ImportError:
        pass
    if isinstance(data, dict):
        return pa.table(data)
    if isinstance(data, (list, tuple)):
        if not data:
            raise ValueError("cannot infer schema from empty data")
        first = data[0]
        if isinstance(first, dict):
            names = list(first.keys())
            cols = {n: [r.get(n) for r in data] for n in names}
            return pa.table(cols)
        if isinstance(first, (list, tuple)):
            if schema is None:
                raise ValueError("schema required for list-of-tuples")
            if isinstance(schema, ST):
                names = schema.names
                arrays = []
                for i, f in enumerate(schema.fields):
                    arrays.append(pa.array([r[i] for r in data],
                                           type=to_arrow_type(f.dataType)))
                return pa.table(arrays, names=names)
            names = list(schema)
            cols = {n: [r[i] for r in data] for i, n in enumerate(names)}
            return pa.table(cols)
    raise TypeError(f"cannot create DataFrame from {type(data)}")


class _Builder:
    def __get__(self, obj, objtype=None):
        return SessionBuilder()


TpuSession.builder = _Builder()

# Spark-compatible alias
SparkSession = TpuSession
