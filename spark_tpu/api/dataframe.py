"""DataFrame API.

Role of the reference's Dataset (sql/api .../Dataset.scala; classic impl
sql/core/.../classic/Dataset.scala) / pyspark.sql.DataFrame: a lazy wrapper
over a logical plan bound to a session.
"""

from __future__ import annotations

from typing import Any, Iterable, Sequence

import pyarrow as pa

from ..errors import AnalysisException
from ..exec.query_execution import QueryExecution
from ..expr import expressions as E
from ..plan import logical as L
from .column import Column, _expr


class Row(dict):
    """Dict-backed row with attribute access (pyspark.sql.Row analog)."""

    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError as e:
            raise AttributeError(k) from e

    def __repr__(self):
        inner = ", ".join(f"{k}={v!r}" for k, v in self.items())
        return f"Row({inner})"


def _to_expr_list(cols, allow_str=True) -> list[E.Expression]:
    out = []
    for c in cols:
        if isinstance(c, Column):
            out.append(c.expr)
        elif isinstance(c, E.Expression):
            out.append(c)
        elif isinstance(c, str) and allow_str:
            if c == "*":
                out.append(E.UnresolvedStar())
            else:
                out.append(E.UnresolvedAttribute(c.split(".")))
        else:
            out.append(E.Literal(c))
    return out


class DataFrame:
    def __init__(self, session, plan: L.LogicalPlan):
        self.session = session
        self.plan = plan
        self._qe: QueryExecution | None = None

    # ------------------------------------------------------------------
    def _with(self, plan: L.LogicalPlan) -> "DataFrame":
        df = DataFrame(self.session, plan)
        df._watermark = getattr(self, "_watermark", None)
        return df

    # --- streaming -----------------------------------------------------
    @property
    def isStreaming(self) -> bool:
        from ..streaming.query import StreamingRelation

        return any(isinstance(n, StreamingRelation)
                   for n in self.plan.iter_nodes())

    def withWatermark(self, column: str, delay: str) -> "DataFrame":
        parts = delay.split()
        v = float(parts[0])
        unit = parts[1] if len(parts) > 1 else "seconds"
        mult = {"millisecond": 1e-3, "second": 1.0, "minute": 60.0,
                "hour": 3600.0, "day": 86400.0}
        for k, m in mult.items():
            if unit.startswith(k) or unit.rstrip("s").startswith(k):
                v *= m
                break
        df = self._with(L.EventTimeWatermark(column, int(v * 1e6),
                                             self.plan))
        df._watermark = (column, v)
        return df

    @property
    def writeStream(self):
        from ..streaming.api import DataStreamWriter

        return DataStreamWriter(self)

    @property
    def query_execution(self) -> QueryExecution:
        if self._qe is None:
            self._qe = QueryExecution(self.session, self.plan)
        return self._qe

    # --- schema -------------------------------------------------------
    @property
    def schema(self):
        return self.query_execution.analyzed.schema()

    @property
    def columns(self) -> list[str]:
        return [a.name for a in self.query_execution.analyzed.output]

    @property
    def dtypes(self) -> list[tuple[str, str]]:
        return [(f.name, f.dataType.simple_string()) for f in self.schema]

    def printSchema(self) -> None:
        for f in self.schema:
            print(f" |-- {f.name}: {f.dataType.simple_string()} "
                  f"(nullable = {str(f.nullable).lower()})")

    def __getitem__(self, item):
        if isinstance(item, str):
            for a in self.query_execution.analyzed.output:
                if a.name == item:
                    return Column(a)
            from ..errors import UnresolvedColumnError

            raise UnresolvedColumnError(item, self.columns[:5])
        if isinstance(item, (list, tuple)):
            return self.select(*item)
        if isinstance(item, Column):
            return self.filter(item)
        raise TypeError(f"cannot index DataFrame with {type(item)}")

    # --- transformations ----------------------------------------------
    def select(self, *cols) -> "DataFrame":
        if not cols:
            cols = ("*",)
        return self._with(L.Project(_to_expr_list(cols), self.plan))

    def selectExpr(self, *exprs: str) -> "DataFrame":
        from ..sql.parser import parse_expression

        return self._with(L.Project(
            [parse_expression(e) for e in exprs], self.plan))

    def filter(self, condition) -> "DataFrame":
        if isinstance(condition, str):
            from ..sql.parser import parse_expression

            cond = parse_expression(condition)
        else:
            cond = _expr(condition)
        return self._with(L.Filter(cond, self.plan))

    where = filter

    def withColumn(self, name: str, col: Column) -> "DataFrame":
        exprs: list[E.Expression] = []
        replaced = False
        for a in self.query_execution.analyzed.output:
            if a.name == name:
                exprs.append(E.Alias(_expr(col), name))
                replaced = True
            else:
                exprs.append(a)
        if not replaced:
            exprs.append(E.Alias(_expr(col), name))
        return self._with(L.Project(exprs, self.plan))

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        exprs = []
        for a in self.query_execution.analyzed.output:
            if a.name == old:
                exprs.append(E.Alias(a, new))
            else:
                exprs.append(a)
        return self._with(L.Project(exprs, self.plan))

    def drop(self, *names: str) -> "DataFrame":
        keep = [a for a in self.query_execution.analyzed.output
                if a.name not in names]
        return self._with(L.Project(keep, self.plan))

    def alias(self, alias: str) -> "DataFrame":
        return self._with(L.SubqueryAlias(alias, self.plan))

    def distinct(self) -> "DataFrame":
        return self._with(L.Distinct(self.plan))

    def dropDuplicates(self, subset: Sequence[str] | None = None) -> "DataFrame":
        if subset is None:
            return self.distinct()
        group = _to_expr_list(subset)
        out = []
        names = set(subset)
        for a in self.query_execution.analyzed.output:
            if a.name in names:
                out.append(a)
            else:
                out.append(E.Alias(E.First(a), a.name))
        return self._with(L.Aggregate(group, out, self.plan))

    def limit(self, n: int) -> "DataFrame":
        return self._with(L.Limit(n, self.plan))

    def offset(self, n: int) -> "DataFrame":
        return self._with(L.Offset(n, self.plan))

    def sort(self, *cols, ascending=None) -> "DataFrame":
        orders = []
        exprs = _to_expr_list(cols)
        if ascending is None:
            asc_list = [True] * len(exprs)
        elif isinstance(ascending, bool):
            asc_list = [ascending] * len(exprs)
        else:
            asc_list = list(ascending)
        for e, a in zip(exprs, asc_list):
            if isinstance(e, E.SortOrder):
                orders.append(e)
            else:
                orders.append(E.SortOrder(e, a))
        return self._with(L.Sort(orders, True, self.plan))

    orderBy = sort

    def sortWithinPartitions(self, *cols) -> "DataFrame":
        exprs = _to_expr_list(cols)
        orders = [e if isinstance(e, E.SortOrder) else E.SortOrder(e, True)
                  for e in exprs]
        return self._with(L.Sort(orders, False, self.plan))

    def repartition(self, num_or_col, *cols) -> "DataFrame":
        if isinstance(num_or_col, int):
            exprs = _to_expr_list(cols)
            return self._with(L.Repartition(num_or_col, True, exprs, self.plan))
        exprs = _to_expr_list((num_or_col,) + cols)
        return self._with(L.Repartition(None, True, exprs, self.plan))

    def coalesce(self, n: int) -> "DataFrame":
        return self._with(L.Repartition(n, False, [], self.plan))

    def union(self, other: "DataFrame") -> "DataFrame":
        return self._with(L.Union([self.plan, other.plan]))

    unionAll = union

    def join(self, other: "DataFrame", on=None, how: str = "inner") -> "DataFrame":
        cond = None
        if on is not None:
            if isinstance(on, Column):
                cond = on.expr
            elif isinstance(on, str):
                on = [on]
            if isinstance(on, (list, tuple)):
                conds = None
                for name in on:
                    c = E.EqualTo(
                        _resolve_using(self, name),
                        _resolve_using(other, name))
                    conds = c if conds is None else E.And(conds, c)
                cond = conds
                # USING semantics: output merges the key columns
                joined = L.Join(self.plan, other.plan, how, cond)
                df = self._with(joined)
                drop_ids = {_resolve_using(other, name).expr_id for name in on}
                keep = [a for a in df.query_execution.analyzed.output
                        if a.expr_id not in drop_ids]
                return df._with(L.Project(
                    keep, df.query_execution.analyzed))
        return self._with(L.Join(self.plan, other.plan, how, cond))

    def crossJoin(self, other: "DataFrame") -> "DataFrame":
        return self._with(L.Join(self.plan, other.plan, "cross", None))

    def groupBy(self, *cols) -> "GroupedData":
        return GroupedData(self, _to_expr_list(cols))

    groupby = groupBy

    def rollup(self, *cols) -> "GroupedData":
        return GroupedData(self, _to_expr_list(cols), sets_kind="rollup")

    def cube(self, *cols) -> "GroupedData":
        return GroupedData(self, _to_expr_list(cols), sets_kind="cube")

    def agg(self, *cols) -> "DataFrame":
        return GroupedData(self, []).agg(*cols)

    def sample(self, fraction: float, seed: int = 42) -> "DataFrame":
        return self._with(L.Sample(fraction, seed, self.plan))

    def mapInPandas(self, fn, schema) -> "DataFrame":
        """Apply fn(pandas.DataFrame) -> pandas.DataFrame per partition
        (reference: Dataset.mapInPandas over MapInPandasExec). Host
        evaluation: partitions cross as Arrow, results re-enter the engine."""
        import pandas as pd
        import pyarrow as pa

        if isinstance(schema, str):
            from ..sql.parser import parse_data_type  # noqa: F401

            raise ValueError("pass a StructType schema")
        parts = self.query_execution.execute()
        from ..physical.operators import attrs_schema
        from ..types import to_arrow_type

        out_tables = []
        for p in parts:
            for b in p:
                pdf = b.to_arrow().to_pandas()
                res = fn(pdf)
                out_tables.append(pa.Table.from_pandas(
                    res, preserve_index=False))
        merged = pa.concat_tables(out_tables, promote_options="permissive") \
            if out_tables else pa.table(
                {f.name: pa.array([], to_arrow_type(f.dataType))
                 for f in schema.fields})
        return self.session.createDataFrame(merged)

    def describe(self, *cols: str) -> "DataFrame":
        """Summary statistics for numeric columns
        (reference: Dataset.describe / StatFunctions)."""
        import pyarrow as pa

        from ..types import NumericType

        targets = [f.name for f in self.schema
                   if isinstance(f.dataType, NumericType)
                   and (not cols or f.name in cols)]
        if not targets:
            return self.session.createDataFrame(
                pa.table({"summary": pa.array([], pa.string())}))
        import spark_tpu.api.functions as FN

        aggs = []
        for c in targets:
            aggs += [FN.count(c).alias(f"count_{c}"),
                     FN.avg(c).alias(f"mean_{c}"),
                     FN.stddev(c).alias(f"stddev_{c}"),
                     FN.min(c).alias(f"min_{c}"),
                     FN.max(c).alias(f"max_{c}")]
        row = self.agg(*aggs).collect()[0]
        stats = ["count", "mean", "stddev", "min", "max"]
        data = {"summary": stats}
        for c in targets:
            data[c] = [str(row[f"{s}_{c}"]) for s in stats]
        return self.session.createDataFrame(pa.table(data))

    summary = describe

    # --- actions -------------------------------------------------------
    def toArrow(self) -> pa.Table:
        return self.query_execution.to_arrow()

    def toPandas(self):
        return self.toArrow().to_pandas()

    def collect(self) -> list[Row]:
        t = self.toArrow()
        return [Row(zip(t.column_names, vals))
                for vals in zip(*[c.to_pylist() for c in t.columns])] \
            if t.num_columns else []

    def count(self) -> int:
        agg = L.Aggregate([], [E.Alias(E.Count(None), "count")], self.plan)
        t = QueryExecution(self.session, agg).to_arrow()
        return int(t.column(0)[0].as_py())

    def first(self) -> Row | None:
        rows = self.limit(1).collect()
        return rows[0] if rows else None

    def head(self, n: int = 1):
        rows = self.limit(n).collect()
        return rows[0] if n == 1 and rows else rows

    def take(self, n: int) -> list[Row]:
        return self.limit(n).collect()

    def isEmpty(self) -> bool:
        return len(self.take(1)) == 0

    def show(self, n: int = 20, truncate: bool = True) -> None:
        t = self.limit(n).toArrow()
        names = t.column_names
        rows = [[_fmt(v, truncate) for v in col.to_pylist()]
                for col in t.columns]
        widths = [max([len(nm)] + [len(r[i]) for i in range(len(r))])
                  for nm, r in zip(names, rows)] if t.num_rows else \
                 [len(nm) for nm in names]
        sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
        print(sep)
        print("|" + "|".join(f" {nm:<{w}} " for nm, w in zip(names, widths)) + "|")
        print(sep)
        for ri in range(t.num_rows):
            print("|" + "|".join(
                f" {rows[ci][ri]:<{widths[ci]}} " for ci in range(len(names)))
                + "|")
        print(sep)

    def explain(self, mode: str = "formatted") -> None:
        """Print the query plans. mode="analysis" additionally runs the
        static plan analyzer (spark_tpu/analysis/plan_lint.py): predicted
        kernel launches per batch per stage, fusion-boundary explanations,
        recompile/overflow hazards — the EXPLAIN CODEGEN analog.
        mode="analyze" EXECUTES the query (one warm run + one measured
        run) and renders the physical plan annotated with measured
        per-operator metrics — rows, wall-ms, attributed kernel launches
        and compile-ms, including inside whole-stage fused operators —
        side by side with the static predictions, flagging drift
        (obs/metrics.AnalyzedReport; the EXPLAIN ANALYZE analog)."""
        print(self.query_execution.explain_string(mode))

    def createOrReplaceTempView(self, name: str) -> None:
        self.session.catalog_.register(name, self.plan)

    def cache(self) -> "DataFrame":
        return self.session._cache_df(self)

    persist = cache

    def unpersist(self) -> "DataFrame":
        return self.session._uncache_df(self)

    def write_parquet(self, path: str) -> None:
        import pyarrow.parquet as pq

        pq.write_table(self.toArrow(), path)

    @property
    def write(self):
        from .readwriter import DataFrameWriter

        return DataFrameWriter(self)

    @property
    def stat(self):
        from .stat import DataFrameStatFunctions

        return DataFrameStatFunctions(self)

    @property
    def na(self):
        from .na import DataFrameNaFunctions

        return DataFrameNaFunctions(self)

    @property
    def rdd(self):
        """Materialize into the RDD layer as Row objects (reference:
        Dataset.rdd). Partition structure is preserved."""
        from ..rdd import RDDContext

        parts = self.query_execution.execute()
        names = self.columns
        rows: list[Row] = []
        splits: list[int] = []
        for p in parts:
            start = len(rows)
            for b in p:
                d = b.to_pydict()
                for vals in zip(*[d[n] for n in names]) if names else []:
                    rows.append(Row(zip(names, vals)))
            splits.append(len(rows) - start)
        sc = getattr(self.session, "_rdd_context", None)
        if sc is None:
            sc = RDDContext(parallelism=max(len(parts), 1))
            self.session._rdd_context = sc
        return sc.parallelize(rows, max(len(parts), 1))

    def fillna(self, value, subset=None) -> "DataFrame":
        return self.na.fill(value, subset)

    def dropna(self, how: str = "any", subset=None) -> "DataFrame":
        return self.na.drop(how, subset)

    def replace(self, to_replace, value=None, subset=None) -> "DataFrame":
        return self.na.replace(to_replace, value, subset)

    def unpivot(self, ids, values, variableColumnName: str = "variable",
                valueColumnName: str = "value") -> "DataFrame":
        """Wide→long (reference: Dataset.unpivot / melt): a union of one
        projection per value column."""
        ids = [ids] if isinstance(ids, str) else list(ids)
        values = [values] if isinstance(values, str) else list(values)
        branches = []
        for v in values:
            branches.append(self.select(
                *ids,
                Column(E.Alias(E.Literal(v), variableColumnName)),
                Column(E.Alias(E.UnresolvedAttribute([v]),
                               valueColumnName))).plan)
        return self._with(L.Union(branches))

    melt = unpivot


def _fmt(v, truncate: bool) -> str:
    s = "NULL" if v is None else str(v)
    if truncate and len(s) > 20:
        s = s[:17] + "..."
    return s


def _resolve_using(df: DataFrame, name: str) -> E.AttributeReference:
    for a in df.query_execution.analyzed.output:
        if a.name.lower() == name.lower():
            return a
    raise AnalysisException(f"USING column {name} not found")


class GroupedData:
    """Role of RelationalGroupedDataset."""

    def __init__(self, df: DataFrame, grouping: list[E.Expression],
                 pivot_col: str | None = None,
                 pivot_values: list | None = None,
                 sets_kind: str | None = None):
        self.df = df
        self.grouping = grouping
        self._pivot_col = pivot_col
        self._pivot_values = pivot_values
        self._sets_kind = sets_kind

    def pivot(self, pivot_col: str, values: list | None = None
              ) -> "GroupedData":
        """Pivot (reference: RelationalGroupedDataset.pivot): each pivot
        value becomes a conditional aggregate column."""
        if values is None:
            import spark_tpu.api.functions as FN

            vals = (self.df.select(pivot_col).distinct()
                    .orderBy(pivot_col).toArrow().column(0).to_pylist())
            values = [v for v in vals if v is not None]
        return GroupedData(self.df, self.grouping, pivot_col, list(values))

    def agg(self, *cols) -> DataFrame:
        aggs = _to_expr_list(cols, allow_str=False)
        if self._pivot_col is not None:
            aggs = self._pivot_aggs(aggs)
        out = list(self.grouping) + aggs
        if self._sets_kind is not None:
            n = len(self.grouping)
            if self._sets_kind == "rollup":
                sets = [list(range(n - i)) for i in range(n + 1)]
            else:  # cube
                import itertools as _it

                sets = [list(c) for k in range(n, -1, -1)
                        for c in _it.combinations(range(n), k)]
            return self.df._with(
                L.GroupingSets(sets, self.grouping, out, self.df.plan))
        return self.df._with(L.Aggregate(self.grouping, out, self.df.plan))

    def _pivot_aggs(self, aggs: list[E.Expression]) -> list[E.Expression]:
        pivot_attr = E.UnresolvedAttribute([self._pivot_col])
        out: list[E.Expression] = []
        for v in self._pivot_values:
            for a in aggs:
                inner = a.child if isinstance(a, E.Alias) else a
                base = a.name if isinstance(a, E.Alias) else None

                def guard(x: E.Expression) -> E.Expression:
                    if isinstance(x, E.AggregateFunction) and \
                            x.child is not None:
                        return x.copy(child=E.If(
                            E.EqualTo(pivot_attr, E.Literal(v)),
                            x.child, E.Literal(None)))
                    if isinstance(x, E.Count) and x.child is None:
                        return E.Count(E.If(
                            E.EqualTo(pivot_attr, E.Literal(v)),
                            E.Literal(1), E.Literal(None)))
                    return x

                guarded = inner.transform_up(guard)
                name = str(v) if len(aggs) == 1 and base is None \
                    else (f"{v}_{base}" if base else f"{v}_{len(out)}")
                out.append(E.Alias(guarded, name))
        return out

    def count(self) -> DataFrame:
        return self.agg(Column(E.Alias(E.Count(None), "count")))

    def applyInPandasWithState(self, fn, schema) -> DataFrame:
        """Arbitrary stateful grouped-map (reference:
        applyInPandasWithState / flatMapGroupsWithState): lazy — on a
        streaming frame each micro-batch calls
        fn(key_tuple, pandas_frame, GroupState); on a static frame one
        pass runs with empty initial state."""
        from ..streaming.stateful_map import StatefulMapGroups

        key_names = []
        for g in self.grouping:
            if isinstance(g, E.UnresolvedAttribute):
                key_names.append(g.name_parts[-1])
            elif isinstance(g, (E.AttributeReference, E.Alias)):
                key_names.append(g.name)
            else:
                raise ValueError("grouping keys must be columns")
        out_attrs = [E.AttributeReference(f.name, f.dataType, True)
                     for f in schema.fields]
        return self.df._with(StatefulMapGroups(
            key_names, fn, out_attrs, self.df.plan))

    def applyInPandas(self, fn, schema=None) -> DataFrame:
        """Grouped-map pandas UDF (reference: FlatMapGroupsInPandasExec /
        RelationalGroupedDataset.applyInPandas): the full frame crosses to
        the host once, pandas groups by the keys, fn runs per group."""
        import pandas as pd
        import pyarrow as pa

        key_names = []
        for g in self.grouping:
            if isinstance(g, E.UnresolvedAttribute):
                key_names.append(g.name_parts[-1])
            elif isinstance(g, E.AttributeReference):
                key_names.append(g.name)
            elif isinstance(g, E.Alias):
                key_names.append(g.name)
            else:
                raise ValueError(
                    "applyInPandas grouping keys must be columns")
        pdf = self.df.toPandas()
        outs = []
        if len(pdf):
            for _, grp in pdf.groupby(key_names, sort=True, dropna=False):
                outs.append(fn(grp.reset_index(drop=True)))
        if outs:
            merged = pa.concat_tables(
                [pa.Table.from_pandas(o, preserve_index=False)
                 for o in outs], promote_options="permissive")
        else:
            from ..types import to_arrow_type

            merged = pa.table(
                {f.name: pa.array([], to_arrow_type(f.dataType))
                 for f in (schema.fields if schema else [])})
        return self.df.session.createDataFrame(merged)

    def sum(self, *names: str) -> DataFrame:  # noqa: A003
        return self.agg(*[Column(E.Sum(E.UnresolvedAttribute([n])))
                          for n in names])

    def avg(self, *names: str) -> DataFrame:
        return self.agg(*[Column(E.Average(E.UnresolvedAttribute([n])))
                          for n in names])

    mean = avg

    def min(self, *names: str) -> DataFrame:  # noqa: A003
        return self.agg(*[Column(E.Min(E.UnresolvedAttribute([n])))
                          for n in names])

    def max(self, *names: str) -> DataFrame:  # noqa: A003
        return self.agg(*[Column(E.Max(E.UnresolvedAttribute([n])))
                          for n in names])
