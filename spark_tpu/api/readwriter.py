"""DataFrameReader / DataFrameWriter.

Role of the reference's DataFrameReader/Writer
(sql/api .../DataFrameReader.scala, sqlx/datasources/DataSource resolution).
"""

from __future__ import annotations

import os
from typing import Any

import pyarrow as pa

from ..errors import AnalysisException
from ..io.sources import (
    CSVSource, DataSource, JDBCSource, JSONSource, ORCSource, ParquetSource,
)
from ..plan.logical import LogicalRelation
from ..expr.expressions import AttributeReference


class DataFrameReader:
    def __init__(self, session):
        self.session = session
        self._options: dict[str, Any] = {}
        self._format = "parquet"
        self._schema = None

    def format(self, fmt: str) -> "DataFrameReader":  # noqa: A003
        self._format = fmt
        return self

    def option(self, k: str, v) -> "DataFrameReader":
        self._options[k] = v
        return self

    def options(self, **kw) -> "DataFrameReader":
        self._options.update(kw)
        return self

    def schema(self, s) -> "DataFrameReader":
        self._schema = s
        return self

    def _df(self, source: DataSource, name: str):
        from .dataframe import DataFrame

        attrs = [AttributeReference(f.name, f.dataType, f.nullable)
                 for f in source.schema.fields]
        return DataFrame(self.session, LogicalRelation(source, attrs, name))

    def parquet(self, path: str):
        return self._df(ParquetSource(path), os.path.basename(path))

    def csv(self, path: str, header: bool | None = None, **kw):
        h = self._options.get("header", True if header is None else header)
        if isinstance(h, str):
            h = h.lower() == "true"
        sep = self._options.get("sep", self._options.get("delimiter", ","))
        return self._df(CSVSource(path, header=h, schema=self._schema,
                                  delimiter=sep),
                        os.path.basename(path))

    def json(self, path: str):
        return self._df(JSONSource(path), os.path.basename(path))

    def orc(self, path: str):
        return self._df(ORCSource(path), os.path.basename(path))

    def text(self, path: str):
        from ..io.sources import TextSource

        return self._df(TextSource(path), os.path.basename(path))

    def avro(self, path: str):
        from ..io.sources import AvroSource

        return self._df(AvroSource(path), os.path.basename(path))

    def xml(self, path: str, rowTag: str | None = None):
        from ..io.sources import XMLSource

        return self._df(XMLSource(
            path, row_tag=rowTag or self._options.get("rowTag", "ROW")),
            os.path.basename(path))

    def jdbc(self, url: str | None = None, table: str | None = None,
             **kw):
        url = url or self._options.get("url")
        table = table or self._options.get("dbtable")
        if not url or not table:
            raise AnalysisException("jdbc requires url and dbtable")
        src = JDBCSource(
            url, table,
            partition_column=kw.get("column",
                                    self._options.get("partitionColumn")),
            lower_bound=kw.get("lowerBound",
                               self._options.get("lowerBound")),
            upper_bound=kw.get("upperBound",
                               self._options.get("upperBound")),
            num_partitions=int(kw.get(
                "numPartitions", self._options.get("numPartitions", 1))),
            connector=self._options.get("connector"))
        return self._df(src, table)

    def table(self, name: str):
        return self.session.table(name)

    def load(self, path: str | None = None):
        fmt = self._format.lower()
        if fmt == "jdbc":
            return self.jdbc()
        if path is None:
            raise AnalysisException(f"format {fmt} requires a path")
        if fmt == "parquet":
            return self.parquet(path)
        if fmt == "csv":
            return self.csv(path)
        if fmt == "json":
            return self.json(path)
        if fmt == "orc":
            return self.orc(path)
        if fmt == "text":
            return self.text(path)
        if fmt == "avro":
            return self.avro(path)
        if fmt == "xml":
            return self.xml(path)
        raise AnalysisException(f"unknown format {fmt}")


class DataFrameWriter:
    def __init__(self, df):
        self.df = df
        self._mode = "errorifexists"
        self._format = "parquet"
        self._options: dict[str, Any] = {}
        self._partition_by: list[str] = []

    def partitionBy(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = m.lower()
        return self

    def format(self, fmt: str) -> "DataFrameWriter":  # noqa: A003
        self._format = fmt
        return self

    def option(self, k, v) -> "DataFrameWriter":
        self._options[k] = v
        return self

    def _check(self, path: str):
        if os.path.exists(path):
            if self._mode in ("error", "errorifexists"):
                raise AnalysisException(f"path {path} already exists")
            if self._mode == "ignore":
                return False
            if self._mode == "overwrite":
                import shutil

                if os.path.isdir(path):
                    shutil.rmtree(path)
                else:
                    os.remove(path)
        return True

    def parquet(self, path: str) -> None:
        self._write_file_format(path, "parquet")

    def orc(self, path: str) -> None:
        self._write_file_format(path, "orc")

    def avro(self, path: str) -> None:
        self._write_file_format(path, "avro")

    @staticmethod
    def _write_one(table: pa.Table, path: str, fmt: str) -> None:
        if fmt == "parquet":
            import pyarrow.parquet as pq

            pq.write_table(table, path)
        elif fmt == "avro":
            from ..io.avro import write_avro

            write_avro(path, table)
        else:
            import pyarrow.orc as po

            po.write_table(table, path)

    def _write_file_format(self, path: str, fmt: str) -> None:
        if not self._check(path):
            return
        table = self.df.toArrow()
        if not self._partition_by:
            self._write_one(table, path, fmt)
            return
        # hive-style layout path/k1=v1/part-*.{fmt} written through the
        # two-phase commit protocol: every partition combo is a task,
        # files land in attempt staging dirs and move into place only at
        # job commit (reference: FileFormatWriter dynamic partitioning +
        # HadoopMapReduceCommitProtocol; arbitration =
        # core/scheduler/OutputCommitCoordinator.scala)
        import pyarrow.compute as pc

        from ..io.commit import FileCommitProtocol

        os.makedirs(path, exist_ok=True)
        proto = FileCommitProtocol(
            path, getattr(self.df.session, "_commit_coordinator", None))
        proto.setup_job()
        keys = self._partition_by
        try:
            combos = table.select(keys).group_by(keys).aggregate([])
            for i in range(combos.num_rows):
                vals = [combos.column(k)[i].as_py() for k in keys]
                mask = None
                for k, v in zip(keys, vals):
                    cond = pc.is_null(table.column(k)) if v is None \
                        else pc.equal(table.column(k), v)
                    mask = cond if mask is None else pc.and_(mask, cond)
                part = table.filter(mask).drop_columns(keys)
                sub = [f"{k}={'__HIVE_DEFAULT_PARTITION__' if v is None else v}"
                       for k, v in zip(keys, vals)]
                attempt = proto.new_task_attempt(i)
                self._write_one(
                    part, attempt.path_for(*sub, f"part-00000.{fmt}"), fmt)
                attempt.commit()
        except BaseException:
            proto.abort_job()
            raise
        proto.commit_job()

    def csv(self, path: str) -> None:
        import pyarrow.csv as pacsv

        if not self._check(path):
            return
        pacsv.write_csv(self.df.toArrow(), path)

    def json(self, path: str) -> None:
        if not self._check(path):
            return
        import json as _json

        t = self.df.toArrow()
        with open(path, "w") as f:
            for row in t.to_pylist():
                f.write(_json.dumps(row, default=str) + "\n")

    def saveAsTable(self, name: str) -> None:
        wh = self.df.session.catalog_.external
        if wh is None:
            self.df.createOrReplaceTempView(name)
            return
        mode = {"errorifexists": "error"}.get(self._mode, self._mode)
        wh.save_table(name, self.df.toArrow(), mode=mode)

    def insertInto(self, name: str) -> None:
        wh = self.df.session.catalog_.external
        if wh is not None and name in wh.list_tables():
            wh.save_table(name, self.df.toArrow(), mode="append")
            return
        raise AnalysisException(f"table {name} is not a saved table")

    def save(self, path: str) -> None:
        getattr(self, self._format)(path)
