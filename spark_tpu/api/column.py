"""User-facing Column DSL.

Role of the reference's Column (sql/api/src/main/scala/org/apache/spark/sql/
Column.scala) / pyspark.sql.Column — a thin wrapper over the expression tree.
"""

from __future__ import annotations

from typing import Any, Sequence

from ..expr import expressions as E
from ..types import DataType


def _expr(v: Any) -> E.Expression:
    if isinstance(v, Column):
        return v.expr
    if isinstance(v, E.Expression):
        return v
    return E.Literal(v)


class Column:
    def __init__(self, expr: E.Expression):
        self.expr = expr

    # --- naming -----------------------------------------------------------
    def alias(self, name: str) -> "Column":
        return Column(E.Alias(self.expr, name))

    name = alias

    # --- nested access ----------------------------------------------------
    def getField(self, name: str) -> "Column":
        """struct field access (reference: Column.getField)."""
        return Column(E.GetStructField(self.expr, name))

    def getItem(self, key) -> "Column":
        """map value / array element access (reference: Column.getItem).
        Dispatch by child type happens at analysis, not construction —
        the child may still be unresolved here."""
        return Column(E.UnresolvedFunction(
            "element_at", [self.expr, E.Literal(key)], False))

    def __getitem__(self, key) -> "Column":
        if isinstance(key, str):
            from ..types import StructType

            try:
                if isinstance(self.expr.dtype, StructType):
                    return self.getField(key)
            except Exception:
                pass
        return self.getItem(key)

    def cast(self, to: DataType | str) -> "Column":
        if isinstance(to, str):
            from ..sql.parser import parse_data_type

            to = parse_data_type(to)
        return Column(E.Cast(self.expr, to))

    # --- arithmetic -------------------------------------------------------
    def __add__(self, o):
        return Column(E.Add(self.expr, _expr(o)))

    def __radd__(self, o):
        return Column(E.Add(_expr(o), self.expr))

    def __sub__(self, o):
        return Column(E.Subtract(self.expr, _expr(o)))

    def __rsub__(self, o):
        return Column(E.Subtract(_expr(o), self.expr))

    def __mul__(self, o):
        return Column(E.Multiply(self.expr, _expr(o)))

    def __rmul__(self, o):
        return Column(E.Multiply(_expr(o), self.expr))

    def __truediv__(self, o):
        return Column(E.Divide(self.expr, _expr(o)))

    def __rtruediv__(self, o):
        return Column(E.Divide(_expr(o), self.expr))

    def __mod__(self, o):
        return Column(E.Remainder(self.expr, _expr(o)))

    def __neg__(self):
        return Column(E.UnaryMinus(self.expr))

    def __pow__(self, o):
        return Column(E.Pow(self.expr, _expr(o)))

    # --- comparisons ------------------------------------------------------
    def __eq__(self, o):  # type: ignore[override]
        return Column(E.EqualTo(self.expr, _expr(o)))

    def __ne__(self, o):  # type: ignore[override]
        return Column(E.NotEqualTo(self.expr, _expr(o)))

    def __lt__(self, o):
        return Column(E.LessThan(self.expr, _expr(o)))

    def __le__(self, o):
        return Column(E.LessThanOrEqual(self.expr, _expr(o)))

    def __gt__(self, o):
        return Column(E.GreaterThan(self.expr, _expr(o)))

    def __ge__(self, o):
        return Column(E.GreaterThanOrEqual(self.expr, _expr(o)))

    def eqNullSafe(self, o):
        return Column(E.EqualNullSafe(self.expr, _expr(o)))

    # --- boolean ----------------------------------------------------------
    def __and__(self, o):
        return Column(E.And(self.expr, _expr(o)))

    def __rand__(self, o):
        return Column(E.And(_expr(o), self.expr))

    def __or__(self, o):
        return Column(E.Or(self.expr, _expr(o)))

    def __ror__(self, o):
        return Column(E.Or(_expr(o), self.expr))

    def __invert__(self):
        return Column(E.Not(self.expr))

    # --- predicates -------------------------------------------------------
    def isNull(self):
        return Column(E.IsNull(self.expr))

    def isNotNull(self):
        return Column(E.IsNotNull(self.expr))

    def isNaN(self):
        return Column(E.IsNaN(self.expr))

    def isin(self, *vals):
        if len(vals) == 1 and isinstance(vals[0], (list, tuple, set)):
            vals = tuple(vals[0])
        return Column(E.In(self.expr, [_expr(v) for v in vals]))

    def between(self, lo, hi):
        return Column(E.And(
            E.GreaterThanOrEqual(self.expr, _expr(lo)),
            E.LessThanOrEqual(self.expr, _expr(hi))))

    def like(self, pattern: str):
        return Column(E.Like(self.expr, pattern))

    def rlike(self, pattern: str):
        return Column(E.RLike(self.expr, pattern))

    def contains(self, s: str):
        return Column(E.Contains(self.expr, s))

    def startswith(self, s: str):
        return Column(E.StartsWith(self.expr, s))

    def endswith(self, s: str):
        return Column(E.EndsWith(self.expr, s))

    def substr(self, pos, length=None):
        return Column(E.Substring(self.expr, E.Literal(pos),
                                  None if length is None else E.Literal(length)))

    # --- sorting ----------------------------------------------------------
    def asc(self):
        return Column(E.SortOrder(self.expr, True))

    def desc(self):
        return Column(E.SortOrder(self.expr, False))

    def asc_nulls_first(self):
        return Column(E.SortOrder(self.expr, True, True))

    def asc_nulls_last(self):
        return Column(E.SortOrder(self.expr, True, False))

    def desc_nulls_first(self):
        return Column(E.SortOrder(self.expr, False, True))

    def desc_nulls_last(self):
        return Column(E.SortOrder(self.expr, False, False))

    # --- window -----------------------------------------------------------
    def over(self, spec) -> "Column":
        from ..expr.window import WindowExpression

        return Column(WindowExpression(self.expr, spec._partition,
                                       spec._order,
                                       getattr(spec, "_frame", None)))

    # --- conditional ------------------------------------------------------
    def when(self, cond: "Column", value) -> "Column":
        if not isinstance(self.expr, E.CaseWhen):
            raise ValueError("when() follows F.when(...)")
        cw = self.expr
        return Column(E.CaseWhen(cw.branches + [(cond.expr, _expr(value))],
                                 None))

    def otherwise(self, value) -> "Column":
        if not isinstance(self.expr, E.CaseWhen):
            raise ValueError("otherwise() follows F.when(...)")
        cw = self.expr
        return Column(E.CaseWhen(cw.branches, _expr(value)))

    def __hash__(self):
        return id(self)

    def __repr__(self):
        return f"Column<{self.expr.simple_string()}>"

    def __bool__(self):
        raise ValueError(
            "Cannot convert Column to bool: use '&' for AND, '|' for OR")
