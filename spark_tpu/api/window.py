"""Window specification API (pyspark.sql.Window analog)."""

from __future__ import annotations

from typing import Sequence

from ..expr import expressions as E
from ..expr.window import WindowExpression
from .column import Column, _expr


class WindowSpec:
    def __init__(self, partition_spec=(), order_spec=(), frame=None):
        self._partition = list(partition_spec)
        self._order = list(order_spec)
        self._frame = frame

    def partitionBy(self, *cols) -> "WindowSpec":
        exprs = [_to_expr(c) for c in cols]
        return WindowSpec(self._partition + exprs, self._order, self._frame)

    def orderBy(self, *cols) -> "WindowSpec":
        orders = []
        for c in cols:
            e = _to_expr(c)
            orders.append(e if isinstance(e, E.SortOrder)
                          else E.SortOrder(e, True))
        return WindowSpec(self._partition, self._order + orders, self._frame)

    def rowsBetween(self, start, end) -> "WindowSpec":
        def off(v):
            if v <= Window.unboundedPreceding:
                return None
            if v >= Window.unboundedFollowing:
                return None
            return int(v)

        return WindowSpec(self._partition, self._order,
                          ("rows", off(start), off(end)))

    def rangeBetween(self, start, end) -> "WindowSpec":
        if start <= Window.unboundedPreceding and end == 0:
            return WindowSpec(self._partition, self._order, None)
        if start <= Window.unboundedPreceding and \
                end >= Window.unboundedFollowing:
            return WindowSpec(self._partition, self._order,
                              ("rows", None, None))

        def off(v):
            if v <= Window.unboundedPreceding or \
                    v >= Window.unboundedFollowing:
                return None
            return int(v)

        return WindowSpec(self._partition, self._order,
                          ("vrange", off(start), off(end)))


class Window:
    unboundedPreceding = -(1 << 62)
    unboundedFollowing = 1 << 62
    currentRow = 0

    @staticmethod
    def partitionBy(*cols) -> WindowSpec:
        return WindowSpec().partitionBy(*cols)

    @staticmethod
    def orderBy(*cols) -> WindowSpec:
        return WindowSpec().orderBy(*cols)


def _to_expr(c):
    if isinstance(c, Column):
        return c.expr
    if isinstance(c, str):
        return E.UnresolvedAttribute(c.split("."))
    return _expr(c)


def over(col: Column, spec: WindowSpec) -> Column:
    return Column(WindowExpression(col.expr, spec._partition, spec._order,
                                   spec._frame))
