"""Window specification API (pyspark.sql.Window analog)."""

from __future__ import annotations

from typing import Sequence

from ..expr import expressions as E
from ..expr.window import WindowExpression
from .column import Column, _expr


class WindowSpec:
    def __init__(self, partition_spec=(), order_spec=()):
        self._partition = list(partition_spec)
        self._order = list(order_spec)

    def partitionBy(self, *cols) -> "WindowSpec":
        exprs = [_to_expr(c) for c in cols]
        return WindowSpec(self._partition + exprs, self._order)

    def orderBy(self, *cols) -> "WindowSpec":
        orders = []
        for c in cols:
            e = _to_expr(c)
            orders.append(e if isinstance(e, E.SortOrder)
                          else E.SortOrder(e, True))
        return WindowSpec(self._partition, self._order + orders)

    def rowsBetween(self, start, end) -> "WindowSpec":
        # only the default frames are supported (tracked for round 2)
        return self

    rangeBetween = rowsBetween


class Window:
    unboundedPreceding = -(1 << 62)
    unboundedFollowing = 1 << 62
    currentRow = 0

    @staticmethod
    def partitionBy(*cols) -> WindowSpec:
        return WindowSpec().partitionBy(*cols)

    @staticmethod
    def orderBy(*cols) -> WindowSpec:
        return WindowSpec().orderBy(*cols)


def _to_expr(c):
    if isinstance(c, Column):
        return c.expr
    if isinstance(c, str):
        return E.UnresolvedAttribute(c.split("."))
    return _expr(c)


def over(col: Column, spec: WindowSpec) -> Column:
    return Column(WindowExpression(col.expr, spec._partition, spec._order))
