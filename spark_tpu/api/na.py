"""DataFrameNaFunctions (df.na) — reference: sql/core DataFrameNaFunctions."""

from __future__ import annotations

from typing import Any, Sequence

import spark_tpu.api.functions as F
from ..types import NumericType, StringType


class DataFrameNaFunctions:
    def __init__(self, df):
        self.df = df

    def drop(self, how: str = "any", subset: Sequence[str] | None = None):
        cols = list(subset) if subset else self.df.columns
        if how == "any":
            out = self.df
            for c in cols:
                out = out.filter(F.col(c).isNotNull())
            return out
        # how == "all": keep rows with at least one non-null
        cond = None
        for c in cols:
            p = F.col(c).isNotNull()
            cond = p if cond is None else (cond | p)
        return self.df.filter(cond)

    def fill(self, value, subset: Sequence[str] | None = None):
        out = self.df
        schema = {f.name: f.dataType for f in self.df.schema}
        if isinstance(value, dict):
            items = value.items()
        else:
            cols = list(subset) if subset else self.df.columns
            items = []
            for c in cols:
                dt = schema[c]
                if isinstance(value, str) and not isinstance(dt, StringType):
                    continue
                if isinstance(value, (int, float)) and not isinstance(
                        dt, NumericType):
                    continue
                items.append((c, value))
        for c, v in items:
            out = out.withColumn(c, F.coalesce(F.col(c), F.lit(v)))
        return out

    def replace(self, to_replace, value=None,
                subset: Sequence[str] | None = None):
        mapping = to_replace if isinstance(to_replace, dict) \
            else {to_replace: value}
        cols = list(subset) if subset else self.df.columns
        schema = {f.name: f.dataType for f in self.df.schema}
        out = self.df
        for c in cols:
            dt = schema[c]
            expr = None
            applied = False
            for old, new in mapping.items():
                if isinstance(old, str) != isinstance(dt, StringType):
                    continue
                branch = F.when(F.col(c) == old, F.lit(new))
                expr = branch if expr is None else expr.when(
                    F.col(c) == old, F.lit(new))
                applied = True
            if applied:
                out = out.withColumn(c, expr.otherwise(F.col(c)))
        return out
