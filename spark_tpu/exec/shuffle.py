"""Shuffle: redistribute rows across partitions.

Role of the reference's sort-based shuffle stack — ShuffleExchangeExec
partition-id computation (sqlx/exchange/ShuffleExchangeExec.scala:344),
SortShuffleManager write paths (core/shuffle/sort/SortShuffleManager.scala:73),
and BlockStoreShuffleReader (core/shuffle/BlockStoreShuffleReader.scala:72).

TPU-native design (SURVEY.md §2.5, §7 step 6): partition ids are computed on
device for a whole batch (hash kernel), rows are grouped by pid with one
`lax.sort`, and the grouped columns cross to the host in a single contiguous
transfer — the host then slices per-partition runs (the "shuffle files") and
rebuilds device batches per reducer. Within a real TPU slice the same kernel
output feeds an ICI all-to-all instead (parallel/collectives.py); this module
is the host/DCN path and the local-mode fallback. String columns travel as
dictionary codes + host dictionaries; reducers merge dictionaries on rebuild.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..columnar.batch import (Column, ColumnarBatch, EMPTY_DICT,
                              StringDict, bucket_capacity)
from ..exec.context import ExecContext
from ..types import StringType, StructType, dict_encoded

Partition = list


def _jnp():
    import jax.numpy as jnp

    return jnp


class _OutBuffer:
    """Accumulates host-side row slices for one reducer partition.

    Memory discipline (UnsafeExternalSorter.java role): past
    ``spill_bytes`` of accumulated host arrays, the live chunks are
    written to one .npz spill file (dictionaries stay in RAM — they are
    shared references, not copies) and dropped; build() streams spills
    back one file at a time, so peak host memory is
    O(spill_bytes + one tile), not O(partition).

    While the rows are host-side anyway, append() keeps a running
    (min, max, any_valid) per stat column — the map-side column stats.
    build() seeds the dense-range device-scalar memo with them, and in
    cluster mode they ride the MapStatus payload so the reduce side
    seeds the same values after the IPC rebuild: post-shuffle dense
    agg/join decisions never launch the krange3 probe.

    ``stat_cols`` restricts accumulation to the PLAN-REACHABLE dense
    candidates (columns some downstream single-integral-key aggregate or
    join can actually consult — physical/exchange.
    annotate_exchange_stat_cols); None keeps the historical behavior of
    every integral column (bare plans built without the planner). Either
    way the set intersects with integral non-dictionary columns, the
    only ones dense_range_stats reads."""

    def __init__(self, schema: StructType, spill_bytes: int | None = None,
                 spill_dir: str | None = None, metrics=None,
                 stat_cols: list | None = None):
        self.schema = schema
        self.chunks: list[list] = []  # per append: [(data, validity, sdict), ...]
        self.rows = 0
        self.spill_bytes = spill_bytes
        self.spill_dir = spill_dir
        self.metrics = metrics
        self._chunk_rows: list[int] = []
        self._live_bytes = 0
        # per spill: (path, [per-chunk [sdict per col]], [per-chunk rows])
        self._spills: list[tuple] = []
        integral = [
            i for i, f in enumerate(schema.fields)
            if np.dtype(f.dataType.device_dtype).kind == "i"
            and not dict_encoded(f.dataType)]
        self._stat_cols = integral if stat_cols is None else \
            [i for i in integral if i in set(stat_cols)]
        # col index -> (kmin, kmax, any_valid) over every appended row
        self.col_stats: dict[int, tuple] = {
            i: (0, 0, False) for i in self._stat_cols}

    def append(self, cols: list, n: int):
        if not n:
            return
        self.chunks.append(cols)
        self._chunk_rows.append(n)
        self.rows += n
        if self.metrics is not None:
            # bytes moved through the shuffle write (codes + validity
            # planes; dictionaries ride by reference) — the compressed-
            # execution scoreboard bench.py --encoded reads
            self.metrics.add("shuffle.bytes_shipped", sum(
                d.nbytes + (v.nbytes if v is not None else 0)
                for d, v, _ in cols))
        for i in self._stat_cols:
            d, v, _ = cols[i]
            live = d if v is None else d[v]
            if len(live):
                lo, hi = int(live.min()), int(live.max())
                plo, phi, seen = self.col_stats[i]
                self.col_stats[i] = ((min(plo, lo), max(phi, hi), True)
                                     if seen else (lo, hi, True))
        if self.spill_bytes is not None:
            self._live_bytes += sum(
                d.nbytes + (v.nbytes if v is not None else 0)
                for d, v, _ in cols)
            if self._live_bytes > self.spill_bytes:
                self._spill()

    def seed_stats(self, batch: ColumnarBatch) -> None:
        """Seed the dense-range memo of one built tile with this
        partition's column stats. The seeded range may be a SUPERSET of
        the tile's own (partition-wide vs per-tile) — sound for the dense
        fast-path decision: kmin only offsets the scatter base and a wider
        span merely widens the table. Partition-wide is deliberate: the
        reduce side of a cluster shuffle seeds the same partition-wide
        values from the MapStatus payload, so local and cluster runs make
        identical dense decisions (the plan analyzer mirrors this)."""
        from ..utils.device_memo import seed_dense_range_memo

        for i, st in self.col_stats.items():
            seed_dense_range_memo(batch.columns[i], batch.row_mask, st)

    def _spill(self):
        import os
        import tempfile

        fd, path = tempfile.mkstemp(suffix=".sparktpu-spill.npz",
                                    dir=self.spill_dir or None)
        os.close(fd)
        arrays = {}
        dicts = []
        for ci, chunk in enumerate(self.chunks):
            dicts.append([sd for _, _, sd in chunk])
            for i, (d, v, _) in enumerate(chunk):
                arrays[f"d{ci}_{i}"] = d
                if v is not None:
                    arrays[f"v{ci}_{i}"] = v
        np.savez(path, **arrays)
        self._spills.append((path, dicts, list(self._chunk_rows)))
        if self.metrics is not None:
            self.metrics.add("shuffle.spill.files")
            self.metrics.add("shuffle.spill.bytes", self._live_bytes)
        self.chunks, self._chunk_rows, self._live_bytes = [], [], 0

    def _iter_chunks(self):
        """Yield (chunk_cols, nrows) in append order, loading spill files
        one at a time."""
        import os

        ncols = len(self.schema.fields)
        for path, dicts, chunk_rows in self._spills:
            with np.load(path, allow_pickle=False) as z:
                for ci, n in enumerate(chunk_rows):
                    chunk = []
                    for i in range(ncols):
                        d = z[f"d{ci}_{i}"]
                        v = (z[f"v{ci}_{i}"] if f"v{ci}_{i}" in z.files
                             else None)
                        chunk.append((d, v, dicts[ci][i]))
                    yield chunk, n
            try:
                os.unlink(path)
            except OSError:
                pass
        for chunk, n in zip(self.chunks, self._chunk_rows):
            yield chunk, n

    def _build_tile(self, chunks: list[list]) -> ColumnarBatch:
        """Merge a group of chunks into one device batch."""
        arrays = []
        validities = []
        dicts = []
        for i, f in enumerate(self.schema.fields):
            datas = [c[i][0] for c in chunks]
            valids = [c[i][1] for c in chunks]
            if dict_encoded(f.dataType):
                sdicts = [c[i][2] for c in chunks]
                merged, recoded = _merge_dict_chunks(sdicts, datas)
                data = (np.concatenate(recoded) if recoded
                        else np.zeros(0, np.int32))
                sd = merged
            else:
                data = np.concatenate(datas) if datas else np.zeros(0)
                sd = None
            if any(v is not None for v in valids):
                vs = [v if v is not None else np.ones(len(d), bool)
                      for v, d in zip(valids, datas)]
                validity = np.concatenate(vs)
            else:
                validity = None
            arrays.append(data)
            validities.append(validity)
            dicts.append(sd)
        return ColumnarBatch.from_numpy(
            self.schema, arrays, dictionaries=dicts, validities=validities)

    def build(self, tile_capacity: int) -> Partition:
        """Rebuild device batches (≤ tile_capacity rows each), streaming
        spilled chunks so peak host memory stays bounded. Chunks are split
        at exact tile boundaries — an overshooting tile would round up to
        the next capacity bucket and break the memory bound."""
        if not self.chunks and not self._spills:
            empty = ColumnarBatch.empty(self.schema)
            self.seed_stats(empty)
            return [empty]
        batches: Partition = []
        pend: list[list] = []
        pend_rows = 0
        for chunk, n in self._iter_chunks():
            off = 0
            while n - off > 0:
                take = min(n - off, tile_capacity - pend_rows)
                if off == 0 and take == n:
                    pend.append(chunk)
                else:
                    pend.append([
                        (d[off:off + take],
                         None if v is None else v[off:off + take], sd)
                        for d, v, sd in chunk])
                pend_rows += take
                off += take
                if pend_rows >= tile_capacity:
                    batches.append(self._build_tile(pend))
                    pend, pend_rows = [], 0
        if pend or not batches:
            batches.append(self._build_tile(pend))
        self._spills = []
        for b in batches:
            self.seed_stats(b)
        return batches


def _merge_dict_chunks(sdicts: list, datas: list):
    from ..columnar.batch import merge_string_dicts

    dicts = [sd or EMPTY_DICT for sd in sdicts]
    if all(d is dicts[0] for d in dicts):
        return dicts[0], [np.asarray(c) for c in datas]
    merged, luts = merge_string_dicts(dicts)
    recoded = [lut[np.clip(codes, 0, len(lut) - 1)]
               for lut, codes in zip(luts, datas)]
    return merged, recoded


def _pull_sorted(batch: ColumnarBatch, perm, counts) -> tuple[list, np.ndarray]:
    """Gather columns by perm on device, transfer to host once."""
    import jax
    jnp = _jnp()

    gathered = []
    for c in batch.columns:
        data = np.asarray(jnp.take(c.data, perm))
        validity = None if c.validity is None else \
            np.asarray(jnp.take(c.validity, perm))
        gathered.append((data, validity, c.dictionary))
    return gathered, np.asarray(counts)


def _out_buffers(num_out: int, schema: StructType, ctx: ExecContext,
                 stat_cols: list | None = None) -> list[_OutBuffer]:
    return [_OutBuffer(schema, spill_bytes=ctx.memory.spill_bytes,
                       spill_dir=ctx.memory.spill_dir, metrics=ctx.metrics,
                       stat_cols=stat_cols)
            for _ in range(num_out)]


def hash_partition_batch(batch: ColumnarBatch,
                         key_positions: Sequence[int], num_out: int,
                         seed: int) -> tuple[list, np.ndarray]:
    """Partition ONE materialized batch by key hash; returns the
    pid-grouped host columns + per-partition counts (the shared
    operator-at-a-time kernels — the fused exchange write in
    physical/fusion.py produces the same shape from one fused dispatch)."""
    import jax

    from ..ops.hashing import hash_columns, partition_ids
    from ..ops.partition import hash_partition
    from ..physical.compile import GLOBAL_KERNEL_CACHE

    try:
        from ..utils.native import radix_partition as native_radix
        has_native = True
    except Exception:
        has_native = False

    jnp = _jnp()
    keys = [batch.columns[i] for i in key_positions]
    key_eqs = [c.eq_keys() for c in keys]
    key_valids = [c.validity for c in keys]
    cap = batch.capacity
    if has_native:
        # fast path: device computes only the pid per row (cheap
        # hash kernel); the C++ counting sort groups rows host-side
        # (native/sparktpu_native.cpp, the RadixSort role) — no
        # device sort, no device gather
        kkey = ("shuffle_pids", cap, num_out, len(keys), seed,
                tuple(str(k.dtype) for k in key_eqs),
                tuple(v is not None for v in key_valids))
        kernel = GLOBAL_KERNEL_CACHE.get_or_build(
            kkey, lambda: jax.jit(
                lambda eqs, valids, mask: jnp.where(
                    mask,
                    partition_ids(hash_columns(eqs, list(valids),
                                               seed=seed),
                                  num_out),
                    num_out)))
        pids = np.asarray(kernel(key_eqs, key_valids, batch.row_mask))
        try:
            order, counts = native_radix(pids, num_out)
        except Exception:
            order = np.argsort(pids, kind="stable")
            counts = np.bincount(
                pids[pids < num_out], minlength=num_out)
        order = order[: int(counts.sum())]
        gathered = []
        for c in batch.columns:
            data = np.asarray(c.data)[order]
            validity = None if c.validity is None else \
                np.asarray(c.validity)[order]
            gathered.append((data, validity, c.dictionary))
        return gathered, counts.astype(np.int64)
    kkey = ("shuffle_hash", cap, num_out, len(keys), seed,
            tuple(str(k.dtype) for k in key_eqs),
            tuple(v is not None for v in key_valids))
    kernel = GLOBAL_KERNEL_CACHE.get_or_build(
        kkey, lambda: jax.jit(
            lambda eqs, valids, mask: hash_partition(
                eqs, valids, mask, num_out, seed=seed)))
    pr = kernel(key_eqs, key_valids, batch.row_mask)
    return _pull_sorted(batch, pr.perm, pr.counts)


def rr_partition_batch(batch: ColumnarBatch, num_out: int,
                       start: int) -> tuple[list, np.ndarray]:
    """Round-robin-partition one batch. The running row offset is a
    kernel ARGUMENT (an int32 device scalar), not part of the cache key:
    one compiled kernel per (capacity, num_out) serves every batch
    position (the historical key embedded start % num_out and compiled
    once per batch — the SampleExec storm shape)."""
    import jax

    from ..ops.partition import round_robin_partition
    from ..physical.compile import GLOBAL_KERNEL_CACHE

    kkey = ("shuffle_rr", batch.capacity, num_out)
    kernel = GLOBAL_KERNEL_CACHE.get_or_build(
        kkey, lambda: jax.jit(
            lambda mask, s: round_robin_partition(mask, num_out, s)))
    pr = kernel(batch.row_mask, np.int32(start % num_out))
    return _pull_sorted(batch, pr.perm, pr.counts)


def range_partition_batch(batch: ColumnarBatch, key_position: int,
                          bounds, descending: bool, num_out: int,
                          string_key: bool) -> tuple[list, np.ndarray]:
    """Range-partition one batch against sampled bounds."""
    import jax

    from ..ops.partition import range_partition, _group_by_pid
    from ..physical.compile import GLOBAL_KERNEL_CACHE

    jnp = _jnp()
    col = batch.columns[key_position]
    cap = batch.capacity
    if string_key:
        # host: dict value → pid lut; device: take + group
        sd = col.dictionary or StringDict([""])
        lut = np.searchsorted(bounds, np.array(sd.values or [""],
                                               dtype=object),
                              side="right").astype(np.int32)
        if descending:
            lut = (num_out - 1) - lut
        lut_d = jnp.asarray(lut)
        pids = jnp.take(lut_d, jnp.clip(col.data, 0, len(lut) - 1))
        kkey = ("shuffle_range_str", cap, num_out)
        kernel = GLOBAL_KERNEL_CACHE.get_or_build(
            kkey, lambda: jax.jit(
                lambda p, m: _group_by_pid(p, m, num_out)))
        pr = kernel(pids, batch.row_mask)
    else:
        barr = jnp.asarray(np.asarray(bounds))
        kkey = ("shuffle_range", cap, num_out, descending,
                str(col.data.dtype), len(bounds))
        kernel = GLOBAL_KERNEL_CACHE.get_or_build(
            kkey, lambda: jax.jit(
                lambda keys, b, mask: range_partition(
                    keys, b, mask, num_out, descending)))
        pr = kernel(col.sort_keys().astype(barr.dtype), barr,
                    batch.row_mask)
    return _pull_sorted(batch, pr.perm, pr.counts)


def shuffle_hash(partitions: list[Partition], key_positions: Sequence[int],
                 num_out: int, schema: StructType, ctx: ExecContext,
                 stats: dict | None = None,
                 seed: int = 42,
                 col_stats: dict | None = None,
                 stat_cols: list | None = None) -> list[Partition]:
    """Hash-repartition. ``seed`` must differ from the upstream exchange's
    when re-splitting already-hash-partitioned data (grace join): reusing
    the seed makes h %% nfrag constant within a partition whenever nfrag
    divides the exchange's partition count — a degenerate split."""
    bufs = _out_buffers(num_out, schema, ctx, stat_cols)
    for part in partitions:
        for batch in part:
            gathered, counts = hash_partition_batch(
                batch, key_positions, num_out, seed)
            _slice_into(bufs, gathered, counts)
    return _finish(bufs, ctx, stats, col_stats)


def shuffle_round_robin(partitions: list[Partition], num_out: int,
                        schema: StructType, ctx: ExecContext,
                        stats: dict | None = None,
                        col_stats: dict | None = None,
                        stat_cols: list | None = None) -> list[Partition]:
    bufs = _out_buffers(num_out, schema, ctx, stat_cols)
    start = 0
    for part in partitions:
        for batch in part:
            gathered, counts = rr_partition_batch(batch, num_out, start)
            _slice_into(bufs, gathered, counts)
            start += int(counts.sum())
    return _finish(bufs, ctx, stats, col_stats)


def shuffle_range(partitions: list[Partition], key_position: int,
                  bounds, descending: bool, num_out: int, schema: StructType,
                  ctx: ExecContext, stats: dict | None = None,
                  col_stats: dict | None = None,
                  stat_cols: list | None = None) -> list[Partition]:
    """Range shuffle for global sort. `bounds` is a host list of boundary
    values in the sort-key domain (numeric) or raw strings."""
    bufs = _out_buffers(num_out, schema, ctx, stat_cols)
    f = schema.fields[key_position]
    string_key = isinstance(f.dataType, StringType)
    for part in partitions:
        for batch in part:
            gathered, counts = range_partition_batch(
                batch, key_position, bounds, descending, num_out,
                string_key)
            _slice_into(bufs, gathered, counts)
    return _finish(bufs, ctx, stats, col_stats)


def shuffle_fused(partitions: list[Partition], writer, num_out: int,
                  schema: StructType, ctx: ExecContext,
                  stats: dict | None = None,
                  col_stats: dict | None = None,
                  stat_cols: list | None = None) -> list[Partition]:
    """Fused exchange map side: `writer` (physical/fusion.ExchangeFusion
    bound to a partitioning) runs ONE jitted kernel per input batch —
    pipeline trace + partition ids + pid-grouped gather — and this loop
    consumes the grouped host columns directly into the reduce buffers:
    no intermediate materialized batch between the stage pipeline and the
    shuffle write. Partitions under spark.tpu.fusion.minRows take the
    shared unfused kernels instead (pipeline + shuffle kind), matching
    the other fused operators' size gate."""
    from ..config import FUSION_MIN_ROWS

    bufs = _out_buffers(num_out, schema, ctx, stat_cols)
    min_rows = int(ctx.conf.get(FUSION_MIN_ROWS))  # tpulint: ignore[host-sync]
    start = 0  # running live-row offset (round-robin positioning)
    for part in partitions:
        fused = sum(b.capacity for b in part) >= min_rows
        for batch in part:
            if fused:
                gathered, counts = writer.partition_batch(batch, start)
            else:
                gathered, counts = writer.partition_unfused(batch, start)
            _slice_into(bufs, gathered, counts)
            # counts is host numpy (materialized by the map-side write)
            start += int(counts.sum())  # tpulint: ignore[host-sync]
    return _finish(bufs, ctx, stats, col_stats)


def gather_single(partitions: list[Partition]) -> list[Partition]:
    """AllTuples: concatenate every partition into one."""
    merged: Partition = []
    for p in partitions:
        merged.extend(p)
    return [merged]


def _slice_into(bufs: list[_OutBuffer], gathered: list, counts: np.ndarray):
    offsets = np.zeros(len(counts) + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    for p in range(len(bufs)):
        lo, hi = int(offsets[p]), int(offsets[p + 1])  # tpulint: ignore[host-sync]
        if hi <= lo:
            continue
        cols = []
        for data, validity, sd in gathered:
            cols.append((data[lo:hi],
                         None if validity is None else validity[lo:hi], sd))
        bufs[p].append(cols, hi - lo)


def _finish(bufs: list[_OutBuffer], ctx: ExecContext,
            stats: dict | None,
            col_stats: dict | None = None) -> list[Partition]:
    tile = ctx.conf.batch_capacity
    out = []
    for i, b in enumerate(bufs):
        if stats is not None:
            stats[i] = b.rows
        if col_stats is not None:
            col_stats[i] = dict(b.col_stats)
        out.append(b.build(tile))
    return out
