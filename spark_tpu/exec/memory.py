"""Device/host memory discipline for blocking operators.

Role of the reference's UnifiedMemoryManager
(core/memory/UnifiedMemoryManager.scala:491) and its spilling consumers
(corej/util/collection/unsafe/sort/UnsafeExternalSorter.java,
TungstenAggregationIterator's sort-based fallback) — redesigned for the
XLA allocation model. JAX/XLA owns the actual HBM allocator, so a
byte-for-byte reservation ledger would double-book what the runtime
already tracks; what the engine must govern is *operator policy*:

- how many rows a blocking operator (sort, join build, aggregation) may
  materialize as one device tile before it must switch to its multi-pass
  path (external range-bucketed sort, grace hash join, blockwise fold);
- when host-side shuffle buffers spill their accumulated chunks to disk
  (UnsafeExternalSorter role — exec/shuffle._OutBuffer calls back here).

Budget resolution order: explicit conf > live device memory stats
(bytes_limit × safety fraction) > conservative default. The same
MemoryManager instance travels with the ExecContext for one query, so
its counters land in the query's SQLMetrics snapshot.
"""

from __future__ import annotations

import numpy as np

from ..config import ConfigEntry, _register
from ..types import dict_encoded

DEVICE_BUDGET = _register(ConfigEntry(
    "spark.tpu.memory.deviceBudgetBytes", 0,
    "Device-memory budget (bytes) a single blocking operator may "
    "materialize as one tile. 0 = auto: live device bytes_limit × 0.5, "
    "else 4 GiB. (Role of spark.memory.fraction over the unified region, "
    "core/memory/UnifiedMemoryManager.scala.)", int))

SPILL_BYTES = _register(ConfigEntry(
    "spark.tpu.shuffle.spillBytes", 1 << 28,
    "Host bytes one shuffle reducer buffer may hold before spilling its "
    "chunks to disk (UnsafeExternalSorter.java role).", int))

SPILL_DIR = _register(ConfigEntry(
    "spark.local.dir", "",
    "Directory for shuffle spill files; '' = the system temp dir "
    "(role of spark.local.dir).", str))

_MIN_TILE_ROWS = 1 << 14


def schema_row_bytes(schema) -> int:
    """Device bytes per row: column data (dict-encoded = int32 codes) +
    validity planes + the row mask."""
    total = 1  # row mask
    for f in schema.fields:
        if dict_encoded(f.dataType):
            total += 4
        else:
            total += np.dtype(f.dataType.device_dtype).itemsize
        total += 1  # validity (may be absent; budget conservatively)
    return total


def _auto_budget() -> int:
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            return limit // 2
    except Exception:
        pass
    return 4 << 30


class MemoryManager:
    """Per-query policy object; see module docstring."""

    def __init__(self, conf, metrics=None):
        explicit = int(conf.get(DEVICE_BUDGET))
        self.device_budget = explicit if explicit > 0 else _auto_budget()
        # an explicit budget is a deliberate cap (tests, constrained
        # slices) and may push tiles below the auto-mode floor
        self._floor = (1 << 10) if explicit > 0 else _MIN_TILE_ROWS
        self.spill_bytes = int(conf.get(SPILL_BYTES))
        self.spill_dir = str(conf.get(SPILL_DIR)) or None
        self.metrics = metrics

    def tile_rows(self, schema, amplification: int = 3) -> int:
        """Max rows a blocking operator may hold in one device tile.

        `amplification` models the operator's working set on top of the
        input tile (sort: keys + permutation + gathered output ≈ 3×;
        join build: build + probe + outputs ≈ 4×)."""
        per_row = schema_row_bytes(schema) * max(1, amplification)
        rows = self.device_budget // per_row
        return max(self._floor, int(rows))

    def count(self, name: str, v: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.add(name, v)
