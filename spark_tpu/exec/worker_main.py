"""Executor worker process entry point (gRPC backend).

Role of the reference's CoarseGrainedExecutorBackend.main
(core/executor/CoarseGrainedExecutorBackend.scala:181 LaunchTask →
core/executor/Executor.scala TaskRunner): register with the driver over
the network, serve task-launch RPCs, heartbeat until the driver goes
away.

Each worker's single RpcServer also serves the BLOCK plane (role of the
executor-side shuffle-block transport, common/network-shuffle
ExternalBlockHandler.java): map-stage outputs persist in this process
under (shuffle_id, reduce_id) and reducers running on OTHER workers (or
the driver) stream them directly in 4 MiB chunks — the driver never
carries shuffle bytes. Workers are joinable by address: any process that
can reach the driver's control endpoint and knows the cluster secret may
register (the standalone Worker/ExternalShuffleService deployment
model), which is what the two-"host" cluster test exercises.
"""

from __future__ import annotations

import os
import pickle
import sys
import threading
import time
import traceback

from ..net.transport import (
    BEST_EFFORT_RETRY, CHUNK_BYTES, RpcClient, RpcServer,
)
from ..utils import faults, lockwatch
from ..utils.counters import LockedCounter

# (shuffle_id, reduce_id) → Arrow IPC bytes; lives for the worker process
BLOCK_STORE: dict = {}
BLOCK_ADDR: str = ""
_STORE_LOCK = threading.Lock()
lockwatch.register("exec.worker_main._STORE_LOCK",
                   sys.modules[__name__], "_STORE_LOCK")


_PUSH_CLIENT = None


def _push_client() -> "RpcClient | None":
    global _PUSH_CLIENT

    push_addr = os.environ.get("SPARK_TPU_SHUFFLE_PUSH_ADDR")
    if not push_addr:
        return None
    with _STORE_LOCK:  # one client per process (racy init leaks)
        if _PUSH_CLIENT is None:
            _PUSH_CLIENT = RpcClient(
                push_addr, os.environ["SPARK_TPU_WORKER_KEY"])
        return _PUSH_CLIENT


def store_map_block(shuffle_id: str, map_id: int, num_maps: int,
                    reduce_id: int, data: bytes) -> None:
    """Store one map task's block for one reduce partition:
    in this worker's memory (serves reducer pulls), in the shared spill
    dir when the external shuffle service runs over one (durability),
    and — push mode — PUSHED to the service's per-reduce-partition
    merger over the network (ShuffleBlockPusher →
    RemoteBlockPushResolver push-merge path; no shared filesystem)."""
    from .map_output import map_block_id

    bid = map_block_id(shuffle_id, map_id, num_maps)
    if faults.ENABLED:
        faults.maybe_fail("shuffle.write", detail=f"{bid}:{reduce_id}")
    with _STORE_LOCK:
        BLOCK_STORE[(bid, reduce_id)] = data
    root = os.environ.get("SPARK_TPU_SHUFFLE_DIR")
    if root:
        from .shuffle_service import persist_block

        persist_block(root, bid, reduce_id, data)
    client = _push_client()
    if client is not None:
        # pushes are idempotent (the merger dedups by (map, reduce)) —
        # absorb a transient service flap instead of failing the task
        client.call(
            "push_block",
            pickle.dumps((shuffle_id, map_id, reduce_id, data)),
            timeout=120, retry=BEST_EFFORT_RETRY)


def put_block(shuffle_id: str, reduce_id: int, data: bytes) -> None:
    store_map_block(shuffle_id, 0, 1, reduce_id, data)


# ---------------------------------------------------------------------------
# Worker-side observability (cluster-mode SQL stage tasks)
# ---------------------------------------------------------------------------

# stage tasks currently running in THIS process, registered for live
# telemetry: the heartbeat loop snapshots each into the next heartbeat
# payload (collect_live_obs) — the reference's periodic Heartbeater
# shipping accumulator updates mid-task
_LIVE_TASKS: dict[int, dict] = {}

# black-box post-task ring (obs/blackbox pull-on-anomaly capture): with
# spark.tpu.obs.bundles armed, every finished stage task leaves a
# bounded summary here (spans capped, host counters only) that the
# driver pulls over the `diagnostic_state` RPC ONLY at bundle time —
# healthy-path heartbeat payloads carry none of it
_DIAG_RING: list[dict] = []
_DIAG_RING_MAX = 32
_DIAG_SPAN_CAP = 200
_DIAG_LOCK = threading.Lock()
lockwatch.register("exec.worker_main._DIAG_LOCK",
                   sys.modules[__name__], "_DIAG_LOCK")


def begin_stage_obs(conf, query_id: str | None = None,
                    stage_id: str | None = None,
                    task_id: int = 0) -> dict | None:
    """Install a process-local observability recorder for one stage task
    (the executor half of the reference's heartbeat-shipped executor
    metrics): a task-lived Tracer, a per-operator metric-record dict for
    the ExecContext, and baselines of THIS process's KernelCache
    counters, so the driver can reconcile attributed launches against
    driver+worker totals. With spark.tpu.heartbeat.obs on, the state is
    also registered for LIVE flushing: every heartbeat ships a
    cumulative snapshot of the task's host counters, closed spans since
    the last flush, and currently-open spans (collect_live_obs). Same
    zero-launch/no-mid-query-sync contract as the driver recorder —
    everything here is host bookkeeping. Returns None when the session
    disabled obs shipping."""
    from ..config import (CLUSTER_OBS_SHIPPING, HEARTBEAT_FLUSH_BUDGET,
                          HEARTBEAT_OBS, KERNEL_ATTRIBUTION, TRACE_ENABLED,
                          TRACE_MAX_SPANS, UI_OPERATOR_METRICS)
    from ..obs import resources as _resources
    from ..obs.tracing import Tracer
    from ..physical.compile import GLOBAL_KERNEL_CACHE as KC

    # ledger + kernel-cost switches follow the shipped session conf (the
    # worker-process analog of TpuSession.__init__'s configure call)
    _resources.configure(conf)
    from ..columnar import encoding as _encoding

    # compressed-execution ingest harvest follows the shipped conf too
    _encoding.configure(conf)
    # fault-injection rules ship with the session conf exactly like the
    # other process-global switches — chaos runs exercise the worker's
    # task/heartbeat/shuffle-write seams, healthy conf disables them
    faults.configure(conf)
    # lock-discipline watching follows the shipped conf as well (the
    # env-var path SPARK_TPU_LOCKWATCH=1 already covered import time)
    lockwatch.configure(conf)
    from . import persist_cache as _persist

    # persistent XLA compile cache: worker processes compile their own
    # stage kernels, so a warm cluster restart needs the same disk cache
    # wired here (spark.tpu.cache.dir ships with the conf)
    _persist.configure(conf)
    from ..obs import export as _export

    # service metrics plane: with spark.tpu.metrics.export on, this
    # worker's heartbeats attach its registry counter snapshot so the
    # driver scrape shows worker-labeled series
    _export.configure(conf)
    from ..obs import blackbox as _blackbox

    # black-box arming ships with the conf too: armed workers retain
    # bounded post-task diagnostic summaries for the driver's
    # pull-on-anomaly `diagnostic_state` RPC (nothing extra ships on
    # the healthy path — the heartbeat payload is unchanged)
    _blackbox.configure(conf)

    # conf values are host data — bool() here never touches device
    if not bool(conf.get(  # tpulint: ignore[host-sync]
            CLUSTER_OBS_SHIPPING)):
        return None
    trace_on = bool(conf.get(TRACE_ENABLED))  # tpulint: ignore[host-sync]
    metrics_on = bool(conf.get(  # tpulint: ignore[host-sync]
        UI_OPERATOR_METRICS))
    attribution = bool(conf.get(  # tpulint: ignore[host-sync]
        KERNEL_ATTRIBUTION))
    tracer = Tracer(enabled=trace_on,
                    max_spans=int(  # tpulint: ignore[host-sync]
                        conf.get(TRACE_MAX_SPANS)))
    state = {"tracer": tracer if trace_on else None,
             "rec": {} if metrics_on else None,
             "attribution": attribution,
             "kinds0": dict(KC.launches_by_kind),
             "launches0": KC.launches,
             "compile_ms0": KC.compile_ms,
             "disk0": _persist.disk_counters(),
             "query_id": query_id, "stage_id": stage_id,
             "task_id": task_id, "flush_seq": 0,
             "span_mark": tracer.mark() if trace_on else 0,
             "unsent_spans": [], "sent_spans": 0,
             "flush_budget": int(conf.get(  # tpulint: ignore[host-sync]
                 HEARTBEAT_FLUSH_BUDGET))}
    if bool(conf.get(HEARTBEAT_OBS)):  # tpulint: ignore[host-sync]
        with _STORE_LOCK:
            _LIVE_TASKS[id(state)] = state
    return state


# heartbeat flush-budget bookkeeping: tasks trimmed to a minimal delta
# because a beat hit spark.tpu.heartbeat.flushBudget (cumulative — the
# driver surfaces it in live status, and stage tasks / tests read it
# concurrently with the heartbeat thread's bumps), and a rotation
# cursor so the trim never starves the same tasks every beat
FLUSH_OVERFLOWS = LockedCounter("exec.worker_main.FLUSH_OVERFLOWS")
# race-lint: ignore[worker-reinit] — rotation cursor, not a metric: a
# fresh worker starting at 0 is exactly the intended semantics
_FLUSH_RR = 0

# rough per-element payload estimates (pickled size order-of-magnitude):
# exact accounting would pickle twice per beat for no benefit
_DELTA_BASE_COST = 256
_OP_RECORD_COST = 160
_SPAN_COST = 240
_OPEN_SPAN_COST = 96


def collect_live_obs() -> list:
    """Snapshot every registered in-flight stage task into live obs
    deltas for the next heartbeat. Each delta is CUMULATIVE since task
    start (snapshots replace on the driver, so a dropped heartbeat loses
    nothing) except closed spans, which ship incrementally via the
    tracer's monotonic sequence mark — carried in a per-task unsent
    buffer until `ack_live_obs` confirms the heartbeat RPC succeeded,
    so a failed beat re-sends them instead of silently dropping them
    (at-least-once across failures; exactly-once on a healthy channel).

    Very wide executors cap the payload per beat at
    spark.tpu.heartbeat.flushBudget: once the (estimated) budget is
    spent, remaining tasks ship minimal counter-only deltas — their
    closed spans STAY in the (bounded) carry buffer for a later beat,
    the overflow is counted (FLUSH_OVERFLOWS → live status), and the
    collection order rotates so no task is trimmed forever; a task
    closing more spans than the carry bound before its rotation turn
    loses its oldest from the LIVE stream only (the task-return record
    ships the tracer's full ring regardless).

    Host counters only: parked row-masks stay parked
    (export_op_records_partial), no kernel is launched, no device array
    is read."""
    global _FLUSH_RR

    from ..obs.metrics import export_op_records_partial
    from ..physical.compile import GLOBAL_KERNEL_CACHE as KC

    with _STORE_LOCK:
        states = list(_LIVE_TASKS.values())
        if states:
            _FLUSH_RR = (_FLUSH_RR + 1) % len(states)
    if states:
        states = states[_FLUSH_RR:] + states[:_FLUSH_RR]
    budget = next((s["flush_budget"] for s in states
                   if s.get("flush_budget")), 0)
    spent = 0
    out = []
    for state in states:
        state["flush_seq"] += 1
        trimmed = budget > 0 and spent >= budget
        # a trimmed task still ships its rolled-up counters — it just
        # drops the per-operator breakdown from the payload
        full = export_op_records_partial(state["rec"])
        recs = {} if trimmed else full
        rows = sum(e.get("rows", 0) for e in full.values())
        rows_exact = all(e.get("rows_exact", True) for e in full.values())
        batches = sum(e.get("batches", 0) for e in full.values())
        tracer = state["tracer"]
        spans_closed: list = []
        open_spans: list = []
        if tracer is not None:
            mark = state["span_mark"]
            state["span_mark"] = tracer.mark()
            carry = state["unsent_spans"]
            carry.extend(tracer.since(mark))
            del carry[:-512]  # bound the carry across a long outage
            if not trimmed:
                spans_closed = list(carry)
                open_spans = tracer.open_spans()
        state["sent_spans"] = len(spans_closed)
        if trimmed:
            FLUSH_OVERFLOWS.bump()
        kinds = {k: v - state["kinds0"].get(k, 0)
                 for k, v in KC.launches_by_kind.items()
                 if v != state["kinds0"].get(k, 0)}
        spent += (_DELTA_BASE_COST + _OP_RECORD_COST * len(recs)
                  + _SPAN_COST * len(spans_closed)
                  + _OPEN_SPAN_COST * len(open_spans))
        out.append({
            "query": state["query_id"], "stage": state["stage_id"],
            "task": state["task_id"], "seq": state["flush_seq"],
            "executor_pid": os.getpid(),
            "rows": rows,
            "rows_exact": rows_exact,
            "batches": batches,
            "launches": KC.launches - state["launches0"],
            "compile_ms": round(KC.compile_ms - state["compile_ms0"], 3),
            "kernel_kinds": kinds,
            "op_records": recs if not trimmed else None,
            "spans_closed": spans_closed,
            "open_spans": open_spans if not trimmed else None,
        })
    return out


def ack_live_obs() -> None:
    """The heartbeat carrying the last `collect_live_obs` snapshot
    reached the driver — drop the closed spans that beat actually
    INCLUDED (a flush-budget trim keeps its carry for the next beat).
    Called only from the (single) heartbeat thread, strictly alternating
    with collect, so nothing is appended to the unsent buffers in
    between (new spans land in the tracer ring and are picked up by the
    next collect's mark)."""
    with _STORE_LOCK:
        states = list(_LIVE_TASKS.values())
    for state in states:
        del state["unsent_spans"][:state.get("sent_spans", 0)]
        state["sent_spans"] = 0


def finish_stage_obs(state: dict | None) -> dict | None:
    """Package the task's observability for the ride back to the driver
    alongside the MapStatus payload: exported per-operator records
    (parked masks resolved — the batches are already host-side for block
    storage), raw spans + the (wall, perf) clock anchor for cross-process
    rebasing, and this process's KernelCache launch/compile deltas.
    Deregisters the task from live flushing FIRST, so no heartbeat can
    ship a partial that postdates the final record."""
    if state is None:
        return None
    from ..obs.metrics import export_op_records
    from ..obs.resources import GLOBAL_LEDGER
    from ..physical.compile import GLOBAL_KERNEL_CACHE as KC

    with _STORE_LOCK:
        _LIVE_TASKS.pop(id(state), None)
    kinds = {k: v - state["kinds0"].get(k, 0)
             for k, v in KC.launches_by_kind.items()
             if v != state["kinds0"].get(k, 0)}
    from . import persist_cache as _pc

    disk = {k: v - state.get("disk0", {}).get(k, 0)
            for k, v in _pc.disk_counters().items()
            if v != state.get("disk0", {}).get(k, 0)}
    tracer = state["tracer"]
    # this process's HBM accounting for the task's query (the ledger is
    # per-process; the driver merges it as the executor's remote peak)
    hbm = GLOBAL_LEDGER.query_record(state["query_id"])
    out = {
        "op_records": export_op_records(state["rec"]),
        "spans": tracer.spans() if tracer is not None else [],
        "anchor": tracer.anchor if tracer is not None else None,
        "kernel_kinds": kinds,
        "kernel_launches": KC.launches - state["launches0"],
        "kernel_compile_ms": round(KC.compile_ms - state["compile_ms0"], 3),
        "compile_disk": disk or None,
        "hbm": {"bytes": hbm["bytes"], "peak": hbm["peak"],
                "ops": {k: v["peak"] for k, v in hbm["ops"].items()}}
        if hbm is not None else None,
        "pid": os.getpid(),
    }
    from ..obs import blackbox as _blackbox

    if _blackbox.ENABLED:
        # armed black box: retain a bounded post-task summary for the
        # driver's pull-on-anomaly diagnostic_state RPC. Host dict
        # copies only — no kernel launch, no device read, and nothing
        # added to the heartbeat payload.
        entry = {"ts": time.time(), "query_id": state["query_id"],
                 "stage_id": state["stage_id"],
                 "task_id": state["task_id"],
                 "spans": out["spans"][-_DIAG_SPAN_CAP:],
                 "anchor": out["anchor"],
                 "kernel_kinds": out["kernel_kinds"],
                 "kernel_launches": out["kernel_launches"],
                 "hbm": out["hbm"], "pid": out["pid"]}
        with _DIAG_LOCK:
            _DIAG_RING.append(entry)
            del _DIAG_RING[:-_DIAG_RING_MAX]
    return out


def _handle_get_block(payload: bytes):
    sid, rid = pickle.loads(payload)
    with _STORE_LOCK:
        data = BLOCK_STORE.get((sid, rid))
    if data is None:
        yield b"missing"
        return
    yield b"ok"
    for off in range(0, len(data), CHUNK_BYTES):
        yield data[off:off + CHUNK_BYTES]


def _handle_block_stats(payload: bytes) -> bytes:
    """Block-store introspection for tests/CI gates: the chaos suite
    asserts failed queries leave ZERO blocks behind on every worker."""
    with _STORE_LOCK:
        return pickle.dumps({
            "blocks": len(BLOCK_STORE),
            "bytes": sum(len(v) for v in BLOCK_STORE.values()),
        })


def _handle_free_shuffle(payload: bytes) -> bytes:
    sid = pickle.loads(payload)
    with _STORE_LOCK:
        # base id and per-map block ids ('<sid>#m<i>') alike
        for k in [k for k in BLOCK_STORE
                  if k[0] == sid or k[0].startswith(sid + "#m")]:
            BLOCK_STORE.pop(k, None)
    return b"ok"


def _handle_lockwatch_edges(_payload: bytes) -> bytes:
    """Worker-side lock-discipline observations for the --race gate's
    direct executor cross-check (PR 17 follow-on): the acquisition-
    order edges, registered slot names, and guard violations THIS
    worker process recorded under SPARK_TPU_LOCKWATCH=1. Pure host
    reads of the lockwatch observation tables."""
    return pickle.dumps({
        "enabled": lockwatch.ENABLED,
        "edges": [[a, b, n]
                  for (a, b), n in lockwatch.order_edges().items()],
        "names": lockwatch.registered_names(),
        "violations": lockwatch.violations(),
        "acquires": sum(lockwatch.acquire_counts().values()),
    })


def _handle_diagnostic_state(_payload: bytes) -> bytes:
    """Black-box fleet state pull (obs/blackbox): the driver calls this
    ONLY while assembling a diagnostic bundle — never on the healthy
    path — and gets this worker's bounded post-task ring plus its
    fault-registry, lockwatch, and metrics-registry state. Pure host
    reads; zero kernel launches."""
    from ..obs import blackbox as _blackbox
    from ..obs import export as _export
    from ..obs.resources import GLOBAL_LEDGER

    with _DIAG_LOCK:
        tasks = [dict(e) for e in _DIAG_RING]
    return pickle.dumps({
        "enabled": _blackbox.ENABLED,
        "pid": os.getpid(),
        "tasks": tasks,
        "hbm": GLOBAL_LEDGER.snapshot(),
        "faults": {"enabled": faults.ENABLED,
                   "fired": faults.fire_counts()},
        "lockwatch": {
            "enabled": lockwatch.ENABLED,
            "violations": lockwatch.violations(),
            "acquires": sum(lockwatch.acquire_counts().values()),
        },
        "metrics": _export.executor_payload() if _export.ENABLED else None,
    })


def _handle_launch_task(payload: bytes) -> bytes:
    """Runs one cloudpickled (fn, args) task. Task failures are data
    (('err', traceback, salvaged_obs)), not transport errors — a
    deterministic task error must not look like an executor loss to the
    driver. The third element carries the failed attempt's packaged
    observability when the task body stamped one onto the exception
    (cluster_sql._run_stage_store) — the wasted-work record the driver
    surfaces in chaos-path EXPLAIN ANALYZE and the query profile."""
    import cloudpickle

    try:
        fn, args = cloudpickle.loads(payload)
        result = fn(*args)
        return pickle.dumps(("ok", result))
    except SystemExit:
        raise
    except BaseException as e:
        salvage = getattr(e, "_salvaged_obs", None)
        try:
            return pickle.dumps(("err", traceback.format_exc(), salvage))
        except Exception:
            # unpicklable salvage (should not happen — it is plain
            # dicts) must not mask the task error
            return pickle.dumps(("err", traceback.format_exc(), None))


def serve_worker(driver_addr: str, token: str, host_label: str = "localhost",
                 bind_host: str = "127.0.0.1",
                 block: bool = True) -> RpcServer:
    """Start the worker server, register with the driver, heartbeat.
    Returns the running RpcServer (caller blocks or not via `block`).
    `bind_host` is bound AND advertised — a worker on another machine
    passes an IP the driver and peer workers can reach."""
    global BLOCK_ADDR

    server = RpcServer(token, host=bind_host)
    server.register("launch_task", _handle_launch_task)
    server.register("free_shuffle", _handle_free_shuffle)
    server.register("block_stats", _handle_block_stats)
    server.register("lockwatch_edges", _handle_lockwatch_edges)
    server.register("diagnostic_state", _handle_diagnostic_state)
    server.register("ping", lambda _p: b"pong")
    server.register_stream("get_block", _handle_get_block)
    addr = server.start()
    BLOCK_ADDR = addr

    driver = RpcClient(driver_addr, token)
    driver.wait_ready()

    def register() -> str:
        return driver.call("register_executor", pickle.dumps({
            "addr": addr, "host": host_label, "pid": os.getpid()}),
            timeout=10).decode()

    eid = register()

    interval = float(os.environ.get(  # tpulint: ignore[host-sync]
        "SPARK_TPU_HEARTBEAT_INTERVAL", "3.0"))

    def heartbeat_loop():
        nonlocal eid
        misses = 0
        while True:
            time.sleep(interval)
            try:
                # chaos seam: an injected heartbeat blackout models the
                # DRIVER never receiving the beat (a receive-path
                # partition) — from the driver's view the executor went
                # silent mid-task, which is exactly what the straggler
                # silence deadline and speculative execution must
                # absorb. The detail carries busy/idle so rules can
                # target beats DURING a task (`@busy`) — an idle-phase
                # blackout would be consumed before the task exists.
                if faults.ENABLED:
                    with _STORE_LOCK:
                        busy = bool(_LIVE_TASKS)
                    faults.maybe_fail(
                        "heartbeat.flush",
                        detail="busy" if busy else "idle")
                # live telemetry rides the liveness heartbeat: snapshots
                # of every in-flight stage task's obs counters/spans
                # (empty list when nothing runs or streaming is off).
                # Span-heavy payloads compress well — gzip them on the
                # wire instead of raising the frame budget.
                obs = collect_live_obs()
                # executor-level HBM occupancy (device ledger snapshot —
                # metadata counters only) rides EVERY beat, so cluster
                # live status shows per-executor HBM even between tasks
                from ..obs.resources import GLOBAL_LEDGER

                body = {
                    "eid": eid, "obs": obs,
                    "hbm": GLOBAL_LEDGER.snapshot(),
                    "obs_overflows": FLUSH_OVERFLOWS.value}
                # per-executor metrics deltas (cumulative snapshots —
                # a lost beat loses nothing) ride the same payload;
                # structurally absent when the metrics plane is off
                from ..obs import export as _export

                if _export.ENABLED:
                    body["metrics"] = _export.executor_payload()
                payload = pickle.dumps(body)
                reply = driver.call("heartbeat", payload, timeout=5,
                                    compress=bool(obs))
                if reply != b"unknown":
                    # the driver ingested the obs payload (it skips the
                    # sink for unknown executors) — drop the span carry
                    ack_live_obs()
                misses = 0
                if reply == b"unknown":
                    # driver declared us lost (e.g. one transient task
                    # RPC failure) — re-register under a fresh id, the
                    # reference's "executor told to re-register" path
                    eid = register()
            except faults.InjectedFault:
                # injected blackout: the beat was "lost on the wire",
                # not a send failure — the worker itself is healthy and
                # must not count it toward the driver-gone suicide
                continue
            except Exception:
                misses += 1
                if misses >= 5:  # driver gone — shut down
                    os._exit(0)

    # race-lint: ignore[bare-submit] — process-lifetime service thread:
    # heartbeats aggregate across every query on this worker and must
    # NOT inherit any single query's contextvar scope
    threading.Thread(target=heartbeat_loop, daemon=True).start()
    if block:
        threading.Event().wait()
    return server


def main() -> None:
    # under `python -m`, this file runs as __main__ while tasks import the
    # canonical spark_tpu.exec.worker_main module — publish the block-store
    # state THERE so both sides share one dict/address
    from spark_tpu.exec import worker_main as canonical

    canonical.serve_worker(
        os.environ["SPARK_TPU_DRIVER_ADDR"],
        os.environ["SPARK_TPU_WORKER_KEY"],
        os.environ.get("SPARK_TPU_WORKER_HOST", "localhost"),
        os.environ.get("SPARK_TPU_BIND_HOST", "127.0.0.1"))


if __name__ == "__main__":
    main()
