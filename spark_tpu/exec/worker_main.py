"""Executor worker process entry point.

Role of the reference's CoarseGrainedExecutorBackend.main
(core/executor/CoarseGrainedExecutorBackend.scala:181 LaunchTask →
core/executor/Executor.scala TaskRunner): connect back to the driver,
loop receiving cloudpickled (fn, args) tasks, execute, reply.

Each worker also runs a BLOCK SERVER (role of the executor-side
shuffle-block transport, common/network-shuffle
ExternalBlockHandler.java): map-stage outputs persist in this process
under (shuffle_id, reduce_id) and reducers running on OTHER workers (or
the driver) fetch them directly over a localhost socket — the driver
never carries shuffle bytes."""

from __future__ import annotations

import os
import sys
import threading
import traceback
from multiprocessing.connection import Client, Listener

# (shuffle_id, reduce_id) → Arrow IPC bytes; lives for the worker process
BLOCK_STORE: dict = {}
BLOCK_ADDR: str = ""
_STORE_LOCK = threading.Lock()


def put_block(shuffle_id: str, reduce_id: int, data: bytes) -> None:
    with _STORE_LOCK:
        BLOCK_STORE[(shuffle_id, reduce_id)] = data


def _serve_block_conn(conn):
    try:
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                return
            op = msg[0]
            if op == "get":
                _, sid, rid = msg
                with _STORE_LOCK:
                    data = BLOCK_STORE.get((sid, rid))
                if data is None:
                    conn.send(("missing", None))
                else:
                    conn.send(("ok", data))
            elif op == "free":
                _, sid = msg
                with _STORE_LOCK:
                    for k in [k for k in BLOCK_STORE if k[0] == sid]:
                        BLOCK_STORE.pop(k, None)
                conn.send(("ok", None))
            else:
                conn.send(("err", f"unknown op {op!r}"))
    finally:
        try:
            conn.close()
        except Exception:
            pass


def _block_server(authkey: bytes) -> str:
    listener = Listener(("127.0.0.1", 0), authkey=authkey)

    def loop():
        while True:
            try:
                conn = listener.accept()
            except OSError:
                return
            threading.Thread(target=_serve_block_conn, args=(conn,),
                             daemon=True).start()

    threading.Thread(target=loop, daemon=True).start()
    host, port = listener.address
    return f"{host}:{port}"


def main() -> None:
    # under `python -m`, this file runs as __main__ while tasks import the
    # canonical spark_tpu.exec.worker_main module — publish the block-store
    # state THERE so both sides share one dict/address
    from spark_tpu.exec import worker_main as canonical

    addr_s = os.environ["SPARK_TPU_WORKER_ADDR"]
    host, port = addr_s.rsplit(":", 1)
    authkey = bytes.fromhex(os.environ["SPARK_TPU_WORKER_KEY"])
    canonical.BLOCK_ADDR = canonical._block_server(authkey)
    conn = Client((host, int(port)), authkey=authkey)
    conn.send(("block_addr", canonical.BLOCK_ADDR))

    import cloudpickle

    while True:
        try:
            payload = conn.recv_bytes()
        except (EOFError, OSError):
            return
        try:
            fn, args = cloudpickle.loads(payload)
            result = fn(*args)
            conn.send(("ok", result))
        except SystemExit:
            raise
        except BaseException:
            try:
                conn.send(("err", traceback.format_exc()))
            except Exception:
                return


if __name__ == "__main__":
    main()
