"""Executor worker process entry point.

Role of the reference's CoarseGrainedExecutorBackend.main
(core/executor/CoarseGrainedExecutorBackend.scala:181 LaunchTask →
core/executor/Executor.scala TaskRunner): connect back to the driver,
loop receiving cloudpickled (fn, args) tasks, execute, reply."""

from __future__ import annotations

import os
import sys
import traceback
from multiprocessing.connection import Client


def main() -> None:
    addr_s = os.environ["SPARK_TPU_WORKER_ADDR"]
    host, port = addr_s.rsplit(":", 1)
    authkey = bytes.fromhex(os.environ["SPARK_TPU_WORKER_KEY"])
    conn = Client((host, int(port)), authkey=authkey)

    import cloudpickle

    while True:
        try:
            payload = conn.recv_bytes()
        except (EOFError, OSError):
            return
        try:
            fn, args = cloudpickle.loads(payload)
            result = fn(*args)
            conn.send(("ok", result))
        except SystemExit:
            raise
        except BaseException:
            try:
                conn.send(("err", traceback.format_exc()))
            except Exception:
                return


if __name__ == "__main__":
    main()
