"""History server: web UI over JSONL event logs.

Role of the reference's HistoryServer + web UI (core/.../history/
HistoryServer.scala; the SQL tab of ui/). A stdlib http.server renders
the application list, per-application query table, and per-query detail
(phases, kernel-cache stats, plan text) from the same JSONL logs
EventLoggingListener writes — no frameworks, zero dependencies.

Start programmatically:
    from spark_tpu.exec.history_server import HistoryServer
    hs = HistoryServer("/tmp/spark-events", port=18080)
    hs.start()          # background thread
or from the shell:  python -m spark_tpu.exec.history_server <log_dir>
"""

from __future__ import annotations

import html
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from .listener import HistoryReader

_STYLE = """
body { font-family: sans-serif; margin: 2em; color: #222; }
table { border-collapse: collapse; margin: 1em 0; }
th, td { border: 1px solid #ccc; padding: 4px 10px; text-align: left; }
th { background: #f0f0f0; }
h1 { font-size: 1.4em; } h2 { font-size: 1.1em; }
a { color: #1a56a0; text-decoration: none; }
pre { background: #f8f8f8; padding: 1em; overflow-x: auto; }
.ok { color: #0a7d20; } .fail { color: #b00020; }
"""


def _page(title: str, body: str) -> bytes:
    return (f"<!doctype html><html><head><title>{html.escape(title)}"
            f"</title><style>{_STYLE}</style></head>"
            f"<body><h1>{html.escape(title)}</h1>{body}</body></html>"
            ).encode()


def _esc(v) -> str:
    return html.escape(str(v)) if v is not None else ""


class _Handler(BaseHTTPRequestHandler):
    reader: HistoryReader = None  # injected by HistoryServer
    profiles = None               # obs.history.ProfileStore | None
    bundles = None                # diagnostic bundle dir (str) | None

    def log_message(self, *a):  # silence per-request stderr noise
        pass

    def _send(self, body: bytes, ctype="text/html", code=200):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _metrics(self) -> None:
        """Prometheus text scrape of the in-process metrics registry
        (obs/export.py) — the reference's PrometheusServlet role, served
        off the same port as the history UI. 503 while the export
        switch (spark.tpu.metrics.export) is off: a scraper should see
        'target down', not an empty-but-healthy page."""
        from ..obs import export as _export

        if not _export.ENABLED:
            self._send(b"# metrics export disabled "
                       b"(spark.tpu.metrics.export=false)\n",
                       "text/plain; version=0.0.4", code=503)
            return
        self._send(_export.render_prometheus().encode(),
                   "text/plain; version=0.0.4")

    def do_GET(self):  # noqa: N802  (http.server API)
        url = urlparse(self.path)
        q = parse_qs(url.query)
        try:
            if url.path == "/":
                self._send(self._index())
            elif url.path == "/metrics":
                self._metrics()
            elif url.path == "/app":
                self._send(self._app(q["id"][0]))
            elif url.path == "/query":
                self._send(self._query(q["id"][0], int(q["n"][0])))
            elif url.path == "/profiles" and self.profiles is not None:
                self._send(self._profiles())
            elif url.path == "/profile" and self.profiles is not None:
                self._send(self._profile(q["fp"][0]))
            elif url.path == "/bundles" and self.bundles is not None:
                self._send(self._bundles())
            elif url.path == "/bundle" and self.bundles is not None:
                self._send(self._bundle(q["id"][0]))
            elif url.path == "/api/applications":
                apps = [{"id": a, **self.reader.summary(a)}
                        for a in self.reader.applications()]
                self._send(json.dumps(apps).encode(), "application/json")
            elif url.path == "/api/profiles" and self.profiles is not None:
                self._send(json.dumps(
                    self.profiles.fingerprints()).encode(),
                    "application/json")
            else:
                self.send_error(404)
        except (KeyError, FileNotFoundError, IndexError, ValueError):
            self.send_error(404)

    def _index(self) -> bytes:
        rows = []
        for a in self.reader.applications():
            s = self.reader.summary(a)
            rows.append(
                f"<tr><td><a href='/app?id={a}'>{_esc(a)}</a></td>"
                f"<td>{s['queries']}</td><td>{s['failed']}</td>"
                f"<td>{s['total_duration_ms']:.0f}</td></tr>")
        body = ("<table><tr><th>Application</th><th>Queries</th>"
                "<th>Failed</th><th>Total ms</th></tr>"
                + "".join(rows) + "</table>")
        if self.profiles is not None:
            body += ("<p><a href='/profiles'>Query flight recorder: "
                     "fingerprint-keyed run profiles &rarr;</a></p>")
        if self.bundles is not None:
            body += ("<p><a href='/bundles'>Black box: anomaly-captured "
                     "diagnostic bundles &rarr;</a></p>")
        return _page("Spark-TPU History Server", body)

    def _bundles(self) -> bytes:
        """Diagnostic bundle index (obs/blackbox): one row per captured
        bundle in the retention ring, newest first — the
        capture-on-anomaly postmortem entry point."""
        import time as _time

        from ..obs.blackbox import list_bundles

        rows = []
        for e in list_bundles(self.bundles):
            age = _time.time() - (e.get("ts") or 0)
            rows.append(
                f"<tr><td><a href='/bundle?id={_esc(e.get('id'))}'>"
                f"{_esc(e.get('id'))}</a></td>"
                f"<td>{_esc(e.get('reason'))}</td>"
                f"<td>{_esc(e.get('trigger_kind') or '')}</td>"
                f"<td>{_esc(e.get('query_id') or '')}</td>"
                f"<td>{e.get('findings') or 0}</td>"
                f"<td>{age:.0f}s ago</td></tr>")
        body = ("<p><a href='/'>&larr; applications</a></p>"
                "<table><tr><th>Bundle</th><th>Reason</th>"
                "<th>Trigger</th><th>Query</th><th>Findings</th>"
                "<th>Captured</th></tr>" + "".join(rows) + "</table>")
        return _page("Diagnostic bundles", body)

    def _bundle(self, bid: str) -> bytes:
        """One bundle's postmortem: the diagnose.py report rendered from
        the bundle directory alone — trigger timeline, counter drift vs
        the embedded same-key baseline, per-executor map."""
        from ..obs.blackbox import load_bundle

        manifest = load_bundle(self.bundles, bid)
        if manifest is None:
            raise KeyError(bid)
        from ..obs.diagnose import render_postmortem

        report = render_postmortem(self.bundles, bid)
        body = (f"<p><a href='/bundles'>&larr; bundles</a></p>"
                f"<pre>{_esc(report)}</pre>")
        return _page(f"Bundle {bid}", body)

    def _profiles(self) -> bytes:
        """Flight-recorder fingerprint list (obs/history.ProfileStore):
        one row per plan fingerprint with its stored-run count — the
        durable 'same query across restarts' view the in-memory SQL tab
        cannot give."""
        import time as _time

        rows = []
        fps = self.profiles.fingerprints()
        for fp, ent in sorted(fps.items(),
                              key=lambda kv: -kv[1]["last_ts"]):
            age = _time.time() - ent["last_ts"] if ent["last_ts"] else 0
            rows.append(
                f"<tr><td><a href='/profile?fp={fp}'>{_esc(fp)}</a></td>"
                f"<td>{_esc(ent['detail'])[:100]}</td>"
                f"<td>{ent['profiles']}</td>"
                f"<td>{age:.0f}s ago</td></tr>")
        body = ("<p><a href='/'>&larr; applications</a></p>"
                "<table><tr><th>Plan fingerprint</th><th>Query</th>"
                "<th>Stored runs</th><th>Last run</th></tr>"
                + "".join(rows) + "</table>")
        return _page("Query flight recorder", body)

    def _profile(self, fp: str) -> bytes:
        """One fingerprint's stored runs: wall/launches/compiles/retries
        per profile plus the recorded tier decision and findings — the
        regression gate's evidence trail, rendered."""
        profs = self.profiles.profiles_for_fingerprint(fp)
        if not profs:
            raise KeyError(fp)
        parts = [f"<p><a href='/profiles'>&larr; fingerprints</a></p>"
                 f"<p>Query: <b>{_esc(profs[-1].get('detail'))}</b><br>"
                 f"query key: {_esc(profs[-1].get('query_key'))}</p>",
                 "<table><tr><th>ts</th><th>wall ms</th>"
                 "<th>launches (by kind)</th><th>compiles</th>"
                 "<th>tier</th><th>retry/fault counters</th>"
                 "<th>HBM peak</th><th>findings</th></tr>"]
        for p in profs:
            kinds = ", ".join(f"{k}:{v}" for k, v in
                              (p.get("launches_by_kind") or {}).items())
            tier = (p.get("tier") or {}).get("tier", "")
            if (p.get("tier") or {}).get("degraded"):
                tier += " (degraded)"
            ctrs = ", ".join(f"{k.split('.')[-1]}:{v}" for k, v in
                             (p.get("counters") or {}).items())
            finds = "; ".join(f"[{f.get('severity')}] {f.get('kind')}"
                              for f in (p.get("findings") or []))
            if p.get("wasted"):
                finds = (finds + "; " if finds else "") + \
                    f"{len(p['wasted'])} wasted attempt(s)"
            parts.append(
                f"<tr><td>{p.get('ts')}</td>"
                f"<td>{p.get('wall_ms')}</td><td>{_esc(kinds)}</td>"
                f"<td>{p.get('compiles')}</td><td>{_esc(tier)}</td>"
                f"<td>{_esc(ctrs)}</td>"
                f"<td>{(p.get('hbm') or {}).get('peak') or ''}</td>"
                f"<td>{_esc(finds)}</td></tr>")
        parts.append("</table>")
        return _page(f"Profiles — {fp}", "".join(parts))

    def _app(self, app: str) -> bytes:
        events = self.reader.load(app)
        rows = []
        n = 0
        for e in events:
            if e["event"] not in ("querySucceeded", "queryFailed"):
                continue
            ok = e["event"] == "querySucceeded"
            cls = "ok" if ok else "fail"
            first_plan_line = (e.get("plan") or "").strip().splitlines()
            desc = first_plan_line[0] if first_plan_line \
                else e.get("query_id", "")
            rows.append(
                f"<tr><td><a href='/query?id={app}&n={n}'>{n}</a></td>"
                f"<td class='{cls}'>{'OK' if ok else 'FAILED'}</td>"
                f"<td>{_esc(desc)[:120]}</td>"
                f"<td>{e.get('duration_ms') or 0:.1f}</td></tr>")
            n += 1
        body = (f"<p><a href='/'>&larr; applications</a></p>"
                "<table><tr><th>#</th><th>Status</th><th>Query</th>"
                "<th>ms</th></tr>" + "".join(rows) + "</table>")
        return _page(f"Application {app}", body)

    def _query(self, app: str, n: int) -> bytes:
        events = self.reader.load(app)
        finished = [e for e in events
                    if e["event"] in ("querySucceeded", "queryFailed")]
        e = finished[n]
        parts = [f"<p><a href='/app?id={app}'>&larr; queries</a></p>"]
        dur = e.get("duration_ms")
        parts.append(f"<p>Status: <b>{_esc(e['event'])}</b>"
                     + (f" &middot; {dur:.1f} ms" if dur else "") + "</p>")
        phases = e.get("phases")
        if phases:
            parts.append("<h2>Phases</h2><table><tr><th>Phase</th>"
                         "<th>ms</th></tr>")
            for k, v in phases.items():  # phase_times are seconds
                parts.append(f"<tr><td>{_esc(k)}</td>"
                             f"<td>{float(v) * 1000:.2f}</td></tr>")
            parts.append("</table>")
        graph = e.get("plan_graph")
        if graph:
            # SparkPlanGraph role: indented operator tree with
            # per-operator SQLMetrics (rows / inclusive ms / batches /
            # attributed kernel launches + compile-ms), whole-stage
            # fused-member re-attribution rows, and the AQE annotations
            parts.append("<h2>Plan graph</h2><table>"
                         "<tr><th style='text-align:left'>Operator</th>"
                         "<th>rows</th><th>ms</th><th>batches</th>"
                         "<th>launches</th><th>compile ms</th></tr>")
            for nd in graph:
                pad = "&nbsp;" * 4 * int(nd.get("depth") or 0)
                rows = nd.get("rows")
                if rows is not None and not nd.get("rows_exact", True):
                    rows = f"&ge;{rows}"  # partial count (mask pull failed)
                ms = nd.get("ms")
                launches = nd.get("launches") or {}
                ls = ", ".join(f"{k}:{v}"
                               for k, v in sorted(launches.items()))
                detail = _esc(str(nd.get("detail") or ""))[:140]
                parts.append(
                    f"<tr><td style='text-align:left'>{pad}"
                    f"<b>{_esc(nd.get('op') or '')}</b> "
                    f"<span style='color:#888'>{detail}</span></td>"
                    f"<td>{'' if rows is None else rows}</td>"
                    f"<td>{'' if ms is None else ms}</td>"
                    f"<td>{nd.get('batches') or ''}</td>"
                    f"<td>{_esc(ls)}</td>"
                    f"<td>{nd.get('compile_ms') or ''}</td></tr>")
                for member in nd.get("fused") or []:
                    parts.append(
                        f"<tr><td style='text-align:left'>{pad}"
                        "&nbsp;&nbsp;&#8627; <span style='color:#888'>"
                        f"fused: {_esc(member)}</span></td>"
                        "<td></td><td></td><td></td>"
                        "<td><span style='color:#888'>shares parent "
                        "dispatch</span></td><td></td></tr>")
            parts.append("</table>")
        spans = e.get("spans")
        if spans:
            # span timeline (SQL-tab execution timeline analog): phase /
            # stage / operator / partition-lane / worker spans with
            # durations — cluster mode ships worker spans back with the
            # stage results, so the cross-process timeline renders here
            # exactly like the local one
            wtracks = {sp.get("thread") for sp in spans
                       if str(sp.get("thread") or "").startswith("worker:")}
            parts.append("<h2>Span timeline</h2>")
            if wtracks:
                parts.append(f"<p>worker tracks: {len(wtracks)}</p>")
            parts.append("<table><tr>"
                         "<th style='text-align:left'>Span</th>"
                         "<th>category</th><th>thread</th><th>ms</th>"
                         "</tr>")
            top = sorted(spans, key=lambda s: -(s.get("dur_ms") or 0))[:60]
            for sp in top:
                parts.append(
                    f"<tr><td style='text-align:left'>"
                    f"{_esc(sp.get('name'))}</td>"
                    f"<td>{_esc(sp.get('cat'))}</td>"
                    f"<td>{_esc(sp.get('thread'))}</td>"
                    f"<td>{sp.get('dur_ms')}</td></tr>")
            parts.append("</table>")
        metrics = e.get("metrics")
        if metrics:
            parts.append("<h2>Metrics</h2><table><tr><th>Metric</th>"
                         "<th>Value</th></tr>")
            for k, v in metrics.items():
                parts.append(f"<tr><td>{_esc(k)}</td>"
                             f"<td>{_esc(v)}</td></tr>")
            parts.append("</table>")
        for key in ("plan", "error"):
            if e.get(key):
                parts.append(f"<h2>{key.title()}</h2>"
                             f"<pre>{_esc(e[key])}</pre>")
        return _page(f"Query {n} — {app}", "".join(parts))


class HistoryServer:
    def __init__(self, log_dir: str, port: int = 18080,
                 host: str = "127.0.0.1",
                 profile_dir: str | None = None,
                 bundle_dir: str | None = None):
        self.reader = HistoryReader(log_dir)
        profiles = None
        if profile_dir:
            from ..obs.history import ProfileStore

            profiles = ProfileStore(profile_dir)
        handler = type("Handler", (_Handler,),
                       {"reader": self.reader, "profiles": profiles,
                        "bundles": bundle_dir or None})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> "HistoryServer":
        # race-lint: ignore[bare-submit] — HTTP accept loop serving
        # COMPLETED queries' history; no live query scope exists here
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="history-server")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def main(argv=None) -> None:
    import argparse

    p = argparse.ArgumentParser(description="Spark-TPU history server")
    p.add_argument("log_dir")
    p.add_argument("--port", type=int, default=18080)
    p.add_argument("--profile-dir", default=None,
                   help="query flight recorder store "
                        "(spark.tpu.obs.profileDir) to serve at /profiles")
    p.add_argument("--bundle-dir", default=None,
                   help="diagnostic bundle ring "
                        "(spark.tpu.obs.bundleDir) to serve at /bundles")
    args = p.parse_args(argv)
    hs = HistoryServer(args.log_dir, port=args.port,
                       profile_dir=args.profile_dir,
                       bundle_dir=args.bundle_dir)
    print(f"history server on http://127.0.0.1:{hs.port}/")
    hs._httpd.serve_forever()


if __name__ == "__main__":
    main()
