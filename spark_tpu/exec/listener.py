"""Listener bus, query events, and event logging.

Role of the reference's event-sourced observability stack (SURVEY.md §5):
LiveListenerBus (core/scheduler/LiveListenerBus.scala — async queued
dispatch), QueryExecutionListener (sql/.../util/QueryExecutionListener.scala),
EventLoggingListener + JsonProtocol (core/scheduler/EventLoggingListener.scala:48,
core/util/JsonProtocol.scala:66), and the History Server's replay
(core/deploy/history/FsHistoryProvider.scala) in miniature.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Optional


@dataclass
class QueryEvent:
    event: str                  # queryStarted | querySucceeded | queryFailed
    query_id: str
    timestamp: float
    duration_ms: float | None = None
    phases: dict = field(default_factory=dict)
    plan: str = ""
    error: str | None = None
    metrics: dict = field(default_factory=dict)
    # executed-plan node list with per-operator rows/ms/batches/attributed
    # kernel launches + AQE notes (SparkPlanGraph role; rendered by the
    # live UI / history server)
    plan_graph: list = field(default_factory=list)
    # query-lifecycle spans (obs/tracing.py dicts: name/cat/ts/dur_ms/
    # thread) — the SQL-tab timeline analog, replayable from the event log
    spans: list = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(asdict(self), default=str)


class ListenerBus:
    """Async queued listener dispatch (LiveListenerBus role). Listeners are
    callables or objects with on_event(event)."""

    def __init__(self):
        self._listeners: list = []
        self._queue: "queue.Queue" = queue.Queue()
        self._thread: threading.Thread | None = None
        self._stopped = threading.Event()
        self._lock = threading.Lock()

    def register(self, listener) -> None:
        with self._lock:
            self._listeners.append(listener)
            if self._thread is None:
                # race-lint: ignore[bare-submit] — listener-bus drain:
                # events carry their query ids IN the payload; the
                # drain thread itself must stay scope-neutral
                self._thread = threading.Thread(
                    target=self._drain, daemon=True, name="listener-bus")
                self._thread.start()

    def unregister(self, listener) -> None:
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    def post(self, event: QueryEvent) -> None:
        with self._lock:
            has = bool(self._listeners)
        if has:
            self._queue.put(event)

    def _drain(self) -> None:
        while not self._stopped.is_set():
            try:
                ev = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            with self._lock:
                listeners = list(self._listeners)
            for l in listeners:
                try:
                    if callable(l):
                        l(ev)
                    else:
                        l.on_event(ev)
                except Exception:
                    pass

    def wait_empty(self, timeout: float = 5.0) -> None:
        deadline = time.time() + timeout
        while not self._queue.empty() and time.time() < deadline:
            time.sleep(0.01)
        time.sleep(0.02)  # let the in-flight event finish

    def stop(self):
        self._stopped.set()


class QueryExecutionListener:
    """Subclass with on_success / on_failure (reference API shape)."""

    def on_event(self, ev: QueryEvent) -> None:
        if ev.event == "querySucceeded":
            self.on_success(ev)
        elif ev.event == "queryFailed":
            self.on_failure(ev)

    def on_success(self, ev: QueryEvent) -> None:  # pragma: no cover
        pass

    def on_failure(self, ev: QueryEvent) -> None:  # pragma: no cover
        pass


class EventLoggingListener:
    """JSON-lines event log per session (EventLoggingListener role)."""

    def __init__(self, log_dir: str, app_id: str | None = None):
        os.makedirs(log_dir, exist_ok=True)
        self.path = os.path.join(
            log_dir, f"app-{app_id or uuid.uuid4().hex[:12]}.jsonl")
        self._lock = threading.Lock()

    def on_event(self, ev: QueryEvent) -> None:
        with self._lock:
            with open(self.path, "a") as f:
                f.write(ev.to_json() + "\n")


class HistoryReader:
    """Replay event logs into a summary (FsHistoryProvider role)."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir

    def applications(self) -> list[str]:
        return sorted(f for f in os.listdir(self.log_dir)
                      if f.endswith(".jsonl"))

    def load(self, app_file: str) -> list[dict]:
        out = []
        with open(os.path.join(self.log_dir, app_file)) as f:
            for line in f:
                if line.strip():
                    out.append(json.loads(line))
        return out

    def summary(self, app_file: str) -> dict:
        events = self.load(app_file)
        return summarize_events(events)


def summarize_events(events: list) -> dict:
    """Replay a query-event stream into an application summary: query/
    failure counts plus the observability rollups (kernel.* dispatch
    counters and per-operator metric totals aggregated over every
    query's plan graph) — the history-server/live-UI shared shape."""
    queries = [e for e in events if e["event"] == "querySucceeded"]
    failed = [e for e in events if e["event"] == "queryFailed"]
    total_ms = sum(e.get("duration_ms") or 0 for e in queries)
    # kernel.* session counters are cumulative — the last event carries
    # the application totals (kernel_cache.* are process-absolute)
    kernel = {}
    if queries:
        kernel = {k: v for k, v in (queries[-1].get("metrics") or {}).items()
                  if k.startswith(("kernel.", "kernel_cache."))}
    operators: dict = {}
    span_ms = 0.0
    worker_spans = 0
    for e in queries:
        for nd in e.get("plan_graph") or []:
            op = nd.get("op") or "?"
            o = operators.setdefault(
                op, {"rows": 0, "ms": 0.0, "launches": 0, "calls": 0})
            if nd.get("rows") is not None:
                o["rows"] += nd["rows"]
            if nd.get("ms") is not None:
                o["ms"] = round(o["ms"] + nd["ms"], 2)
            o["launches"] += sum((nd.get("launches") or {}).values())
            o["calls"] += 1
        for sp in e.get("spans") or []:
            span_ms += sp.get("dur_ms") or 0
            # cluster mode: spans shipped from worker processes land on
            # "worker:<executor>/<thread>" tracks (Tracer.ingest)
            if str(sp.get("thread") or "").startswith("worker:"):
                worker_spans += 1
    return {"queries": len(queries), "failed": len(failed),
            "total_duration_ms": total_ms, "kernel": kernel,
            "operators": operators,
            "span_count": sum(len(e.get("spans") or []) for e in queries),
            "worker_span_count": worker_spans,
            "span_total_ms": round(span_ms, 2)}
