"""External shuffle service: map outputs that survive executor loss.

Role of the reference's ExternalShuffleService
(core/deploy/ExternalShuffleService.scala + common/network-shuffle
ExternalBlockHandler.java): shuffle blocks live OUTSIDE the executor
that produced them, so losing an executor after its map stage completed
does not force recomputation — reducers fetch from the service instead.

Design: workers persist each block to a shared spill directory
(atomic tmp+rename, so a concurrent reader never sees a partial file)
in addition to their in-memory store; the service is an RpcServer over
that directory speaking the same get_block/free_shuffle protocol as the
worker block plane, so BlockClient can fall back to it transparently
when the producer is gone. On one host the directory is shared
filesystem; a multi-host deployment runs one service per host over its
local disks, exactly the reference's YARN aux-service shape.
"""

from __future__ import annotations

import os
import pickle
import threading

from ..net.transport import CHUNK_BYTES, RpcServer

_SAFE = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ"
            "0123456789._-")


def _safe_name(s: str) -> str:
    return "".join(c if c in _SAFE else "_" for c in s)


def block_path(root: str, shuffle_id: str, reduce_id: int) -> str:
    return os.path.join(root, _safe_name(shuffle_id), f"{reduce_id}.block")


def persist_block(root: str, shuffle_id: str, reduce_id: int,
                  data: bytes) -> None:
    """Atomic write: readers (the service, possibly mid-fetch) must never
    observe a partial block."""
    import uuid

    path = block_path(root, shuffle_id, reduce_id)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    # per-call unique tmp: concurrent duplicate pushes (speculation) land
    # in ONE service process, so pid alone is not unique
    tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def merged_path(root: str, shuffle_id: str, reduce_id: int) -> str:
    return os.path.join(root, _safe_name(shuffle_id),
                        f"merged.{reduce_id}.chunk")


class ExternalShuffleService:
    """Serves persisted shuffle blocks over the block-plane protocol,
    and MERGES pushed blocks per reduce partition (role of the
    reference's RemoteBlockPushResolver.java:97 — magnet push-merge):
    mappers push (shuffle, map, reduce, data); the service appends each
    block to one merged chunk file per reduce partition, deduping by
    map id (speculative duplicates are byte-identical by lineage
    determinism, so keep-first is safe); finalize closes the shuffle to
    late pushes and returns the per-partition map-id sets — the
    MergeStatus payload."""

    def __init__(self, root: str, token: str, host: str = "127.0.0.1"):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._server = RpcServer(token, host=host)
        self._server.register_stream("get_block", self._get_block)
        self._server.register_stream("get_merged", self._get_merged)
        self._server.register("free_shuffle", self._free_shuffle)
        self._server.register("put_block", self._put_block)
        self._server.register("push_block", self._push_block)
        self._server.register("finalize_merge", self._finalize_merge)
        self._server.register("ping", lambda _p: b"pong")
        self.address = ""
        self._lock = threading.Lock()
        # shuffle_id → {"finalized": bool,
        #               "index": {rid: [(map_id, length), ...]}}
        self._merges: dict[str, dict] = {}

    def start(self) -> str:
        self.address = self._server.start()
        return self.address

    def stop(self) -> None:
        self._server.stop()

    # -- handlers --------------------------------------------------------
    def _get_block(self, payload: bytes):
        sid, rid = pickle.loads(payload)
        path = block_path(self.root, sid, rid)
        if not os.path.exists(path):
            yield b"missing"
            return
        yield b"ok"
        with open(path, "rb") as f:
            while True:
                chunk = f.read(CHUNK_BYTES)
                if not chunk:
                    break
                yield chunk

    def _put_block(self, payload: bytes) -> bytes:
        """PUSH path (reference: push-based shuffle, ShuffleBlockPusher →
        RemoteBlockPushResolver.java:97): a mapper on another host ships
        its block over the network instead of relying on a shared
        filesystem. One message per block (the transport's 256 MiB frame
        cap bounds block size; a real magnet deployment would chunk)."""
        sid, rid, data = pickle.loads(payload)
        persist_block(self.root, sid, rid, data)
        return b"ok"

    # -- push-merge (magnet) handlers ------------------------------------
    def _push_block(self, payload: bytes) -> bytes:
        """Append one pushed map block to the reduce partition's merged
        chunk. Replies: ok | dup (map id already merged) | late (shuffle
        already finalized — the pusher's data is DROPPED, exactly the
        reference's stale-push handling)."""
        sid, map_id, rid, data = pickle.loads(payload)
        with self._lock:
            m = self._merges.setdefault(
                sid, {"finalized": False, "index": {}})
            if m["finalized"]:
                return b"late"
            frames = m["index"].setdefault(rid, [])
            if any(mid == map_id for mid, _ in frames):
                return b"dup"
            path = merged_path(self.root, sid, rid)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "ab") as f:
                f.write(data)
            frames.append((map_id, len(data)))
        return b"ok"

    def _finalize_merge(self, payload: bytes) -> bytes:
        sid = pickle.loads(payload)
        with self._lock:
            m = self._merges.setdefault(
                sid, {"finalized": False, "index": {}})
            m["finalized"] = True
            return pickle.dumps({rid: tuple(mid for mid, _ in frames)
                                 for rid, frames in m["index"].items()})

    def _get_merged(self, payload: bytes):
        sid, rid = pickle.loads(payload)
        with self._lock:
            m = self._merges.get(sid)
            frames = list(m["index"].get(rid, ())) if m else None
        path = merged_path(self.root, sid, rid)
        if not frames or not os.path.exists(path):
            yield b"missing"
            return
        yield pickle.dumps(frames)          # [(map_id, length), ...]
        with open(path, "rb") as f:
            while True:
                chunk = f.read(CHUNK_BYTES)
                if not chunk:
                    break
                yield chunk

    def _free_shuffle(self, payload: bytes) -> bytes:
        """Remove a shuffle's originals, merged chunks, and per-map
        block dirs (map block ids are '<sid>#m<i>', sanitized to
        '<sid>_m<i>' on disk)."""
        import shutil

        sid = pickle.loads(payload)
        safe = _safe_name(sid)
        with self._lock:
            for k in [k for k in self._merges
                      if k == sid or k.startswith(sid + "#m")]:
                self._merges.pop(k, None)
        for name in (os.listdir(self.root)
                     if os.path.isdir(self.root) else ()):
            if name == safe or name.startswith(safe + "_m"):
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)
        return b"ok"
