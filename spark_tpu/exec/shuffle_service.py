"""External shuffle service: map outputs that survive executor loss.

Role of the reference's ExternalShuffleService
(core/deploy/ExternalShuffleService.scala + common/network-shuffle
ExternalBlockHandler.java): shuffle blocks live OUTSIDE the executor
that produced them, so losing an executor after its map stage completed
does not force recomputation — reducers fetch from the service instead.

Design: workers persist each block to a shared spill directory
(atomic tmp+rename, so a concurrent reader never sees a partial file)
in addition to their in-memory store; the service is an RpcServer over
that directory speaking the same get_block/free_shuffle protocol as the
worker block plane, so BlockClient can fall back to it transparently
when the producer is gone. On one host the directory is shared
filesystem; a multi-host deployment runs one service per host over its
local disks, exactly the reference's YARN aux-service shape.
"""

from __future__ import annotations

import os
import pickle
import threading

from ..net.transport import CHUNK_BYTES, RpcServer

_SAFE = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ"
            "0123456789._-")


def _safe_name(s: str) -> str:
    return "".join(c if c in _SAFE else "_" for c in s)


def block_path(root: str, shuffle_id: str, reduce_id: int) -> str:
    return os.path.join(root, _safe_name(shuffle_id), f"{reduce_id}.block")


def persist_block(root: str, shuffle_id: str, reduce_id: int,
                  data: bytes) -> None:
    """Atomic write: readers (the service, possibly mid-fetch) must never
    observe a partial block."""
    import uuid

    path = block_path(root, shuffle_id, reduce_id)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    # per-call unique tmp: concurrent duplicate pushes (speculation) land
    # in ONE service process, so pid alone is not unique
    tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


class ExternalShuffleService:
    """Serves persisted shuffle blocks over the block-plane protocol."""

    def __init__(self, root: str, token: str, host: str = "127.0.0.1"):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._server = RpcServer(token, host=host)
        self._server.register_stream("get_block", self._get_block)
        self._server.register("free_shuffle", self._free_shuffle)
        self._server.register("put_block", self._put_block)
        self._server.register("ping", lambda _p: b"pong")
        self.address = ""
        self._lock = threading.Lock()

    def start(self) -> str:
        self.address = self._server.start()
        return self.address

    def stop(self) -> None:
        self._server.stop()

    # -- handlers --------------------------------------------------------
    def _get_block(self, payload: bytes):
        sid, rid = pickle.loads(payload)
        path = block_path(self.root, sid, rid)
        if not os.path.exists(path):
            yield b"missing"
            return
        yield b"ok"
        with open(path, "rb") as f:
            while True:
                chunk = f.read(CHUNK_BYTES)
                if not chunk:
                    break
                yield chunk

    def _put_block(self, payload: bytes) -> bytes:
        """PUSH path (reference: push-based shuffle, ShuffleBlockPusher →
        RemoteBlockPushResolver.java:97): a mapper on another host ships
        its block over the network instead of relying on a shared
        filesystem. One message per block (the transport's 256 MiB frame
        cap bounds block size; a real magnet deployment would chunk)."""
        sid, rid, data = pickle.loads(payload)
        persist_block(self.root, sid, rid, data)
        return b"ok"

    def _free_shuffle(self, payload: bytes) -> bytes:
        import shutil

        sid = pickle.loads(payload)
        shutil.rmtree(os.path.join(self.root, _safe_name(sid)),
                      ignore_errors=True)
        return b"ok"
