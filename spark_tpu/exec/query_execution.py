"""QueryExecution: the lazy phase pipeline.

Role of the reference's QueryExecution (sqlx/QueryExecution.scala —
lazyAnalyzed:192 → withCachedData → lazyOptimizedPlan:311 → lazySparkPlan:335
→ lazyExecutedPlan:353 → toRdd), with a QueryPlanningTracker-style per-phase
timing record (sqlcat/QueryPlanningTracker.scala).
"""

from __future__ import annotations

import time
from functools import cached_property

import pyarrow as pa

from ..columnar.ops import concat_batches
from ..config import MAX_RESULT_ROWS
from ..exec.context import ExecContext
from ..plan.logical import LogicalPlan
from ..physical.operators import PhysicalPlan, attrs_schema


def _unconvert(value, dt):
    """arrow python value → Literal-compatible value."""
    import datetime
    import decimal

    if isinstance(value, decimal.Decimal):
        return value
    return value


class QueryPlanningTracker:
    """Per-query rule/phase timing (reference:
    sqlcat/QueryPlanningTracker.scala — phases via measurePhase, rules
    via RuleExecutor.executeAndTrack; dumpTimeSpent role filled by
    top_rules)."""

    def __init__(self):
        self.rules: dict[str, float] = {}
        self.rule_hits: dict[str, int] = {}

    def record_rule(self, name: str, seconds: float) -> None:
        self.rules[name] = self.rules.get(name, 0.0) + seconds
        self.rule_hits[name] = self.rule_hits.get(name, 0) + 1

    def top_rules(self, n: int = 10) -> list[tuple[str, float, int]]:
        return sorted(((k, v, self.rule_hits[k])
                       for k, v in self.rules.items()),
                      key=lambda t: -t[1])[:n]


class QueryExecution:
    # flight-recorder close results (obs/history.py): populated by
    # execute() when spark.tpu.obs.profileDir is set; class defaults so
    # probes on a recorder-off (or failed-close) query read None/empty
    # instead of AttributeError
    _last_profile: dict | None = None
    _last_regressions: tuple = ()
    # black-box close result (obs/blackbox.py): bundle id captured for
    # this execution, None when nothing triggered / bundles off
    _last_bundle: str | None = None

    def __init__(self, session, logical: LogicalPlan):
        self.session = session
        self.logical = logical
        self.phase_times: dict[str, float] = {}
        self.tracker = QueryPlanningTracker()

    @property
    def _tracer(self):
        t = getattr(self.session, "tracer", None)
        return t if (t is not None and t.enabled) else None

    def _timed(self, name: str, fn):
        tracer = self._tracer
        t0 = time.perf_counter()
        if tracer is not None:
            # the execution phase roots the query's flow graph: stage →
            # partition-lane/worker spans draw arrows back to it
            with tracer.span(name, cat="phase", flow=(name == "execution")):
                out = fn()
        else:
            out = fn()
        self.phase_times[name] = time.perf_counter() - t0
        return out

    @cached_property
    def analyzed(self) -> LogicalPlan:
        return self._timed("analysis",
                           lambda: self.session._analyzer.execute(
                               self.logical, tracker=self.tracker))

    @cached_property
    def with_cached_data(self) -> LogicalPlan:
        """Cached-fragment substitution (reference: QueryExecution
        withCachedData → CacheManager.useCachedData)."""
        analyzed = self.analyzed
        use = getattr(self.session, "_use_cached", None)
        return use(analyzed) if use else analyzed

    @cached_property
    def optimized(self) -> LogicalPlan:
        plan = self.with_cached_data
        out = self._timed("optimization",
                          lambda: self.session._optimizer.execute(
                              plan, tracker=self.tracker))
        return self._materialize_scalar_subqueries(out)

    def _materialize_scalar_subqueries(self, plan: LogicalPlan) -> LogicalPlan:
        """Execute remaining (uncorrelated) scalar subqueries once and
        substitute literals (role of the reference's SubqueryExec
        materialization before the main query runs)."""
        from ..plan.subquery import ScalarSubquery
        from ..expr.expressions import Literal

        has = any(isinstance(x, ScalarSubquery)
                  for n in plan.iter_nodes()
                  for e in n.expressions()
                  for x in e.iter_nodes())
        if not has:
            return plan

        def fix_expr(e):
            if isinstance(e, ScalarSubquery):
                sub_qe = QueryExecution(self.session, e.plan)
                table = sub_qe.to_arrow()
                if table.num_rows > 1:
                    raise RuntimeError(
                        "scalar subquery returned more than one row")
                value = table.column(0)[0].as_py() if table.num_rows else None
                dt = e.dtype
                return Literal(_unconvert(value, dt), dt) \
                    if value is not None else Literal(None, dt)
            return e

        def rule(node):
            return node.transform_expressions(fix_expr)

        return plan.transform_up(rule)

    @cached_property
    def physical(self) -> PhysicalPlan:
        optimized = self.optimized
        return self._timed("planning",
                           lambda: self.session._planner().plan(optimized))

    def _history_replan(self, plan):
        """Re-enter the compile-tier chooser with a recorded prior run's
        observed shuffle volume (warm-start manifest "observed_rows").
        Returns the whole-tier wrapped plan, or None to keep `plan`
        unchanged. Recurring queries over external sources — whose
        plan-time leaf statistics are unknown — reach the whole tier
        before their first batch moves."""
        from ..config import ADAPTIVE_READMISSION

        if not self.session.conf.get(ADAPTIVE_READMISSION):
            return None
        if getattr(self.session, "_sql_cluster", None) is not None:
            return None
        from ..exec import persist_cache as _persist

        if not _persist.cache_root(self.session.conf):
            return None
        from ..physical.mesh_whole import MeshWholeQueryExec
        from ..physical.whole_query import WholeQueryExec, choose_tier

        if isinstance(plan, (WholeQueryExec, MeshWholeQueryExec)):
            return None
        try:
            fp = self.plan_fingerprint()["fingerprint"]
            seed = _persist.manifest_seed(self.session.conf, fp) or {}
        except Exception:
            return None
        observed = seed.get("observed_rows")
        if not observed:
            return None
        dec = choose_tier(plan, self.session.conf,
                          observed_rows=int(observed))
        if dec.tier != "whole":
            return None
        dec.details["history_replanned"] = True
        return WholeQueryExec(plan, dec)

    def execute(self) -> list:
        from ..config import (KERNEL_ATTRIBUTION, PROGRESS_CONSOLE,
                              PROGRESS_UPDATE_INTERVAL,
                              UI_OPERATOR_METRICS)
        from ..obs.metrics import discard_pending, finalize_plan_metrics
        from ..obs.tracing import current_query, pop_query, push_query
        from .scheduler import DAGScheduler

        plan = self.physical
        from ..physical.exchange import annotate_exchange_stat_cols

        # map-side shuffle stat accumulation is restricted to columns a
        # downstream dense decision can actually consult (the plan
        # analyzer mirrors the same reachability rule)
        annotate_exchange_stat_cols(plan)
        # recurring-query history re-planning (spark.tpu.adaptive.
        # readmission): a prior same-fingerprint run recorded its
        # observed shuffle volume in the warm-start manifest; a plan the
        # tier chooser refused for lack of plan-time statistics re-enters
        # choose_tier with the OBSERVED volume before the first batch
        # moves. Pure host work; no-op without a cache dir or history.
        history_replanned = self._history_replan(plan)
        if history_replanned is not None:
            plan = history_replanned
            self.__dict__["physical"] = plan
        # HBM admission control: with spark.tpu.memory.budget set, the
        # analyzer's memory model pre-flights predicted peak HBM and an
        # over-budget plan fails HERE — named stage, nothing dispatched —
        # instead of as an opaque XLA OOM mid-query (obs/resources.py)
        from ..obs.resources import check_memory_budget

        # the serving layer's admission pre-flight (serve/service.py)
        # already analyzed this plan — reuse its report instead of
        # paying a second whole-plan analysis on the serving hot path
        check_memory_budget(
            plan, self.session.conf,
            # a history re-plan changed the tier after the serving-layer
            # pre-flight: its report modeled the OLD plan — re-analyze
            report=None if history_replanned is not None
            else getattr(self, "_preflight_report", None),
            cluster=getattr(self.session, "_sql_cluster", None) is not None)
        # execution always runs under a query scope: collects push one in
        # to_arrow, but direct execute() callers (bench._run_blocked,
        # tests) would otherwise stream worker heartbeat deltas with no
        # query key — phantom entries the live store could never close
        qid = current_query()
        eph_token = None
        if qid is None:
            import uuid

            qid = uuid.uuid4().hex[:12]
            eph_token = push_query(qid)
        from .context import ScopedMetrics

        # ScopedMetrics: every counter this query adds lands on the
        # session totals (unchanged) AND a query-local copy — profiles
        # and EXPLAIN ANALYZE then read scope-exact per-query deltas
        # that concurrent collects cannot contaminate
        ctx = ExecContext(conf=self.session.conf,
                          metrics=ScopedMetrics(self.session._metrics),
                          block_manager=getattr(
                              self.session, "block_manager", None),
                          tracer=self._tracer,
                          live_obs=getattr(self.session, "live_obs",
                                           None),
                          query_id=qid)
        # conf values are host data — bool() here never touches device
        if bool(self.session.conf.get(  # tpulint: ignore[host-sync]
                UI_OPERATOR_METRICS)):
            ctx.plan_metrics = {}
            ctx.kernel_attribution = bool(  # tpulint: ignore[host-sync]
                self.session.conf.get(KERNEL_ATTRIBUTION))
            # stable metric keys BEFORE execution: the stage builder
            # copies exchanges and their ancestors (with_new_children),
            # and copies share __dict__, so a pre-assigned id survives
            # into the executed objects where id() would not. The walk
            # descends through a whole-query wrapper into its inner
            # plan: a runtime tier degrade re-executes the inner
            # operators directly, and their records must land under
            # keys the plan graph can render (PR 11 follow-on (d))
            from ..obs.metrics import iter_metric_nodes

            for i, n in enumerate(iter_metric_nodes(self.physical)):
                n._metric_id = i
            # AQE annotations are per-QUERY: baseline the session-level
            # adaptive counters so plan_graph reports the delta
            self._adaptive_baseline = {
                k: v for k, v in ctx.metrics.snapshot()["counters"].items()
                if k.startswith("adaptive.")}
        self._last_ctx = ctx
        if history_replanned is not None:
            ctx.metrics.add("adaptive.history_replans")
        # query flight recorder (obs/history.py): with a profile dir
        # configured, snapshot the process counters the close-time
        # profile deltas against. One conf read when off; the snapshot
        # itself is a few dict copies — pure host bookkeeping
        from ..config import OBS_PROFILE_DIR

        recorder = None
        if str(self.session.conf.get(  # tpulint: ignore[host-sync]
                OBS_PROFILE_DIR) or ""):
            # close-time deltas come from the per-query kernel ledger
            # and ScopedMetrics (scope-exact under concurrency); the
            # snapshots here remain only as the fallback for contexts
            # without a ledger, plus the wall-clock anchor
            from ..physical.compile import GLOBAL_KERNEL_CACHE as _KC

            recorder = {
                "kinds": dict(_KC.launches_by_kind),
                "misses": _KC.misses,
                "compile_ms": _KC.compile_ms,
                "disk_hit_compiles": _KC.disk_hit_compiles,
                "counters": dict(
                    self.session._metrics.snapshot()["counters"]),
                "t0": time.perf_counter()}
        # persistent-cache warm start (exec/persist_cache.py): with a
        # cache dir configured, seed this query's capacity-retry state
        # from the newest same-fingerprint manifest record, and snapshot
        # the XLA disk-cache traffic so the per-query compile.disk_*
        # metric deltas below attribute disk-served vs true cold
        # compiles. Pure host work, skipped entirely on the default
        # (cache dir empty) path.
        from ..exec import persist_cache as _persist

        persist_on = bool(  # tpulint: ignore[host-sync]
            _persist.cache_root(self.session.conf))
        disk_before = _persist.disk_counters() if persist_on else None
        if persist_on:
            try:
                ctx.persist_seed = _persist.manifest_seed(
                    self.session.conf,
                    self.plan_fingerprint()["fingerprint"])
            except Exception:
                ctx.persist_seed = None
        if getattr(self, "_rc_miss_pending", False):
            # the result-cache probe in to_arrow ran BEFORE the recorder
            # baseline above: counting the miss here (after it) lands it
            # in this run's profile counter deltas, so the executed
            # profile attributes its own result-cache miss
            self._rc_miss_pending = False
            ctx.metrics.add("result_cache.miss")
        bus = getattr(self.session, "listener_bus", None)
        cluster = getattr(self.session, "_sql_cluster", None)
        if cluster is not None:
            from .cluster_sql import ClusterDAGScheduler

            sched = ClusterDAGScheduler(
                ctx, cluster, self.session.conf.overrides(),
                listener_bus=bus)
        else:
            sched = DAGScheduler(ctx, listener_bus=bus)
        # live progress: local stages get the same in-flight feed
        # cluster tasks stream over heartbeats — a flush thread (spawned
        # through scoped_submit so the query scope rides along) samples
        # the driver-side plan_metrics into the live store while the
        # console reporter renders bars from it
        stop_flusher = None
        live = ctx.live_obs
        console_on = bool(self.session.conf.get(  # tpulint: ignore[host-sync]
            PROGRESS_CONSOLE))
        if live is not None and console_on:
            from ..obs.live import start_query_flusher

            self.session._ensure_progress_reporter()
            if ctx.plan_metrics is not None:
                stop_flusher = start_query_flusher(
                    live, ctx,
                    interval=float(  # tpulint: ignore[host-sync]
                        self.session.conf.get(PROGRESS_UPDATE_INTERVAL)))
        # per-query kernel ledger: KernelCache launch/compile events of
        # this execution window accumulate here through the query-scope
        # contextvar (copied into par_map lanes / scoped_submit pools),
        # so concurrent collects on one process read disjoint deltas
        from ..obs.metrics import (
            QueryKernelLedger, pop_query_ledger, push_query_ledger,
        )

        ctx.kernel_ledger = QueryKernelLedger()
        led_token = push_query_ledger(ctx.kernel_ledger)
        try:
            out = self._timed("execution", lambda: sched.run(plan))
        except Exception as exec_err:
            discard_pending(ctx.plan_metrics)
            # black box: a fatal execution error (chaos retry
            # exhaustion, stage-regeneration limit, ...) bundles the
            # partial evidence before the error propagates. One module
            # bool read when off; a capture failure never masks the
            # query error.
            from ..obs import blackbox

            if blackbox.ENABLED:
                try:
                    self._last_bundle = blackbox.capture_failure(
                        self, ctx, exec_err)
                except Exception:
                    ctx.metrics.add("obs.bundle_errors")
            raise
        finally:
            pop_query_ledger(led_token)
            if stop_flusher is not None:
                stop_flusher()
            if live is not None:
                live.query_finished(ctx.query_id)
            if eph_token is not None:
                pop_query(eph_token)
        # query end: resolve row counts parked during sync-free collection
        # (one memoized host read per distinct mask identity — the only
        # device read the metrics layer performs, after the last dispatch)
        finalize_plan_metrics(ctx.plan_metrics)
        if persist_on:
            # per-query XLA disk-cache traffic + the warm-start manifest
            # write (capacity outcomes of this run, keyed by the full
            # plan fingerprint). Never fails the query. The traffic
            # deltas come from THIS query's kernel ledger (the monitor
            # listener fires on the compiling thread, inside the query
            # scope) — scope-exact under concurrent collects; the
            # process-snapshot diff remains only as the fallback.
            try:
                snap = ctx.kernel_ledger.snapshot() \
                    if ctx.kernel_ledger is not None else None
                if snap is not None:
                    deltas = {"compile.disk_hit": snap["disk_hits"],
                              "compile.disk_miss": snap["disk_misses"]}
                else:
                    disk_after = _persist.disk_counters()
                    deltas = {key: disk_after[key] - disk_before[key]
                              for key in ("compile.disk_hit",
                                          "compile.disk_miss")}
                for key, d in deltas.items():
                    if d:
                        ctx.metrics.add(key, d)
                # measured shuffle volume of this run (adaptive history
                # re-planning food): host-side per-reducer counters the
                # map side already accumulated — zero device reads
                from ..physical.exchange import ShuffleExchangeExec

                observed = sum(
                    sum(n.last_stats.values())
                    for n in self.physical.iter_nodes()
                    if isinstance(n, ShuffleExchangeExec))
                _persist.record_manifest(
                    self.session.conf, self.plan_fingerprint(),
                    tier=getattr(self.physical, "decision", None)
                    and self.physical.decision.to_dict(),
                    join_caps=getattr(ctx, "persist_join_caps", None),
                    mesh_quotas=getattr(ctx, "persist_mesh_quotas", None),
                    prior=getattr(ctx, "persist_seed", None),
                    join_spans=getattr(ctx, "persist_join_spans", None),
                    observed_rows=observed or None)
            except Exception:
                ctx.metrics.add("cache.manifest_errors")
        if recorder is not None:
            # flight recorder close: assemble the QueryProfile, persist
            # it fingerprint-keyed, and regression-check against the
            # stored baseline. Runs AFTER the query's last device
            # interaction; a recorder failure must never fail the query
            from ..obs.history import close_query_profile

            try:
                self._last_profile, self._last_regressions = \
                    close_query_profile(self, ctx, recorder)
            except Exception:
                ctx.metrics.add("obs.profile_errors")
        # black-box close sweep (obs/blackbox.py): register this
        # execution for post-close triggers (the SLO verdict lands on
        # ticket release) and capture a diagnostic bundle if any trigger
        # finding was raised during the run. Runs AFTER the flight
        # recorder so the bundle embeds the fresh profile; one module
        # bool read when off, zero kernel launches always.
        from ..obs import blackbox

        if blackbox.ENABLED:
            try:
                self._last_bundle = blackbox.maybe_capture(self, ctx)
            except Exception:
                ctx.metrics.add("obs.bundle_errors")
        return out

    def plan_fingerprint(self) -> dict:
        """Canonical structural fingerprint of the executed physical
        plan (obs/history.py): the full hash + per-stage
        sub-fingerprints the persistent compile/result caches key by.
        Pure host work; memoized per QueryExecution (the physical plan
        is cached, so the fingerprint cannot drift under it)."""
        fp = getattr(self, "_plan_fingerprint", None)
        if fp is None:
            from ..obs.history import plan_fingerprint

            fp = self._plan_fingerprint = plan_fingerprint(
                self.physical, self.session.conf)
        return fp

    def to_arrow(self) -> pa.Table:
        import uuid

        from ..obs.tracing import pop_query, push_query
        from .listener import QueryEvent

        qid = uuid.uuid4().hex[:12]
        bus = getattr(self.session, "listener_bus", None)
        tracer = self._tracer
        # query-scope tag (NOT a buffer offset): every span this collect
        # records — on this thread, in par_map lanes (copied contexts),
        # or in cluster workers (tag ships with the task) — is stamped
        # with qid, so concurrent collects on one shared session produce
        # disjoint span sets
        qtoken = push_query(qid)
        t0 = time.perf_counter()
        if bus is not None:
            bus.post(QueryEvent("queryStarted", qid, time.time()))
        # persistent result cache (exec/persist_cache.py): a repeated
        # identical query — same plan fingerprint, same leaf data
        # versions — answers straight from the on-disk Arrow payload
        # with ZERO kernel launches (planning above is host-only work).
        # Shared across sessions, processes, and the cluster driver; the
        # plan analyzer's launch model mirrors this hit path exactly.
        from ..exec import persist_cache as _persist

        result_cache = None
        result_cache_key = None
        result_deps: list = []
        try:
            result_cache = _persist.result_cache_for(self.session.conf)
            if result_cache is not None:
                result_cache_key, result_deps = _persist.result_key(
                    self.physical, self.session.conf,
                    fingerprint=self.plan_fingerprint())
        except Exception:
            result_cache = None
        if result_cache is not None and result_cache_key is not None:
            cached = result_cache.lookup(result_cache_key)
            if cached is not None:
                # the executed path enforces the limit after collect;
                # the hit path must enforce it too (maxRows is NOT part
                # of the cache key — a lowered limit after the store
                # must still reject the oversized answer)
                limit = int(self.session.conf.get(  # tpulint: ignore[host-sync]
                    MAX_RESULT_ROWS))
                if cached.num_rows > limit:
                    err = RuntimeError(
                        f"result has {cached.num_rows} rows > "
                        "spark.tpu.collect.maxRows")
                    if bus is not None:
                        # the executed path's rejection posts queryFailed
                        # from its except handler — a started query must
                        # never be left without a terminal event
                        bus.post(QueryEvent(
                            "queryFailed", qid, time.time(),
                            duration_ms=(time.perf_counter() - t0) * 1000,
                            error=f"RuntimeError: {err}"))
                    pop_query(qtoken)
                    raise err
                metrics = self.session._metrics
                metrics.add("result_cache.hit")
                metrics.add("result_cache.hit_bytes",
                            int(cached.nbytes))  # tpulint: ignore[host-sync]
                if tracer is not None:
                    with tracer.span("result_cache.hit", cat="phase",
                                     args={"key": result_cache_key,
                                           "rows": cached.num_rows}):
                        pass
                parse_spans = self._consume_parse_spans()
                if bus is not None:
                    bus.post(QueryEvent(
                        "querySucceeded", qid, time.time(),
                        duration_ms=(time.perf_counter() - t0) * 1000,
                        phases=dict(self.phase_times),
                        plan=self.physical.tree_string(),
                        metrics={"result_cache.hit": 1},
                        plan_graph=[],
                        spans=(parse_spans + tracer.spans_for(qid))
                        if tracer is not None else []))
                pop_query(qtoken)
                return cached
            # counted inside execute() AFTER the recorder baseline, so
            # the executed run's profile attributes its own miss
            self._rc_miss_pending = True
        try:
            from contextlib import nullcontext

            parts = self.execute()
            with tracer.span("collect", cat="phase") if tracer is not None \
                    else nullcontext():
                batches = [b for p in parts for b in p]
                schema = attrs_schema(self.physical.output)
                if not batches:
                    from ..columnar.batch import ColumnarBatch

                    batches = [ColumnarBatch.empty(schema)]
                tables = [b.to_arrow() for b in batches]
                try:
                    # identical schemas concat fine even with duplicate
                    # output names (legal, as in the reference); permissive
                    # unify (which rejects duplicates) only for promotions
                    out = pa.concat_tables(tables)
                except pa.lib.ArrowInvalid:
                    out = pa.concat_tables(tables,
                                           promote_options="permissive")
            limit = int(self.session.conf.get(MAX_RESULT_ROWS))
            if out.num_rows > limit:
                raise RuntimeError(
                    f"result has {out.num_rows} rows > "
                    "spark.tpu.collect.maxRows")
            if result_cache is not None and result_cache_key is not None:
                # populate the result cache (host-side IPC write; the
                # flock-safe LRU evicts past maxBytes). A store failure
                # must never fail the query.
                try:
                    if result_cache.store(result_cache_key, out,
                                          result_deps):
                        self.session._metrics.add("result_cache.store")
                        self.session._metrics.add(
                            "result_cache.bytes",
                            int(out.nbytes))  # tpulint: ignore[host-sync]
                except Exception:
                    self.session._metrics.add("result_cache.errors")
            # consume parse spans on first collect even with tracing off
            # NOW — a later traced collect must not re-report them
            parse_spans = self._consume_parse_spans()
            if bus is not None:
                from ..physical.compile import GLOBAL_KERNEL_CACHE as KC

                counters = dict(
                    self.session._metrics.snapshot()["counters"])
                # process-absolute kernel cache/dispatch counters (the
                # per-query deltas live under kernel.* via the scheduler)
                counters.update(KC.counters())
                counters.update(
                    {f"rule.{name}_ms": round(sec * 1000, 3)
                     for name, sec, _ in self.tracker.top_rules(5)})
                bus.post(QueryEvent(
                    "querySucceeded", qid, time.time(),
                    duration_ms=(time.perf_counter() - t0) * 1000,
                    phases=dict(self.phase_times),
                    plan=self.physical.tree_string(),
                    metrics=counters,
                    plan_graph=self.plan_graph(),
                    spans=(parse_spans + tracer.spans_for(qid))
                    if tracer is not None else []))
            return out
        except Exception as e:
            if bus is not None:
                bus.post(QueryEvent(
                    "queryFailed", qid, time.time(),
                    duration_ms=(time.perf_counter() - t0) * 1000,
                    error=f"{type(e).__name__}: {e}"))
            raise
        finally:
            pop_query(qtoken)

    def _consume_parse_spans(self) -> list:
        """Parse spans ride the parsed plan (session.sql records them
        before this QueryExecution exists); consume ON FIRST COLLECT so a
        re-collected DataFrame does not re-report a parse that never
        ran."""
        spans = getattr(self.logical, "_parse_spans", None)
        if spans is None:
            return []
        try:
            del self.logical._parse_spans
        except AttributeError:
            pass
        return spans

    def plan_graph(self) -> list:
        """The executed plan as a node list with per-operator SQLMetrics
        (rows / inclusive ms / batches / attributed kernel launches and
        compile-ms) and AQE annotations (role of sqlx/execution/ui/
        SparkPlanGraph.scala — the UI renders this instead of re-parsing
        plan text)."""
        from ..obs.metrics import (
            finalize_plan_metrics, fused_members, iter_plan_metrics,
            metric_children, metric_key,
        )

        ctx = getattr(self, "_last_ctx", None)
        rec = getattr(ctx, "plan_metrics", None) or {}
        finalize_plan_metrics(rec)  # resolve any parked row masks
        nodes = []
        for node, depth, key, fields in iter_plan_metrics(self.physical,
                                                          rec):
            nodes.append({
                "id": key,
                "depth": depth,
                "op": node.graph_name()
                if hasattr(node, "graph_name") else type(node).__name__,
                "detail": node.simple_string()
                if hasattr(node, "simple_string") else "",
                **fields,
                "fused": fused_members(node) or None,
                "children": [metric_key(c) for c in metric_children(node)],
            })
        # AQE re-plan annotations: THIS query's delta over the session
        # counters (they are cumulative across queries)
        annotations = []
        if ctx is not None:
            base = getattr(self, "_adaptive_baseline", {})
            for k, v in ctx.metrics.snapshot()["counters"].items():
                if k.startswith("adaptive."):
                    d = v - base.get(k, 0)
                    if d:
                        annotations.append(f"{k} = {d}")
        if annotations:
            nodes.append({"id": 0, "depth": 0, "op": "AQE",
                          "detail": "; ".join(annotations),
                          "rows": None, "ms": None, "children": []})
        return nodes

    def analysis_report(self):
        """Static plan/trace analysis of the optimized physical plan:
        predicted kernel launches per batch per stage, fusion-boundary
        explanations, recompile and dtype-overflow hazards (role of the
        reference's debugCodegen, sqlx/execution/debug/package.scala).
        Pure host work — nothing executes on device."""
        from ..analysis.plan_lint import analyze_plan

        return analyze_plan(
            self.physical, self.session.conf,
            cluster=getattr(self.session, "_sql_cluster", None) is not None)

    def analyzed_report(self, warm: bool = True):
        """EXPLAIN ANALYZE: execute the query and annotate the physical
        plan with MEASURED per-operator metrics (rows, inclusive wall-ms,
        batches, attributed kernel launches + compile-ms — including
        inside whole-stage fused operators, whose single dispatch is
        re-attributed to the FuseStages members), side by side with the
        static analyzer's predictions. Drift between the two (measured
        launches ≠ predicted, runtime minRows gate decisions, capacity
        retries) is surfaced as first-class findings.

        The static model predicts one WARM run (kernels compiled,
        device-cached scans resident, device-scalar memos primed), so by
        default the query executes once to warm and the SECOND run is
        measured — the same steady-state discipline as
        tests/test_plan_analysis.py. Pass warm=False to measure the cold
        run (compile misses then show up as drift findings)."""
        from ..config import KERNEL_ATTRIBUTION, UI_OPERATOR_METRICS
        from ..obs.metrics import build_analyzed_report
        from ..physical.compile import GLOBAL_KERNEL_CACHE as KC

        # the report's whole point is per-operator annotation: force
        # metrics collection AND launch attribution for the runs EXPLAIN
        # ANALYZE itself drives, even in sessions that disable them
        # (bench-style), then restore the session's settings
        conf = self.session.conf
        forced = (UI_OPERATOR_METRICS, KERNEL_ATTRIBUTION)
        saved = {e.key: conf.overrides().get(e.key)
                 for e in forced if e.key in conf.overrides()}
        for e in forced:
            conf.set(e, True)
        prev_ctx = getattr(self, "_last_ctx", None)
        try:
            if warm:
                QueryExecution(self.session, self.logical).to_arrow()
            # prediction AFTER the warm run: with the persistent result
            # cache on, the warm run populates the entry the measured
            # run will hit, and the analyzer's result-probe mirror must
            # see the same cache state the measured run does (predicted
            # zero-launch hit == measured zero launches). Cache off:
            # ordering is irrelevant — the analysis is pure plan work.
            prediction = self.analysis_report()
            before_kinds = dict(KC.launches_by_kind)
            before_counters = dict(
                self.session._metrics.snapshot()["counters"])
            t0 = time.perf_counter()
            self.to_arrow()
            wall_ms = (time.perf_counter() - t0) * 1000
        finally:
            for e in forced:
                if e.key in saved:
                    conf.set(e, saved[e.key])
                else:
                    conf.unset(e)
        ctx = getattr(self, "_last_ctx", None)
        # a result-cache hit answers without executing: _last_ctx is then
        # stale (the warm run's, or absent) and the measured deltas fall
        # back to the zero-launch process snapshot
        fresh = ctx is not None and ctx is not prev_ctx
        ledger = getattr(ctx, "kernel_ledger", None) if fresh else None
        if ledger is not None:
            # scope-exact per-query deltas: concurrent collects on this
            # process (a serving workload) cannot contaminate them
            measured = {k: v for k, v in ledger.snapshot()["kinds"].items()
                        if v}
        else:
            after_kinds = dict(KC.launches_by_kind)
            measured = {k: v - before_kinds.get(k, 0)
                        for k, v in after_kinds.items()
                        if v != before_kinds.get(k, 0)}
        # cluster mode: the measured run's worker processes shipped their
        # own KernelCache deltas back with the stage results — measured
        # launches are DRIVER + WORKER totals, same ground truth the
        # per-operator attribution merge uses
        wkinds = getattr(ctx, "worker_kernel_kinds", None) if fresh \
            else None
        if wkinds:
            for k, v in wkinds.items():
                measured[k] = measured.get(k, 0) + v
        scoped = getattr(getattr(ctx, "metrics", None), "local_counters",
                         None) if fresh else None
        if scoped is not None:
            counter_deltas = {k: v for k, v in scoped().items() if v}
        else:
            after_counters = dict(
                self.session._metrics.snapshot()["counters"])
            counter_deltas = {k: v - before_counters.get(k, 0)
                              for k, v in after_counters.items()
                              if v != before_counters.get(k, 0)}
        # device-resource view of the measured run: the ledger's
        # per-query record (driver watermarks + worker peaks merged from
        # the shipped task obs) reconciles against the analyzer's
        # per-stage memory model inside the report
        from ..obs.resources import GLOBAL_LEDGER, device_peak_gbps

        resources = GLOBAL_LEDGER.query_record(
            getattr(ctx, "query_id", None))
        report = build_analyzed_report(
            self.physical, getattr(ctx, "plan_metrics", None),
            prediction, measured, counter_deltas, wall_ms,
            resources=resources,
            peak_gbps=device_peak_gbps(self.session.conf))
        # straggler findings the live telemetry raised during the
        # measured run surface as first-class EXPLAIN ANALYZE findings
        live = getattr(ctx, "live_obs", None)
        if live is not None:
            report.findings.extend(
                live.findings_for(getattr(ctx, "query_id", None)))
        return report

    def explain_string(self, mode: str = "formatted") -> str:
        if mode == "analysis":
            return "\n".join([
                "== Physical Plan ==", self.physical.tree_string(),
                self.analysis_report().render(),
            ])
        if mode == "analyze":
            return self.analyzed_report().render()
        parts = [
            "== Analyzed Logical Plan ==", self.analyzed.tree_string(),
            "== Optimized Logical Plan ==", self.optimized.tree_string(),
            "== Physical Plan ==", self.physical.tree_string(),
        ]
        return "\n".join(parts)
