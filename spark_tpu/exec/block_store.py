"""Unified tiered block store for cached data.

Role of the reference's BlockManager + MemoryStore/DiskStore
(core/storage/BlockManager.scala, storage/memory/MemoryStore.scala:232
putIteratorAsValues → evictBlocksToFreeSpace, storage/DiskStore.scala),
re-shaped for the XLA memory model:

- **device tier**: scan-pinned device batches (the `df.cache()` hot
  path). XLA owns HBM, so this tier governs *entries*, not allocator
  bytes: each pinned partition registers its size and LRU entries are
  dropped (device buffers freed by GC) when the device budget is hit.
- **host tier**: Arrow IPC bytes in RAM under a byte budget with LRU
  eviction to disk (MemoryStore → DiskStore flow).
- **disk tier**: spill files under a byte budget; beyond it, blocks
  DROP entirely and re-materialize from lineage on the next access —
  the RDD recompute-on-miss contract, so a cache larger than
  RAM + disk degrades instead of killing the session.

Access promotes disk blocks back to the host tier. All transitions are
counted so tests (and the UI storage page) can see evictions happen.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

from ..config import ConfigEntry, _register

CACHE_MEMORY_BUDGET = _register(ConfigEntry(
    "spark.tpu.cache.memoryBudgetBytes", 1 << 30,
    "Host-RAM bytes the unified block store may hold before LRU "
    "eviction to the disk tier (MemoryStore budget role).", int))

CACHE_DISK_BUDGET = _register(ConfigEntry(
    "spark.tpu.cache.diskBudgetBytes", 4 << 30,
    "Disk bytes the block store may hold; beyond it blocks drop and "
    "re-materialize from lineage on miss (DiskStore budget role).", int))

CACHE_DEVICE_ENTRY_BUDGET = _register(ConfigEntry(
    "spark.tpu.cache.deviceBudgetBytes", 0,
    "Device bytes of scan-pinned cached batches before LRU entries are "
    "unpinned (0 = auto: half the blocking-operator device budget).",
    int))


class _HostBlock:
    __slots__ = ("data", "nbytes")

    def __init__(self, data: bytes):
        self.data = data
        self.nbytes = len(data)


class BlockManager:
    """Session-level tiered store; thread-safe (queries may cache
    concurrently from scheduler threads)."""

    def __init__(self, conf, spill_dir: str | None = None, metrics=None):
        self.memory_budget = int(conf.get(CACHE_MEMORY_BUDGET))
        self.disk_budget = int(conf.get(CACHE_DISK_BUDGET))
        dev = int(conf.get(CACHE_DEVICE_ENTRY_BUDGET))
        if dev <= 0:
            from .memory import DEVICE_BUDGET, _auto_budget

            explicit = int(conf.get(DEVICE_BUDGET))
            dev = (explicit if explicit > 0 else _auto_budget()) // 2
        self.device_budget = dev
        self.metrics = metrics
        self._lock = threading.RLock()
        self._host: "OrderedDict[str, _HostBlock]" = OrderedDict()
        self._host_bytes = 0
        self._disk: "OrderedDict[str, tuple[str, int]]" = OrderedDict()
        self._disk_bytes = 0
        self._spill_dir = spill_dir
        self._spill_created = False
        # device tier: block_id → (owner dict, key, nbytes); owner is a
        # scan's _device_cache, entries die when popped from it
        self._device: "OrderedDict[str, tuple[dict, object, int]]" = \
            OrderedDict()
        self._device_bytes = 0

    # -- internals -------------------------------------------------------
    def _count(self, name: str, v: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.add(name, v)

    def _spill_path(self, block_id: str) -> str:
        if not self._spill_created:
            import tempfile

            self._spill_dir = tempfile.mkdtemp(
                prefix="sparktpu-blocks-",
                dir=self._spill_dir or None)
            self._spill_created = True
        safe = "".join(c if c.isalnum() or c in "._-" else "_"
                       for c in block_id)
        return os.path.join(self._spill_dir, f"{safe}.block")

    def _evict_host_until(self, incoming: int) -> None:
        """LRU host→disk until `incoming` bytes fit (evictBlocksToFreeSpace
        role). A block larger than the whole budget goes straight to
        disk — never wedge the store."""
        while self._host and \
                self._host_bytes + incoming > self.memory_budget:
            bid, blk = self._host.popitem(last=False)
            self._host_bytes -= blk.nbytes
            self._put_disk(bid, blk.data)
            self._count("cache.evictions_to_disk")

    def _put_disk(self, block_id: str, data: bytes) -> None:
        if len(data) > self.disk_budget:
            # an un-storable block must not evict everything else first
            self._count("cache.blocks_dropped")
            return
        while self._disk and \
                self._disk_bytes + len(data) > self.disk_budget:
            dropped, (path, nbytes) = self._disk.popitem(last=False)
            self._disk_bytes -= nbytes
            try:
                os.unlink(path)
            except OSError:
                pass
            self._count("cache.blocks_dropped")
        path = self._spill_path(block_id)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        self._disk[block_id] = (path, len(data))
        self._disk_bytes += len(data)

    # -- host/disk API ---------------------------------------------------
    def put(self, block_id: str, data: bytes) -> None:
        with self._lock:
            self.remove(block_id)
            if len(data) > self.memory_budget:
                self._put_disk(block_id, data)
                self._count("cache.direct_to_disk")
                return
            self._evict_host_until(len(data))
            self._host[block_id] = _HostBlock(data)
            self._host_bytes += len(data)

    def get(self, block_id: str) -> bytes | None:
        with self._lock:
            blk = self._host.get(block_id)
            if blk is not None:
                self._host.move_to_end(block_id)
                self._count("cache.host_hits")
                return blk.data
            ent = self._disk.get(block_id)
            if ent is not None:
                path, nbytes = ent
                try:
                    with open(path, "rb") as f:
                        data = f.read()
                except OSError:
                    self._disk.pop(block_id, None)
                    self._disk_bytes -= nbytes
                    self._count("cache.misses")
                    return None
                self._count("cache.disk_hits")
                # promote to the host tier (access heat)
                self._disk.pop(block_id, None)
                self._disk_bytes -= nbytes
                try:
                    os.unlink(path)
                except OSError:
                    pass
                if len(data) <= self.memory_budget:
                    self._evict_host_until(len(data))
                    self._host[block_id] = _HostBlock(data)
                    self._host_bytes += len(data)
                else:
                    self._put_disk(block_id, data)
                return data
            self._count("cache.misses")
            return None

    def remove(self, block_id: str) -> None:
        with self._lock:
            blk = self._host.pop(block_id, None)
            if blk is not None:
                self._host_bytes -= blk.nbytes
            ent = self._disk.pop(block_id, None)
            if ent is not None:
                self._disk_bytes -= ent[1]
                try:
                    os.unlink(ent[0])
                except OSError:
                    pass
            dev = self._device.pop(block_id, None)
            if dev is not None:
                owner, key, nbytes = dev
                owner.pop(key, None)
                self._device_bytes -= nbytes

    # -- device tier -----------------------------------------------------
    def pin_device(self, block_id: str, owner: dict, key,
                   nbytes: int) -> None:
        """Register a scan-pinned device entry; LRU-unpin older entries
        over budget (their device buffers free when the owner dict
        drops the reference — XLA's allocator reclaims on GC)."""
        with self._lock:
            old = self._device.pop(block_id, None)
            if old is not None:
                o_owner, o_key, o_bytes = old
                self._device_bytes -= o_bytes
                if o_key != key:        # re-pin under a new cache key:
                    o_owner.pop(o_key, None)  # release the old batches
            self._device[block_id] = (owner, key, nbytes)
            self._device_bytes += nbytes
            while len(self._device) > 1 and \
                    self._device_bytes > self.device_budget:
                _, (o, k, nb) = self._device.popitem(last=False)
                o.pop(k, None)
                self._device_bytes -= nb
                self._count("cache.device_unpinned")

    def touch_device(self, block_id: str) -> None:
        with self._lock:
            if block_id in self._device:
                self._device.move_to_end(block_id)

    # -- lifecycle -------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {"host_blocks": len(self._host),
                    "host_bytes": self._host_bytes,
                    "disk_blocks": len(self._disk),
                    "disk_bytes": self._disk_bytes,
                    "device_entries": len(self._device),
                    "device_bytes": self._device_bytes}

    def clear(self) -> None:
        with self._lock:
            for bid in list(self._host) + list(self._disk) + \
                    list(self._device):
                self.remove(bid)
            if self._spill_created and self._spill_dir:
                import shutil

                shutil.rmtree(self._spill_dir, ignore_errors=True)
                self._spill_created = False
