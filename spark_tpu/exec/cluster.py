"""Local cluster: multi-process executor backend.

Role of the reference's `local-cluster[n,cores,mem]` mode
(core/SparkContext.scala:3464 regex → core/deploy/LocalSparkCluster.scala:38):
real PROCESS boundaries on one host so distributed logic — task shipping,
executor failure, retry, excludelists — is exercised without a cluster
(SURVEY.md §4 'Multi-process distributed without a cluster').

Workers are spawned with the TPU tunnel disabled and connect back over an
authenticated localhost socket; tasks ship as cloudpickle payloads (the
ClosureCleaner/serializer role). Executor loss is detected on send/recv
failure, recorded in the HealthTracker, and the task retries on another
executor (TaskSetManager.maxFailures role).
"""

from __future__ import annotations

import os
import secrets
import subprocess
import sys
import threading
import time
from multiprocessing.connection import Client, Listener
from typing import Any, Callable

import cloudpickle

from .scheduler import ExecutorRegistry, HealthTracker


class _Worker:
    def __init__(self, proc: subprocess.Popen, conn, executor_id: str):
        self.proc = proc
        self.conn = conn
        self.executor_id = executor_id
        self.lock = threading.Lock()

    def run(self, payload: bytes) -> Any:
        with self.lock:
            self.conn.send_bytes(payload)
            status, result = self.conn.recv()
        if status == "err":
            raise RemoteTaskError(result)
        return result

    def close(self):
        try:
            self.conn.close()
        except Exception:
            pass
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()


class RemoteTaskError(RuntimeError):
    """The task itself raised on the worker (no retry — deterministic)."""


class ExecutorLostError(RuntimeError):
    pass


class LocalCluster:
    def __init__(self, num_workers: int = 2, max_task_failures: int = 3):
        self.max_task_failures = max_task_failures
        self.registry = ExecutorRegistry()
        self.health = HealthTracker(self.registry, max_failures=2)
        authkey = secrets.token_bytes(16)
        self._listener = Listener(("127.0.0.1", 0), authkey=authkey)
        addr = self._listener.address
        self._workers: dict[str, _Worker] = {}
        self._rr = 0
        self._lock = threading.Lock()
        env = dict(os.environ)
        env["PALLAS_AXON_POOL_IPS"] = ""       # no TPU tunnel in workers
        env["JAX_PLATFORMS"] = "cpu"
        env["SPARK_TPU_WORKER_KEY"] = authkey.hex()
        env["SPARK_TPU_WORKER_ADDR"] = f"{addr[0]}:{addr[1]}"
        env.setdefault("PYTHONPATH", "")
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = root + os.pathsep + env["PYTHONPATH"]
        self.authkey_hex = authkey.hex()
        for _ in range(num_workers):
            proc = subprocess.Popen(
                [sys.executable, "-m", "spark_tpu.exec.worker_main"],
                env=env)
            conn = self._listener.accept()
            # consume the handshake (the worker announces its block-server
            # address; the authoritative copy rides in each MapStatus)
            try:
                conn.recv()
            except (EOFError, OSError):
                pass
            eid = self.registry.register(host="localhost", slots=1)
            self._workers[eid] = _Worker(proc, conn, eid)

    # ------------------------------------------------------------------
    def _pick(self) -> _Worker:
        with self._lock:
            alive = [self._workers[e.executor_id]
                     for e in self.registry.alive()
                     if e.executor_id in self._workers]
            if not alive:
                raise ExecutorLostError("no alive executors")
            w = alive[self._rr % len(alive)]
            self._rr += 1
            return w

    def run_task(self, fn: Callable, *args) -> Any:
        return self.run_task_traced(fn, *args)[0]

    def run_task_traced(self, fn: Callable, *args) -> tuple:
        """Run a task; returns (result, worker) so callers can register
        which executor holds the outputs (MapOutputTracker role)."""
        payload = cloudpickle.dumps((fn, args))
        last: Exception | None = None
        for _ in range(self.max_task_failures):
            w = self._pick()
            try:
                return w.run(payload), w
            except RemoteTaskError:
                raise  # the function itself failed; retrying won't help
            except Exception as e:  # connection/process death
                last = e
                self.registry.remove(w.executor_id)  # executor lost
                w.close()
        raise ExecutorLostError(
            f"task failed after {self.max_task_failures} executor losses: "
            f"{last}")

    def map(self, fn: Callable, items) -> list:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=max(len(self._workers), 1)) as p:
            return list(p.map(lambda x: self.run_task(fn, x), items))

    def num_alive(self) -> int:
        return len(self.registry.alive())

    def stop(self):
        for w in self._workers.values():
            w.close()
        try:
            self._listener.close()
        except Exception:
            pass
