"""Cluster backend: multi-process executors over the gRPC transport.

Role of the reference's cluster scheduling backend + local-cluster test
mode (core/scheduler/cluster/CoarseGrainedSchedulerBackend.scala:372
makeOffers/:426 launchTasks; core/SparkContext.scala:3464 local-cluster
regex → core/deploy/LocalSparkCluster.scala:38): the driver runs a
control-plane RpcServer (executor registration + heartbeats), workers
dial in by ADDRESS with the cluster secret and are scheduled tasks over
their own task/block endpoint. Registration is address-based, so any
process that can reach the driver endpoint joins the same way the
reference's standalone workers do — LocalCluster merely spawns its
initial workers itself. Defaults bind 127.0.0.1 (same-host process
groups, the local-cluster test mode); a genuine multi-host deployment
passes bind_host=<reachable IP> here and in worker_env.

Tasks ship as cloudpickle payloads (the ClosureCleaner/serializer role).
Executor loss is detected on RPC failure (UNAVAILABLE ≙ Netty channel
inactive), recorded in the HealthTracker, and the task retries on
another executor (TaskSetManager.maxFailures role).
"""

from __future__ import annotations

import contextvars
import os
import pickle
import secrets
import subprocess
import sys
import threading
import time
from typing import Any, Callable

import cloudpickle

from ..net.transport import (
    RemoteRpcError, RpcClient, RpcServer, RpcUnavailableError,
)
from .scheduler import ExecutorRegistry, HealthTracker


class RemoteTaskError(RuntimeError):
    """The task itself raised on the worker (no retry — deterministic)."""


class ExecutorLostError(RuntimeError):
    pass


class _Worker:
    def __init__(self, client: RpcClient, executor_id: str, host: str,
                 pid: int | None = None,
                 proc: subprocess.Popen | None = None):
        self.client = client
        self.executor_id = executor_id
        self.host = host
        self.pid = pid
        self.proc = proc
        self.lock = threading.Lock()  # one in-flight task per slot
        self.busy = False
        self.idle_since = time.monotonic()

    def try_acquire(self) -> bool:
        if self.lock.acquire(blocking=False):
            self.busy = True
            return True
        return False

    def release(self) -> None:
        self.busy = False
        self.idle_since = time.monotonic()
        try:
            self.lock.release()
        except RuntimeError:
            pass

    def run_locked(self, payload: bytes) -> Any:
        """Execute with the slot already held by the caller."""
        raw = self.client.call("launch_task", payload)
        try:
            decoded = pickle.loads(raw)
            status, result = decoded[0], decoded[1]
        except Exception as e:
            raise RemoteTaskError(f"undecodable task reply: {e}")
        if status == "err":
            err = RemoteTaskError(result)
            # a failed stage task ships its packaged obs alongside the
            # traceback (chaos salvage) — ride it on the exception so
            # the retry loop can hand the wasted-work record upward
            if len(decoded) > 2 and decoded[2] is not None:
                err.salvaged_obs = decoded[2]
            raise err
        return result

    def run(self, payload: bytes) -> Any:
        """Acquire the slot (blocking), execute, release."""
        self.lock.acquire()
        self.busy = True
        try:
            return self.run_locked(payload)
        finally:
            self.release()

    def close(self):
        self.client.close()
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()


def worker_env(driver_addr: str, token: str,
               host_label: str = "localhost",
               bind_host: str = "127.0.0.1",
               heartbeat_interval: float | None = None) -> dict:
    """Environment for a worker process: CPU-pinned jax (workers never
    dial the TPU tunnel — the chip belongs to the driver) + driver
    coordinates. `bind_host` is the address the worker's own server
    binds AND advertises; a worker on another machine sets it to an IP
    the driver and peer workers can reach. `heartbeat_interval` sets the
    executor heartbeat/live-obs flush cadence in seconds
    (spark.tpu.heartbeat.interval)."""
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""  # no TPU tunnel in workers
    env["JAX_PLATFORMS"] = "cpu"
    env["SPARK_TPU_WORKER_KEY"] = token
    env["SPARK_TPU_DRIVER_ADDR"] = driver_addr
    env["SPARK_TPU_WORKER_HOST"] = host_label
    env["SPARK_TPU_BIND_HOST"] = bind_host
    if heartbeat_interval is not None:
        env["SPARK_TPU_HEARTBEAT_INTERVAL"] = str(heartbeat_interval)
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    return env


class LocalCluster:
    """Spawns num_workers executor processes and schedules tasks on them.
    More executors — including ones labeled as other "hosts" — may join
    at any time via the driver address + secret. With
    dynamic_allocation=True an allocation thread grows the pool when
    tasks back up behind busy executors and retires idle ones back to
    num_workers (role of core/ExecutorAllocationManager.scala:102 —
    backlog-driven scale-out, idle-timeout scale-in)."""

    def __init__(self, num_workers: int = 2, max_task_failures: int = 3,
                 bind_host: str = "127.0.0.1",
                 speculation: bool = False,
                 speculation_multiplier: float = 1.5,
                 speculation_interval: float | None = None,
                 dynamic_allocation: bool = False,
                 max_workers: int | None = None,
                 executor_idle_timeout: float = 10.0,
                 shuffle_service: bool = False,
                 push_shuffle: bool = False,
                 heartbeat_interval: float | None = None):
        self.max_task_failures = max_task_failures
        self.registry = ExecutorRegistry()
        # timed exclusion by default (excludeOnFailure semantics); the
        # SQL scheduler re-configures from session conf at query time
        self.health = HealthTracker(self.registry, max_failures=2,
                                    exclude_s=30.0)
        self.token = secrets.token_hex(16)
        self.bind_host = bind_host
        self.heartbeat_interval = heartbeat_interval
        # live-telemetry sink: executor heartbeats carry obs deltas of
        # running stage tasks; the owning session points this at its
        # LiveObs.on_heartbeat (obs/live.py). None = deltas dropped.
        self.obs_sink = None
        # straggler signal hook (obs/live.LiveObs.active_stragglers):
        # when it reports flagged tasks, speculation launches the backup
        # copy immediately instead of waiting out the duration-history
        # threshold
        self.speculation_signal = None
        # speculative execution (TaskSetManager.scala:80-88 checkSpeculatableTasks
        # role): when a task runs longer than multiplier × median of
        # completed tasks (or the fixed interval), a second copy launches
        # on another executor; first success wins. Exactly-one-commit for
        # file outputs is the OutputCommitCoordinator's job (io/commit.py).
        self.speculation = speculation
        self.speculation_multiplier = speculation_multiplier
        self.speculation_interval = speculation_interval
        self._durations: list[float] = []
        self.stats: dict[str, int] = {}
        self._workers: dict[str, _Worker] = {}
        self._rr = 0
        self._lock = threading.Lock()
        self._joined = threading.Condition(self._lock)
        self._slot_free = threading.Condition()
        self._barriers: dict[str, dict] = {}
        self._barrier_cv = threading.Condition()
        # FAIR scheduler pools (core/scheduler/Pool.scala +
        # SchedulableBuilder.scala FAIR mode): when tasks from several
        # pools contend for slots, the pool with the smallest
        # running/weight ratio is offered the next free slot
        self.pool_weights: dict[str, float] = {"default": 1.0}
        self._pool_running: dict[str, int] = {}
        self._pool_waiting: dict[str, int] = {}

        # 64 handler threads: barrier_sync PARKS a thread per waiting gang
        # member (see _on_barrier), and heartbeats must still get served
        # while a gang waits — run_barrier_job caps gangs at half this
        self._server = RpcServer(self.token, host=bind_host,
                                 max_workers=64)
        self._server.register("register_executor", self._on_register)
        self._server.register("heartbeat", self._on_heartbeat)
        self._server.register("barrier_sync", self._on_barrier)
        self.driver_addr = self._server.start()

        # external shuffle service: blocks survive executor loss
        # (exec/shuffle_service.py; ExternalShuffleService.scala role)
        self.shuffle_service = None
        self.shuffle_service_addr: str | None = None
        self._shuffle_dir: str | None = None
        self.push_shuffle = push_shuffle
        if shuffle_service or push_shuffle:
            import tempfile

            from .shuffle_service import ExternalShuffleService

            self._shuffle_dir = tempfile.mkdtemp(prefix="sparktpu-shuffle-")
            self.shuffle_service = ExternalShuffleService(
                self._shuffle_dir, self.token, host=bind_host)
            self.shuffle_service_addr = self.shuffle_service.start()

        procs = [self._spawn() for _ in range(num_workers)]
        self._await_workers(num_workers, procs)

        self.min_workers = num_workers
        self.max_workers = max_workers or num_workers * 4
        self.idle_timeout = executor_idle_timeout
        self._active_tasks = 0
        self._stopping = False
        if dynamic_allocation:
            # race-lint: ignore[bare-submit] — executor-fleet sizing
            # loop: session-lifetime, aggregates across queries
            threading.Thread(target=self._allocation_loop,
                             daemon=True).start()

    # -- control-plane handlers (run on server threads) -----------------
    def _on_register(self, payload: bytes) -> bytes:
        info = pickle.loads(payload)
        client = RpcClient(info["addr"], self.token)
        # Connect BEFORE registering: a fresh channel's first call can
        # fail UNAVAILABLE transiently while TCP/HTTP2 set up, which the
        # task path would misread as executor loss — and an unreachable
        # worker must not become a ghost registry entry.
        try:
            client.wait_ready(10)
        except Exception:
            client.close()
            raise
        eid = self.registry.register(host=info["host"], slots=1)
        with self._lock:
            self._workers[eid] = _Worker(client, eid, info["host"],
                                         pid=info.get("pid"))
            self._joined.notify_all()
        return eid.encode()

    def _on_heartbeat(self, payload: bytes) -> bytes:
        """Heartbeat = liveness + live telemetry (HeartbeatReceiver +
        the reference's executor metrics/accumulator-update channel in
        one call): the payload is a pickled {eid, obs} dict whose obs
        list carries per-task mid-stage snapshots, routed to the
        session's LiveObs. Bare-eid payloads (externally-started legacy
        workers) stay accepted."""
        try:
            msg = pickle.loads(payload)
        except Exception:
            msg = {"eid": payload.decode()}
        eid = msg["eid"]
        ok = self.registry.heartbeat(eid)
        sink = self.obs_sink
        if ok and sink is not None and (
                msg.get("obs") or msg.get("hbm") is not None
                or msg.get("metrics") is not None):
            try:
                # the sink is LiveObs.on_heartbeat, which takes the
                # executor-level resource fields too (per-executor HBM
                # occupancy, the flush-budget overflow counter, and —
                # with the metrics plane on — the worker's registry
                # counter snapshot for worker-labeled scrape series)
                sink(eid, msg.get("obs") or [],
                     hbm=msg.get("hbm"),
                     overflows=msg.get("obs_overflows"),
                     metrics=msg.get("metrics"))
            except Exception:
                # telemetry must never fail a liveness heartbeat — but a
                # sink bug must not vanish either: count every swallowed
                # error where live status can see it (a bare `pass` here
                # once hid every sink regression)
                with self._lock:
                    self.stats["heartbeat.telemetry_errors"] = \
                        self.stats.get("heartbeat.telemetry_errors", 0) + 1
                owner = getattr(sink, "__self__", None)
                if owner is not None:
                    try:
                        owner.telemetry_errors += 1
                    except Exception:
                        pass
        return b"ok" if ok else b"unknown"

    # ------------------------------------------------------------------
    def _spawn(self, host_label: str = "localhost") -> subprocess.Popen:
        env = worker_env(self.driver_addr, self.token, host_label,
                         bind_host=self.bind_host,
                         heartbeat_interval=self.heartbeat_interval)
        if self.push_shuffle:
            # push mode: blocks travel over the network to the service —
            # the cross-host deployment (no shared filesystem assumed)
            env["SPARK_TPU_SHUFFLE_PUSH_ADDR"] = self.shuffle_service_addr
        elif self._shuffle_dir:
            env["SPARK_TPU_SHUFFLE_DIR"] = self._shuffle_dir
        return subprocess.Popen(
            [sys.executable, "-m", "spark_tpu.exec.worker_main"], env=env)

    def _await_workers(self, expect: int, procs: list, timeout: float = 60.0):
        deadline = time.monotonic() + timeout
        with self._lock:
            while len(self._workers) < expect:
                rest = deadline - time.monotonic()
                if rest <= 0 or not self._joined.wait(timeout=rest):
                    raise RuntimeError(
                        f"only {len(self._workers)}/{expect} workers "
                        f"registered within {timeout}s")
        # adopt process handles BY PID (registration order ≠ spawn order;
        # a swapped handle would make _Worker.close() terminate the wrong
        # — possibly healthy — process)
        with self._lock:
            by_pid = {p.pid: p for p in procs}
            for w in self._workers.values():
                if w.proc is None and w.pid in by_pid:
                    w.proc = by_pid.pop(w.pid)

    def add_worker(self, host_label: str = "localhost") -> None:
        """Join one more executor process (dynamic allocation growth)."""
        before = len(self._workers)
        proc = self._spawn(host_label)
        self._await_workers(before + 1, [proc])

    # ------------------------------------------------------------------
    def _pick_free(self, timeout: float | None = None,
                   avoid: frozenset | set = frozenset()) -> _Worker | None:
        """ACQUIRE a free executor slot (central task queue semantics —
        TaskSchedulerImpl.resourceOffers: tasks go to whichever executor
        has a free slot, instead of binding to one at submit and queueing
        behind it, which would leave executors added by dynamic
        allocation idle). Caller must release().

        `avoid` de-prioritizes executors that already failed THIS task
        (TaskSetManager's per-task attempt excludelist role): avoided
        executors are offered the slot only when no other executor is
        free — progress beats purity on a shrunken cluster. Executors
        excluded cluster-wide (HealthTracker window exclusion) never
        appear at all: registry.alive() filters them."""
        deadline = None if timeout is None else time.monotonic() + timeout
        override_counted = False
        while True:
            with self._lock:
                alive = [self._workers[e.executor_id]
                         for e in self.registry.alive()
                         if e.executor_id in self._workers]
                if not alive:
                    # distinguish a DEAD cluster from a fully-EXCLUDED
                    # one: excluded executors are alive processes that
                    # will rejoin at their re-inclusion horizon —
                    # failing the query with 'no alive executors' would
                    # be both misleading and a needless abort. Schedule
                    # on excluded executors rather than starve (the
                    # reference aborts the task set here; overriding
                    # keeps liveness and the override is counted).
                    registered = [self._workers[e.executor_id]
                                  for e in self.registry.registered()
                                  if e.executor_id in self._workers]
                    if not registered:
                        raise ExecutorLostError("no alive executors")
                    alive = registered
                    if not override_counted:
                        override_counted = True
                        self.stats["exclusion_overridden"] = \
                            self.stats.get("exclusion_overridden", 0) + 1
                order = alive[self._rr % len(alive):] + \
                    alive[:self._rr % len(alive)]
                self._rr += 1
            if avoid:
                order = [w for w in order
                         if w.executor_id not in avoid] + \
                        [w for w in order if w.executor_id in avoid]
            for w in order:
                if w.try_acquire():
                    return w
            if deadline is not None and time.monotonic() >= deadline:
                return None
            with self._slot_free:
                self._slot_free.wait(timeout=0.05)

    def set_pool_weight(self, pool: str, weight: float) -> None:
        self.pool_weights[pool] = float(weight)

    def run_task(self, fn: Callable, *args, pool: str = "default") -> Any:
        return self.run_task_traced(fn, *args, pool=pool)[0]

    def run_task_traced(self, fn: Callable, *args,
                        pool: str = "default", task_key=None,
                        on_failed_attempt: Callable | None = None) -> tuple:
        """Run a task; returns (result, worker) so callers can register
        which executor holds the outputs (MapOutputTracker role).
        `task_key` identifies the task to the live straggler signal
        (cluster_sql passes (shuffle id, map id)) so speculation scopes
        its decision to THIS task. `on_failed_attempt(executor_id, err,
        salvaged_obs)` is invoked (best-effort) for every attempt the
        retry loop absorbs — transient task failures and executor
        losses — so the caller can record the wasted work the failed
        attempt's salvaged obs describes."""
        payload = cloudpickle.dumps((fn, args))
        with self._lock:
            self._active_tasks += 1
        try:
            return self._run_with_retries(payload, pool, task_key,
                                          on_failed_attempt)
        finally:
            with self._lock:
                self._active_tasks -= 1

    def _pool_turn(self, pool: str) -> bool:
        """FAIR arbitration: this pool may take the next slot iff no
        contending pool (one with waiters) has a smaller
        running/weight share."""
        with self._lock:
            my = self._pool_running.get(pool, 0) / \
                self.pool_weights.get(pool, 1.0)
            for p, waiting in self._pool_waiting.items():
                if p == pool or waiting <= 0:
                    continue
                share = self._pool_running.get(p, 0) / \
                    self.pool_weights.get(p, 1.0)
                if share < my:
                    return False
            return True

    def _is_transient_task_error(self, e: Exception) -> bool:
        """Worker-side task failures worth retrying on ANOTHER executor
        (and counting against the reporting executor's excludeOnFailure
        window): injected chaos faults and runtime resource exhaustion.
        FetchFailed is NOT one — it must reach the DAG scheduler intact
        so lineage regenerates the lost map stage. Everything else stays
        deterministic (retrying a genuine task bug elsewhere fails the
        same way and wastes an executor's failure budget)."""
        from ..utils.faults import is_transient_marker
        from .map_output import FetchFailedError

        text = str(e)
        if FetchFailedError.MARKER in text:
            return False
        return is_transient_marker(text)

    def _record_failure(self, executor_id: str, lost: bool) -> None:
        """Count a task failure / executor loss in the HealthTracker
        (window-based exclusion) and the cluster stats."""
        with self._lock:
            k = "executor_losses" if lost else "transient_task_failures"
            self.stats[k] = self.stats.get(k, 0) + 1
        try:
            self.health.record_failure(executor_id)
        except Exception:
            pass

    @staticmethod
    def _notify_failed_attempt(cb, eid: str, e: Exception) -> None:
        """Best-effort wasted-work notification — the retry path must
        never fail because the obs side-channel did."""
        if cb is None:
            return
        try:
            cb(eid, e, getattr(e, "salvaged_obs", None))
        except Exception:
            pass

    def _run_with_retries(self, payload: bytes,
                          pool: str = "default", task_key=None,
                          on_failed_attempt: Callable | None = None) -> tuple:
        last: Exception | None = None
        avoid: set = set()   # executors that already failed THIS task
        with self._lock:
            self._pool_waiting[pool] = self._pool_waiting.get(pool, 0) + 1
        waiting = True  # balances _pool_waiting on EVERY exit path
        try:
            for _ in range(self.max_task_failures):
                # fairness must be re-checked every time a slot frees: a
                # task already spinning in _pick_free would otherwise race
                # slots it is not entitled to
                w = None
                while w is None:
                    if not self._pool_turn(pool):
                        with self._slot_free:
                            self._slot_free.wait(timeout=0.05)
                        continue
                    w = self._pick_free(timeout=0.05, avoid=avoid)
                with self._lock:
                    self._pool_waiting[pool] -= 1
                    waiting = False
                    self._pool_running[pool] = \
                        self._pool_running.get(pool, 0) + 1
                try:
                    if self.speculation:
                        return self._run_speculative(payload, w, task_key)
                    try:
                        return w.run_locked(payload), w
                    finally:
                        w.release()
                        self._notify_slot_free()
                except (RemoteTaskError, RemoteRpcError) as e:
                    # only a TASK-side raise can be transient: a
                    # RemoteRpcError (oversized payload, bad auth) has
                    # RESOURCE_EXHAUSTED-shaped text but is the CALL
                    # failing deterministically, not the executor
                    if isinstance(e, RemoteTaskError) and \
                            self._is_transient_task_error(e):
                        # transient worker-side failure (injected fault /
                        # resource exhaustion): the executor is alive but
                        # suspect — count it toward exclusion and retry
                        # the task elsewhere (TaskSetManager.maxFailures).
                        # Under speculation the raiser may be the BACKUP
                        # copy's executor, stamped on the exception.
                        last = e
                        failed_eid = getattr(e, "failing_executor",
                                             w.executor_id)
                        self._record_failure(failed_eid, lost=False)
                        self._notify_failed_attempt(on_failed_attempt,
                                                    failed_eid, e)
                        avoid.add(failed_eid)
                        with self._lock:  # retry waits for a slot again
                            self._pool_waiting[pool] += 1
                            waiting = True
                        continue
                    # the task (or its payload) failed deterministically —
                    # retrying on another healthy executor won't help, and
                    # the executor that reported it is NOT dead
                    raise
                except (RpcUnavailableError, OSError) as e:
                    last = e
                    self._record_failure(w.executor_id, lost=True)
                    self._notify_failed_attempt(on_failed_attempt,
                                                w.executor_id, e)
                    self.registry.remove(w.executor_id)  # executor lost
                    avoid.add(w.executor_id)
                    w.close()
                    self._notify_slot_free()
                    with self._lock:  # retry waits for a slot again
                        self._pool_waiting[pool] += 1
                        waiting = True
                finally:
                    with self._lock:
                        self._pool_running[pool] -= 1
        finally:
            if waiting:
                with self._lock:
                    self._pool_waiting[pool] -= 1
        if last is not None and not isinstance(
                last, (RpcUnavailableError, OSError, ExecutorLostError)):
            raise last  # transient task failures exhausted the budget
        raise ExecutorLostError(
            f"task failed after {self.max_task_failures} executor losses: "
            f"{last}")

    def _notify_slot_free(self) -> None:
        with self._slot_free:
            self._slot_free.notify_all()

    # -- dynamic allocation (ExecutorAllocationManager.scala:102) --------
    def _allocation_loop(self):
        backlog_ticks = 0
        while not self._stopping:
            time.sleep(0.5)
            alive = self.registry.alive()
            n = len(alive)
            with self._lock:
                backlog = self._active_tasks - n
            backlog_ticks = backlog_ticks + 1 if backlog > 0 else 0
            if backlog_ticks >= 2 and n < self.max_workers:
                try:
                    self.add_worker()
                    self.stats["executors_added"] = \
                        self.stats.get("executors_added", 0) + 1
                except Exception:
                    pass
                backlog_ticks = 0
            elif n > self.min_workers:
                now = time.monotonic()
                with self._lock:
                    idle = [w for e in alive
                            if (w := self._workers.get(e.executor_id))
                            is not None and not w.busy
                            and w.proc is not None
                            and now - w.idle_since > self.idle_timeout]
                if idle and len(alive) > self.min_workers:
                    w = max(idle, key=lambda x: now - x.idle_since)
                    self.registry.remove(w.executor_id)
                    with self._lock:
                        self._workers.pop(w.executor_id, None)
                    w.close()
                    self.stats["executors_retired"] = \
                        self.stats.get("executors_retired", 0) + 1

    # -- speculation -----------------------------------------------------
    def _speculation_threshold(self) -> float | None:
        if self.speculation_interval is not None:
            return self.speculation_interval
        with self._lock:
            hist = sorted(self._durations)
        if len(hist) < 3:  # not enough history to call a straggler
            return None
        return max(0.1, self.speculation_multiplier
                   * hist[len(hist) // 2])

    def _signal_flags(self, task_key) -> bool:
        """Does the live straggler signal (obs/live.py via
        cluster_sql's keyed lambda) flag THIS task? Scoping the check
        to the task key keeps one straggler from collapsing the
        speculation threshold for every in-flight task — which is also
        why a task WITHOUT a key never consumes the signal: an unkeyed
        run_task with 'is any task anywhere straggling?' semantics
        would double-launch every unrelated task the moment one
        straggler is flagged. Keyless tasks rely on the
        duration-history threshold alone."""
        sig = self.speculation_signal
        if sig is None or task_key is None:
            return False
        try:
            try:
                # host list truthiness (LiveObs findings), never device
                return bool(sig(task_key))  # tpulint: ignore[host-sync]
            except TypeError:
                return bool(sig())  # tpulint: ignore[host-sync]
        except Exception:
            return False

    def _run_speculative(self, payload: bytes, primary: _Worker,
                         task_key=None) -> tuple:
        """First-success-wins across up to two attempts. `primary`
        arrives with its slot already acquired; each attempt thread
        releases its own slot. The straggler's reply (it still completes
        eventually) is discarded; any file commits it tries are
        arbitrated by the OutputCommitCoordinator."""
        import queue

        q: queue.Queue = queue.Queue()
        in_flight = [0]

        def attempt(w: _Worker):
            t0 = time.monotonic()
            try:
                q.put(("ok", w.run_locked(payload), w,
                       time.monotonic() - t0))
            except (RemoteTaskError, RemoteRpcError) as e:
                q.put(("task_err", e, w, 0.0))
            except Exception as e:
                q.put(("lost", e, w, 0.0))
            finally:
                w.release()
                self._notify_slot_free()

        def launch(w: _Worker):
            in_flight[0] += 1
            # the attempt dispatches THIS query's task: copy the
            # caller's contextvar scope onto the runner thread so any
            # obs recorded around the RPC keeps its query attribution
            ctx = contextvars.copy_context()
            # race-lint: ignore[bare-submit] — scope propagated
            # explicitly via ctx.run on the line above
            threading.Thread(target=ctx.run, args=(attempt, w),
                             daemon=True).start()

        launch(primary)
        threshold = self._speculation_threshold()
        sig = self.speculation_signal
        first = None
        backup_launched = False
        deadline = (time.monotonic() + threshold) \
            if threshold is not None else None
        # wait for the primary: the duration-history threshold bounds
        # the wait, and the live straggler signal — polled, scoped to
        # THIS task — cuts it short the moment the task is flagged
        # mid-flight (a straggler is only ever flagged AFTER launch, so
        # a one-shot check at launch time would never fire)
        while first is None and not backup_launched:
            if deadline is None and sig is None:
                break  # no speculation trigger possible: plain wait below
            now = time.monotonic()
            if (deadline is not None and now >= deadline) or \
                    self._signal_flags(task_key):
                try:
                    backup = self._pick_free(timeout=0)
                except ExecutorLostError:
                    backup = None
                if backup is not None:
                    self.stats["speculative_launched"] = \
                        self.stats.get("speculative_launched", 0) + 1
                    launch(backup)
                backup_launched = True
                break
            step = 0.1 if sig is not None else deadline - now
            if deadline is not None:
                step = min(step, max(deadline - now, 0.0))
            try:
                first = q.get(timeout=max(step, 0.0))
            except queue.Empty:
                pass
        while True:
            kind, val, w, dur = first if first is not None else q.get()
            first = None
            in_flight[0] -= 1
            if kind == "ok":
                with self._lock:
                    self._durations.append(dur)
                if in_flight[0] > 0:
                    self.stats["speculative_wins"] = \
                        self.stats.get("speculative_wins", 0) + 1
                return val, w
            if kind == "task_err":
                # the failure may come from the BACKUP copy — stamp the
                # actually-failing executor so the retry loop's failure
                # accounting does not blame the (possibly healthy)
                # primary
                try:
                    val.failing_executor = w.executor_id
                except Exception:
                    pass
                raise val
            # executor lost: drop it; if a copy is still running, let it
            # decide the task, else surface to the retry loop
            self.registry.remove(w.executor_id)
            w.close()
            if in_flight[0] == 0:
                raise val

    # -- barrier (BarrierTaskContext.scala barrier()/allGather()) --------
    def _on_barrier(self, payload: bytes) -> bytes:
        # bid carries the epoch (barrier_id#round) — see
        # exec/barrier.py BarrierTaskContext._sync
        bid, task_id, num_tasks, message, timeout = pickle.loads(payload)
        deadline = time.monotonic() + timeout
        with self._barrier_cv:
            st = self._barriers.setdefault(
                bid, {"msgs": {}, "done": False})
            st["msgs"][task_id] = message
            if len(st["msgs"]) >= num_tasks:
                st["done"] = True
                st["out"] = [st["msgs"][t] for t in sorted(st["msgs"])]
                self._barrier_cv.notify_all()
            else:
                while not st["done"]:
                    rest = deadline - time.monotonic()
                    if rest <= 0 or not self._barrier_cv.wait(timeout=rest):
                        st["msgs"].pop(task_id, None)
                        raise TimeoutError(
                            f"barrier {bid}: {len(st['msgs'])}/"
                            f"{num_tasks} tasks after {timeout}s")
            out = st["out"]
            st["returned"] = st.get("returned", 0) + 1
            if st["returned"] >= num_tasks:
                self._barriers.pop(bid, None)
            return pickle.dumps(out)

    def alive_workers(self) -> list:
        with self._lock:
            return [self._workers[e.executor_id]
                    for e in self.registry.alive()
                    if e.executor_id in self._workers]

    def registered_workers(self) -> list:
        """Every connected worker INCLUDING excluded ones — cleanup
        paths must reach executors that exclusion removed from
        scheduling (their block stores still hold data)."""
        with self._lock:
            return [self._workers[e.executor_id]
                    for e in self.registry.registered()
                    if e.executor_id in self._workers]

    def lockwatch_edges(self) -> dict:
        """Collect each worker's lockwatch observations (order edges,
        registered slot names, guard violations) over RPC so the --race
        gate can fold executor-process lock behaviour into the same
        cross-check it runs on the driver. Unreachable workers are
        skipped — the gate asserts on who DID answer."""
        with self._lock:
            workers = list(self._workers.items())
        out: dict = {}
        for eid, w in workers:
            try:
                raw = w.client.call("lockwatch_edges", b"", timeout=15)
                out[eid] = pickle.loads(raw)
            except Exception:
                continue
        return out

    def diagnostic_state(self) -> dict:
        """Black-box fleet state pull (obs/blackbox): each worker's
        bounded post-task diagnostic ring plus its fault-registry,
        lockwatch, and metrics state, fetched over RPC ONLY while a
        diagnostic bundle is being assembled — the healthy path never
        calls this, so heartbeat payloads stay unchanged. Unreachable
        workers are skipped (the bundle records who answered)."""
        with self._lock:
            workers = list(self._workers.items())
        out: dict = {}
        for eid, w in workers:
            try:
                raw = w.client.call("diagnostic_state", b"", timeout=15)
                out[eid] = pickle.loads(raw)
            except Exception:
                continue
        return out

    def run_task_on(self, worker, fn: Callable, *args) -> Any:
        """Run on a SPECIFIC executor (barrier gangs need distinct
        executors — two gang members queued on one worker's slot would
        deadlock at the sync point)."""
        return worker.run(cloudpickle.dumps((fn, args)))

    def map(self, fn: Callable, items) -> list:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=max(len(self._workers), 1)) as p:
            return list(p.map(lambda x: self.run_task(fn, x), items))

    def num_alive(self) -> int:
        return len(self.registry.alive())

    @property
    def authkey_hex(self) -> str:
        """Cluster secret (name kept from the pipe-transport era; it is
        the auth token FetchExec ships to consumers)."""
        return self.token

    def stop(self):
        self._stopping = True
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            w.close()
        if self.shuffle_service is not None:
            self.shuffle_service.stop()
            import shutil

            shutil.rmtree(self._shuffle_dir, ignore_errors=True)
        self._server.stop()
