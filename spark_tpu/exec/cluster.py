"""Cluster backend: multi-process executors over the gRPC transport.

Role of the reference's cluster scheduling backend + local-cluster test
mode (core/scheduler/cluster/CoarseGrainedSchedulerBackend.scala:372
makeOffers/:426 launchTasks; core/SparkContext.scala:3464 local-cluster
regex → core/deploy/LocalSparkCluster.scala:38): the driver runs a
control-plane RpcServer (executor registration + heartbeats), workers
dial in by ADDRESS with the cluster secret and are scheduled tasks over
their own task/block endpoint. Registration is address-based, so any
process that can reach the driver endpoint joins the same way the
reference's standalone workers do — LocalCluster merely spawns its
initial workers itself. Defaults bind 127.0.0.1 (same-host process
groups, the local-cluster test mode); a genuine multi-host deployment
passes bind_host=<reachable IP> here and in worker_env.

Tasks ship as cloudpickle payloads (the ClosureCleaner/serializer role).
Executor loss is detected on RPC failure (UNAVAILABLE ≙ Netty channel
inactive), recorded in the HealthTracker, and the task retries on
another executor (TaskSetManager.maxFailures role).
"""

from __future__ import annotations

import os
import pickle
import secrets
import subprocess
import sys
import threading
import time
from typing import Any, Callable

import cloudpickle

from ..net.transport import (
    RemoteRpcError, RpcClient, RpcServer, RpcUnavailableError,
)
from .scheduler import ExecutorRegistry, HealthTracker


class RemoteTaskError(RuntimeError):
    """The task itself raised on the worker (no retry — deterministic)."""


class ExecutorLostError(RuntimeError):
    pass


class _Worker:
    def __init__(self, client: RpcClient, executor_id: str, host: str,
                 pid: int | None = None,
                 proc: subprocess.Popen | None = None):
        self.client = client
        self.executor_id = executor_id
        self.host = host
        self.pid = pid
        self.proc = proc
        self.lock = threading.Lock()  # one in-flight task per slot

    def run(self, payload: bytes) -> Any:
        with self.lock:
            raw = self.client.call("launch_task", payload)
        try:
            status, result = pickle.loads(raw)
        except Exception as e:
            raise RemoteTaskError(f"undecodable task reply: {e}")
        if status == "err":
            raise RemoteTaskError(result)
        return result

    def close(self):
        self.client.close()
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()


def worker_env(driver_addr: str, token: str,
               host_label: str = "localhost",
               bind_host: str = "127.0.0.1") -> dict:
    """Environment for a worker process: CPU-pinned jax (workers never
    dial the TPU tunnel — the chip belongs to the driver) + driver
    coordinates. `bind_host` is the address the worker's own server
    binds AND advertises; a worker on another machine sets it to an IP
    the driver and peer workers can reach."""
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""  # no TPU tunnel in workers
    env["JAX_PLATFORMS"] = "cpu"
    env["SPARK_TPU_WORKER_KEY"] = token
    env["SPARK_TPU_DRIVER_ADDR"] = driver_addr
    env["SPARK_TPU_WORKER_HOST"] = host_label
    env["SPARK_TPU_BIND_HOST"] = bind_host
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    return env


class LocalCluster:
    """Spawns num_workers executor processes and schedules tasks on them.
    More executors — including ones labeled as other "hosts" — may join
    at any time via the driver address + secret."""

    def __init__(self, num_workers: int = 2, max_task_failures: int = 3,
                 bind_host: str = "127.0.0.1"):
        self.max_task_failures = max_task_failures
        self.registry = ExecutorRegistry()
        self.health = HealthTracker(self.registry, max_failures=2)
        self.token = secrets.token_hex(16)
        self.bind_host = bind_host
        self._workers: dict[str, _Worker] = {}
        self._rr = 0
        self._lock = threading.Lock()
        self._joined = threading.Condition(self._lock)

        self._server = RpcServer(self.token, host=bind_host)
        self._server.register("register_executor", self._on_register)
        self._server.register("heartbeat", self._on_heartbeat)
        self.driver_addr = self._server.start()

        procs = [self._spawn() for _ in range(num_workers)]
        self._await_workers(num_workers, procs)

    # -- control-plane handlers (run on server threads) -----------------
    def _on_register(self, payload: bytes) -> bytes:
        info = pickle.loads(payload)
        client = RpcClient(info["addr"], self.token)
        # Connect BEFORE registering: a fresh channel's first call can
        # fail UNAVAILABLE transiently while TCP/HTTP2 set up, which the
        # task path would misread as executor loss — and an unreachable
        # worker must not become a ghost registry entry.
        try:
            client.wait_ready(10)
        except Exception:
            client.close()
            raise
        eid = self.registry.register(host=info["host"], slots=1)
        with self._lock:
            self._workers[eid] = _Worker(client, eid, info["host"],
                                         pid=info.get("pid"))
            self._joined.notify_all()
        return eid.encode()

    def _on_heartbeat(self, payload: bytes) -> bytes:
        ok = self.registry.heartbeat(payload.decode())
        return b"ok" if ok else b"unknown"

    # ------------------------------------------------------------------
    def _spawn(self, host_label: str = "localhost") -> subprocess.Popen:
        return subprocess.Popen(
            [sys.executable, "-m", "spark_tpu.exec.worker_main"],
            env=worker_env(self.driver_addr, self.token, host_label))

    def _await_workers(self, expect: int, procs: list, timeout: float = 60.0):
        deadline = time.monotonic() + timeout
        with self._lock:
            while len(self._workers) < expect:
                rest = deadline - time.monotonic()
                if rest <= 0 or not self._joined.wait(timeout=rest):
                    raise RuntimeError(
                        f"only {len(self._workers)}/{expect} workers "
                        f"registered within {timeout}s")
        # adopt process handles BY PID (registration order ≠ spawn order;
        # a swapped handle would make _Worker.close() terminate the wrong
        # — possibly healthy — process)
        with self._lock:
            by_pid = {p.pid: p for p in procs}
            for w in self._workers.values():
                if w.proc is None and w.pid in by_pid:
                    w.proc = by_pid.pop(w.pid)

    def add_worker(self, host_label: str = "localhost") -> None:
        """Join one more executor process (dynamic allocation growth)."""
        before = len(self._workers)
        proc = self._spawn(host_label)
        self._await_workers(before + 1, [proc])

    # ------------------------------------------------------------------
    def _pick(self) -> _Worker:
        with self._lock:
            alive = [self._workers[e.executor_id]
                     for e in self.registry.alive()
                     if e.executor_id in self._workers]
            if not alive:
                raise ExecutorLostError("no alive executors")
            w = alive[self._rr % len(alive)]
            self._rr += 1
            return w

    def run_task(self, fn: Callable, *args) -> Any:
        return self.run_task_traced(fn, *args)[0]

    def run_task_traced(self, fn: Callable, *args) -> tuple:
        """Run a task; returns (result, worker) so callers can register
        which executor holds the outputs (MapOutputTracker role)."""
        payload = cloudpickle.dumps((fn, args))
        last: Exception | None = None
        for _ in range(self.max_task_failures):
            w = self._pick()
            try:
                return w.run(payload), w
            except (RemoteTaskError, RemoteRpcError):
                # the task (or its payload) failed deterministically —
                # retrying on another healthy executor won't help, and
                # the executor that reported it is NOT dead
                raise
            except (RpcUnavailableError, OSError) as e:
                last = e
                self.registry.remove(w.executor_id)  # executor lost
                w.close()
        raise ExecutorLostError(
            f"task failed after {self.max_task_failures} executor losses: "
            f"{last}")

    def map(self, fn: Callable, items) -> list:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=max(len(self._workers), 1)) as p:
            return list(p.map(lambda x: self.run_task(fn, x), items))

    def num_alive(self) -> int:
        return len(self.registry.alive())

    @property
    def authkey_hex(self) -> str:
        """Cluster secret (name kept from the pipe-transport era; it is
        the auth token FetchExec ships to consumers)."""
        return self.token

    def stop(self):
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            w.close()
        self._server.stop()
