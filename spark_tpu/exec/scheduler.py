"""Stage-DAG scheduler and control plane.

Role of the reference's scheduling stack (SURVEY.md §2.1):
  * DAGScheduler (core/scheduler/DAGScheduler.scala:648 createShuffleMapStage,
    :1614 submitStage, :1831 submitMissingTasks): the plan DAG is cut into
    stages at exchange boundaries; parents run before children; a failed
    stage retries up to spark.stage.maxAttempts.
  * TaskScheduler/TaskSetManager (core/scheduler/TaskSchedulerImpl.scala,
    TaskSetManager.scala): per-stage task sets with per-task retry.
  * Executor registry + HeartbeatReceiver (core/HeartbeatReceiver.scala) and
    HealthTracker (core/scheduler/HealthTracker.scala:52): failure detection
    and excludelists for the multi-host backend.
  * BarrierCoordinator (core/BarrierCoordinator.scala): gang-sync for SPMD
    stages — on a TPU mesh every pjit program is already gang-scheduled, so
    the barrier is only needed for host-side phases.

Local mode runs stages in-process (a stage = the maximal exchange-free
physical subtree; partitions already execute as device programs inside it).
The control-plane classes are the contract for the multi-host DCN backend.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..physical.operators import PhysicalPlan
from .context import ExecContext


# ---------------------------------------------------------------------------
# Concurrent partition dispatch
# ---------------------------------------------------------------------------

def par_map(fn: Callable, items: list, workers: int) -> list:
    """Run `fn` over `items` on up to `workers` threads, preserving order.

    The async dispatch plane for partition-granular operator work: XLA
    dispatch is asynchronous, so a Python thread per partition keeps the
    device queue fed across partitions instead of round-tripping host →
    device → host between every launch (role of the reference's task-slot
    parallelism inside one executor). Threads are ephemeral daemons striding
    over the item list — no pool to leak, deterministic output order, first
    exception re-raised like the serial loop would. Each lane runs inside
    a copy of the caller's contextvars context so the obs/ kernel-
    attribution scope (the operator that called par_map) follows the
    work onto the lane threads."""
    n = len(items)
    if n <= 1 or workers <= 1:
        return [fn(x) for x in items]
    import contextvars

    w = min(workers, n)
    out: list = [None] * n
    errors: list = []

    def run(lane: int) -> None:
        for i in range(lane, n, w):
            if errors:
                return
            try:
                out[i] = fn(items[i])
            except BaseException as e:  # propagate to caller, stop lanes
                errors.append(e)
                return

    # one context copy per lane: a Context cannot be entered concurrently
    contexts = [contextvars.copy_context() for _ in range(w)]
    threads = [threading.Thread(target=contexts[k].run, args=(run, k),
                                daemon=True, name=f"tpu-dispatch-{k}")
               for k in range(w)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return out


# ---------------------------------------------------------------------------
# Stage graph
# ---------------------------------------------------------------------------

@dataclass
class Stage:
    stage_id: int
    root: PhysicalPlan           # subtree with exchanges as leaves
    parents: list["Stage"] = field(default_factory=list)
    attempts: int = 0
    result: list | None = None   # materialized partitions

    def __hash__(self):
        return self.stage_id


def build_stage_graph(plan: PhysicalPlan) -> tuple[Stage, list[Stage]]:
    """Cut the physical plan at exchange boundaries
    (DAGScheduler.createShuffleMapStage role). Each stage's root is an
    exchange (shuffle/broadcast "map stage") or the result subtree; nested
    exchanges become _StageOutput leaves wired to parent stages."""
    from ..physical.exchange import BroadcastExchangeExec, ShuffleExchangeExec

    counter = [0]
    stages: list[Stage] = []

    def convert(node: PhysicalPlan, parent_list: list[Stage]) -> PhysicalPlan:
        if isinstance(node, (ShuffleExchangeExec, BroadcastExchangeExec)):
            sub_parents: list[Stage] = []
            new_child = convert(node.child, sub_parents)
            counter[0] += 1
            st = Stage(counter[0], node.with_new_children([new_child]),
                       sub_parents)
            stages.append(st)
            parent_list.append(st)
            return _StageOutput(st, node.output)
        return node.map_children(lambda c: convert(c, parent_list))

    root_parents: list[Stage] = []
    root_plan = convert(plan, root_parents)
    counter[0] += 1
    result_stage = Stage(counter[0], root_plan, root_parents)
    stages.append(result_stage)
    return result_stage, stages


class _StageOutput(PhysicalPlan):
    """Leaf standing for a parent stage's materialized output."""

    child_fields = ()

    def __init__(self, stage: Stage, attrs):
        self.stage = stage
        self.attrs = attrs

    @property
    def output(self):
        return self.attrs

    def output_partitioning(self):
        from ..physical.partitioning import UnknownPartitioning

        n = len(self.stage.result) if self.stage.result is not None else 1
        return UnknownPartitioning(n)

    def execute(self, ctx):
        assert self.stage.result is not None, \
            f"parent stage {self.stage.stage_id} not materialized"
        return self.stage.result

    def simple_string(self):
        return f"StageOutput(#{self.stage.stage_id})"


def _stage_leaves(root: PhysicalPlan) -> list["_StageOutput"]:
    return [n for n in root.iter_nodes() if isinstance(n, _StageOutput)]


def _reachable_stages(result_stage: Stage) -> list[Stage]:
    """Stages transitively referenced from the result stage via
    _StageOutput leaves (replanning can orphan stages; orphans never run)."""
    seen: dict[int, Stage] = {}
    work = [result_stage]
    while work:
        st = work.pop()
        if st.stage_id in seen:
            continue
        seen[st.stage_id] = st
        for leaf in _stage_leaves(st.root):
            work.append(leaf.stage)
    return list(seen.values())


def _build_side_stage_ids(stages: list[Stage], done: set[int]) -> set[int]:
    """Stage ids feeding the build (right) side of a not-yet-broadcast
    hash join — materializing those first gives AQE demotion its shot."""
    from ..physical.operators import HashJoinExec

    build: list[Stage] = []
    for st in stages:
        if st.stage_id in done:
            continue
        for n in st.root.iter_nodes():
            if isinstance(n, HashJoinExec) and not n.is_broadcast and \
                    isinstance(n.right, _StageOutput):
                build.append(n.right.stage)
    # close over ancestors: the whole build-side chain runs before any
    # probe-side shuffle
    out: set[int] = set()
    while build:
        st = build.pop()
        if st.stage_id in out:
            continue
        out.add(st.stage_id)
        build.extend(leaf.stage for leaf in _stage_leaves(st.root))
    return out


class DAGScheduler:
    """Runs a stage graph with per-stage retry (stage = unit of recovery;
    deterministic re-execution replays the subtree, the lineage property
    the reference relies on)."""

    def __init__(self, ctx: ExecContext, max_attempts: int = 2,
                 listener_bus=None):
        self.ctx = ctx
        self.max_attempts = max_attempts
        self.bus = listener_bus

    def run(self, plan: PhysicalPlan) -> list:
        from ..physical.compile import GLOBAL_KERNEL_CACHE

        kc_before = GLOBAL_KERNEL_CACHE.counters()
        try:
            return self._run(plan)
        finally:
            # per-run kernel dispatch/cache deltas into the query metrics
            # (satellite of SQLMetrics: dispatch-count regressions surface
            # in listener snapshots and BENCH output)
            for k, v in GLOBAL_KERNEL_CACHE.counters().items():
                d = round(v - kc_before.get(k, 0))
                if d:
                    self.ctx.metrics.add(f"kernel.{k.split('.', 1)[1]}", d)

    def _run(self, plan: PhysicalPlan) -> list:
        result_stage, stages = build_stage_graph(plan)
        done: set[int] = set()

        tracer = getattr(self.ctx, "tracer", None)

        def run_stage(stage: Stage) -> None:
            last_err: Exception | None = None
            for attempt in range(self.max_attempts):
                stage.attempts = attempt + 1
                try:
                    self._post("stageSubmitted", stage)
                    t0 = time.perf_counter()
                    if tracer is not None:
                        # flow=True links execution phase → stage → lane
                        # spans as Perfetto flow arrows in the export
                        with tracer.span(f"stage-{stage.stage_id}",
                                         cat="stage",
                                         args={"attempt": attempt + 1},
                                         flow=True):
                            stage.result = stage.root.execute(self.ctx)
                    else:
                        stage.result = stage.root.execute(self.ctx)
                    from ..columnar.validate import maybe_validate

                    maybe_validate(stage.result, self.ctx,
                                   f"stage-{stage.stage_id}")
                    self.ctx.metrics.add("scheduler.stages_completed")
                    self._post("stageCompleted", stage,
                               dur=(time.perf_counter() - t0) * 1000)
                    done.add(stage.stage_id)
                    return
                except Exception as e:  # deterministic retry (lineage)
                    last_err = e
                    self.ctx.metrics.add("scheduler.stage_retries")
                    self._post("stageFailed", stage, error=str(e))
            raise last_err  # noqa: B904

        from ..physical.adaptive import (
            aqe_replanning_enabled, install_runtime_filters, maybe_readmit,
            replan_stages,
        )

        adaptive = aqe_replanning_enabled(self.ctx)

        # iterative ready-set loop (AdaptiveSparkPlanExec.scala:301 role):
        # materialize one ready stage at a time, re-plan the remainder with
        # observed sizes after each completion; stages the re-plan inlined
        # or replaced drop out of the reachable set and never run
        while result_stage.stage_id not in done:
            needed = _reachable_stages(result_stage)
            ready = [st for st in needed
                     if st.stage_id not in done
                     and all(leaf.stage.stage_id in done
                             for leaf in _stage_leaves(st.root))]
            if not ready:
                raise RuntimeError("stage graph stalled (cycle?)")
            # potential broadcast build sides first so a small side can
            # demote the join before the probe shuffle runs
            if adaptive:
                build_ids = _build_side_stage_ids(needed, done)
                ready.sort(key=lambda s: (s.stage_id not in build_ids,
                                          s.stage_id))
            st = ready[0]
            run_stage(st)
            if st is not result_stage:
                if adaptive:
                    replan_stages(needed, done, self.ctx)
                # spark.tpu.adaptive.* family (each self-gating): push
                # materialized build-side key domains into unrun probe
                # shuffles, then try to collapse the remaining plan into
                # one whole-tier program with the observed sizes
                install_runtime_filters(needed, done, self.ctx)
                maybe_readmit(result_stage, done, self.ctx)
        return result_stage.result

    def _post(self, kind: str, stage: Stage, dur=None, error=None):
        if self.bus is None:
            return
        from .listener import QueryEvent

        self.bus.post(QueryEvent(
            kind, f"stage-{stage.stage_id}", time.time(),
            duration_ms=dur, error=error,
            metrics={"attempt": stage.attempts}))


# ---------------------------------------------------------------------------
# Control plane (multi-host contract)
# ---------------------------------------------------------------------------

@dataclass
class ExecutorInfo:
    executor_id: str
    host: str
    slots: int
    last_heartbeat: float = field(default_factory=time.time)
    failures: int = 0            # lifetime task-failure total (surfaced)
    excluded: bool = False       # permanent exclusion (legacy/manual)
    excluded_until: float = 0.0  # timed exclusion (excludeOnFailure)

    def is_excluded(self, now: float | None = None) -> bool:
        return self.excluded or \
            self.excluded_until > (time.time() if now is None else now)


class ExecutorRegistry:
    """Executor registration + heartbeat expiry
    (CoarseGrainedSchedulerBackend + HeartbeatReceiver roles)."""

    def __init__(self, heartbeat_timeout_s: float = 120.0):
        self.timeout = heartbeat_timeout_s
        self._executors: dict[str, ExecutorInfo] = {}
        self._lock = threading.Lock()

    def register(self, host: str, slots: int = 1) -> str:
        eid = f"exec-{uuid.uuid4().hex[:8]}"
        with self._lock:
            self._executors[eid] = ExecutorInfo(eid, host, slots)
        return eid

    def heartbeat(self, executor_id: str) -> bool:
        with self._lock:
            e = self._executors.get(executor_id)
            if e is None:
                return False  # reference: executor told to re-register
            e.last_heartbeat = time.time()
            return True

    def remove(self, executor_id: str) -> None:
        """Executor lost (process death / connection drop) — immediate
        deregistration (reference: CoarseGrainedSchedulerBackend
        RemoveExecutor)."""
        with self._lock:
            self._executors.pop(executor_id, None)

    def expire_dead(self) -> list[str]:
        now = time.time()
        dead = []
        with self._lock:
            for eid, e in list(self._executors.items()):
                if now - e.last_heartbeat > self.timeout:
                    dead.append(eid)
                    del self._executors[eid]
        return dead

    def alive(self) -> list[ExecutorInfo]:
        now = time.time()
        with self._lock:
            return [e for e in self._executors.values()
                    if not e.is_excluded(now)]

    def registered(self) -> list[ExecutorInfo]:
        """All registered executors INCLUDING excluded ones — the
        last-resort scheduling pool when exclusion would otherwise
        starve the cluster."""
        with self._lock:
            return list(self._executors.values())


class HealthTracker:
    """Executor excludelist on repeated failures (the reference's
    HealthTracker.scala:52 + TaskSetExcludelist): failures are counted
    per executor inside a sliding window; crossing `max_failures` inside
    `window_s` excludes the executor from scheduling for `exclude_s`
    seconds (timed re-inclusion — a transiently-sick executor rejoins,
    a permanently-sick one re-excludes on its next failures). Failure
    history lives here (not on ExecutorInfo), so counters survive an
    executor being removed and re-registered and are reportable after
    loss."""

    def __init__(self, registry: ExecutorRegistry,
                 max_failures: int = 2, window_s: float = 60.0,
                 exclude_s: float = 0.0, enabled: bool = True):
        self.registry = registry
        self.max_failures = max_failures
        self.window_s = window_s
        # 0.0 keeps the legacy permanent-exclusion semantics (tests and
        # callers that never configure a timeout)
        self.exclude_s = exclude_s
        self.enabled = enabled
        self._lock = threading.Lock()
        self._failures: dict[str, list[float]] = {}
        self._totals: dict[str, int] = {}
        self._excluded_until: dict[str, float] = {}
        # host-granular exclusion: when EVERY executor on one host has
        # tripped the failure window, the box itself is suspect (NIC,
        # PCIe link, thermal) — the host is excluded as a unit with the
        # same timed re-inclusion horizon as its members
        self._host_excluded_until: dict[str, float] = {}
        # on_exclude(eid, until, failures) — the cluster scheduler hooks
        # this to surface exclusion in live status / EXPLAIN ANALYZE
        self.on_exclude = None
        # on_exclude_host(host, until, eids) — fired once per host trip
        self.on_exclude_host = None

    def configure(self, enabled: bool | None = None,
                  max_failures: int | None = None,
                  window_s: float | None = None,
                  exclude_s: float | None = None) -> None:
        if enabled is not None:
            self.enabled = enabled
        if max_failures is not None:
            self.max_failures = max_failures
        if window_s is not None:
            self.window_s = window_s
        if exclude_s is not None:
            self.exclude_s = exclude_s

    def record_failure(self, executor_id: str) -> bool:
        """Count one task failure against the executor. Returns True if
        the executor is now (or already) excluded."""
        if not self.enabled:
            return False
        now = time.time()
        with self._lock:
            times = self._failures.setdefault(executor_id, [])
            times.append(now)
            times[:] = [t for t in times if now - t <= self.window_s]
            self._totals[executor_id] = \
                self._totals.get(executor_id, 0) + 1
            total = self._totals[executor_id]
            trip = len(times) >= self.max_failures
            if trip:
                until = (now + self.exclude_s) if self.exclude_s > 0 \
                    else float("inf")
                self._excluded_until[executor_id] = until
                # the window restarts after an exclusion: re-inclusion
                # gives the executor a clean slate to prove itself
                times.clear()
        host_trip = None
        with self.registry._lock:
            e = self.registry._executors.get(executor_id)
            if e is None:
                # executor already deregistered (process death) — the
                # failure still counts toward its history
                excluded = True
            else:
                e.failures = total
                if trip:
                    if self.exclude_s > 0:
                        e.excluded_until = until
                    else:
                        e.excluded = True
                excluded = e.is_excluded()
                if trip:
                    # host-granular escalation: every executor on this
                    # host now excluded → exclude the host as a unit
                    peers = [p for p in self.registry._executors.values()
                             if p.host == e.host]
                    if peers and all(p.is_excluded(now) for p in peers):
                        horizon = until
                        for p in peers:
                            if not p.excluded:
                                # synchronized re-inclusion: the whole
                                # host rejoins at once, or not at all
                                p.excluded_until = max(
                                    p.excluded_until, horizon)
                        host_trip = (e.host, horizon,
                                     [p.executor_id for p in peers])
        if host_trip is not None:
            host, horizon, eids = host_trip
            with self._lock:
                # one event per trip: an already-excluded host extending
                # its horizon re-fires only past the prior horizon
                if self._host_excluded_until.get(host, 0.0) >= horizon:
                    host_trip = None
                else:
                    self._host_excluded_until[host] = horizon
        if trip and self.on_exclude is not None:
            try:
                self.on_exclude(executor_id,
                                self._excluded_until[executor_id], total)
            except Exception:
                pass    # surfacing must never fail the scheduling path
        if host_trip is not None and self.on_exclude_host is not None:
            try:
                self.on_exclude_host(*host_trip)
            except Exception:
                pass
        return excluded

    def failure_count(self, executor_id: str) -> int:
        with self._lock:
            return self._totals.get(executor_id, 0)

    def reset(self) -> None:
        """Clear all failure history and lift every exclusion (the
        operator's 'clear the excludelist' action)."""
        with self._lock:
            self._failures.clear()
            self._totals.clear()
            self._excluded_until.clear()
            self._host_excluded_until.clear()
        with self.registry._lock:
            for e in self.registry._executors.values():
                e.excluded = False
                e.excluded_until = 0.0
                e.failures = 0

    def excluded(self) -> dict[str, float]:
        """Currently-excluded executors → re-inclusion time."""
        now = time.time()
        with self._lock:
            return {eid: until
                    for eid, until in self._excluded_until.items()
                    if until > now}

    def excluded_hosts(self) -> dict[str, float]:
        """Currently-excluded hosts → re-inclusion time."""
        now = time.time()
        with self._lock:
            return {host: until
                    for host, until in self._host_excluded_until.items()
                    if until > now}


class BarrierCoordinator:
    """allGather/barrier for gang-scheduled host phases
    (core/BarrierTaskContext.scala barrier()/allGather())."""

    def __init__(self, num_tasks: int):
        self.num_tasks = num_tasks
        self._barrier = threading.Barrier(num_tasks)
        self._messages: dict[int, object] = {}
        self._lock = threading.Lock()

    def barrier(self, task_id: int, timeout: float = 60.0) -> None:
        self._barrier.wait(timeout)

    def all_gather(self, task_id: int, message,
                   timeout: float = 60.0) -> list:
        with self._lock:
            self._messages[task_id] = message
        self._barrier.wait(timeout)
        with self._lock:
            out = [self._messages[i] for i in sorted(self._messages)]
        self._barrier.wait(timeout)
        with self._lock:
            self._messages.pop(task_id, None)
        return out
