"""Barrier execution: gang-synchronised host-side stages.

Role of the reference's barrier mode (core/rdd/RDDBarrier.scala:33,
core/BarrierTaskContext.scala barrier():328 / allGather(), coordinated
by core/BarrierCoordinator.scala on the driver). On a TPU mesh every
pjit program is already gang-scheduled SPMD — the barrier API exists
for HOST phases (data loading, shuffle rendezvous, parameter servers)
that must sync across executor processes. The sync itself is a driver
RPC: all tasks of a stage post their message and block until the full
gang arrives, exactly the reference's RequestToSync/allGather protocol.
"""

from __future__ import annotations

import pickle
import uuid

from ..net.transport import RpcClient


class BarrierTaskContext:
    """Handle given to each task of a barrier stage."""

    def __init__(self, driver_addr: str, token: str, barrier_id: str,
                 task_id: int, num_tasks: int, timeout: float = 60.0):
        self.barrier_id = barrier_id
        self.task_id = task_id
        self.num_tasks = num_tasks
        self.timeout = timeout
        self._driver_addr = driver_addr
        self._token = token
        self._round = 0  # each sync is its own epoch server-side

    def _sync(self, message) -> list:
        # the round number keys a FRESH server-side rendezvous per sync:
        # a fast task entering sync N+1 while a slow one is still
        # returning from sync N must not collide with (or reset) N's
        # state (reference: BarrierCoordinator's ContextBarrierState
        # tracks barrierEpoch the same way)
        key = f"{self.barrier_id}#{self._round}"
        self._round += 1
        with RpcClient(self._driver_addr, self._token) as c:
            raw = c.call("barrier_sync", pickle.dumps(
                (key, self.task_id, self.num_tasks, message,
                 self.timeout)), timeout=self.timeout + 10)
        return pickle.loads(raw)

    def barrier(self) -> None:
        """Block until every task of the stage reaches this call."""
        self._sync(None)

    def allGather(self, message) -> list:
        """Block until all tasks post, then return all messages ordered
        by task id."""
        return self._sync(message)


def _barrier_task(fn_payload: bytes, driver_addr: str, token: str,
                  barrier_id: str, task_id: int, num_tasks: int):
    """Worker-side wrapper: rebuild the context, run the user fn."""
    import cloudpickle

    fn = cloudpickle.loads(fn_payload)
    ctx = BarrierTaskContext(driver_addr, token, barrier_id, task_id,
                             num_tasks)
    return fn(ctx)


def run_barrier_job(cluster, fn, num_tasks: int) -> list:
    """Launch fn(ctx) as a gang of num_tasks tasks, one per executor,
    all running simultaneously (RDDBarrier.mapPartitions contract: the
    whole gang or nothing). Returns results ordered by task id."""
    import cloudpickle
    from concurrent.futures import ThreadPoolExecutor

    if cluster.num_alive() < num_tasks:
        raise RuntimeError(
            f"barrier stage needs {num_tasks} executors, "
            f"{cluster.num_alive()} alive")  # reference: barrier stages
        # require slots ≥ tasks up front (SPARK-24819)
    if num_tasks > 32:
        # each waiting gang member parks one driver RPC server thread
        # (pool of 64, shared with heartbeats) — a larger gang would
        # starve the pool and never release
        raise RuntimeError("barrier gangs are limited to 32 tasks")
    bid = uuid.uuid4().hex[:12]
    payload = cloudpickle.dumps(fn)
    gang = cluster.alive_workers()[:num_tasks]

    def one(tid: int):
        # one DISTINCT executor per gang member: two members sharing a
        # worker slot would deadlock at the sync point
        return cluster.run_task_on(
            gang[tid], _barrier_task, payload, cluster.driver_addr,
            cluster.token, bid, tid, num_tasks)

    with ThreadPoolExecutor(max_workers=num_tasks) as pool:
        return list(pool.map(one, range(num_tasks)))
