"""Live web UI: the running session's queries, phases, plans, metrics.

Role of the reference's SparkUI + AppStatusListener
(core/ui/SparkUI.scala served from the live AppStatusStore,
core/status/AppStatusListener.scala — every bus event lands in an
in-memory store the UI renders). The renderer is shared with the
history server (exec/history_server.py) — the live store simply
presents the HistoryReader surface over an in-memory deque instead of
JSONL files, the same live/replay split the reference gets from
ElementTrackingStore over kvstore.

    spark = TpuSession("app")
    ui = spark.startUI()        # http://127.0.0.1:<port>/
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import asdict
from http.server import ThreadingHTTPServer

from .history_server import _Handler
from .listener import QueryEvent


class LiveStatusStore:
    """In-memory event store fed by the listener bus (AppStatusListener
    + AppStatusStore roles), shaped like HistoryReader for the shared
    renderer."""

    def __init__(self, app_name: str, max_events: int = 2000,
                 live_obs=None):
        self.app_name = app_name
        self._events: deque = deque(maxlen=max_events)
        self._running: dict[str, dict] = {}
        self._lock = threading.Lock()
        # obs/live.LiveObs when the session streams heartbeat telemetry:
        # the summary then carries IN-FLIGHT stage progress (rows so
        # far, per-task heartbeat age) and straggler findings — the live
        # UI's view into queries that have not finished yet
        self.live_obs = live_obs

    def on_event(self, ev: QueryEvent) -> None:
        d = asdict(ev)
        with self._lock:
            if ev.event == "queryStarted":
                self._running[ev.query_id] = d
            else:
                self._running.pop(ev.query_id, None)
            self._events.append(d)

    # -- HistoryReader surface -------------------------------------------
    def applications(self) -> list[str]:
        return [self.app_name]

    def load(self, _app: str) -> list[dict]:
        with self._lock:
            return list(self._events)

    def summary(self, _app: str) -> dict:
        from .listener import summarize_events

        events = self.load(_app)
        with self._lock:
            running = len(self._running)
        # same rollup as the history server (kernel.* + per-operator
        # totals) so both UIs render one shape, plus the live-only count
        out = summarize_events(events)
        out["running"] = running
        if self.live_obs is not None:
            out["live"] = self.live_obs.snapshot()
        return out


class SparkUI:
    """Live HTTP UI bound to one session's listener bus."""

    def __init__(self, session, port: int = 0, host: str = "127.0.0.1"):
        name = getattr(session, "app_name", None) or "session"
        self.store = LiveStatusStore(
            name, live_obs=getattr(session, "live_obs", None))
        session.listener_bus.register(self.store)
        handler = type("Handler", (_Handler,), {"reader": self.store})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self._httpd.server_address[1]
        self.url = f"http://{host}:{self.port}/"
        self._session = session
        self._thread: threading.Thread | None = None

    def start(self) -> "SparkUI":
        # race-lint: ignore[bare-submit] — UI HTTP accept loop:
        # session-lifetime, reads finished snapshots only
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="spark-ui")
        self._thread.start()
        return self

    def stop(self) -> None:
        try:
            self._session.listener_bus.unregister(self.store)
        except Exception:
            pass
        self._httpd.shutdown()
        self._httpd.server_close()
