"""Execution context shared across a query run.

Role of the reference's TaskContext + SQLMetrics plumbing (core/TaskContext,
sqlx/metric/SQLMetrics.scala:35): carries session conf and accumulates
per-operator metrics.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field

from ..config import SQLConf


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: dict[str, int] = defaultdict(int)
        self.timers: dict[str, float] = defaultdict(float)

    def add(self, name: str, v: int = 1) -> None:
        with self._lock:
            self.counters[name] += v

    def time(self, name: str):
        return _Timer(self, name)

    def snapshot(self) -> dict:
        with self._lock:
            return {"counters": dict(self.counters),
                    "timers": dict(self.timers)}


class ScopedMetrics(Metrics):
    """Per-query view over the session Metrics.

    Every add() lands on BOTH the session-global counters (unchanged
    behavior: listeners, bench and the gates keep reading cumulative
    session totals) and a query-local copy, so close-time consumers
    (query profiles, EXPLAIN ANALYZE counter deltas) read scope-exact
    per-query deltas instead of process-snapshot differences that
    concurrent queries on one session would contaminate.
    snapshot() deliberately stays the SESSION view — existing callers
    (plan_graph's adaptive baseline) diff session-cumulative counters."""

    def __init__(self, base: Metrics):
        super().__init__()
        self.base = base

    def add(self, name: str, v: int = 1) -> None:
        self.base.add(name, v)
        super().add(name, v)

    def time(self, name: str):
        return self.base.time(name)

    def snapshot(self) -> dict:
        return self.base.snapshot()

    def local_counters(self) -> dict:
        """This query's own counter increments (scope-exact)."""
        with self._lock:
            return dict(self.counters)


class _Timer:
    def __init__(self, m: Metrics, name: str):
        self.m = m
        self.name = name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        with self.m._lock:
            self.m.timers[self.name] += time.perf_counter() - self.t0
        return False


@dataclass
class ExecContext:
    conf: SQLConf = field(default_factory=SQLConf)
    metrics: Metrics = field(default_factory=Metrics)
    _memory: object = field(default=None, repr=False)
    # session BlockManager when the query runs under one (device-pin
    # budget for scan caches; None in bare contexts/workers)
    block_manager: object = field(default=None, repr=False)
    # id(physical node) → obs.metrics op record (rows/ms/batches/launch
    # attribution) when per-operator SQLMetrics collection is on
    # (ui/SparkPlanGraph role); None = no profiling
    plan_metrics: dict | None = field(default=None, repr=False)
    # session Tracer when span tracing is on (obs/tracing.py); None = off
    tracer: object = field(default=None, repr=False)
    # attribute KernelCache launches to the executing operator
    # (spark.tpu.metrics.kernelAttribution, resolved once per query)
    kernel_attribution: bool = field(default=True, repr=False)
    # cluster mode: per-kind kernel-launch deltas shipped back from
    # worker processes this query (ClusterDAGScheduler._merge_task_obs);
    # EXPLAIN ANALYZE reconciles measured launches as driver + this
    worker_kernel_kinds: dict | None = field(default=None, repr=False)
    # session LiveObs (obs/live.py) when live telemetry is wired: the
    # cluster scheduler closes task records against it and the straggler
    # detector reads it; None = no live store
    live_obs: object = field(default=None, repr=False)
    # query-scope tag of the collect driving this execution (set by
    # QueryExecution.execute from the tracing contextvar) — keys the
    # live store and EXPLAIN ANALYZE's straggler-finding lookup
    query_id: str | None = field(default=None, repr=False)
    # persistent-cache warm start (exec/persist_cache.py): the newest
    # manifest record for this query's plan fingerprint (join/mesh
    # capacity outcomes of a prior same-fingerprint run) set by
    # QueryExecution when spark.tpu.cache.dir is configured; executors
    # of capacity-retry loops read their seed from it and stash this
    # run's outcomes below for the close-time manifest write
    persist_seed: dict | None = field(default=None, repr=False)
    persist_join_caps: list | None = field(default=None, repr=False)
    persist_mesh_quotas: dict | None = field(default=None, repr=False)
    # per-join build-side key spans ([lo, hi, unique] or None, aligned
    # with persist_join_caps) observed by the whole-program tiers — the
    # manifest carries them so a warm restart compiles the dense
    # direct-address probe variant directly
    persist_join_spans: list | None = field(default=None, repr=False)
    # per-query kernel ledger (obs/metrics.QueryKernelLedger) installed
    # by QueryExecution.execute for the execution window: scope-exact
    # launch/compile deltas under concurrent collects (the contextvar
    # copy rides into par_map lanes and scoped_submit pools); profiles
    # and EXPLAIN ANALYZE read this instead of process-snapshot deltas
    kernel_ledger: object = field(default=None, repr=False)
    # chaos salvage (cluster mode): wasted-work records of failed task
    # attempts whose worker-side obs rode the error payload back
    # (ClusterDAGScheduler._record_failed_attempt) — kept SEPARATE from
    # plan_metrics/worker_kernel_kinds so launch reconciliation still
    # counts only work that contributed to the result; the query
    # profile and EXPLAIN ANALYZE findings surface it as waste
    failed_attempt_obs: list | None = field(default=None, repr=False)

    @property
    def memory(self):
        """Per-query MemoryManager (UnifiedMemoryManager role)."""
        if self._memory is None:
            from .memory import MemoryManager

            self._memory = MemoryManager(self.conf, self.metrics)
        return self._memory

    @property
    def partition_parallelism(self) -> int:
        """Concurrent partition-dispatch lanes for operator execution
        (spark.tpu.exec.partitionParallelism; 0 = auto)."""
        n = int(self.conf.get("spark.tpu.exec.partitionParallelism", 0))
        if n <= 0:
            import os

            n = min(4, os.cpu_count() or 1)
        return n

    def par_map(self, fn, items: list) -> list:
        """Dispatch independent partitions concurrently (async pipelining
        across partitions; see exec/scheduler.par_map). `fn` must be pure
        per-item device/host work — it must not recurse into plan
        execution. With tracing on, each partition records its own span
        from its lane thread (distinct trace tracks), so the async
        pipeline's overlap is visible in the exported timeline."""
        from .scheduler import par_map

        items = list(items)
        tracer = self.tracer
        if tracer is not None and tracer.enabled and len(items) > 1:
            from ..obs.metrics import current_op_name

            op = current_op_name() or "partition"

            def traced(pair, _fn=fn, _op=op):
                i, item = pair
                # flow=True: the lane span parents to the enclosing flow
                # span (stage/worker task) — the lane context is a copy
                # of the dispatching thread's, so the parent id is visible
                with tracer.span(f"{_op}[p{i}]", cat="partition",
                                 flow=True):
                    return _fn(item)

            return par_map(traced, list(enumerate(items)),
                           self.partition_parallelism)
        return par_map(fn, items, self.partition_parallelism)
