"""Execution context shared across a query run.

Role of the reference's TaskContext + SQLMetrics plumbing (core/TaskContext,
sqlx/metric/SQLMetrics.scala:35): carries session conf and accumulates
per-operator metrics.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field

from ..config import SQLConf


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: dict[str, int] = defaultdict(int)
        self.timers: dict[str, float] = defaultdict(float)

    def add(self, name: str, v: int = 1) -> None:
        with self._lock:
            self.counters[name] += v

    def time(self, name: str):
        return _Timer(self, name)

    def snapshot(self) -> dict:
        with self._lock:
            return {"counters": dict(self.counters),
                    "timers": dict(self.timers)}


class _Timer:
    def __init__(self, m: Metrics, name: str):
        self.m = m
        self.name = name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        with self.m._lock:
            self.m.timers[self.name] += time.perf_counter() - self.t0
        return False


@dataclass
class ExecContext:
    conf: SQLConf = field(default_factory=SQLConf)
    metrics: Metrics = field(default_factory=Metrics)
    _memory: object = field(default=None, repr=False)
    # session BlockManager when the query runs under one (device-pin
    # budget for scan caches; None in bare contexts/workers)
    block_manager: object = field(default=None, repr=False)
    # id(physical node) → {rows, ms, calls} when per-operator SQLMetrics
    # collection is on (ui/SparkPlanGraph role); None = no profiling
    plan_metrics: dict | None = field(default=None, repr=False)

    @property
    def memory(self):
        """Per-query MemoryManager (UnifiedMemoryManager role)."""
        if self._memory is None:
            from .memory import MemoryManager

            self._memory = MemoryManager(self.conf, self.metrics)
        return self._memory

    @property
    def partition_parallelism(self) -> int:
        """Concurrent partition-dispatch lanes for operator execution
        (spark.tpu.exec.partitionParallelism; 0 = auto)."""
        n = int(self.conf.get("spark.tpu.exec.partitionParallelism", 0))
        if n <= 0:
            import os

            n = min(4, os.cpu_count() or 1)
        return n

    def par_map(self, fn, items: list) -> list:
        """Dispatch independent partitions concurrently (async pipelining
        across partitions; see exec/scheduler.par_map). `fn` must be pure
        per-item device/host work — it must not recurse into plan
        execution."""
        from .scheduler import par_map

        return par_map(fn, list(items), self.partition_parallelism)
