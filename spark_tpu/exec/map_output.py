"""Map-output tracking and shuffle-block fetch.

Role of the reference's MapOutputTracker (core/MapOutputTracker.scala —
driver-side registry of MapStatus: which executor holds which shuffle
partition, and how big it is) and BlockStoreShuffleReader
(core/shuffle/BlockStoreShuffleReader.scala:72 — reducers pull blocks
from the executors that wrote them). Stage-granular variant: a map stage
runs whole on one executor, so each reduce partition is exactly one
block at one address.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from multiprocessing.connection import Client


class FetchFailedError(RuntimeError):
    """A shuffle block could not be fetched (executor lost its store).
    Carries the shuffle id so the scheduler can regenerate the parent
    stage (DAGScheduler FetchFailed → resubmit map stage)."""

    MARKER = "SPARK_TPU_FETCH_FAILED"

    def __init__(self, shuffle_id: str, detail: str = ""):
        super().__init__(f"{self.MARKER}:{shuffle_id}: {detail}")
        self.shuffle_id = shuffle_id


@dataclass
class MapStatus:
    """Where a map stage's output lives + per-reduce-partition sizes
    (core/scheduler/MapStatus.scala: location + getSizeForBlock)."""

    shuffle_id: str
    block_addr: str      # host:port of the executor's block server
    executor_id: str
    rows: list = field(default_factory=list)    # per reduce partition
    bytes: list = field(default_factory=list)   # per reduce partition

    @property
    def num_partitions(self) -> int:
        return len(self.rows)


class MapOutputTracker:
    """Driver-side registry: shuffle_id → MapStatus."""

    def __init__(self):
        self._lock = threading.Lock()
        self._statuses: dict[str, MapStatus] = {}

    def register(self, status: MapStatus) -> None:
        with self._lock:
            self._statuses[status.shuffle_id] = status

    def get(self, shuffle_id: str) -> MapStatus | None:
        with self._lock:
            return self._statuses.get(shuffle_id)

    def unregister(self, shuffle_id: str) -> None:
        with self._lock:
            self._statuses.pop(shuffle_id, None)

    def shuffle_ids(self) -> list[str]:
        with self._lock:
            return list(self._statuses)


class BlockClient:
    """One authenticated connection to an executor's block server, reused
    across block requests (ShuffleBlockFetcherIterator keeps one channel
    per (host, port) too — per-block reconnect pays the auth handshake
    num_partitions times)."""

    def __init__(self, addr: str, authkey_hex: str, shuffle_id: str):
        self.shuffle_id = shuffle_id
        if ":" not in addr:
            raise FetchFailedError(shuffle_id, f"bad block address {addr!r}")
        host, port = addr.rsplit(":", 1)
        self.addr = addr
        try:
            self._conn = Client((host, int(port)),
                                authkey=bytes.fromhex(authkey_hex))
        except (OSError, EOFError) as e:
            raise FetchFailedError(shuffle_id, f"{addr} unreachable: {e}")

    def get(self, reduce_id: int) -> bytes:
        try:
            self._conn.send(("get", self.shuffle_id, reduce_id))
            status, data = self._conn.recv()
        except (OSError, EOFError) as e:
            raise FetchFailedError(self.shuffle_id,
                                   f"{self.addr} died mid-fetch: {e}")
        if status != "ok":
            raise FetchFailedError(
                self.shuffle_id, f"block {reduce_id} missing at {self.addr}")
        return data

    def close(self) -> None:
        try:
            self._conn.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def fetch_block(addr: str, authkey_hex: str, shuffle_id: str,
                reduce_id: int) -> bytes:
    """Pull one block (one-shot convenience over BlockClient)."""
    with BlockClient(addr, authkey_hex, shuffle_id) as c:
        return c.get(reduce_id)


def free_shuffle(addr: str, authkey_hex: str, shuffle_id: str) -> None:
    """Best-effort release of a shuffle's blocks on one executor."""
    if ":" not in addr:
        return
    host, port = addr.rsplit(":", 1)
    try:
        conn = Client((host, int(port)),
                      authkey=bytes.fromhex(authkey_hex))
        try:
            conn.send(("free", shuffle_id))
            conn.recv()
        finally:
            conn.close()
    except (OSError, EOFError):
        pass
