"""Map-output tracking and shuffle-block fetch.

Role of the reference's MapOutputTracker (core/MapOutputTracker.scala —
driver-side registry of MapStatus: which executor holds which shuffle
partition, and how big it is) and BlockStoreShuffleReader
(core/shuffle/BlockStoreShuffleReader.scala:72 — reducers pull blocks
from the executors that wrote them). Stage-granular variant: a map stage
runs whole on one executor, so each reduce partition is exactly one
block at one address.
"""

from __future__ import annotations

import pickle
import threading
import time
from dataclasses import dataclass, field

from ..net.transport import (
    BEST_EFFORT_RETRY, RpcClient, RpcUnavailableError,
)
from ..utils import faults


class FetchFailedError(RuntimeError):
    """A shuffle block could not be fetched (executor lost its store).
    Carries the shuffle id so the scheduler can regenerate the parent
    stage (DAGScheduler FetchFailed → resubmit map stage)."""

    MARKER = "SPARK_TPU_FETCH_FAILED"

    def __init__(self, shuffle_id: str, detail: str = ""):
        super().__init__(f"{self.MARKER}:{shuffle_id}: {detail}")
        self.shuffle_id = shuffle_id


def map_block_id(shuffle_id: str, map_id: int, num_maps: int) -> str:
    """Block-store key for one map task's output. Single-mapper shuffles
    keep the bare shuffle id (the historical key) so stage-granular
    stages are wire-compatible with every fetch path."""
    return shuffle_id if num_maps <= 1 else f"{shuffle_id}#m{map_id}"


def merge_flow_id(shuffle_id: str) -> str:
    """Deterministic Perfetto flow id of a shuffle's push-merge step:
    the driver's merge-finalize span claims it as flow_id, reduce-side
    fetches that consume merged chunks list it as a flow_parent — both
    sides derive it from the shuffle id alone, so the arrow resolves
    across processes (and never dangles: with no merge span in the
    trace, the exporter drops the unresolved parent)."""
    return f"{shuffle_id}#merged"


@dataclass
class MapStatus:
    """Where ONE map task's output lives + per-reduce-partition sizes
    (core/scheduler/MapStatus.scala: location + getSizeForBlock)."""

    shuffle_id: str      # block-store key (map_block_id of this map task)
    block_addr: str      # host:port of the executor's block server
    executor_id: str
    rows: list = field(default_factory=list)    # per reduce partition
    bytes: list = field(default_factory=list)   # per reduce partition
    map_id: int = 0
    # map-side integral column stats per reduce partition:
    # {reduce_id: {col_idx: (kmin, kmax, any_valid)}} — the reduce side
    # seeds the dense-range device-scalar memo with these after the IPC
    # rebuild, so post-shuffle dense agg/join decisions never launch the
    # krange3 probe (exec/shuffle._OutBuffer accumulates them host-side
    # while slicing rows; zero extra device work)
    col_stats: dict | None = None
    # dictionary IDENTITY of every encoded string column this map task
    # shipped: {reduce_id: {col_idx: (StringDict.token per batch, ...)}}.
    # Blocks travel as codes + dictionary (compressed execution); equal
    # tokens let the reduce side rebuild ONE shared StringDict per
    # distinct dictionary and remap blocks by reference — no re-encode,
    # no host sync, and downstream concat/merge hits the identity fast
    # path across map tasks
    dict_ids: dict | None = None

    @property
    def num_partitions(self) -> int:
        return len(self.rows)


@dataclass
class MergeStatus:
    """Result of finalizing server-side merge of pushed blocks (role of
    core/scheduler/MergeStatus.scala + the shuffleMergeFinalized RPC):
    which map ids made it into the merged chunk of each reduce
    partition, and where the merged chunks live."""

    shuffle_id: str
    service_addr: str
    num_maps: int
    # reduce_id → map ids present in that partition's merged chunk
    merged: dict = field(default_factory=dict)


@dataclass
class ShuffleStatus:
    """All map outputs of one shuffle: per-map-task statuses plus the
    merge result when push-merge ran (MapOutputTracker's value type)."""

    shuffle_id: str
    maps: list = field(default_factory=list)    # list[MapStatus]
    merge: MergeStatus | None = None

    @property
    def num_partitions(self) -> int:
        return self.maps[0].num_partitions if self.maps else 0

    @property
    def executor_id(self) -> str:
        return self.maps[0].executor_id if self.maps else ""

    @property
    def block_addr(self) -> str:
        return self.maps[0].block_addr if self.maps else ""

    @property
    def total_bytes(self) -> int:
        return sum(sum(m.bytes) for m in self.maps)


class MapOutputTracker:
    """Driver-side registry: shuffle_id → ShuffleStatus."""

    def __init__(self):
        self._lock = threading.Lock()
        self._statuses: dict[str, ShuffleStatus] = {}

    def register(self, status) -> None:
        if isinstance(status, MapStatus):
            status = ShuffleStatus(status.shuffle_id, [status])
        with self._lock:
            self._statuses[status.shuffle_id] = status

    def register_merge(self, merge: MergeStatus) -> None:
        with self._lock:
            st = self._statuses.get(merge.shuffle_id)
            if st is not None:
                st.merge = merge

    def get(self, shuffle_id: str) -> ShuffleStatus | None:
        with self._lock:
            return self._statuses.get(shuffle_id)

    def unregister(self, shuffle_id: str) -> None:
        with self._lock:
            self._statuses.pop(shuffle_id, None)

    def shuffle_ids(self) -> list[str]:
        with self._lock:
            return list(self._statuses)


class BlockClient:
    """One authenticated gRPC channel to an executor's block server,
    reused across block requests (ShuffleBlockFetcherIterator keeps one
    channel per (host, port) too — per-block reconnect pays TCP+HTTP/2
    setup num_partitions times). Blocks arrive as chunked streams.

    A failed fetch RETRIES a bounded number of rounds before it maps to
    FetchFailedError (primary, then the external shuffle service when
    present, each round): raising FetchFailed costs a full lineage
    stage regeneration, so a transient block-server flap must be
    absorbed here (spark.tpu.shuffle.fetch.maxRetries — the reference's
    spark.shuffle.io.maxRetries/retryWait role). Only after the retry
    budget is spent does the scheduler see FetchFailed and regenerate
    the producing stage."""

    def __init__(self, addr: str, authkey_hex: str, shuffle_id: str,
                 fallback_addr: str | None = None,
                 max_retries: int = 2, retry_wait_ms: float = 50.0):
        self.shuffle_id = shuffle_id
        if ":" not in addr:
            raise FetchFailedError(shuffle_id, f"bad block address {addr!r}")
        self.addr = addr
        self._key = authkey_hex
        self._client = RpcClient(addr, authkey_hex)
        # external shuffle service (exec/shuffle_service.py): blocks that
        # outlive the producing executor — tried before declaring
        # FetchFailed, which would recompute the whole map stage
        self.fallback_addr = fallback_addr
        self._fallback: RpcClient | None = None
        self.max_retries = max(int(max_retries), 0)
        self.retry_wait_ms = float(retry_wait_ms)
        self.retries_used = 0      # rounds past the first (metrics)

    def _fetch_from(self, client: RpcClient, reduce_id: int) -> bytes:
        if faults.ENABLED:
            faults.maybe_fail(
                "block.fetch",
                detail=f"{self.shuffle_id}:{reduce_id}@{client.addr}",
                exc=RpcUnavailableError)
        frames = client.stream(
            "get_block", pickle.dumps((self.shuffle_id, reduce_id)),
            timeout=120)
        head = next(frames, None)
        if head != b"ok":
            raise FetchFailedError(
                self.shuffle_id,
                f"block {reduce_id} missing at {client.addr}")
        return b"".join(frames)

    def _try_fallback(self, reduce_id: int) -> bytes:
        if self._fallback is None:
            self._fallback = RpcClient(self.fallback_addr, self._key)
        return self._fetch_from(self._fallback, reduce_id)

    def get(self, reduce_id: int) -> bytes:
        last: Exception | None = None
        for attempt in range(self.max_retries + 1):
            if attempt:
                self.retries_used += 1
                time.sleep(self.retry_wait_ms * attempt / 1000.0)
            missing = False
            try:
                return self._fetch_from(self._client, reduce_id)
            except (RpcUnavailableError, FetchFailedError) as e:
                last = e
                # a REACHABLE server answering 'missing' is definitive
                # (the store lost the block — it will not reappear);
                # only transport failures are worth another round
                missing = isinstance(e, FetchFailedError)
                if self.fallback_addr is not None:
                    try:
                        return self._try_fallback(reduce_id)
                    except (RpcUnavailableError, FetchFailedError) as e2:
                        last = e2
                        missing = missing and \
                            isinstance(e2, FetchFailedError)
            if missing:
                break   # every source says gone — regen now, not later
        raise FetchFailedError(
            self.shuffle_id,
            f"block {reduce_id} unavailable after "
            f"{self.retries_used + 1} fetch round(s) at {self.addr}"
            + (f" (+ service {self.fallback_addr})"
               if self.fallback_addr else "")
            + f": {last}")

    def close(self) -> None:
        self._client.close()
        if self._fallback is not None:
            self._fallback.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def fetch_block(addr: str, authkey_hex: str, shuffle_id: str,
                reduce_id: int) -> bytes:
    """Pull one block (one-shot convenience over BlockClient)."""
    with BlockClient(addr, authkey_hex, shuffle_id) as c:
        return c.get(reduce_id)


def fetch_merged(client: RpcClient, shuffle_id: str,
                 reduce_id: int) -> list | None:
    """Fetch one MERGED chunk from the shuffle service and split it back
    into per-map frames [(map_id, raw_block_bytes), ...] (role of the
    reference's merged-shuffle-chunk fetch, ShuffleBlockFetcherIterator
    push-merged path). Returns None when the chunk is missing or fails
    integrity (frame lengths disagree with the index) — callers fall
    back to per-map original blocks."""
    try:
        frames = client.stream(
            "get_merged", pickle.dumps((shuffle_id, reduce_id)),
            timeout=120)
        head = next(frames, None)
        if head is None or head == b"missing":
            return None
        index = pickle.loads(head)          # [(map_id, length), ...]
        data = b"".join(frames)
    except Exception:
        return None
    out, off = [], 0
    for map_id, length in index:
        if off + length > len(data):
            return None                     # truncated/corrupt chunk
        out.append((map_id, data[off:off + length]))
        off += length
    if off != len(data):
        return None
    return out


def free_shuffle(addr: str, authkey_hex: str, shuffle_id: str) -> None:
    """Best-effort release of a shuffle's blocks on one executor. A
    transient flap retries briefly (BEST_EFFORT_RETRY) — leaked blocks
    outlive the flap, a dead executor's blocks died with it."""
    if ":" not in addr:
        return
    try:
        with RpcClient(addr, authkey_hex) as c:
            c.call("free_shuffle", pickle.dumps(shuffle_id), timeout=10,
                   retry=BEST_EFFORT_RETRY)
    except Exception:
        pass
