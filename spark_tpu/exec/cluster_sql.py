"""Distributed SQL stage execution over the process cluster.

Role of the reference's cluster-mode SQL execution (DAGScheduler map
stages running on executors, shuffle blocks fetched between them —
core/scheduler/DAGScheduler.scala + ShuffleBlockFetcherIterator): a
stage's physical subtree is cloudpickled to a worker process, which
STORES its output partitions in its local block server and returns only
a MapStatus (address + per-partition rows/bytes). Consumer stages
receive Fetch leaves and pull the blocks directly from the producing
worker — shuffle data never rides through the driver. A failed fetch
(worker died after producing) surfaces as FetchFailedError and the
scheduler regenerates the lost map stage from lineage, exactly the
reference's FetchFailed → resubmit path. The result (final) stage always
runs in the driver so device caches and session services stay local.

The columnar kernels are identical on driver and workers — a worker is
just another process with its own XLA client (CPU in the local cluster;
one chip per host in a real multi-host deployment, where this same
contract rides DCN instead of localhost pipes)."""

from __future__ import annotations

import uuid
from concurrent.futures import ThreadPoolExecutor

import cloudpickle

from ..physical.operators import PhysicalPlan
from .map_output import (
    FetchFailedError, MapOutputTracker, MapStatus, fetch_block, free_shuffle,
)
from .scheduler import DAGScheduler, Stage, _StageOutput, build_stage_graph


def _partitions_to_ipc(parts):
    import pyarrow as pa

    out = []
    for p in parts:
        tabs = []
        for b in p:
            t = b.to_arrow()
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, t.schema) as w:
                w.write_table(t)
            tabs.append(sink.getvalue().to_pybytes())
        out.append(tabs)
    return out


def _ipc_to_partition(tabs, schema):
    import pyarrow as pa

    from ..columnar.arrow import record_batch_to_columnar

    return [record_batch_to_columnar(
        pa.ipc.open_stream(pa.BufferReader(raw)).read_all(), schema)
        for raw in tabs]


def _ipc_to_partitions(payload, attrs):
    from ..physical.operators import attrs_schema

    schema = attrs_schema(attrs)
    return [_ipc_to_partition(tabs, schema) for tabs in payload]


class FetchExec(PhysicalPlan):
    """Leaf that pulls a parent stage's partitions from the executor that
    produced them (the BlockStoreShuffleReader role). One block per
    reduce partition (stage-granular map tasks)."""

    child_fields = ()

    def __init__(self, attrs, shuffle_id: str, block_addr: str,
                 authkey_hex: str, num_partitions: int,
                 fallback_addr: str | None = None):
        self.attrs = list(attrs)
        self.shuffle_id = shuffle_id
        self.block_addr = block_addr
        self.authkey_hex = authkey_hex
        self.num_partitions = num_partitions
        self.fallback_addr = fallback_addr  # external shuffle service

    @property
    def output(self):
        return self.attrs

    def output_partitioning(self):
        from ..physical.partitioning import UnknownPartitioning

        return UnknownPartitioning(max(self.num_partitions, 1))

    def execute(self, ctx):
        import pickle

        from ..physical.operators import attrs_schema
        from .map_output import BlockClient

        schema = attrs_schema(self.attrs)
        out = []
        # one authenticated connection per producer, reused across blocks
        with BlockClient(self.block_addr, self.authkey_hex,
                         self.shuffle_id,
                         fallback_addr=self.fallback_addr) as client:
            for rid in range(self.num_partitions):
                raw = client.get(rid)
                out.append(_ipc_to_partition(pickle.loads(raw), schema))
        ctx.metrics.add("shuffle.blocks_fetched", self.num_partitions)
        return out

    def simple_string(self):
        return f"Fetch[{self.shuffle_id}@{self.block_addr}]" \
               f"({self.num_partitions} parts)"


def _run_stage_store(plan_bytes: bytes, conf_overrides: dict,
                     shuffle_id: str):
    """Map-stage task body: execute the subtree, store each output
    partition as a block in THIS worker's store, return per-partition
    (rows, bytes) — the MapStatus payload. Runs in a worker process."""
    import pickle

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    jax.config.update("jax_enable_x64", True)

    from ..config import SQLConf
    from . import worker_main as WM
    from .context import ExecContext

    plan = cloudpickle.loads(plan_bytes)
    ctx = ExecContext(conf=SQLConf(dict(conf_overrides)))
    parts = plan.execute(ctx)
    rows, sizes = [], []
    for rid, part in enumerate(parts):
        ipc = _partitions_to_ipc([part])[0]
        raw = pickle.dumps(ipc)
        WM.put_block(shuffle_id, rid, raw)
        rows.append(sum(b.num_rows() for b in part))
        sizes.append(len(raw))
    counters = ctx.metrics.snapshot()["counters"]
    return ("mapstatus", WM.BLOCK_ADDR, rows, sizes, counters)


class ClusterDAGScheduler(DAGScheduler):
    """DAGScheduler that ships non-result stages to cluster workers.

    Stage = unit of distribution AND recovery: executor loss during a
    task retries via the cluster's attempt loop; executor loss AFTER a
    map stage completed surfaces as FetchFailedError in a consumer and
    regenerates the lost stage from lineage."""

    def __init__(self, ctx, cluster, conf_overrides: dict,
                 max_attempts: int = 2, listener_bus=None):
        super().__init__(ctx, max_attempts, listener_bus)
        self.cluster = cluster
        self.conf_overrides = dict(conf_overrides)
        self.map_outputs = MapOutputTracker()
        self._run_id = uuid.uuid4().hex[:12]
        from ..config import SPECULATION

        if ctx.conf.get(SPECULATION):
            cluster.speculation = True

    def run(self, plan):
        import threading
        from collections import defaultdict

        result_stage, stages = build_stage_graph(plan)
        done: set[int] = set()
        # per-stage locks serialize materialization/invalidation of a
        # SHARED parent reached from concurrently-materializing consumers
        # (diamond DAGs) — lock order is always child→parent, a DAG, so
        # no cycles
        locks: dict[int, threading.Lock] = defaultdict(threading.Lock)

        def invalidate_if_stale(stage: Stage, failed_sid: str) -> None:
            """Under the stage's lock: drop its outputs only if they are
            still the ones the fetch failed against (another consumer may
            have regenerated it already)."""
            with locks[stage.stage_id]:
                cur = self._shuffle_id(stage)
                st = self.map_outputs.get(cur)
                if cur == failed_sid or st is None:
                    done.discard(stage.stage_id)
                    stage.result = None
                    self.map_outputs.unregister(cur)

        def materialize(stage: Stage) -> None:
            with locks[stage.stage_id]:
                _materialize_locked(stage)

        def _materialize_locked(stage: Stage) -> None:
            if stage.stage_id in done:
                return
            if len(stage.parents) > 1:
                with ThreadPoolExecutor(len(stage.parents)) as pool:
                    list(pool.map(materialize, stage.parents))
            else:
                for p in stage.parents:
                    materialize(p)
            last_err = None
            for attempt in range(self.max_attempts):
                stage.attempts = attempt + 1
                try:
                    self._post("stageSubmitted", stage)
                    if stage is result_stage:
                        root = _substitute_parents(stage.root, self)
                        stage.result = root.execute(self.ctx)
                    else:
                        stage.result = self._run_remote(stage)
                    self.ctx.metrics.add("scheduler.stages_completed")
                    self._post("stageCompleted", stage)
                    done.add(stage.stage_id)
                    return
                except Exception as e:
                    last_err = e
                    sid = _fetch_failed_shuffle_id(e)
                    if sid is not None:
                        # a parent's blocks are gone — regenerate it from
                        # lineage before retrying this stage
                        self.ctx.metrics.add("scheduler.fetch_failures")
                        for p in stage.parents:
                            invalidate_if_stale(p, sid)
                        for p in stage.parents:
                            materialize(p)
                    else:
                        self.ctx.metrics.add("scheduler.stage_retries")
                    self._post("stageFailed", stage, error=str(e))
            raise last_err  # noqa: B904

        try:
            materialize(result_stage)
            return result_stage.result
        finally:
            self._free_shuffles()

    # ------------------------------------------------------------------
    def _shuffle_id(self, stage: Stage) -> str:
        return f"{self._run_id}.{stage.stage_id}.{stage.attempts}"

    def _run_remote(self, stage: Stage):
        shipped = _substitute_parents(stage.root, self)
        payload = cloudpickle.dumps(shipped)
        sid = self._shuffle_id(stage)
        result, worker = self.cluster.run_task_traced(
            _run_stage_store, payload, self.conf_overrides, sid)
        tag, addr, rows, sizes, counters = result
        assert tag == "mapstatus", tag
        status = MapStatus(sid, addr, worker.executor_id, rows, sizes)
        self.map_outputs.register(status)
        # fold worker-side operator metrics into the driver's view (the
        # executor-heartbeat metrics channel, reduced to per-task return)
        for k, v in counters.items():
            self.ctx.metrics.add(k, v)
        self.ctx.metrics.add("scheduler.stages_remote")
        self.ctx.metrics.add("shuffle.bytes_written", sum(sizes))
        return status

    def _free_shuffles(self) -> None:
        key = self.cluster.authkey_hex
        for sid in self.map_outputs.shuffle_ids():
            st = self.map_outputs.get(sid)
            if st is not None:
                free_shuffle(st.block_addr, key, sid)
            self.map_outputs.unregister(sid)


def _fetch_failed_shuffle_id(e: Exception) -> str | None:
    """Extract the shuffle id from a FetchFailedError, including one that
    crossed a process boundary as a RemoteTaskError traceback string."""
    if isinstance(e, FetchFailedError):
        return e.shuffle_id
    text = str(e)
    marker = FetchFailedError.MARKER + ":"
    if marker in text:
        return text.split(marker, 1)[1].split(":", 1)[0]
    return None


def _substitute_parents(node, sched: ClusterDAGScheduler):
    """Replace _StageOutput leaves with Fetch leaves bound to the
    executor holding the parent's blocks."""
    if isinstance(node, _StageOutput):
        st = node.stage
        status = st.result
        assert isinstance(status, MapStatus), \
            f"parent stage {st.stage_id} not materialized"
        return FetchExec(node.attrs, status.shuffle_id, status.block_addr,
                         sched.cluster.authkey_hex, status.num_partitions,
                         fallback_addr=getattr(sched.cluster,
                                               "shuffle_service_addr", None))
    return node.map_children(lambda c: _substitute_parents(c, sched))
