"""Distributed SQL stage execution over the process cluster.

Role of the reference's cluster-mode SQL execution (DAGScheduler map
stages running on executors, shuffle blocks fetched between them —
core/scheduler/DAGScheduler.scala + ShuffleBlockFetcherIterator): a
stage's physical subtree is cloudpickled to a worker process, which
STORES its output partitions in its local block server and returns only
a MapStatus (address + per-partition rows/bytes). Consumer stages
receive Fetch leaves and pull the blocks directly from the producing
worker — shuffle data never rides through the driver. A failed fetch
(worker died after producing) surfaces as FetchFailedError and the
scheduler regenerates the lost map stage from lineage, exactly the
reference's FetchFailed → resubmit path. The result (final) stage always
runs in the driver so device caches and session services stay local.

The columnar kernels are identical on driver and workers — a worker is
just another process with its own XLA client (CPU in the local cluster;
one chip per host in a real multi-host deployment, where this same
contract rides DCN instead of localhost pipes)."""

from __future__ import annotations

import uuid
from concurrent.futures import ThreadPoolExecutor

import cloudpickle

from ..physical.operators import PhysicalPlan
from .map_output import (
    FetchFailedError, MapOutputTracker, MapStatus, MergeStatus,
    ShuffleStatus, fetch_block, fetch_merged, free_shuffle, map_block_id,
    merge_flow_id,
)
from .scheduler import DAGScheduler, Stage, _StageOutput, build_stage_graph


def _partitions_to_ipc(parts):
    import pyarrow as pa

    out = []
    for p in parts:
        tabs = []
        for b in p:
            t = b.to_arrow()
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, t.schema) as w:
                w.write_table(t)
            tabs.append(sink.getvalue().to_pybytes())
        out.append(tabs)
    return out


def _partition_to_ipc_encoded(part):
    """Compressed shuffle wire format: each batch serializes with its
    StringType columns DICTIONARY-ENCODED (arrow dictionary arrays —
    int32 codes + the dictionary, never decoded row values). The
    per-column dictionary TOKENS (StringDict.token content fingerprints)
    are returned SEPARATELY — they ride the MapStatus (`dict_ids`), the
    control-plane carrier the reduce side consults to recognize equal
    dictionaries across blocks and remap by reference. Returns
    (("enc1", ipc_list), {col_idx: (token per batch, ...)})."""
    import pyarrow as pa

    from ..columnar.batch import EMPTY_DICT
    from ..types import StringType

    tabs = []
    dtokens: dict[int, list] = {}
    for bi, b in enumerate(part):
        t = b.to_arrow(encoded=True)
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, t.schema) as w:
            w.write_table(t)
        tabs.append(sink.getvalue().to_pybytes())
        for ci, (f, c) in enumerate(zip(b.schema.fields, b.columns)):
            if isinstance(f.dataType, StringType):
                dtokens.setdefault(ci, []).append(
                    (c.dictionary or EMPTY_DICT).token())
    return ("enc1", tabs), {ci: tuple(ts) for ci, ts in dtokens.items()}


def _ipc_to_partition(payload, schema, seed_ranges=None, dict_cache=None,
                      dict_tokens=None):
    """Rebuild one block's batches. `dict_tokens` ({col_idx: (token per
    batch, ...)}, from the producing MapStatus.dict_ids) + `dict_cache`
    intern equal dictionaries to one shared StringDict object."""
    import pyarrow as pa

    from ..columnar.arrow import record_batch_to_columnar

    if isinstance(payload, tuple) and payload and payload[0] == "enc1":
        _tag, tabs = payload
        out = []
        for bi, raw in enumerate(tabs):
            toks = None
            if dict_tokens:
                toks = {ci: ts[bi] for ci, ts in dict_tokens.items()
                        if bi < len(ts)}
            out.append(record_batch_to_columnar(
                pa.ipc.open_stream(pa.BufferReader(raw)).read_all(),
                schema, seed_ranges=seed_ranges,
                dict_cache=dict_cache, dict_tokens=toks))
        return out
    return [record_batch_to_columnar(
        pa.ipc.open_stream(pa.BufferReader(raw)).read_all(), schema,
        seed_ranges=seed_ranges)
        for raw in payload]


def _ipc_to_partitions(payload, attrs):
    from ..physical.operators import attrs_schema

    schema = attrs_schema(attrs)
    dict_cache: dict = {}
    return [_ipc_to_partition(tabs, schema, dict_cache=dict_cache)
            for tabs in payload]


class FetchExec(PhysicalPlan):
    """Leaf that pulls a parent shuffle's partitions (the
    BlockStoreShuffleReader role). Each reduce partition is the ordered
    concatenation of every map task's block for it; when the parent was
    push-merged, the service's merged chunk is fetched FIRST and only
    map ids missing from it (or a corrupt chunk) fall back to the
    per-map original blocks — the reference's push-merged read path
    (ShuffleBlockFetcherIterator merged chunks + fallbackFetch).

    `part_indices` restricts the fetch to a subset of reduce partitions:
    the leaf-slicing handle that turns a consumer stage into multiple
    map tasks."""

    child_fields = ()
    # adaptive.coalesce_after_exchange treats this leaf as the shuffle
    # it stands in for: cluster reduce stages coalesce like local runs
    is_shuffle_read = True

    def __init__(self, attrs, shuffle_id: str, maps: list,
                 authkey_hex: str, num_partitions: int,
                 fallback_addr: str | None = None,
                 merge: tuple | None = None,
                 part_indices: list | None = None,
                 col_stats: dict | None = None,
                 dict_ids: dict | None = None,
                 fetch_retries: int = 2,
                 fetch_wait_ms: float = 50.0):
        self.attrs = list(attrs)
        self.shuffle_id = shuffle_id
        self.maps = list(maps)              # [(map_id, block_addr), ...]
        self.authkey_hex = authkey_hex
        self.num_partitions = num_partitions
        self.fallback_addr = fallback_addr  # external shuffle service
        self.merge = merge       # (service_addr, {rid: (map ids merged)})
        self.part_indices = part_indices
        # bounded-fetch-retry knobs, captured as plain values at plan
        # substitution time (the leaf ships to worker processes, which
        # must retry with the DRIVER session's settings)
        self.fetch_retries = fetch_retries
        self.fetch_wait_ms = fetch_wait_ms
        # {rid: {col_idx: (kmin, kmax, any)}} merged across map tasks —
        # seeds the dense-range memo on rebuild (no krange3 probe on
        # post-shuffle dense decisions; same stats the local write seeds)
        self.col_stats = col_stats
        # {map_id: {rid: {col_idx: (StringDict.token per batch, ...)}}} —
        # the dictionary IDENTITY each map task registered on its
        # MapStatus: rebuilds intern equal dictionaries by token and
        # remap blocks by reference (no re-encode, no host sync)
        self.dict_ids = dict_ids

    @property
    def output(self):
        return self.attrs

    def output_partitioning(self):
        from ..physical.partitioning import UnknownPartitioning

        n = (len(self.part_indices) if self.part_indices is not None
             else self.num_partitions)
        return UnknownPartitioning(max(n, 1))

    def _flow_parents(self) -> list:
        """Deterministic flow ids of the spans that produced this
        shuffle's blocks: the map-task spans (`_run_stage_store` stamps
        `map_block_id` on its task root span) and — when the shuffle was
        push-merged — the driver's merge-finalize span
        (`merge_flow_id`), so exchange edges run map task → merge →
        reduce fetch instead of stopping at the fetch. The exporter
        draws the arrows across processes; capped so args stay small on
        very wide shuffles."""
        num_maps = len(self.maps)
        parents = [map_block_id(self.shuffle_id, mid, num_maps)
                   for mid, _ in sorted(self.maps)[:16]]
        if self.merge is not None and any(self.merge[1].values()):
            # merged chunks have a producing span on the driver
            # (ClusterDAGScheduler._finalize_merge) — parent to it too
            parents.append(merge_flow_id(self.shuffle_id))
        return parents

    def _fetch_rid(self, rid: int, clients: dict, schema, ctx,
                   dict_cache: dict | None = None) -> list:
        """One reduce partition: merged chunk first, per-map fallback."""
        import pickle

        from ..net.transport import RpcClient
        from .map_output import BlockClient

        num_maps = len(self.maps)
        frames: dict[int, bytes] = {}
        if self.merge is not None and num_maps > 0:
            service_addr, merged_index = self.merge
            if merged_index.get(rid):
                if "merged" not in clients:
                    clients["merged"] = RpcClient(service_addr,
                                                  self.authkey_hex)
                got = fetch_merged(clients["merged"], self.shuffle_id, rid)
                if got is not None:
                    frames = dict(got)
                    ctx.metrics.add("shuffle.merged_chunks_fetched")
        part: list = []
        for map_id, addr in sorted(self.maps):
            raw = frames.get(map_id)
            if raw is None:
                bid = map_block_id(self.shuffle_id, map_id, num_maps)
                key = ("map", map_id)
                if key not in clients:
                    clients[key] = BlockClient(
                        addr, self.authkey_hex, bid,
                        fallback_addr=self.fallback_addr,
                        max_retries=self.fetch_retries,
                        retry_wait_ms=self.fetch_wait_ms)
                try:
                    raw = clients[key].get(rid)
                except FetchFailedError as e:
                    # last alternate source before the expensive lineage
                    # regen: a push-merged chunk that failed its FIRST
                    # read (or was skipped) may hold this map's frame
                    raw = self._merged_rescue(clients, rid, map_id)
                    if raw is None:
                        # re-key to the BASE shuffle id: the scheduler
                        # regenerates the whole map stage, not one task
                        raise FetchFailedError(self.shuffle_id,
                                               str(e)) from None
                    ctx.metrics.add("shuffle.fetch_merged_rescues")
                ctx.metrics.add("shuffle.blocks_fetched")
            seed = (self.col_stats or {}).get(rid)
            toks = ((self.dict_ids or {}).get(map_id) or {}).get(rid)
            part.extend(_ipc_to_partition(pickle.loads(raw), schema, seed,
                                          dict_cache=dict_cache,
                                          dict_tokens=toks))
        return part

    def _merged_rescue(self, clients: dict, rid: int,
                       map_id: int) -> bytes | None:
        """Retry the push-merged chunk as an ALTERNATE SOURCE for one
        map's frame after its per-map block fetch exhausted retries."""
        if self.merge is None:
            return None
        service_addr, merged_index = self.merge
        if map_id not in (merged_index.get(rid) or ()):
            return None
        from ..net.transport import RpcClient

        if "merged" not in clients:
            clients["merged"] = RpcClient(service_addr, self.authkey_hex)
        got = fetch_merged(clients["merged"], self.shuffle_id, rid)
        if got is None:
            return None
        return dict(got).get(map_id)

    def execute(self, ctx):
        from contextlib import nullcontext

        from ..physical.operators import attrs_schema

        schema = attrs_schema(self.attrs)
        rids = (self.part_indices if self.part_indices is not None
                else range(self.num_partitions))
        clients: dict = {}
        # one dictionary intern table per fetch: encoded blocks carrying
        # the same StringDict.token rebuild to ONE shared dictionary
        # object across map tasks and reduce partitions (identity remap)
        dict_cache: dict = {}
        tracer = getattr(ctx, "tracer", None)
        # exchange-edge flow: this fetch's span parents to the map-task
        # spans that stored the blocks (possibly in another process —
        # the ids are derived from the shuffle id on both sides)
        sp = tracer.span(f"fetch[{self.shuffle_id}]", cat="exchange",
                         args={"flow_parent": self._flow_parents()}) \
            if tracer is not None else nullcontext()
        try:
            with sp:
                return [self._fetch_rid(rid, clients, schema, ctx,
                                        dict_cache)
                        for rid in rids]
        finally:
            retries = sum(getattr(c, "retries_used", 0)
                          for c in clients.values())
            if retries:
                # transient flaps this fetch absorbed WITHOUT paying a
                # lineage regen (the chaos gate's zero-regen assertion)
                ctx.metrics.add("shuffle.fetch_retries", retries)
            for c in clients.values():
                c.close()

    def simple_string(self):
        sl = (f" slice{list(self.part_indices)}"
              if self.part_indices is not None else "")
        return f"Fetch[{self.shuffle_id}×{len(self.maps)}maps]" \
               f"({self.num_partitions} parts{sl})"


def _run_stage_store(plan_bytes: bytes, conf_overrides: dict,
                     shuffle_id: str, map_id: int = 0, num_maps: int = 1,
                     query_id: str | None = None,
                     flow_parent: str | None = None):
    """Map-task body: execute the (possibly leaf-sliced) subtree, store
    each output partition as a block in THIS worker's store (and push it
    to the merge service in push mode), return per-partition
    (rows, bytes) — the MapStatus payload — plus the task's shipped
    observability (per-operator records, spans, kernel deltas). While
    the task RUNS, the same recorder streams partial snapshots on the
    executor heartbeat (worker_main.collect_live_obs — the reference's
    periodic Heartbeater), keyed by the (query, shuffle, map) identity
    passed here so the driver's LiveObs merges them per task.
    Runs in a worker process: the obs recorder is process-local, spans
    record under the driver's query scope, and the task root span
    carries a deterministic flow id (`map_block_id`) so reduce-side
    fetches can draw cross-process arrows to it."""
    import pickle

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    jax.config.update("jax_enable_x64", True)

    from ..config import SQLConf
    from ..obs.tracing import pop_query, push_query
    from . import worker_main as WM
    from .context import ExecContext

    plan = cloudpickle.loads(plan_bytes)
    conf = SQLConf(dict(conf_overrides))
    obs = WM.begin_stage_obs(conf, query_id=query_id,
                             stage_id=shuffle_id, task_id=map_id)
    ctx = ExecContext(conf=conf)
    if obs is not None:
        if obs["rec"] is not None:
            ctx.plan_metrics = obs["rec"]
            ctx.kernel_attribution = obs["attribution"]
        ctx.tracer = obs["tracer"]
    qtoken = push_query(query_id) if query_id is not None else None
    try:  # noqa: SIM105 — failed tasks must deregister from live flushing
        # chaos seam (rules just installed from the shipped conf by
        # begin_stage_obs): an injected raise surfaces to the driver as
        # a TRANSIENT task failure (retried on another executor,
        # counted toward this executor's exclusion window); kill mode
        # hard-exits the process mid-task (the worker-death scenario).
        # Inside the try: a raise must deregister the live recorder or
        # the task would stream ghost partials forever
        from ..utils import faults

        if faults.ENABLED:
            faults.maybe_fail("worker.task",
                              detail=f"{shuffle_id}#m{map_id}")
        task_span = ctx.tracer.span(
            f"task[{map_block_id(shuffle_id, map_id, num_maps)}]",
            cat="worker",
            args={"flow_id": map_block_id(shuffle_id, map_id, num_maps),
                  **({"flow_parent": flow_parent}
                     if flow_parent is not None else {})},
            flow=True) if ctx.tracer is not None else None
        if task_span is not None:
            task_span.__enter__()
        try:
            from ..columnar.encoding import encoding_enabled

            encoded = encoding_enabled(conf)
            parts = plan.execute(ctx)
            rows, sizes = [], []
            dict_ids: dict = {}
            for rid, part in enumerate(parts):
                if encoded:
                    # ship dictionary codes + per-column dictionaries
                    # (tokens identify them on the MapStatus) instead of
                    # decoded values — compressed execution's wire format
                    ipc, toks = _partition_to_ipc_encoded(part)
                    if toks:
                        dict_ids[rid] = toks
                else:
                    ipc = _partitions_to_ipc([part])[0]
                raw = pickle.dumps(ipc)
                WM.store_map_block(shuffle_id, map_id, num_maps, rid, raw)
                rows.append(sum(b.num_rows() for b in part))
                sizes.append(len(raw))
        finally:
            if task_span is not None:
                task_span.__exit__(None, None, None)
    except BaseException as e:
        # the task failed: stop streaming its partials NOW (the retry
        # will register a fresh recorder under the same identity). The
        # packaged obs is NOT discarded with the error: stamped onto the
        # exception, it rides the launch_task error payload back to the
        # driver (chaos salvage — a failed attempt's wasted work shows
        # in EXPLAIN ANALYZE findings and the query profile instead of
        # vanishing with the traceback)
        salvage = WM.finish_stage_obs(obs)
        if salvage is not None:
            try:
                e._salvaged_obs = salvage
            except Exception:
                pass  # exceptions with __slots__ just lose the ride
        raise
    finally:
        if qtoken is not None:
            pop_query(qtoken)
    counters = ctx.metrics.snapshot()["counters"]
    # map-side column stats (shuffle-exchange roots accumulate them while
    # slicing rows host-side) ride the MapStatus payload: the reduce side
    # seeds its dense-range memo from them instead of probing on device
    col_stats = getattr(plan, "last_col_stats", None) or None
    return ("mapstatus", WM.BLOCK_ADDR, rows, sizes, counters,
            WM.finish_stage_obs(obs), col_stats, dict_ids or None)


class ClusterDAGScheduler(DAGScheduler):
    """DAGScheduler that ships non-result stages to cluster workers.

    Stage = unit of distribution AND recovery: executor loss during a
    task retries via the cluster's attempt loop; executor loss AFTER a
    map stage completed surfaces as FetchFailedError in a consumer and
    regenerates the lost stage from lineage."""

    def __init__(self, ctx, cluster, conf_overrides: dict,
                 max_attempts: int = 2, listener_bus=None):
        super().__init__(ctx, max_attempts, listener_bus)
        self.cluster = cluster
        self.conf_overrides = dict(conf_overrides)
        self.map_outputs = MapOutputTracker()
        self._run_id = uuid.uuid4().hex[:12]
        import threading

        self._obs_lock = threading.Lock()  # worker obs merges race
        from ..config import SPECULATION

        if ctx.conf.get(SPECULATION):
            cluster.speculation = True
        # live telemetry: heartbeat-streamed partials land in the
        # session's LiveObs (obs/live.py); the final task-return record
        # supersedes them (_run_remote → task_finished). The straggler
        # detector doubles as the speculative-execution signal hook.
        self.live = getattr(ctx, "live_obs", None)
        # excludeOnFailure: configure the cluster's HealthTracker from
        # session conf and hook exclusion events into the live store
        # (console executor rows, live status, EXPLAIN ANALYZE findings)
        from ..config import (
            EXCLUDE_MAX_FAILURES, EXCLUDE_ON_FAILURE, EXCLUDE_TIMEOUT_SECS,
            EXCLUDE_WINDOW_SECS,
        )

        health = getattr(cluster, "health", None)
        if health is not None:
            health.configure(
                enabled=bool(ctx.conf.get(  # tpulint: ignore[host-sync]
                    EXCLUDE_ON_FAILURE)),
                max_failures=int(ctx.conf.get(  # tpulint: ignore[host-sync]
                    EXCLUDE_MAX_FAILURES)),
                window_s=float(ctx.conf.get(  # tpulint: ignore[host-sync]
                    EXCLUDE_WINDOW_SECS)),
                exclude_s=float(ctx.conf.get(  # tpulint: ignore[host-sync]
                    EXCLUDE_TIMEOUT_SECS)))
            health.on_exclude = self._on_executor_excluded
            health.on_exclude_host = self._on_host_excluded
        if self.live is not None:
            if getattr(cluster, "obs_sink", None) is None:
                cluster.obs_sink = self.live.on_heartbeat
            if getattr(cluster, "speculation", False):
                # keyed on (stage sid, map_id): the speculative wait
                # consults the signal for ITS OWN task, so one flagged
                # straggler doesn't collapse the threshold for every
                # in-flight task (key=None keeps the any-straggler view)
                cluster.speculation_signal = (
                    lambda key=None, live=self.live: any(
                        key is None or (f[1], f[2]) == key
                        for f in live.active_stragglers()))

    def _on_executor_excluded(self, eid: str, until: float,
                              failures: int) -> None:
        """HealthTracker exclusion hook: surface the event in the live
        store so console executor rows, live status, and EXPLAIN
        ANALYZE findings all show WHY an executor stopped taking tasks
        (the reference's TaskSetExcludelist → UI excludelist view)."""
        if self.live is None:
            return
        import math

        from ..obs.tracing import current_query

        horizon = None if math.isinf(until) else until
        self.live.executor_excluded(eid, horizon, failures)
        self.live.add_finding(current_query(), {
            "severity": "warning", "kind": "exec.excluded",
            "executor": eid,
            "msg": f"executor {eid} excluded after {failures} task "
                   "failure(s) in the excludeOnFailure window"
                   + ("" if horizon is None else
                      " (timed re-inclusion pending)")})

    def _on_host_excluded(self, host: str, until: float,
                          eids: list) -> None:
        """Host-granular escalation hook: every executor on one host
        tripped the failure window, so the HealthTracker excluded the
        box as a unit — surfaced exactly like executor exclusion (live
        status host row + a finding on the current query)."""
        if self.live is None:
            return
        import math

        from ..obs.tracing import current_query

        horizon = None if math.isinf(until) else until
        self.live.host_excluded(host, horizon, eids)
        self.live.add_finding(current_query(), {
            "severity": "warning", "kind": "host.excluded",
            "host": host, "executors": list(eids),
            "msg": f"host {host} excluded: all {len(eids)} of its "
                   "executors tripped the excludeOnFailure window"
                   + ("" if horizon is None else
                      " (timed re-inclusion pending)")})

    def _run(self, plan):
        # DAGScheduler.run wraps this with the driver-process KernelCache
        # delta accounting; worker-process deltas merge in via each
        # task's shipped obs payload (_merge_task_obs), so kernel.*
        # query metrics are driver+worker totals in cluster mode
        import threading
        from collections import defaultdict

        from ..config import STAGE_MAX_REGENS
        from ..errors import StageRegenerationLimitError

        max_regens = int(self.ctx.conf.get(  # tpulint: ignore[host-sync]
            STAGE_MAX_REGENS))
        regens = [0]   # FetchFailed-driven regenerations THIS query
        # sibling stages materialize on pool threads and can catch
        # FetchFailed concurrently — the cap counter must not lose
        # increments to a torn read-modify-write
        regen_lock = threading.Lock()

        result_stage, stages = build_stage_graph(plan)
        done: set[int] = set()
        # per-stage locks serialize materialization/invalidation of a
        # SHARED parent reached from concurrently-materializing consumers
        # (diamond DAGs) — lock order is always child→parent, a DAG, so
        # no cycles
        locks: dict[int, threading.Lock] = defaultdict(threading.Lock)

        def invalidate_if_stale(stage: Stage, failed_sid: str) -> None:
            """Under the stage's lock: drop its outputs only if they are
            still the ones the fetch failed against (another consumer may
            have regenerated it already)."""
            with locks[stage.stage_id]:
                cur = self._shuffle_id(stage)
                st = self.map_outputs.get(cur)
                if cur == failed_sid or st is None:
                    done.discard(stage.stage_id)
                    stage.result = None
                    if st is not None:
                        # free the stale attempt's blocks + merged chunks
                        # NOW — once unregistered, _free_shuffles can no
                        # longer see this sid and the service state leaks
                        self._free_one(st)
                    self.map_outputs.unregister(cur)

        def materialize(stage: Stage) -> None:
            with locks[stage.stage_id]:
                _materialize_locked(stage)

        def _materialize_locked(stage: Stage) -> None:
            if stage.stage_id in done:
                return
            if len(stage.parents) > 1:
                from ..obs.metrics import scoped_submit

                # copied contextvars Context per submit: the pool threads
                # start with an EMPTY context, which would silently drop
                # the query-scope tag and re-bucket kernel attribution
                # (matching scheduler.par_map's lane discipline)
                with ThreadPoolExecutor(len(stage.parents)) as pool:
                    futures = [scoped_submit(pool, materialize, p)
                               for p in stage.parents]
                    for f in futures:
                        f.result()
            else:
                for p in stage.parents:
                    materialize(p)
            tracer = getattr(self.ctx, "tracer", None)
            last_err = None
            for attempt in range(self.max_attempts):
                stage.attempts = attempt + 1
                try:
                    self._post("stageSubmitted", stage)
                    from contextlib import nullcontext

                    sp = tracer.span(f"stage-{stage.stage_id}", cat="stage",
                                     args={"attempt": attempt + 1},
                                     flow=True) \
                        if tracer is not None else nullcontext()
                    with sp:
                        if stage is result_stage:
                            root = _substitute_parents(stage.root, self)
                            stage.result = root.execute(self.ctx)
                        else:
                            stage.result = self._run_remote(stage)
                    self.ctx.metrics.add("scheduler.stages_completed")
                    self._post("stageCompleted", stage)
                    done.add(stage.stage_id)
                    return
                except Exception as e:
                    last_err = e
                    if self.live is not None:
                        # the retry runs under a NEW sid — close the
                        # failed attempt's live entries or they trip the
                        # heartbeat-silence straggler deadline forever
                        from ..obs.tracing import current_query as _cq

                        self.live.stage_abandoned(
                            _cq(), self._shuffle_id(stage))
                    # (partial map outputs of the failed attempt are
                    # freed by _run_remote's own handler, closest to
                    # the failure and covering BaseException too)
                    sid = _fetch_failed_shuffle_id(e)
                    if sid is not None:
                        # a parent's blocks are gone — regenerate it from
                        # lineage before retrying this stage. Bounded per
                        # query: an executor set losing outputs faster
                        # than lineage regenerates them must terminate in
                        # a CLASSIFIED error, not an infinite loop
                        with regen_lock:
                            regens[0] += 1
                            n_regens = regens[0]
                        if n_regens > max_regens:
                            raise StageRegenerationLimitError(
                                n_regens, max_regens, sid) from e
                        self.ctx.metrics.add("scheduler.fetch_failures")
                        self._record_lost_shuffle_executors(sid, str(e))
                        for p in stage.parents:
                            invalidate_if_stale(p, sid)
                        for p in stage.parents:
                            materialize(p)
                    else:
                        self.ctx.metrics.add("scheduler.stage_retries")
                    self._post("stageFailed", stage, error=str(e))
            raise last_err  # noqa: B904

        try:
            materialize(result_stage)
            return result_stage.result
        finally:
            self._free_shuffles()

    # ------------------------------------------------------------------
    def _shuffle_id(self, stage: Stage) -> str:
        return f"{self._run_id}.{stage.stage_id}.{stage.attempts}"

    def _map_task_count(self, shipped) -> int:
        """How many map tasks to split this stage into. >1 only when the
        stage root is a hash/round-robin shuffle exchange and every
        multi-partition Fetch leaf has the same partition count (the
        co-partitioned zip contract — all such leaves are sliced by the
        same index set). Range exchanges never slice: each task samples
        its own bounds, which would break the global order contract."""
        from ..config import SHUFFLE_MAP_PARALLELISM
        from ..physical.exchange import ShuffleExchangeExec
        from ..physical.partitioning import (
            HashPartitioning, UnknownPartitioning,
        )

        want = self.ctx.conf.get(SHUFFLE_MAP_PARALLELISM)
        if want == 1:
            return 1
        if not isinstance(shipped, ShuffleExchangeExec):
            return 1
        if not isinstance(shipped.partitioning,
                          (HashPartitioning, UnknownPartitioning)):
            return 1
        counts = {f.num_partitions
                  for f in shipped.iter_nodes()
                  if isinstance(f, FetchExec) and f.num_partitions > 1}
        if len(counts) != 1:
            return 1
        p = counts.pop()
        n_workers = max(len(self.cluster.registry.alive()), 1)
        cap = n_workers if want <= 0 else want
        return max(1, min(cap, p, n_workers))

    def _run_remote(self, stage: Stage):
        from ..obs.metrics import scoped_submit
        from ..obs.tracing import current_flow, current_query

        shipped = _substitute_parents(stage.root, self)
        sid = self._shuffle_id(stage)
        num_maps = self._map_task_count(shipped)
        # the driver's query scope + the enclosing stage span's flow id
        # ride into the task so worker spans tag and link correctly
        qid = current_query()
        flow_parent = current_flow()

        def run_map(map_id: int):
            import time as _time

            plan = (_slice_fetch_leaves(shipped, map_id, num_maps)
                    if num_maps > 1 else shipped)
            t_start = _time.time()
            result, worker = self.cluster.run_task_traced(
                _run_stage_store, cloudpickle.dumps(plan),
                self.conf_overrides, sid, map_id, num_maps,
                qid, flow_parent, task_key=(sid, map_id),
                on_failed_attempt=lambda eid, err, salvage, _m=map_id:
                    self._record_failed_attempt(qid, sid, _m, eid, err,
                                                salvage))
            (tag, addr, rows, sizes, counters, obs, col_stats,
             dict_ids) = result
            assert tag == "mapstatus", tag
            # close the task in the live store the moment ITS result
            # lands (not at the stage barrier): the final record
            # supersedes the heartbeat partials, and a completed peer's
            # rate immediately becomes the straggler bar for siblings
            # still running (TaskSetManager marks success per task).
            # started= gives fast no-heartbeat tasks their real duration
            # (first_seen alone would make their rate explode)
            if self.live is not None:
                self.live.task_finished(qid, sid, map_id, obs,
                                        rows=sum(rows),
                                        executor=worker.executor_id,
                                        started=t_start)
            return (MapStatus(map_block_id(sid, map_id, num_maps), addr,
                              worker.executor_id, rows, sizes, map_id,
                              col_stats, dict_ids),
                    counters, obs, worker.executor_id)

        try:
            if num_maps == 1:
                outcomes = [run_map(0)]
            else:
                with ThreadPoolExecutor(num_maps) as pool:
                    futures = [scoped_submit(pool, run_map, m)
                               for m in range(num_maps)]
                    outcomes = [f.result() for f in futures]
        except BaseException:
            # sibling map tasks that SUCCEEDED stored blocks under this
            # sid; the status never registers, so free them now or they
            # leak on the workers (the stage retry uses a fresh sid)
            self._free_sid_best_effort(sid)
            raise
        status = ShuffleStatus(sid, [ms for ms, *_ in outcomes])
        self.map_outputs.register(status)
        if getattr(self.cluster, "push_shuffle", False) and \
                self.cluster.shuffle_service_addr:
            status.merge = self._finalize_merge(sid, num_maps)
        # fold worker-side operator metrics into the driver's view
        # (task-return records already closed the live store per task,
        # inside run_map)
        for ms, counters, obs, eid in outcomes:
            for k, v in counters.items():
                self.ctx.metrics.add(k, v)
            self._merge_task_obs(obs, eid, qid)
        self.ctx.metrics.add("scheduler.stages_remote")
        self.ctx.metrics.add("scheduler.map_tasks", num_maps)
        self.ctx.metrics.add("shuffle.bytes_written", status.total_bytes)
        return status

    def _record_failed_attempt(self, qid: str | None, sid: str,
                               map_id: int, executor_id: str,
                               err: Exception,
                               salvage: dict | None) -> None:
        """Chaos salvage (PR 11 follow-on (a)): a failed task attempt's
        worker-side obs rode the error payload instead of dying with
        it. Record the WASTED work — kernel deltas, span count, compile
        ms — on the ExecContext (the query profile's `wasted` section),
        ingest the attempt's spans into the tracer so the timeline
        shows the abandoned attempt, and raise a warning finding so
        chaos-path EXPLAIN ANALYZE names the waste. Deliberately NOT
        merged into plan_metrics or worker_kernel_kinds: launch
        reconciliation must keep counting only work that produced the
        result."""
        # tail of the error text: a cross-process traceback buries the
        # actual failure (the injected-fault marker, the XLA error) at
        # the END of the string
        entry = {"stage": sid, "task": map_id, "executor": executor_id,
                 "error": str(err)[-200:]}
        launches = 0
        if salvage:
            kinds = salvage.get("kernel_kinds") or {}
            launches = salvage.get("kernel_launches", 0)
            entry.update({
                "kernel_kinds": dict(kinds),
                "launches": launches,
                "compile_ms": salvage.get("kernel_compile_ms", 0.0),
                "spans": len(salvage.get("spans") or ())})
            tracer = getattr(self.ctx, "tracer", None)
            if tracer is not None and salvage.get("spans"):
                tracer.ingest(salvage["spans"],
                              anchor=salvage.get("anchor"),
                              track=f"worker:{executor_id}", query_id=qid)
        with self._obs_lock:
            if self.ctx.failed_attempt_obs is None:
                self.ctx.failed_attempt_obs = []
            self.ctx.failed_attempt_obs.append(entry)
        self.ctx.metrics.add("scheduler.task_failures_salvaged")
        if self.live is not None:
            self.live.add_finding(qid, {
                "severity": "warning", "kind": "obs.wasted-work",
                "executor": executor_id,
                "msg": f"task {sid}#m{map_id} attempt on {executor_id} "
                       f"failed after {launches} kernel launch(es) — "
                       "its obs rode the error payload (salvaged wasted "
                       "work; retried elsewhere)"})

    def _merge_task_obs(self, obs: dict | None, executor_id: str,
                        qid: str | None) -> None:
        """Fold one map task's shipped observability into the driver's
        query view: per-operator records by `_metric_id` (so EXPLAIN
        ANALYZE / plan_graph / history server render identical shape
        local vs cluster), spans into the session tracer under the
        worker's own track, and the worker process's KernelCache deltas
        into the query metrics + the per-query worker launch ledger
        (`ctx.worker_kernel_kinds` — EXPLAIN ANALYZE reconciles measured
        launches against driver+worker totals with it)."""
        if obs is None:
            return
        if self.ctx.plan_metrics is not None and obs.get("op_records"):
            from ..obs.metrics import merge_op_records

            merge_op_records(self.ctx.plan_metrics, obs["op_records"])
        tracer = getattr(self.ctx, "tracer", None)
        if tracer is not None and obs.get("spans"):
            tracer.ingest(obs["spans"], anchor=obs.get("anchor"),
                          track=f"worker:{executor_id}", query_id=qid)
        if obs.get("kernel_launches"):
            self.ctx.metrics.add("kernel.launches", obs["kernel_launches"])
        if obs.get("kernel_compile_ms"):
            # round, not truncate — matching the driver-side wrapper in
            # DAGScheduler.run so many small tasks don't bias totals low
            self.ctx.metrics.add("kernel.compile_ms",
                                 round(obs["kernel_compile_ms"]))
        kinds = obs.get("kernel_kinds")
        if kinds:
            with self._obs_lock:
                wk = self.ctx.worker_kernel_kinds
                if wk is None:
                    wk = self.ctx.worker_kernel_kinds = {}
                for k, v in kinds.items():
                    wk[k] = wk.get(k, 0) + v
        disk = obs.get("compile_disk")
        if disk:
            # worker-process XLA disk-cache traffic folds into the same
            # per-query compile.disk_* metrics the driver deltas record
            # (exec/persist_cache.py) — a warm cluster restart's
            # "zero true cold compiles" claim covers workers too
            for k, v in disk.items():
                if v:
                    self.ctx.metrics.add(k, v)
        if obs.get("hbm"):
            # worker HBM is a DIFFERENT device's memory: it folds into
            # the query record as a per-executor remote peak (EXPLAIN
            # ANALYZE's memory section), never into the driver balance
            from ..obs.resources import GLOBAL_LEDGER

            GLOBAL_LEDGER.merge_remote(qid, executor_id, obs["hbm"])

    def _finalize_merge(self, sid: str, num_maps: int):
        """Close the shuffle to late pushes and register which map ids
        each reduce partition's merged chunk holds (the reference's
        shuffleMergeFinalized → MergeStatus registration,
        core/scheduler/MergeStatus.scala). The finalize records a
        PRODUCING span for the merged chunks (the service process has no
        tracer): it claims the deterministic `merge_flow_id` and parents
        to the map-task spans, so exchange edges run map task → merge →
        reduce fetch instead of stopping at the fetch."""
        import pickle
        from contextlib import nullcontext

        from ..net.transport import RetryPolicy, RpcClient

        addr = self.cluster.shuffle_service_addr
        tracer = getattr(self.ctx, "tracer", None)
        sp = tracer.span(
            f"merge[{sid}]", cat="exchange",
            args={"flow_id": merge_flow_id(sid),
                  "flow_parent": [map_block_id(sid, m, num_maps)
                                  for m in range(min(num_maps, 16))],
                  "service": addr},
            flow=True) if tracer is not None else nullcontext()
        with sp:
            try:
                with RpcClient(addr, self.cluster.authkey_hex) as c:
                    # idempotent (finalize twice returns the same index)
                    # — absorb a transient service flap with backoff
                    merged = pickle.loads(
                        c.call("finalize_merge", pickle.dumps(sid),
                               timeout=30,
                               retry=RetryPolicy.from_conf(self.ctx.conf)))
            except Exception:
                return None    # merge unavailable — per-map fetch works
        merge = MergeStatus(sid, addr, num_maps, merged)
        self.map_outputs.register_merge(merge)
        return merge

    def _record_lost_shuffle_executors(self, sid: str,
                                       error_text: str = "") -> None:
        """A FetchFailed names a lost shuffle — count the failure
        against the executor whose block server actually failed (the
        reference's fetch-failure → HealthTracker attribution): the
        error text carries the failing block address, so only producers
        whose address appears in it are blamed (blaming every producer
        of a wide shuffle would exclude healthy executors). Falls back
        to all producers only when no address matches (e.g. a
        re-serialized error lost the detail)."""
        health = getattr(self.cluster, "health", None)
        st = self.map_outputs.get(sid)
        if health is None or st is None:
            return
        producers = {ms.executor_id: ms.block_addr
                     for ms in st.maps if ms.executor_id}
        blamed = [eid for eid, addr in producers.items()
                  if addr and addr in error_text]
        for eid in (blamed or producers):
            try:
                health.record_failure(eid)
            except Exception:
                pass

    def _free_sid_best_effort(self, sid: str) -> None:
        """Free one shuffle id's blocks on EVERY registered worker
        (INCLUDING excluded ones — an executor excluded mid-stage still
        holds its stored blocks) plus the shuffle service — the cleanup
        path for sids that never made it into the MapOutputTracker (a
        stage attempt that stored some map blocks and then failed):
        _free_shuffles can only free what was registered, so partial
        outputs would leak worker memory for the life of the process."""
        key = self.cluster.authkey_hex
        for w in getattr(self.cluster, "registered_workers", list)():
            try:
                free_shuffle(w.client.addr, key, sid)
            except Exception:
                pass
        service = getattr(self.cluster, "shuffle_service_addr", None)
        if service:
            free_shuffle(service, key, sid)

    def _free_one(self, st: ShuffleStatus) -> None:
        """Best-effort release of one shuffle's blocks on its executors
        and its originals/merged chunks at the service."""
        key = self.cluster.authkey_hex
        for ms in st.maps:
            free_shuffle(ms.block_addr, key, ms.shuffle_id)
        service = getattr(self.cluster, "shuffle_service_addr", None)
        if service:
            free_shuffle(service, key, st.shuffle_id)

    def _free_shuffles(self) -> None:
        for sid in self.map_outputs.shuffle_ids():
            st = self.map_outputs.get(sid)
            if st is not None:
                self._free_one(st)
            self.map_outputs.unregister(sid)


def _fetch_failed_shuffle_id(e: Exception) -> str | None:
    """Extract the shuffle id from a FetchFailedError, including one that
    crossed a process boundary as a RemoteTaskError traceback string."""
    if isinstance(e, FetchFailedError):
        return e.shuffle_id
    text = str(e)
    marker = FetchFailedError.MARKER + ":"
    if marker in text:
        return text.split(marker, 1)[1].split(":", 1)[0]
    return None


def _merged_col_stats(maps: list) -> dict | None:
    """Union the per-map-task column stats into per-reduce-partition
    stats: min of mins, max of maxes, any OR — the reduce partition's
    rows are exactly the union of every map task's slice for it."""
    out: dict = {}
    for ms in maps:
        for rid, cols in (ms.col_stats or {}).items():
            cur = out.setdefault(rid, {})
            for ci, (lo, hi, any_v) in cols.items():
                if ci in cur:
                    plo, phi, seen = cur[ci]
                    if any_v and seen:
                        cur[ci] = (min(plo, lo), max(phi, hi), True)
                    elif any_v:
                        cur[ci] = (lo, hi, True)
                else:
                    cur[ci] = (lo, hi, any_v)
    return out or None


def _substitute_parents(node, sched: ClusterDAGScheduler):
    """Replace _StageOutput leaves with Fetch leaves bound to the
    executors holding the parent's map outputs (plus the merge index
    when the parent shuffle was push-merged)."""
    if isinstance(node, _StageOutput):
        from ..config import FETCH_MAX_RETRIES, FETCH_RETRY_WAIT_MS

        st = node.stage
        status = st.result
        assert isinstance(status, ShuffleStatus), \
            f"parent stage {st.stage_id} not materialized"
        merge = None
        if status.merge is not None:
            merge = (status.merge.service_addr, status.merge.merged)
        return FetchExec(node.attrs, status.shuffle_id,
                         [(m.map_id, m.block_addr) for m in status.maps],
                         sched.cluster.authkey_hex, status.num_partitions,
                         fallback_addr=getattr(sched.cluster,
                                               "shuffle_service_addr", None),
                         merge=merge,
                         col_stats=_merged_col_stats(status.maps),
                         dict_ids={m.map_id: m.dict_ids
                                   for m in status.maps
                                   if m.dict_ids} or None,
                         fetch_retries=int(  # tpulint: ignore[host-sync]
                             sched.ctx.conf.get(FETCH_MAX_RETRIES)),
                         fetch_wait_ms=float(  # tpulint: ignore[host-sync]
                             sched.ctx.conf.get(FETCH_RETRY_WAIT_MS)))
    return node.map_children(lambda c: _substitute_parents(c, sched))


def _slice_fetch_leaves(node, map_id: int, num_maps: int):
    """Restrict every multi-partition Fetch leaf to the round-robin
    slice `map_id::num_maps` of its reduce partitions — the unit of work
    of one map task. Single-partition leaves (broadcast relations) are
    left whole so every task sees the full build side."""
    if isinstance(node, FetchExec) and node.num_partitions > 1:
        return FetchExec(
            node.attrs, node.shuffle_id, node.maps, node.authkey_hex,
            node.num_partitions, fallback_addr=node.fallback_addr,
            merge=node.merge,
            part_indices=list(range(map_id, node.num_partitions,
                                    num_maps)),
            col_stats=node.col_stats, dict_ids=node.dict_ids,
            fetch_retries=node.fetch_retries,
            fetch_wait_ms=node.fetch_wait_ms)
    return node.map_children(
        lambda c: _slice_fetch_leaves(c, map_id, num_maps))
