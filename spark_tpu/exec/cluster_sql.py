"""Distributed SQL stage execution over the process cluster.

Role of the reference's cluster-mode SQL execution (DAGScheduler map
stages running on executors, shuffle blocks fetched between them —
core/scheduler/DAGScheduler.scala + ShuffleBlockFetcherIterator): here a
stage's physical subtree is cloudpickled to a worker process, its parent
stages' outputs travel as Arrow IPC partition payloads, and results come
back the same way. Independent parent stages run on different workers
concurrently. The result (final) stage always runs in the driver so
device caches and session services stay local.

The columnar kernels are identical on driver and workers — a worker is
just another process with its own XLA client (CPU in the local cluster;
one chip per host in a real multi-host deployment, where this same
contract rides DCN instead of localhost pipes)."""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import cloudpickle

from ..physical.operators import PhysicalPlan
from .scheduler import DAGScheduler, Stage, _StageOutput, build_stage_graph


def _partitions_to_ipc(parts):
    import pyarrow as pa

    out = []
    for p in parts:
        tabs = []
        for b in p:
            t = b.to_arrow()
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, t.schema) as w:
                w.write_table(t)
            tabs.append(sink.getvalue().to_pybytes())
        out.append(tabs)
    return out


def _ipc_to_partitions(payload, attrs):
    import pyarrow as pa

    from ..columnar.arrow import record_batch_to_columnar
    from ..physical.operators import attrs_schema

    schema = attrs_schema(attrs)
    parts = []
    for tabs in payload:
        batches = []
        for raw in tabs:
            t = pa.ipc.open_stream(pa.BufferReader(raw)).read_all()
            batches.append(record_batch_to_columnar(t, schema))
        parts.append(batches)
    return parts


class PrecomputedIPCExec(PhysicalPlan):
    """Leaf carrying a parent stage's output as Arrow IPC payloads —
    the shuffle-block-fetch stand-in shipped inside the task."""

    child_fields = ()

    def __init__(self, attrs, payload):
        self.attrs = list(attrs)
        self.payload = payload

    @property
    def output(self):
        return self.attrs

    def output_partitioning(self):
        from ..physical.partitioning import UnknownPartitioning

        return UnknownPartitioning(max(len(self.payload), 1))

    def execute(self, ctx):
        return _ipc_to_partitions(self.payload, self.attrs)

    def simple_string(self):
        return f"PrecomputedIPC({len(self.payload)} parts)"


def _run_stage_remote(plan_bytes: bytes, conf_overrides: dict):
    """Task body executed in a worker process (no TPU tunnel there)."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    jax.config.update("jax_enable_x64", True)

    from ..config import SQLConf
    from .context import ExecContext

    plan = cloudpickle.loads(plan_bytes)
    ctx = ExecContext(conf=SQLConf(dict(conf_overrides)))
    return _partitions_to_ipc(plan.execute(ctx))


class ClusterDAGScheduler(DAGScheduler):
    """DAGScheduler that ships non-result stages to cluster workers.

    Stage = unit of distribution AND recovery: a worker loss surfaces as
    a task error and the stage retries (possibly on another worker) via
    the inherited attempt loop."""

    def __init__(self, ctx, cluster, conf_overrides: dict,
                 max_attempts: int = 2, listener_bus=None):
        super().__init__(ctx, max_attempts, listener_bus)
        self.cluster = cluster
        self.conf_overrides = dict(conf_overrides)

    def run(self, plan):
        result_stage, stages = build_stage_graph(plan)
        done: set[int] = set()

        def materialize(stage: Stage) -> None:
            if stage.stage_id in done:
                return
            if len(stage.parents) > 1:
                with ThreadPoolExecutor(len(stage.parents)) as pool:
                    list(pool.map(materialize, stage.parents))
            else:
                for p in stage.parents:
                    materialize(p)
            last_err = None
            for attempt in range(self.max_attempts):
                stage.attempts = attempt + 1
                try:
                    self._post("stageSubmitted", stage)
                    if stage is result_stage:
                        stage.result = stage.root.execute(self.ctx)
                    else:
                        stage.result = self._run_remote(stage)
                    self.ctx.metrics.add("scheduler.stages_completed")
                    self._post("stageCompleted", stage)
                    done.add(stage.stage_id)
                    return
                except Exception as e:
                    last_err = e
                    self.ctx.metrics.add("scheduler.stage_retries")
                    self._post("stageFailed", stage, error=str(e))
            raise last_err  # noqa: B904

        materialize(result_stage)
        return result_stage.result

    def _run_remote(self, stage: Stage):
        shipped = _substitute_parents(stage.root)
        payload = cloudpickle.dumps(shipped)
        ipc = self.cluster.run_task(_run_stage_remote, payload,
                                    self.conf_overrides)
        self.ctx.metrics.add("scheduler.stages_remote")
        return _ipc_to_partitions(ipc, list(stage.root.output))


def _substitute_parents(node):
    """Replace _StageOutput leaves with IPC payload leaves for shipping."""
    if isinstance(node, _StageOutput):
        return PrecomputedIPCExec(
            node.attrs, _partitions_to_ipc(node.stage.result))
    return node.map_children(_substitute_parents)
