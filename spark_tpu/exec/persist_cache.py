"""Persistent compile & result caches: fingerprint-keyed warm restarts.

ROADMAP direction 1's restart story, keyed by the PR 12 fingerprints
(obs/history.plan_fingerprint — per-stage sub-fingerprints are the
per-stage compile keys): the suite and the serving path are both
compile-bound, PR 10's whole-query tier made cold compiles the dominant
per-query cost, and a restarted server used to pay every one of them
again while a repeated dashboard query re-launched kernels to recompute
an identical answer. Three layers, all rooted at `spark.tpu.cache.dir`
(empty by default — every persistent cache OFF; the tier-1 exact-count
tests and the plan analyzer's default launch model assume that default):

  * **Persistent compile cache** (`spark.tpu.cache.compile.enabled`) —
    jax's XLA persistent compilation cache pointed at `<dir>/xla`, with
    the entry-size/compile-time floors dropped so every engine kernel
    qualifies. The normal `jax.jit` dispatch path stays intact — this
    deliberately does NOT route through AOT `lowered.compile()`, whose
    backend compile is not shared with the dispatch path on this jax
    version (the PR 12 kernelMemory finding). A jax monitoring listener
    counts the cache's hit/miss events into `compile.disk_hit` /
    `compile.disk_miss`, and the KernelCache classifies each kernel's
    first invocation accordingly — the obs layer tells a disk-served
    compile apart from a true cold one.

  * **Warm-start manifest** (`<dir>/manifest.jsonl`, a shared
    utils/diskstore.JsonlRing) — per-fingerprint records of the
    KernelCache metadata a restart cannot recompute without paying
    retries: the tier decision, the whole-query program's final join
    output capacities, and mesh exchanges' final quota outcomes. A warm
    process seeds its capacity state from the last same-fingerprint
    record, so the first dispatch compiles the FINAL program of the
    cold run (one engine compile, served from the XLA disk cache)
    instead of replaying the capacity-retry ladder. The plan analyzer
    mirrors the same lookup (analysis/plan_lint.py).

  * **Result cache** (`spark.tpu.cache.result.enabled`, `<dir>/result`)
    — full `plan_fingerprint` + a data-version component (warehouse /
    external file identity, in-memory table content hash) → Arrow IPC
    payload in a bounded, flock-safe on-disk LRU
    (`spark.tpu.cache.result.maxBytes`). A hit answers a repeated query
    with ZERO kernel launches, shared across connect sessions,
    processes, and the cluster driver. Non-deterministic plans and
    plans with unknown leaf data identity bypass the cache; the catalog
    write path invalidates by dependency on append/overwrite (and the
    file identity folded into the key makes stale hits structurally
    impossible even without the explicit purge).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import sys
import threading
import time

from ..utils import lockwatch

__all__ = ["configure", "cache_root", "compile_cache_active",
           "result_cache_active", "disk_counters", "reset_disk_counters",
           "ResultCache",
           "result_cache_for", "result_key", "result_probe",
           "invalidate_path", "record_manifest", "manifest_seed",
           "mesh_quota_key", "mesh_quota_key_plain", "mesh_quota_key_fused"]

_MANIFEST_RING = 2048
_HASH_MAX_BYTES = 256 << 20   # refuse to content-hash bigger tables
_ADDR = re.compile(r"\bat 0x[0-9a-fA-F]+|\b0x[0-9a-fA-F]+")


# ---------------------------------------------------------------------------
# conf plumbing
# ---------------------------------------------------------------------------

def cache_root(conf) -> str:
    from ..config import CACHE_DIR

    return str(conf.get(CACHE_DIR) or "")  # tpulint: ignore[host-sync]


def compile_cache_active(conf) -> bool:
    from ..config import CACHE_COMPILE

    enabled = conf.get(CACHE_COMPILE)  # conf value: host data
    return bool(cache_root(conf)) and bool(enabled)  # tpulint: ignore[host-sync]


def result_cache_active(conf) -> bool:
    from ..config import CACHE_RESULT

    enabled = conf.get(CACHE_RESULT)  # conf value: host data
    return bool(cache_root(conf)) and bool(enabled)  # tpulint: ignore[host-sync]


# ---------------------------------------------------------------------------
# persistent XLA compile cache + disk-traffic counters
# ---------------------------------------------------------------------------

# process-global counters of the XLA persistent-cache events, fed by the
# jax monitoring listener below. Plain ints bumped under a lock: the obs
# layer deltas them per query and the KernelCache classifies each
# kernel's first invocation (disk-served vs true cold compile).
_COUNTER_LOCK = threading.Lock()
lockwatch.register("exec.persist_cache._COUNTER_LOCK",
                   sys.modules[__name__], "_COUNTER_LOCK")
DISK_HITS = 0
DISK_MISSES = 0

_configured_dir: str | None = None
_listener_installed = False


def _on_monitor_event(event: str, **_kw) -> None:
    global DISK_HITS, DISK_MISSES
    if event == "/jax/compilation_cache/cache_hits":
        with _COUNTER_LOCK:
            DISK_HITS += 1
    elif event == "/jax/compilation_cache/cache_misses":
        with _COUNTER_LOCK:
            DISK_MISSES += 1
    else:
        return
    # per-query attribution: the XLA compile runs on the dispatching
    # thread, so the query-scope contextvar is live here — land the
    # event on the current query's kernel ledger too (scope-exact
    # compile.disk_* deltas under concurrent collects; the process
    # counters above stay the global ground truth)
    from ..obs.metrics import record_compile_disk_event

    record_compile_disk_event(
        hit=event == "/jax/compilation_cache/cache_hits")


def disk_counters() -> dict:
    """Process-absolute XLA persistent-cache traffic (the compile.* keys
    the obs layer deltas per query)."""
    with _COUNTER_LOCK:
        return {"compile.disk_hit": DISK_HITS,
                "compile.disk_miss": DISK_MISSES}


def reset_disk_counters() -> None:
    """Per-process re-init (a fresh cluster worker starts its disk
    tallies at zero regardless of what the driver has accumulated)."""
    global DISK_HITS, DISK_MISSES
    with _COUNTER_LOCK:
        DISK_HITS = 0
        DISK_MISSES = 0


def configure(conf) -> None:
    """Idempotent per-session/per-worker switch (the persist analog of
    obs.resources.configure): with a cache dir configured and the
    compile cache enabled, point jax's persistent compilation cache at
    `<dir>/xla` and install the hit/miss event listener. Never raises
    into session construction."""
    global _configured_dir, _listener_installed
    if not compile_cache_active(conf):
        return
    target = os.path.join(cache_root(conf), "xla")
    try:
        import jax

        from ..config import CACHE_COMPILE_MAX_BYTES

        if _configured_dir != target:
            os.makedirs(target, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", target)
            # every engine kernel qualifies: the suite is compile-bound
            # precisely because of many small programs
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
            max_bytes = int(conf.get(  # tpulint: ignore[host-sync]
                CACHE_COMPILE_MAX_BYTES))
            if max_bytes > 0:
                jax.config.update("jax_compilation_cache_max_size",
                                  max_bytes)
            _configured_dir = target
        if not _listener_installed:
            import jax._src.monitoring as _mon

            _mon.register_event_listener(_on_monitor_event)
            _listener_installed = True
    except Exception:
        # the persistent cache is an optimization: a read-only FS or a
        # jax without the knobs must never fail session construction
        pass


# ---------------------------------------------------------------------------
# data-version component of the result key
# ---------------------------------------------------------------------------

# identity-keyed memo of table content hashes: arrow tables are
# immutable, so one digest per live table object is sound — and the
# repeated-query path (every collect AND every analysis probe calls
# result_key) must not re-hash a big table per repetition. Entries
# carry a weakref so a recycled id() can never alias a dead table.
_HASH_MEMO: dict = {}


def _arrow_content_hash(table) -> str | None:
    """Stable content hash of an in-memory arrow table (schema + the
    IPC-stream serialization of its logical values). Two sessions built
    from identical host data produce the same hash, so result-cache
    entries are shared across processes. Hashing the IPC bytes rather
    than the raw buffers is a correctness requirement, not a
    convenience: slices share their parent's buffers with the offset
    carried on the Array, so two DIFFERENT-valued slices of one table
    are byte-identical at the buffer level — the IPC writer serializes
    logical content, and identical stream bytes decode to identical
    values by construction. (Value-equal tables that were CONSTRUCTED
    differently — e.g. a non-zero-offset slice vs a rebuilt copy of the
    same rows — may still hash apart: that direction is only a missed
    cache hit, never a wrong answer.) Tables past the hash budget
    return None (uncacheable — hashing would cost more than re-running
    the query saves)."""
    import io

    import pyarrow as pa

    try:
        ent = _HASH_MEMO.get(id(table))
        if ent is not None and ent[0]() is table:
            return ent[1]
        if table.nbytes > _HASH_MAX_BYTES:
            return None
        h = hashlib.blake2b(digest_size=16)
        h.update(str(table.schema).encode())

        class _HashSink(io.RawIOBase):
            def writable(self) -> bool:
                return True

            def write(self, buf) -> int:
                mv = memoryview(buf)
                h.update(mv)
                return mv.nbytes

        with pa.ipc.new_stream(_HashSink(), table.schema) as w:
            w.write_table(table)
        digest = h.hexdigest()
        try:
            import weakref

            if len(_HASH_MEMO) > 256:
                for k in [k for k, (r, _d) in _HASH_MEMO.items()
                          if r() is None]:
                    del _HASH_MEMO[k]
            _HASH_MEMO[id(table)] = (weakref.ref(table), digest)
        except TypeError:
            pass  # not weakref-able: just skip the memo
        return digest
    except Exception:
        return None


def _iter_plan(physical):
    """Every node, descending through the whole-query wrapper (its
    child_fields=() hides the inner plan from iter_nodes)."""
    stack = [physical]
    while stack:
        n = stack.pop()
        yield n
        kids = list(n.children)
        inner = getattr(n, "plan", None)
        if not kids and inner is not None and hasattr(inner, "children"):
            kids = [inner]
        stack.extend(kids)


_NONDETERMINISTIC = ("Rand", "Randn", "Uuid", "Shuffle",
                     "MonotonicallyIncreasingID", "SparkPartitionID",
                     "InputFileName", "CurrentTimestamp", "CurrentDate",
                     "Now", "LocalTimestamp")


def leaf_data_versions(physical):
    """(versions, deps) — one identity token per leaf, plus the file
    paths the entry depends on (the catalog write path invalidates by
    dep). None when any leaf's data identity is unknown: the plan
    fingerprint alone does NOT identify the answer (it hashes schema and
    row counts, not values), so such plans never reach the result
    cache."""
    from ..physical import operators as O

    versions: list = []
    deps: list[str] = []
    for node in _iter_plan(physical):
        if node.children:
            continue
        if isinstance(node, O.RangeExec):
            versions.append(("range", node.start, node.end, node.step))
            continue
        if isinstance(node, O.LocalTableScanExec):
            ch = _arrow_content_hash(node.table)
            if ch is None:
                return None, None
            versions.append(("arrow", ch))
            continue
        if isinstance(node, O.ScanExec):
            src = getattr(node, "source", None)
            table = getattr(src, "table", None)
            files = getattr(src, "files", None)
            if table is not None:
                ch = _arrow_content_hash(table)
                if ch is None:
                    return None, None
                versions.append(("arrow", ch))
                continue
            if files:
                idents = []
                try:
                    for f in files:
                        st = os.stat(f)
                        idents.append((os.path.abspath(f), st.st_size,
                                       st.st_mtime_ns))
                except OSError:
                    return None, None
                versions.append(("files", tuple(idents)))
                deps.extend(p for p, _s, _m in idents)
                continue
            return None, None
        if isinstance(node, _whole_query_cls()):
            continue  # wrapper, its inner plan already walked
        # any other leaf (streaming source, fetch stub): unknown identity
        return None, None
    return versions, deps


def _whole_query_cls():
    from ..physical.whole_query import WholeQueryExec

    return WholeQueryExec


class _Unkeyable(Exception):
    """Plan state whose value identity cannot be rendered
    deterministically — the plan is uncacheable, never mis-keyed."""


_RENDER_MAX_DEPTH = 64

# per-class memo of the __init__ parameter names to render (None for a
# class whose constructor cannot be introspected)
_CTOR_PARAMS: dict = {}


def _ctor_param_names(cls):
    hit = _CTOR_PARAMS.get(cls)
    if hit is not None or cls in _CTOR_PARAMS:
        return hit
    import inspect

    names = None
    try:
        sig = inspect.signature(cls.__init__)
        names = []
        for name, p in sig.parameters.items():
            if name == "self":
                continue
            if p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD):
                names = None
                break
            names.append(name)
    except (TypeError, ValueError):
        names = None
    _CTOR_PARAMS[cls] = names
    return names


def _engine_state(v, seen: tuple, depth: int) -> str:
    """Render an engine-owned object (plan node, expression, spec,
    source, …) as its type, its CONSTRUCTOR state, and its display
    string. Constructor state — the current attribute value of every
    __init__ parameter — is exactly the semantic identity: public
    runtime scratch (exchange last_stats), private memos (_fp_memo,
    _struct_key, _metric_id, lazily-bound lambdas), and display
    truncation all stay out, so the render is stable across execution
    and re-analysis while still capturing every value-bearing field
    that a lossy simple_string() omits (AggSpec.param, window frame
    bounds, …). The display string rides along as belt-and-braces for
    any class whose derived-but-not-parameter state matters."""
    names = _ctor_param_names(type(v))
    if names is None:
        raise _Unkeyable(f"{type(v).__name__} constructor")

    def _item(name, val):
        # expr-ids are re-assigned on every re-analysis: render them as
        # \x00-marked tokens so the ordinal remap in _exact_plan_detail
        # makes them stable. The marker byte cannot collide with user
        # data: every string value renders through repr(), which escapes
        # control characters, so a raw NUL in the render text can only
        # come from here (a bare `#N` pattern would also match literals
        # like '#901' and merge two different queries' keys)
        if name == "expr_id" and isinstance(val, int):
            return f"{name}=\x00{val}\x00"
        return f"{name}={_render_value(val, seen, depth + 1)}"

    items = []
    try:
        for name in names:
            items.append(_item(name, getattr(v, name)))
    except AttributeError:
        # a constructor arg stored under a different attribute name
        # (FusedAggregateExec's `outputs` → `pipe_outputs`): fall back
        # to the full public __dict__ — a SUPERSET of the stored ctor
        # state, so no semantics are lost; underscore fields (memos,
        # caches, runtime scratch) stay out either way
        d = getattr(v, "__dict__", None)
        if d is None:
            raise _Unkeyable(type(v).__name__)
        items = [_item(k, x) for k, x in sorted(d.items())
                 if not k.startswith("_")]
    else:
        # plan-time splices hang semantic state on fields that are NOT
        # constructor args: fused pipelines absorbed into an exchange /
        # join probe side (the producing ComputeExec leaves the tree —
        # pipe_fusion is the ONLY carrier of its filters) and the
        # exchange stat-column annotation. Set before any key
        # computation, never mutated at runtime.
        for name in ("pipe_fusion", "pipe_attrs", "probe_fusion",
                     "probe_attrs", "stat_cols"):
            if name not in names:
                val = getattr(v, name, None)
                if val is not None:
                    items.append(_item(name, val))
    disp = ""
    if hasattr(v, "simple_string"):
        try:
            # display #N tokens are expr-ids (re-assigned per analysis)
            # or #N-shaped literal substrings (already rendered exactly
            # in the constructor state above): collapse them all — the
            # display is belt-and-braces detail, and keeping raw ids
            # would make the key parse-volatile
            disp = ":" + re.sub(r"#\d+", "#",
                                _ADDR.sub("@", v.simple_string()))
        except Exception:
            disp = ""
    return f"{type(v).__name__}{{{','.join(items)}}}{disp}"


def _render_value(v, seen: tuple, depth: int = 0) -> str:
    """Deterministic, value-complete rendering of one plan-node field.
    This deliberately does NOT trust `simple_string()`/`repr` alone for
    engine objects: several operators' display strings are lossy
    (HashAggregateExec prints aggregate fn names but not AggSpec.param,
    WindowExec prints function names but not partition/order keys or
    frame bounds) and a display-keyed result cache served one query's
    rows for another. Engine-owned objects (anything under spark_tpu,
    expressions included) render via _engine_state; nested plan nodes
    render as placeholders (the plan walk visits each exactly once);
    arrow/numpy payloads render as placeholders (leaf content identity
    rides leaf_data_versions); functions render as their code-object
    identity with closure cells rendered through this same function.
    Anything whose state cannot be rendered without a process-volatile
    memory address raises _Unkeyable — a conservative cache MISS,
    never a collision."""
    if depth > _RENDER_MAX_DEPTH:
        raise _Unkeyable("nesting depth")
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return repr(v)
    import numpy as np
    import pyarrow as pa

    if isinstance(v, np.generic):
        return repr(v)
    if isinstance(v, (pa.Table, pa.RecordBatch, pa.ChunkedArray, pa.Array,
                      np.ndarray)):
        return "<data>"
    if isinstance(v, (list, tuple)):
        return ("[" + ",".join(_render_value(x, seen, depth + 1)
                               for x in v) + "]")
    if isinstance(v, (set, frozenset)):
        return ("{" + ",".join(sorted(_render_value(x, seen, depth + 1)
                                      for x in v)) + "}")
    if isinstance(v, dict):
        return "{" + ",".join(
            f"{_render_value(k, seen, depth + 1)}:"
            f"{_render_value(x, seen, depth + 1)}"
            for k, x in sorted(v.items(), key=lambda kv: repr(kv[0]))) + "}"
    if callable(v) and hasattr(v, "__code__"):
        try:
            c = v.__code__
            cells = tuple(_render_value(cell.cell_contents, seen, depth + 1)
                          for cell in (v.__closure__ or ()))
        except _Unkeyable:
            raise
        except Exception:
            raise _Unkeyable("function identity")
        return "fn:" + hashlib.blake2b(
            c.co_code + repr((c.co_consts, cells)).encode(),
            digest_size=8).hexdigest()
    from ..expr.expressions import Expression
    from ..physical.operators import PhysicalPlan
    from ..plan.logical import LogicalPlan

    if isinstance(v, (PhysicalPlan, LogicalPlan)):
        # child position/count still lands in the render; the node's own
        # fields are rendered by the _iter_plan walk, exactly once
        return f"<plan:{type(v).__name__}>"
    if isinstance(v, Expression) and (
            not getattr(v, "deterministic", True)
            or type(v).__name__ in _NONDETERMINISTIC):
        # determinism gate ON the render walk, so its coverage is the
        # key's coverage by construction: a non-deterministic expression
        # nested anywhere key-reachable — an AggSpec's input_expr, a
        # fused pipeline's filters riding pipe_fusion/probe_fusion —
        # makes the plan uncacheable (a shallow node-attribute scan
        # missed exactly those carriers and cached rand()-dependent
        # results)
        raise _Unkeyable(f"non-deterministic {type(v).__name__}")
    if any(x is v for x in seen):
        raise _Unkeyable("cycle")
    if type(v).__module__.startswith("spark_tpu"):
        return _engine_state(v, seen + (v,), depth)
    r = repr(v)
    if _ADDR.search(r):
        raise _Unkeyable(type(v).__name__)
    return f"{type(v).__name__}:{r}"


def _exact_plan_detail(physical) -> str | None:
    """Value-EXACT plan identity folded into the result key beside the
    telemetry fingerprint. obs/history's fingerprint sanitizer strips
    expr-ids and hex-literal-like tokens and truncates node detail to
    200 chars — exactly right for profile keying across runs, unsound
    as the sole correctness key for RETURNED ROWS (two queries
    differing only in a 16-char hex string literal, or past the detail
    cap, would collide). This component renders every node's FULL field
    state through _render_value (display strings are lossy — see its
    docstring), remapping expr-ids to first-occurrence ordinals (they
    are re-assigned on every re-analysis of the same query text, but
    ordinals are stable for the same plan shape while still telling
    same-named attributes apart). Function-valued state (Python UDFs
    included) folds code-object identity so a redefined same-name UDF
    cannot serve the old function's cached answer. Returns None —
    uncacheable — for any state without a deterministic rendering."""
    parts: list[str] = []
    try:
        for node in _iter_plan(physical):
            parts.append(_engine_state(node, (node,), 0))
    except _Unkeyable:
        return None
    ids: dict = {}

    def _ordinal(m) -> str:
        t = m.group(0)
        if t not in ids:
            ids[t] = len(ids)
        return f"@{ids[t]}"

    # only \x00-marked expr-id tokens remap: repr() escapes control
    # bytes, so user literals (even '#901'-shaped ones) can never match
    return re.sub("\x00\\d+\x00", _ordinal, "\n".join(parts))


def result_key(physical, conf, fingerprint: dict | None = None):
    """(cache key, file deps) of a plan's result, or (None, None) when
    the plan is uncacheable (non-deterministic expressions / unknown
    leaf data identity / un-keyable UDF). The key folds the full plan
    fingerprint (the PR 12 structural hash including tier-relevant
    config), the value-exact plan detail (_exact_plan_detail — the
    sanitized fingerprint alone is not a correctness key), and the
    per-leaf data versions, so a table append/overwrite or a different
    in-memory input lands on a different key by construction. The
    determinism gate rides the detail render itself (_render_value), so
    a non-deterministic expression anywhere in the keyed state makes
    the plan uncacheable. Pass the caller's memoized `fingerprint` to
    skip recomputing it."""
    exact = _exact_plan_detail(physical)
    if exact is None:
        return None, None
    versions, deps = leaf_data_versions(physical)
    if versions is None:
        return None, None
    if fingerprint is None:
        from ..obs.history import plan_fingerprint

        fingerprint = plan_fingerprint(physical, conf)
    key = hashlib.sha256(json.dumps(
        {"fp": fingerprint["fingerprint"],
         "exact": hashlib.sha256(exact.encode("utf-8", "replace"))
         .hexdigest(),
         "data": versions},
        sort_keys=True, default=str).encode()).hexdigest()[:32]
    return key, sorted(set(deps))


# ---------------------------------------------------------------------------
# the on-disk result cache
# ---------------------------------------------------------------------------

class ResultCache:
    """Bounded, flock-safe on-disk LRU of Arrow IPC query results.

    Layout under `<cache dir>/result/`: one `<key>.arrow` payload + one
    `<key>.meta.json` sidecar ({deps, bytes, ts}) per entry, plus a
    `manifest.jsonl` (shared utils/diskstore.JsonlRing) whose sidecar
    flock is the cross-process mutex for store/evict/invalidate and
    whose ring records the write/invalidate history. Reads (lookup) are
    lockless — the payload is written tmp-then-rename, so a reader sees
    a whole file or no file — and touch the payload mtime, which is the
    LRU clock eviction orders by."""

    def __init__(self, root: str, max_bytes: int):
        from ..utils.diskstore import JsonlRing

        self.dir = os.path.join(root, "result")
        os.makedirs(self.dir, exist_ok=True)
        self.max_bytes = int(max_bytes)
        self.manifest = JsonlRing(os.path.join(self.dir, "manifest.jsonl"),
                                  ring=_MANIFEST_RING)

    # -- paths -------------------------------------------------------------
    def _payload(self, key: str) -> str:
        return os.path.join(self.dir, f"{key}.arrow")

    def _meta(self, key: str) -> str:
        return os.path.join(self.dir, f"{key}.meta.json")

    # -- reads (lockless) --------------------------------------------------
    def lookup(self, key: str):
        """The cached arrow table, or None. A hit touches the payload's
        mtime (the LRU clock)."""
        import pyarrow as pa

        path = self._payload(key)
        try:
            with pa.memory_map(path) as src:
                out = pa.ipc.open_file(src).read_all()
        except (FileNotFoundError, OSError):
            return None
        except Exception:
            return None  # torn/corrupt payload: treat as a miss
        try:
            # LRU-clock touch is best-effort: a payload readable but not
            # writable (cache dir shared across uids) must still HIT —
            # result_probe's has() mirror predicts this path, and a
            # touch failure turning reads into misses would break the
            # predicted-zero-launch exactness contract
            os.utime(path, None)
        except OSError:
            pass
        return out

    def has(self, key: str) -> bool:
        return os.path.isfile(self._payload(key))

    # -- writes (flock-serialized) -----------------------------------------
    def store(self, key: str, table, deps: list[str]) -> bool:
        """Persist one result; False when it exceeds the per-entry bound
        (an eighth of the budget — one giant result must not evict the
        whole working set)."""
        import pyarrow as pa

        nbytes = int(table.nbytes)  # tpulint: ignore[host-sync]
        if self.max_bytes > 0 and nbytes > self.max_bytes // 8:
            return False
        path = self._payload(key)
        with self.manifest.locked():
            if os.path.isfile(path):
                return True  # a concurrent writer won the race
            tmp = path + f".tmp{os.getpid()}"
            try:
                with pa.OSFile(tmp, "wb") as sink:
                    with pa.ipc.new_file(sink, table.schema) as w:
                        w.write_table(table)
                os.replace(tmp, path)
            except Exception:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                return False
            with open(self._meta(key), "w") as f:
                json.dump({"deps": list(deps or ()), "bytes": nbytes,
                           "ts": round(time.time(), 3)}, f)
            self.manifest.append({"op": "put", "key": key,
                                  "bytes": nbytes,
                                  "deps": list(deps or ()),
                                  "ts": round(time.time(), 3)})
            self._evict_locked()
        return True

    def _entries(self) -> list[tuple]:
        """[(mtime, bytes, key)] of live payloads."""
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            if not name.endswith(".arrow"):
                continue
            p = os.path.join(self.dir, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            out.append((st.st_mtime_ns, st.st_size, name[:-len(".arrow")]))
        return out

    def _drop(self, key: str) -> None:
        for p in (self._payload(key), self._meta(key)):
            try:
                os.remove(p)
            except OSError:
                pass

    def _evict_locked(self) -> int:
        """LRU eviction to the byte budget; caller holds the manifest
        lock. Returns evicted entry count."""
        if self.max_bytes <= 0:
            return 0
        entries = sorted(self._entries())
        total = sum(b for _m, b, _k in entries)
        n = 0
        for _mtime, nbytes, key in entries:
            if total <= self.max_bytes:
                break
            self._drop(key)
            total -= nbytes
            n += 1
            self.manifest.append({"op": "evict", "key": key})
        return n

    def invalidate_deps(self, path: str) -> int:
        """Drop every entry depending on `path` (a file or a directory
        prefix — the catalog write path passes the table directory).
        Returns the dropped entry count."""
        prefix = os.path.abspath(path)
        n = 0
        with self.manifest.locked():
            for _mtime, _bytes, key in self._entries():
                try:
                    with open(self._meta(key)) as f:
                        deps = json.load(f).get("deps", [])
                except (OSError, json.JSONDecodeError):
                    deps = []
                if any(d == prefix or d.startswith(prefix + os.sep)
                       for d in deps):
                    self._drop(key)
                    n += 1
                    self.manifest.append({"op": "invalidate", "key": key,
                                          "path": prefix})
        return n

    def total_bytes(self) -> int:
        return sum(b for _m, b, _k in self._entries())


# one ResultCache instance per (root, budget): the object is cheap but
# its __init__ makedirs — and the hot path constructs one per probe,
# per collect, and per catalog write
_RESULT_CACHE_MEMO: dict = {}


def result_cache_for(conf):
    """The session's ResultCache, or None when the result cache is off."""
    if not result_cache_active(conf):
        return None
    from ..config import CACHE_RESULT_MAX_BYTES

    max_bytes = conf.get(CACHE_RESULT_MAX_BYTES)  # conf value: host data
    key = (cache_root(conf), int(max_bytes))  # tpulint: ignore[host-sync]
    rc = _RESULT_CACHE_MEMO.get(key)
    if rc is None:
        rc = _RESULT_CACHE_MEMO[key] = ResultCache(key[0], key[1])
    return rc


def result_probe(physical, conf) -> bool:
    """Would this plan's collect answer from the result cache RIGHT NOW?
    The plan analyzer's launch model calls this (the zero-launch hit
    path must predict exactly); the implementation is the same key
    computation the execution path uses, so the mirror cannot drift.
    Never raises."""
    try:
        if not result_cache_active(conf):
            return False
        key, _deps = result_key(physical, conf)
        if key is None:
            return False
        return result_cache_for(conf).has(key)
    except Exception:
        return False


def invalidate_path(conf, path: str) -> int:
    """Catalog write-path hook: drop result-cache entries depending on
    `path` (table directory / data file). Invoked on save/append/
    overwrite/drop; a no-op when the result cache is off."""
    rc = result_cache_for(conf)
    if rc is None:
        return 0
    try:
        return rc.invalidate_deps(path)
    except Exception:
        return 0


# ---------------------------------------------------------------------------
# warm-start manifest (per-fingerprint KernelCache metadata)
# ---------------------------------------------------------------------------

def _manifest(conf):
    from ..utils.diskstore import JsonlRing

    root = cache_root(conf)
    if not root:
        return None
    return JsonlRing(os.path.join(root, "manifest.jsonl"),
                     ring=_MANIFEST_RING)


# per-process parse memo of the manifest file, keyed by mtime: the
# steady-state serving path reads the manifest once per QUERY (execute's
# seed lookup + plan_lint's mirror), and re-parsing up to 2*ring JSON
# lines each time would tax exactly the repeated-query path this module
# exists to make cheap. GIL-atomic dict ops; a stale racing read just
# re-loads.
_MANIFEST_MEMO: dict = {}


def _manifest_records(m) -> list:
    try:
        mtime = os.stat(m.path).st_mtime_ns
    except OSError:
        return []
    hit = _MANIFEST_MEMO.get(m.path)
    if hit is not None and hit[0] == mtime:
        return hit[1]
    recs = m.load()
    _MANIFEST_MEMO[m.path] = (mtime, recs)
    return recs


def mesh_quota_key(tag: str, num_out: int, rows_per_shard: int,
                   detail: str) -> str:
    """Stable identity of one mesh exchange's quota outcome inside a
    fingerprint's manifest record. Both the execution layer
    (parallel/mesh_exchange.py) and the plan analyzer's mesh mirror
    compute it from the same staging-geometry inputs, so the warm-start
    lookup and its launch-model mirror cannot disagree."""
    return f"mesh:{tag}:p{num_out}:r{rows_per_shard}:{detail}"


def mesh_quota_key_plain(num_out: int, rows_per_shard: int,
                         key_positions, dtypes) -> str:
    """The plain mesh stage's quota slot: geometry + key POSITIONS +
    schema dtypes, not just the key count — two same-geometry plain
    exchanges in one plan shuffling by different columns must not share
    one manifest slot (last-writer-wins would mis-seed one of them on
    every warm restart)."""
    return mesh_quota_key(
        "p", num_out, rows_per_shard,
        f"k{tuple(key_positions)}:s{'|'.join(dtypes)}")


def mesh_quota_key_fused(num_out: int, rows_per_shard: int,
                         key_idx, out_len: int, dtypes) -> str:
    """The fused mesh stage's quota slot (see mesh_quota_key_plain)."""
    return mesh_quota_key(
        "f", num_out, rows_per_shard,
        f"o{out_len}:{tuple(key_idx)}:s{'|'.join(dtypes)}")


def record_manifest(conf, fingerprint: dict, tier: dict | None,
                    join_caps: list | None,
                    mesh_quotas: dict | None,
                    prior: dict | None = None,
                    join_spans: list | None = None,
                    observed_rows: int | None = None) -> None:
    """Persist one query's capacity outcomes keyed by its full plan
    fingerprint (driver-only, at query close). Only written when there
    is something a warm restart could seed — the empty steady state is
    the default and needs no record. `prior` is the seed record this
    run started from (ctx.persist_seed): a seeded steady-state run
    whose outcomes match it appends nothing — the manifest records
    capacity CHANGES, not every repetition. `join_spans` carries the
    observed build-side key span per whole-program join
    ([lo, hi, unique] or None, aligned with join_caps): a warm restart
    compiles the dense direct-address probe variant directly instead of
    re-learning the span through the sorted probe. `observed_rows` is
    the run's measured shuffle volume (adaptive history re-planning:
    a recurring query over statistics-less external sources re-enters
    the tier chooser with it before the first batch moves); a whole-tier
    run shuffles nothing, so a missing value carries the prior's
    forward."""
    if observed_rows is None and prior is not None:
        observed_rows = prior.get("observed_rows")
    if not join_caps and not mesh_quotas and not join_spans \
            and not observed_rows:
        return
    m = _manifest(conf)
    if m is None:
        return
    try:
        rec = {
            "fp": fingerprint["fingerprint"],
            "stages": [s["fingerprint"]
                       for s in fingerprint.get("stages", ())],
            "tier": (tier or {}).get("tier"),
            "join_caps": [int(c) for c in (join_caps or ())],
            "mesh_quotas": {k: int(v)
                            for k, v in (mesh_quotas or {}).items()},
            "join_spans": [None if s is None else [int(x) for x in s]
                           for s in (join_spans or ())],
            "observed_rows": None if observed_rows is None
            else int(observed_rows)}
        if prior is not None and all(
                # records predating join_spans normalize to the empty
                # list, so a seeded steady-state rerun stays append-free
                (prior.get(k) or rec[k].__class__()) == rec[k]
                if k == "join_spans" else prior.get(k) == rec[k]
                for k in ("fp", "tier", "join_caps", "mesh_quotas",
                          "join_spans", "observed_rows")):
            return
        m.append({**rec, "ts": round(time.time(), 3)})
    except Exception:
        pass  # manifest writes must never fail a query


def manifest_seed(conf, fingerprint_hash: str) -> dict | None:
    """The newest manifest record for this full fingerprint, or None.
    Shared by the execution layer (QueryExecution stashes it on the
    ExecContext) and the plan analyzer's capacity mirrors. Never
    raises."""
    m = _manifest(conf)
    if m is None:
        return None
    try:
        hit = None
        for rec in _manifest_records(m):
            if rec.get("fp") == fingerprint_hash:
                hit = rec
        return hit
    except Exception:
        return None
