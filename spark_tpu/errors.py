"""Error-condition framework.

Modeled on the reference's SparkThrowable/error-class system
(common/utils/src/main/resources/error/ + SparkThrowable JSON error conditions,
see SURVEY.md §2.2 "utils / utils-java") but as a small Python exception
hierarchy with stable error classes.
"""

from __future__ import annotations


class SparkTpuError(Exception):
    """Base error. `error_class` is a stable machine-readable identifier."""

    error_class: str = "INTERNAL_ERROR"

    def __init__(self, message: str, error_class: str | None = None):
        super().__init__(message)
        if error_class is not None:
            self.error_class = error_class


class AnalysisException(SparkTpuError):
    """Raised during analysis/resolution (reference: AnalysisException)."""

    error_class = "ANALYSIS_ERROR"


class ParseException(AnalysisException):
    """SQL text could not be parsed (reference: ParseException)."""

    error_class = "PARSE_SYNTAX_ERROR"


class UnresolvedColumnError(AnalysisException):
    error_class = "UNRESOLVED_COLUMN"

    def __init__(self, name: str, candidates: list[str] | None = None):
        hint = f". Did you mean one of: {candidates}?" if candidates else ""
        super().__init__(
            f"A column or function parameter with name `{name}` cannot be resolved{hint}"
        )
        self.name = name


class TypeCheckError(AnalysisException):
    error_class = "DATATYPE_MISMATCH"


class ExecutionError(SparkTpuError):
    """Raised while executing a physical plan."""

    error_class = "EXECUTION_ERROR"


class CapacityOverflowError(ExecutionError):
    """A static-shape kernel produced more rows than its output capacity.

    The runtime catches this and retries with the next capacity bucket
    (the TPU analog of the reference's spill-to-disk escape hatches, e.g.
    TungstenAggregationIterator's sort-based fallback).
    """

    error_class = "CAPACITY_OVERFLOW"

    def __init__(self, needed: int, capacity: int, site: str = ""):
        super().__init__(
            f"Kernel at {site or '<unknown>'} needed {needed} output rows "
            f"but static capacity is {capacity}"
        )
        self.needed = needed
        self.capacity = capacity


class StageRegenerationLimitError(ExecutionError):
    """A query kept losing shuffle outputs (FetchFailed) and hit the
    per-query stage-regeneration cap (spark.tpu.scheduler.maxStageRegens)
    — the classified terminal form of what would otherwise be an
    unbounded regenerate/fetch/fail loop (reference: DAGScheduler's
    abort after spark.stage.maxConsecutiveAttempts)."""

    error_class = "STAGE_REGENERATION_LIMIT"

    def __init__(self, regens: int, cap: int, shuffle_id: str = ""):
        super().__init__(
            f"query exceeded {cap} shuffle-stage regenerations "
            f"({regens} FetchFailed recoveries; last lost shuffle "
            f"{shuffle_id or '<unknown>'}) — executors are losing map "
            "outputs faster than lineage can regenerate them")
        self.regens = regens
        self.cap = cap


class ServingError(SparkTpuError):
    """Raised by the multi-tenant serving layer (spark_tpu/serve/)."""

    error_class = "SERVING_ERROR"


class ServerDraining(ServingError):
    """The server is shutting down gracefully: in-flight queries are
    completing, new queries are rejected (role of the reference's
    HiveThriftServer2 deregistration + session-manager stop — clients
    should reconnect elsewhere or retry after the restart)."""

    error_class = "SERVER_DRAINING"

    def __init__(self, message: str | None = None):
        super().__init__(
            message or "server is draining: in-flight queries are "
                       "completing, new queries are rejected")


class AdmissionTimeout(ServingError):
    """A query waited in its fair-scheduler pool's queue past the pool's
    queue timeout without winning a slot (pool saturated or its
    in-flight HBM reservation never freed enough budget)."""

    error_class = "ADMISSION_TIMEOUT"

    def __init__(self, pool: str, timeout_s: float):
        super().__init__(
            f"query admission timed out after {timeout_s:g}s in pool "
            f"'{pool}' (pool saturated; raise "
            "spark.tpu.serve.queueTimeout, the pool's weight, or "
            "spark.tpu.serve.maxConcurrent)")
        self.pool = pool
        self.timeout_s = timeout_s


class PoolQueueFull(ServingError):
    """A fair-scheduler pool's bounded admission queue is full — the
    query is rejected immediately instead of waiting (load shedding;
    role of the reference's spark.scheduler.* pool backlog limits)."""

    error_class = "POOL_QUEUE_FULL"

    def __init__(self, pool: str, size: int):
        super().__init__(
            f"admission queue of pool '{pool}' is full ({size} queued "
            "queries); rejecting instead of queueing unboundedly — "
            "raise spark.tpu.serve.queueSize or add capacity")
        self.pool = pool
        self.size = size


class UnknownPoolError(ServingError):
    """A statement named a fair-scheduler pool that is not declared —
    via a `/*+ POOL(x) */` hint or an explicit collect(pool=...). Typed
    (not a silent fallback to 'default'): a routing typo that quietly
    lands a batch query in the interactive pool defeats the isolation
    the pools exist for."""

    error_class = "UNKNOWN_POOL"

    def __init__(self, pool: str, valid: list[str]):
        super().__init__(
            f"unknown fair-scheduler pool '{pool}'; declared pools: "
            f"{', '.join(sorted(valid)) or '(none)'} — declare it in "
            f"spark.tpu.scheduler.pools or use an existing pool")
        self.pool = pool
        self.valid = sorted(valid)


class UnsupportedOperationError(SparkTpuError):
    error_class = "UNSUPPORTED_OPERATION"
