"""SQL data types and schemas.

Role of the reference's sql/api types (StructType/StructField/DataType; see
SURVEY.md §2.3 "Row formats") re-designed for a columnar TPU engine: every
type carries its *device representation* (a JAX dtype) plus host/Arrow
mapping. Strings are dictionary-encoded (int32 codes on device); dates are
int32 days since epoch; timestamps int64 microseconds; decimals are scaled
int64 (XLA emulates int64 with int32 pairs on TPU).
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass, field

import numpy as np

__all__ = [
    "DataType",
    "NumericType",
    "IntegralType",
    "FractionalType",
    "BooleanType",
    "ByteType",
    "ShortType",
    "IntegerType",
    "LongType",
    "FloatType",
    "DoubleType",
    "StringType",
    "DateType",
    "TimestampType",
    "DecimalType",
    "NullType",
    "BinaryType",
    "StructField",
    "StructType",
    "ArrayType",
    "MapType",
    "boolean",
    "int8",
    "int16",
    "int32",
    "int64",
    "float32",
    "float64",
    "string",
    "date",
    "timestamp",
    "null_type",
    "common_type",
    "dict_encoded",
    "from_arrow_type",
    "to_arrow_type",
]


@dataclass(frozen=True)
class DataType:
    """Base SQL type. Subclasses are singletons except DecimalType."""

    def simple_string(self) -> str:
        return type(self).__name__.replace("Type", "").lower()

    # --- device representation ---------------------------------------
    @property
    def device_dtype(self) -> np.dtype:
        """numpy/JAX dtype of the on-device representation."""
        raise NotImplementedError

    @property
    def is_string_like(self) -> bool:
        return False

    def __repr__(self) -> str:  # pragma: no cover
        return self.simple_string()


class NullType(DataType):
    @property
    def device_dtype(self) -> np.dtype:
        return np.dtype(np.int32)


class NumericType(DataType):
    pass


class IntegralType(NumericType):
    pass


class FractionalType(NumericType):
    pass


class BooleanType(DataType):
    @property
    def device_dtype(self) -> np.dtype:
        return np.dtype(np.bool_)


class ByteType(IntegralType):
    @property
    def device_dtype(self) -> np.dtype:
        return np.dtype(np.int8)


class ShortType(IntegralType):
    @property
    def device_dtype(self) -> np.dtype:
        return np.dtype(np.int16)


class IntegerType(IntegralType):
    @property
    def device_dtype(self) -> np.dtype:
        return np.dtype(np.int32)


class LongType(IntegralType):
    @property
    def device_dtype(self) -> np.dtype:
        return np.dtype(np.int64)


class FloatType(FractionalType):
    @property
    def device_dtype(self) -> np.dtype:
        return np.dtype(np.float32)


class DoubleType(FractionalType):
    @property
    def device_dtype(self) -> np.dtype:
        return np.dtype(np.float64)


class StringType(DataType):
    """Dictionary-encoded UTF-8 string: device = int32 codes into a host
    dictionary (reference stores raw UTF8String bytes in UnsafeRow,
    common/unsafe/.../UTF8String.java; on TPU we keep bytes host-side and
    compute on codes/hashes — SURVEY.md §7 'Hard parts' (2))."""

    @property
    def device_dtype(self) -> np.dtype:
        return np.dtype(np.int32)

    @property
    def is_string_like(self) -> bool:
        return True


class BinaryType(StringType):
    """Binary blobs, dictionary-encoded like strings."""


class DateType(DataType):
    """Days since 1970-01-01 (matches Arrow date32)."""

    @property
    def device_dtype(self) -> np.dtype:
        return np.dtype(np.int32)


class TimestampType(DataType):
    """Microseconds since epoch (matches Arrow timestamp[us])."""

    @property
    def device_dtype(self) -> np.dtype:
        return np.dtype(np.int64)


@dataclass(frozen=True)
class DecimalType(FractionalType):
    """Fixed-point decimal stored as scaled int64 on device.

    The reference implements Decimal over JVM BigDecimal/Long
    (sql/api .../types/DecimalType.scala). TPUs have no int128; we cap
    precision at 18 (int64-safe) and widen sums via int64 with overflow
    checks host-side.
    """

    precision: int = 10
    scale: int = 0

    MAX_PRECISION = 18

    def simple_string(self) -> str:
        return f"decimal({self.precision},{self.scale})"

    @property
    def device_dtype(self) -> np.dtype:
        return np.dtype(np.int64)


@dataclass(frozen=True)
class ArrayType(DataType):
    """Ragged arrays have no dense device layout; array columns are
    dictionary-encoded like strings — int32 codes on device, the list
    values host-side in the column's dictionary."""

    element_type: DataType = field(default_factory=lambda: IntegerType())

    def simple_string(self) -> str:
        return f"array<{self.element_type.simple_string()}>"

    @property
    def device_dtype(self) -> np.dtype:
        return np.dtype(np.int32)


@dataclass(frozen=True)
class MapType(DataType):
    """Maps are dictionary-encoded like arrays (int32 codes on device,
    python dicts host-side) — reference: UnsafeMapData.java role, with
    the TPU analog being host dictionaries + device gather LUTs."""

    key_type: "DataType" = field(default_factory=lambda: StringType())
    value_type: "DataType" = field(default_factory=lambda: IntegerType())

    def simple_string(self) -> str:
        return (f"map<{self.key_type.simple_string()},"
                f"{self.value_type.simple_string()}>")

    @property
    def device_dtype(self) -> np.dtype:
        return np.dtype(np.int32)


# Singleton-ish instances
boolean = BooleanType()
int8 = ByteType()
int16 = ShortType()
int32 = IntegerType()
int64 = LongType()
float32 = FloatType()
float64 = DoubleType()
string = StringType()
binary = BinaryType()
date = DateType()
timestamp = TimestampType()
null_type = NullType()


@dataclass(frozen=True)
class StructField:
    name: str
    dataType: DataType
    nullable: bool = True

    def __repr__(self) -> str:  # pragma: no cover
        return f"{self.name}:{self.dataType.simple_string()}"


@dataclass(frozen=True)
class StructType(DataType):
    fields: tuple[StructField, ...] = ()

    def __init__(self, fields=()):
        object.__setattr__(self, "fields", tuple(fields))

    @property
    def names(self) -> list[str]:
        return [f.name for f in self.fields]

    @property
    def device_dtype(self) -> np.dtype:
        # struct COLUMNS are dictionary-encoded (codes on device, python
        # dicts host-side), like arrays/maps
        return np.dtype(np.int32)

    def field_type(self, name: str) -> "DataType | None":
        for f in self.fields:
            if f.name == name:
                return f.dataType
        return None

    def add(self, name: str, dataType: DataType, nullable: bool = True) -> "StructType":
        return StructType(self.fields + (StructField(name, dataType, nullable),))

    def __getitem__(self, name: str) -> StructField:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def simple_string(self) -> str:
        inner = ",".join(f"{f.name}:{f.dataType.simple_string()}" for f in self.fields)
        return f"struct<{inner}>"


# ---------------------------------------------------------------------------
# Type coercion lattice (reference: sqlcat/analysis/TypeCoercion.scala)
# ---------------------------------------------------------------------------

_NUMERIC_ORDER: list[DataType] = [int8, int16, int32, int64, float32, float64]


def _numeric_rank(dt: DataType) -> int:
    if isinstance(dt, DecimalType):
        return _NUMERIC_ORDER.index(int64)  # decimals widen like long
    for i, t in enumerate(_NUMERIC_ORDER):
        if type(dt) is type(t):
            return i
    return -1


def common_type(a: DataType, b: DataType) -> DataType | None:
    """Tightest common type both sides can be cast to, or None."""
    if a == b:
        return a
    if isinstance(a, NullType):
        return b
    if isinstance(b, NullType):
        return a
    if isinstance(a, DecimalType) and isinstance(b, DecimalType):
        scale = max(a.scale, b.scale)
        intd = max(a.precision - a.scale, b.precision - b.scale)
        return DecimalType(min(intd + scale, DecimalType.MAX_PRECISION), scale)
    if isinstance(a, DecimalType) and isinstance(b, IntegralType):
        return a
    if isinstance(b, DecimalType) and isinstance(a, IntegralType):
        return b
    if isinstance(a, DecimalType) and isinstance(b, FractionalType):
        return float64
    if isinstance(b, DecimalType) and isinstance(a, FractionalType):
        return float64
    ra, rb = _numeric_rank(a), _numeric_rank(b)
    if ra >= 0 and rb >= 0:
        return _NUMERIC_ORDER[max(ra, rb)]
    if isinstance(a, StringType) and isinstance(b, StringType):
        return string
    # date/timestamp promotion
    if isinstance(a, DateType) and isinstance(b, TimestampType):
        return timestamp
    if isinstance(b, DateType) and isinstance(a, TimestampType):
        return timestamp
    # string <-> other: cast string side (Spark coerces string to the other type
    # in BinaryComparison); we model as the other type
    if isinstance(a, StringType):
        return b
    if isinstance(b, StringType):
        return a
    return None


# ---------------------------------------------------------------------------
# Arrow mapping
# ---------------------------------------------------------------------------

def dict_encoded(dt) -> bool:
    """True for types whose columns are host-dictionary-encoded (int32
    codes on device): strings/binary, arrays, maps, structs."""
    return isinstance(dt, (StringType, ArrayType, MapType, StructType))


def from_arrow_type(at) -> DataType:
    import pyarrow as pa

    if pa.types.is_boolean(at):
        return boolean
    if pa.types.is_int8(at):
        return int8
    if pa.types.is_int16(at):
        return int16
    if pa.types.is_int32(at):
        return int32
    if pa.types.is_int64(at):
        return int64
    if pa.types.is_float32(at):
        return float32
    if pa.types.is_float64(at):
        return float64
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return string
    if pa.types.is_binary(at) or pa.types.is_large_binary(at):
        return binary
    if pa.types.is_date32(at):
        return date
    if pa.types.is_timestamp(at):
        return timestamp
    if pa.types.is_decimal(at):
        return DecimalType(min(at.precision, DecimalType.MAX_PRECISION), at.scale)
    if pa.types.is_dictionary(at):
        return from_arrow_type(at.value_type)
    if pa.types.is_list(at) or pa.types.is_large_list(at):
        return ArrayType(from_arrow_type(at.value_type))
    if pa.types.is_map(at):
        return MapType(from_arrow_type(at.key_type),
                       from_arrow_type(at.item_type))
    if pa.types.is_struct(at):
        return StructType(tuple(
            StructField(f.name, from_arrow_type(f.type), f.nullable)
            for f in at))
    if pa.types.is_null(at):
        return null_type
    raise NotImplementedError(f"Arrow type not supported: {at}")


def to_arrow_type(dt: DataType):
    import pyarrow as pa

    if isinstance(dt, BooleanType):
        return pa.bool_()
    if isinstance(dt, ByteType):
        return pa.int8()
    if isinstance(dt, ShortType):
        return pa.int16()
    if isinstance(dt, IntegerType):
        return pa.int32()
    if isinstance(dt, LongType):
        return pa.int64()
    if isinstance(dt, FloatType):
        return pa.float32()
    if isinstance(dt, DoubleType):
        return pa.float64()
    if isinstance(dt, BinaryType):
        return pa.binary()
    if isinstance(dt, StringType):
        return pa.string()
    if isinstance(dt, DateType):
        return pa.date32()
    if isinstance(dt, TimestampType):
        return pa.timestamp("us")
    if isinstance(dt, DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, NullType):
        return pa.null()
    if isinstance(dt, ArrayType):
        return pa.list_(to_arrow_type(dt.element_type))
    if isinstance(dt, MapType):
        return pa.map_(to_arrow_type(dt.key_type),
                       to_arrow_type(dt.value_type))
    if isinstance(dt, StructType):
        return pa.struct([(f.name, to_arrow_type(f.dataType))
                          for f in dt.fields])
    raise NotImplementedError(f"no arrow type for {dt}")


def infer_type(value) -> DataType:
    """Infer a DataType from a Python literal value."""
    if value is None:
        return null_type
    if isinstance(value, bool):
        return boolean
    if isinstance(value, int):
        return int32 if -(2**31) <= value < 2**31 else int64
    if isinstance(value, float):
        return float64
    if isinstance(value, str):
        return string
    if isinstance(value, bytes):
        return binary
    if isinstance(value, datetime.datetime):
        return timestamp
    if isinstance(value, datetime.date):
        return date
    import decimal as _d

    if isinstance(value, _d.Decimal):
        sign, digits, exp = value.as_tuple()
        scale = max(0, -exp)
        return DecimalType(max(len(digits), scale), scale)
    raise TypeError(f"cannot infer SQL type for {value!r}")
