"""Driver-side standalone cluster backend.

Role of the reference's StandaloneAppClient + StandaloneSchedulerBackend
(core/deploy/client/StandaloneAppClient.scala:60 registerWithMaster,
core/scheduler/cluster/StandaloneSchedulerBackend.scala): the driver
keeps its own control plane (executor registration, heartbeats, task
dispatch — the LocalCluster machinery), but instead of spawning local
executor processes it asks a MASTER daemon for them; worker daemons
launch the executor processes, which then dial the driver directly.
Worker churn is the master's problem (it re-places lost executors); the
driver's HealthTracker + task retry absorb the loss in-flight.
"""

from __future__ import annotations

import pickle
import time

from ..exec.cluster import LocalCluster
from ..net.transport import RpcClient


def parse_master_url(url: str) -> str:
    """grpc://host:port → host:port (the reference's spark://host:port)."""
    for prefix in ("grpc://", "spark://"):
        if url.startswith(prefix):
            return url[len(prefix):]
    return url


class StandaloneCluster(LocalCluster):
    """A cluster whose executors come from a standalone master."""

    def __init__(self, master_url: str, master_secret: str,
                 num_executors: int = 2, app_name: str = "app",
                 bind_host: str = "127.0.0.1",
                 executor_wait_timeout: float = 60.0, **kw):
        super().__init__(num_workers=0, bind_host=bind_host, **kw)
        self.master_addr = parse_master_url(master_url)
        self._master_secret = master_secret
        self.app_id = ""
        self._master = None
        try:
            self._master = RpcClient(self.master_addr, master_secret)
            self._master.wait_ready(30)
            env_extra = {}
            if self.push_shuffle and self.shuffle_service_addr:
                env_extra["SPARK_TPU_SHUFFLE_PUSH_ADDR"] = \
                    self.shuffle_service_addr
            if self.heartbeat_interval is not None:
                # daemon-launched executors heartbeat (and flush live
                # obs) at the session's configured cadence too
                env_extra["SPARK_TPU_HEARTBEAT_INTERVAL"] = \
                    str(self.heartbeat_interval)
            self.app_id = self._master.call("submit_app", pickle.dumps({
                "name": app_name,
                "driver_addr": self.driver_addr,
                "driver_token": self.token,
                "executors": num_executors,
                "env_extra": env_extra,
            }), timeout=30).decode()
            self.min_workers = num_executors
            self.max_workers = num_executors
            self._await_executors(num_executors, executor_wait_timeout)
        except BaseException:
            # a failed join must not leave the driver's RPC/shuffle
            # services running or the app registered at the master (its
            # reconcile loop would keep launching executors for a dead
            # driver)
            self.stop()
            raise

    def _await_executors(self, expect: int, timeout: float) -> None:
        """Executors are launched by REMOTE worker daemons — there are
        no local Popen handles to adopt, just registrations to await."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while len(self._workers) < expect:
                rest = deadline - time.monotonic()
                if rest <= 0 or not self._joined.wait(timeout=rest):
                    raise RuntimeError(
                        f"only {len(self._workers)}/{expect} executors "
                        f"joined from master {self.master_addr} "
                        f"within {timeout}s")

    def wait_for_executors(self, expect: int, timeout: float = 60.0):
        """Block until the master has re-placed executors up to
        `expect` alive (used after worker churn)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.num_alive() >= expect:
                return
            time.sleep(0.2)
        raise TimeoutError(
            f"{self.num_alive()}/{expect} executors after {timeout}s")

    def stop(self):
        if self._master is not None:
            try:
                if self.app_id:
                    self._master.call("app_finished",
                                      pickle.dumps(self.app_id), timeout=10)
            except Exception:
                pass
            finally:
                self._master.close()
                self._master = None
        super().stop()
