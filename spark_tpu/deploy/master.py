"""Standalone master daemon.

Role of the reference's Master (core/deploy/master/Master.scala): the
cluster-wide resource arbiter. Worker daemons register and heartbeat;
applications submit a desired executor count plus their driver's
address/secret; the master PLACES executor launches on alive workers
and keeps the fleet reconciled — a worker (or executor) death is
detected by heartbeat loss and the missing executors are re-placed on
the survivors, exactly the reference's `schedule()` loop
(Master.scala:744). Executors themselves register with the APP's
driver directly (the CoarseGrainedExecutorBackend flow): the master
never sits on the task or shuffle data paths.

TPU note: a "worker" here is one host of a TPU pod slice. The master
only arbitrates processes; all device-mesh collectives ride ICI inside
the app's own jit programs.
"""

from __future__ import annotations

import pickle
import threading
import time
import uuid

from ..net.transport import RpcClient, RpcServer


class _WorkerInfo:
    def __init__(self, wid: str, addr: str, host: str, cores: int,
                 client: RpcClient):
        self.wid = wid
        self.addr = addr
        self.host = host
        self.cores = cores
        self.client = client
        self.last_heartbeat = time.monotonic()
        # app_id → executors this worker reports alive (from heartbeats)
        self.app_executors: dict[str, int] = {}


class _AppInfo:
    def __init__(self, app_id: str, name: str, driver_addr: str,
                 driver_token: str, executors: int, env_extra: dict):
        self.app_id = app_id
        self.name = name
        self.driver_addr = driver_addr
        self.driver_token = driver_token
        self.desired = executors
        self.env_extra = dict(env_extra)
        self.last_launch = 0.0


class Master:
    """gRPC control daemon: worker registry + app placement/reconcile."""

    def __init__(self, token: str, host: str = "127.0.0.1",
                 heartbeat_timeout: float = 10.0,
                 reconcile_cooldown: float = 3.0):
        self.token = token
        self.heartbeat_timeout = heartbeat_timeout
        self.reconcile_cooldown = reconcile_cooldown
        self._lock = threading.Lock()
        self._workers: dict[str, _WorkerInfo] = {}
        self._apps: dict[str, _AppInfo] = {}
        self._rr = 0
        self._stopping = False
        self._server = RpcServer(token, host=host)
        self._server.register("register_worker", self._on_register_worker)
        self._server.register("worker_heartbeat", self._on_heartbeat)
        self._server.register("submit_app", self._on_submit_app)
        self._server.register("app_finished", self._on_app_finished)
        self._server.register("master_state", self._on_state)
        self._server.register("ping", lambda _p: b"pong")
        self.address = ""

    def start(self) -> str:
        self.address = self._server.start()
        # race-lint: ignore[bare-submit] — master liveness monitor:
        # process-lifetime, never runs query-scoped work
        threading.Thread(target=self._monitor_loop, daemon=True).start()
        return self.address

    def stop(self) -> None:
        self._stopping = True
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            try:
                w.client.close()
            except Exception:
                pass
        self._server.stop()

    # -- handlers --------------------------------------------------------
    def _on_register_worker(self, payload: bytes) -> bytes:
        info = pickle.loads(payload)
        client = RpcClient(info["addr"], self.token)
        try:
            client.wait_ready(10)
        except Exception:
            client.close()
            raise
        wid = f"worker-{uuid.uuid4().hex[:8]}"
        with self._lock:
            self._workers[wid] = _WorkerInfo(
                wid, info["addr"], info.get("host", "unknown"),
                int(info.get("cores", 1)), client)
        return wid.encode()

    def _on_heartbeat(self, payload: bytes) -> bytes:
        wid, app_counts = pickle.loads(payload)
        with self._lock:
            w = self._workers.get(wid)
            if w is None:
                return b"unknown"   # told to re-register (Master.scala
            w.last_heartbeat = time.monotonic()
            w.app_executors = dict(app_counts)
        return b"ok"

    def _on_submit_app(self, payload: bytes) -> bytes:
        req = pickle.loads(payload)
        app_id = f"app-{uuid.uuid4().hex[:8]}"
        app = _AppInfo(app_id, req.get("name", "app"), req["driver_addr"],
                       req["driver_token"], int(req["executors"]),
                       req.get("env_extra", {}))
        with self._lock:
            self._apps[app_id] = app
        self._reconcile(app)
        return app_id.encode()

    def _on_app_finished(self, payload: bytes) -> bytes:
        app_id = pickle.loads(payload)
        with self._lock:
            self._apps.pop(app_id, None)
            workers = list(self._workers.values())
        for w in workers:
            try:
                w.client.call("kill_app", pickle.dumps(app_id), timeout=10)
            except Exception:
                pass
        return b"ok"

    def _on_state(self, _payload: bytes) -> bytes:
        with self._lock:
            return pickle.dumps({
                "workers": [{"id": w.wid, "addr": w.addr, "host": w.host,
                             "cores": w.cores,
                             "apps": dict(w.app_executors)}
                            for w in self._workers.values()],
                "apps": [{"id": a.app_id, "name": a.name,
                          "desired": a.desired,
                          "driver": a.driver_addr}
                         for a in self._apps.values()],
            })

    # -- placement / reconcile ------------------------------------------
    def _alive_workers(self) -> list[_WorkerInfo]:
        now = time.monotonic()
        with self._lock:
            return [w for w in self._workers.values()
                    if now - w.last_heartbeat <= self.heartbeat_timeout]

    def _reconcile(self, app: _AppInfo) -> None:
        """Launch executors until the app's reported-alive total reaches
        its desired count, spreading round-robin over alive workers
        (Master.scala:744 schedule / spreadOutApps)."""
        now = time.monotonic()
        if now - app.last_launch < self.reconcile_cooldown:
            return      # let just-launched executors show up in heartbeats
        alive = self._alive_workers()
        if not alive:
            return
        have = sum(w.app_executors.get(app.app_id, 0) for w in alive)
        deficit = app.desired - have
        if deficit <= 0:
            return
        app.last_launch = now
        req = pickle.dumps({
            "app_id": app.app_id,
            "driver_addr": app.driver_addr,
            "driver_token": app.driver_token,
            "env_extra": app.env_extra,
        })
        for i in range(deficit):
            w = alive[(self._rr + i) % len(alive)]
            try:
                w.client.call("launch_executor", req, timeout=30)
            except Exception:
                continue    # worker just died — next tick re-places
        self._rr += deficit

    def _monitor_loop(self) -> None:
        while not self._stopping:
            time.sleep(1.0)
            now = time.monotonic()
            with self._lock:
                dead = [wid for wid, w in self._workers.items()
                        if now - w.last_heartbeat > self.heartbeat_timeout]
                for wid in dead:
                    w = self._workers.pop(wid)
                    try:
                        w.client.close()
                    except Exception:
                        pass
                apps = list(self._apps.values())
            for app in apps:
                try:
                    self._reconcile(app)
                except Exception:
                    pass


def main(argv=None) -> int:
    import argparse
    import os

    p = argparse.ArgumentParser(prog="sparktpu-master")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--secret",
                   default=os.environ.get("SPARK_TPU_MASTER_SECRET"))
    p.add_argument("--announce-file", default=None,
                   help="write the bound address here once serving "
                        "(deployment scripts / tests read it back)")
    args = p.parse_args(argv)
    if not args.secret:
        raise SystemExit("--secret or SPARK_TPU_MASTER_SECRET required")
    m = Master(args.secret, host=args.host)
    addr = m.start()
    print(f"sparktpu master listening at {addr}", flush=True)
    if args.announce_file:
        tmp = args.announce_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(addr)
        os.replace(tmp, args.announce_file)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    m.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
