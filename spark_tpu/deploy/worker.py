"""Standalone worker daemon.

Role of the reference's Worker (core/deploy/worker/Worker.scala): a
per-host daemon that registers with the master, heartbeats its state,
and LAUNCHES executor processes on demand (Worker.scala LaunchExecutor
→ ExecutorRunner). Executors are `spark_tpu.exec.worker_main` processes
wired to the submitting app's driver address + secret; they register
with the driver themselves, so the master/worker control plane never
carries task or shuffle traffic. Dead executors are reaped and reported
via heartbeat so the master can re-place them.
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import threading
import time

from ..net.transport import RpcClient, RpcServer


class WorkerDaemon:
    def __init__(self, master_addr: str, token: str,
                 host: str = "127.0.0.1", cores: int = 2,
                 heartbeat_interval: float | None = None):
        self.master_addr = master_addr
        self.token = token
        self.host = host
        self.cores = cores
        if heartbeat_interval is None:
            # same knob the executor heartbeat honors (worker_env /
            # spark.tpu.heartbeat.interval), capped so master-side
            # liveness expiry stays responsive on long settings
            heartbeat_interval = min(float(os.environ.get(
                "SPARK_TPU_HEARTBEAT_INTERVAL", "1.0")), 5.0)
        self.heartbeat_interval = heartbeat_interval
        self._lock = threading.Lock()
        # app_id → list of executor Popen handles
        self._executors: dict[str, list[subprocess.Popen]] = {}
        self._stopping = False
        self._server = RpcServer(token, host=host)
        self._server.register("launch_executor", self._on_launch)
        self._server.register("kill_app", self._on_kill_app)
        self._server.register("ping", lambda _p: b"pong")
        self.address = ""
        self.worker_id = ""
        self._master: RpcClient | None = None

    def start(self) -> str:
        self.address = self._server.start()
        self._master = RpcClient(self.master_addr, self.token)
        self._master.wait_ready(30)
        self.worker_id = self._register()
        # race-lint: ignore[bare-submit] — deploy-plane heartbeat:
        # process-lifetime, never runs query-scoped work
        threading.Thread(target=self._heartbeat_loop, daemon=True).start()
        return self.address

    def _register(self) -> str:
        return self._master.call("register_worker", pickle.dumps({
            "addr": self.address, "host": self.host, "cores": self.cores,
        }), timeout=10).decode()

    def stop(self) -> None:
        self._stopping = True
        with self._lock:
            apps = list(self._executors)
        for app_id in apps:
            self._kill_app(app_id)
        if self._master is not None:
            self._master.close()
        self._server.stop()

    # -- handlers --------------------------------------------------------
    def _on_launch(self, payload: bytes) -> bytes:
        from ..exec.cluster import worker_env

        req = pickle.loads(payload)
        env = worker_env(req["driver_addr"], req["driver_token"],
                         host_label=self.host, bind_host=self.host)
        env.update(req.get("env_extra", {}))
        proc = subprocess.Popen(
            [sys.executable, "-m", "spark_tpu.exec.worker_main"], env=env)
        with self._lock:
            self._executors.setdefault(req["app_id"], []).append(proc)
        return b"ok"

    def _on_kill_app(self, payload: bytes) -> bytes:
        self._kill_app(pickle.loads(payload))
        return b"ok"

    def _kill_app(self, app_id: str) -> None:
        with self._lock:
            procs = self._executors.pop(app_id, [])
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()

    # -- heartbeat / reap ------------------------------------------------
    def _alive_counts(self) -> dict[str, int]:
        with self._lock:
            # reap exited executors while counting (ExecutorRunner's
            # exit-notification role)
            out = {}
            for app_id, procs in list(self._executors.items()):
                live = [p for p in procs if p.poll() is None]
                self._executors[app_id] = live
                out[app_id] = len(live)
            return out

    def _heartbeat_loop(self) -> None:
        while not self._stopping:
            time.sleep(self.heartbeat_interval)
            try:
                reply = self._master.call(
                    "worker_heartbeat",
                    pickle.dumps((self.worker_id, self._alive_counts())),
                    timeout=5)
                if reply == b"unknown":
                    # master restarted / expired us — rejoin under a new
                    # id (Worker.scala reregisterWithMaster role)
                    self.worker_id = self._register()
            except Exception:
                pass    # master briefly unreachable — keep trying


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="sparktpu-worker")
    p.add_argument("master", help="master address host:port")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--cores", type=int, default=2)
    p.add_argument("--secret",
                   default=os.environ.get("SPARK_TPU_MASTER_SECRET"))
    p.add_argument("--announce-file", default=None)
    args = p.parse_args(argv)
    if not args.secret:
        raise SystemExit("--secret or SPARK_TPU_MASTER_SECRET required")
    w = WorkerDaemon(args.master.replace("grpc://", ""), args.secret,
                     host=args.host, cores=args.cores)
    addr = w.start()
    print(f"sparktpu worker {w.worker_id} at {addr} "
          f"(master {args.master})", flush=True)
    if args.announce_file:
        tmp = args.announce_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(addr)
        os.replace(tmp, args.announce_file)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    w.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
