"""Standalone deploy layer: master + worker daemons and the driver-side
standalone cluster backend (role of the reference's
core/deploy/master/Master.scala, worker/Worker.scala,
client/StandaloneAppClient.scala)."""
