"""Mesh-native SPMD stage fusion: one sharded dispatch per stage per step.

Role of the reference's whole shuffle stage — map-side pipeline, partition
writer, block transfer, reduce-side read (sqlx/exchange/
ShuffleExchangeExec.scala + the SortShuffleManager data plane) — compiled
as ONE XLA program over a jax.sharding.Mesh: the traced filter/project
pipeline (physical/compile.trace_pipeline), the partition-id computation,
the per-shard bucket-by-destination, and the `lax.all_to_all` over the
ICI all run under a single `shard_map`, so a shuffle stage costs exactly
one dispatch per step regardless of how many batches staged into it
(JAMPI in PAPERS.md: barrier-mode ICI collectives beat host-mediated
shuffle by an order of magnitude; this is ROADMAP direction 1).

Layout discipline (the SpecLayout pattern, SNIPPETS [2]): every operand
declares its canonical PartitionSpec once in `MeshSpecLayout` — row data
is sharded over the data axis, pipeline aux tables are replicated — and
staging `device_put`s against those specs BEFORE the jit call, so no
input is ever resharded implicitly and outputs stay shard-resident for
the reduce-side consumer (each reduce partition's batch wraps its
device's shard directly; the agg partial / join build feed reads it
without a host hop).

Buffer donation: the staged send buffers are dead the moment the program
consumes them, so they ride `donate_argnums` and XLA reuses their HBM
in-place for the all-to-all staging/outputs. Staging is deliberately
sized so each per-shard send plane equals the receive plane
(shard_cap == P * quota) — the donated input aliases its output
one-for-one instead of tripping XLA's "donated buffer not usable" path.
The HBM ledger (obs/resources.DeviceLedger) charges the staged buffers
explicitly and releases them at dispatch when donated (the arrays are
genuinely invalidated by the call) vs. after output registration when
not — the per-query watermark is the scoreboard for the donation win.

Static-shape discipline: each (src→dst) pair gets a fixed row `quota`;
the program psums an overflow count and the host retries with a doubled
quota — the same capacity-bucket contract as the join/aggregate kernels.
Per-partition live counts come back as a sharded [P] array computed
in-program, so building the reduce batches needs one host pull, not one
sync per partition.
"""

from __future__ import annotations

import contextlib
import warnings
from typing import Sequence

import numpy as np

from ..columnar.batch import bucket_capacity

__all__ = ["MeshSpecLayout", "StagedBuffers", "build_fused_stage",
           "build_plain_stage", "expected_donation_residue",
           "mesh_stage_geometry"]

# Donation is the default; tests A/B the HBM watermark by flipping this
# module switch (the undonated program compiles under a distinct cache
# key). Not a SQLConf: there is no reason to run undonated in production.
DONATE_DEFAULT = True

@contextlib.contextmanager
def expected_donation_residue():
    """Suppress jax's 'donated buffers were not usable' warning for ONE
    mesh-stage dispatch: a donated plane whose dtype has no matching
    output (an input column the projection drops) cannot alias, which is
    expected here — the size-matched staging makes every surviving plane
    alias cleanly. Scoped per call site, never process-wide: that warning
    is the only signal a FUTURE donation site regressed its aliasing."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        yield


def mesh_stage_geometry(total_cap: int, num_out: int) -> tuple[int, int, int]:
    """(rows_per_shard, shard_cap, quota) for staging `total_cap` input
    slots across `num_out` shards.

    rows_per_shard — input slots assigned to each shard (row-block
    split of the concatenated batches, so every device gets data).
    quota — per-(src,dst) row budget of the first attempt: 2× the
    uniform share, the historical overflow headroom.
    shard_cap — per-shard staged capacity, padded to P*quota so the
    send planes are the SAME size as the receive planes and donation
    aliases in-place. The plan analyzer mirrors these formulas exactly
    (analysis/plan_lint.py mesh model)."""
    rows_per_shard = max(-(-total_cap // num_out), 1)
    base = bucket_capacity(max(rows_per_shard, 64))
    quota = max(16, 2 * base // num_out)
    return rows_per_shard, num_out * quota, quota


# ---------------------------------------------------------------------------
# canonical operand layouts (the SpecLayout pattern)
# ---------------------------------------------------------------------------

class MeshSpecLayout:
    """Canonical PartitionSpecs per operand role for a mesh stage.

    One authority for how every array of the stage program is laid out
    over the mesh: staging places inputs against these specs and the
    shard_map in/out_specs are derived from the same methods, so a batch
    flows shard-resident between stages with no implicit resharding."""

    def __init__(self, axis: str = "data"):
        from jax.sharding import PartitionSpec as P

        self.axis = axis
        self._P = P

    def rows(self):
        """Row-sharded planes: column data, validity, row mask, keys."""
        return self._P(self.axis)

    def replicated(self):
        """Pipeline aux tables (dictionaries' luts) and scalar operands:
        every shard reads the full array."""
        return self._P()

    def row_sharding(self, mesh):
        from jax.sharding import NamedSharding

        return NamedSharding(mesh, self.rows())

    def replicated_sharding(self, mesh):
        from jax.sharding import NamedSharding

        return NamedSharding(mesh, self.replicated())


# ---------------------------------------------------------------------------
# HBM ledger bookkeeping for staged send buffers
# ---------------------------------------------------------------------------

class StagedBuffers:
    """Explicit ledger ownership of one attempt's staged device arrays.

    `release_consumed()` drops the charge of every array the dispatch
    invalidated (donation) the moment it returns — the buffers are
    genuinely gone, and the per-query watermark records the in-place
    reuse. Undonated arrays stay charged until `release_all()` (or GC of
    this holder), which runs after the reduce-side output batches have
    registered — the honest input+output overlap."""

    def __init__(self, arrays: Sequence):
        from ..obs.resources import GLOBAL_LEDGER, ledger_enabled

        self._ledger = GLOBAL_LEDGER if ledger_enabled() else None
        self._entries = []
        if self._ledger is not None:
            for a in arrays:
                if a is None or not hasattr(a, "dtype"):
                    continue
                token = self._ledger.charge_arrays([a])
                if token:
                    self._entries.append((a, token))

    def release_consumed(self) -> None:
        if self._ledger is None:
            return
        kept = []
        for a, token in self._entries:
            if getattr(a, "is_deleted", lambda: False)():
                self._ledger.release_arrays(token)
            else:
                kept.append((a, token))
        self._entries = kept

    def release_all(self) -> None:
        if self._ledger is None:
            return
        for _a, token in self._entries:
            self._ledger.release_arrays(token)
        self._entries = []

    def __del__(self):  # backstop — release_all is idempotent
        try:
            self.release_all()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# the SPMD stage programs
# ---------------------------------------------------------------------------

def _exchange_tail(arrays, pids, row_mask, num_out: int, quota: int,
                   axis: str, stat_spec: tuple = ()):
    """Shared post-pid leg of a stage program, per shard: bucket live
    rows by destination into [P, quota] blocks, all-to-all every plane,
    and report (received arrays, received mask, per-shard live count,
    global overflow, per-shard column stats). `arrays` entries may be
    None (absent validity planes) and pass through as None.

    `stat_spec` = ((data_idx, valid_idx | -1), ...) into `arrays`: for
    each listed integral column the program reduces the RECEIVED rows to
    (min, max, live count) per shard — one [n_stat, 3] int64 block per
    reduce partition, riding the dispatch's outputs. Post-exchange
    per-shard is exactly the union of the map-side per-(src,dst) stats
    MapStatus ships on the host path (same rows, same extrema), so the
    seeded dense-range span equals what the krange3 probe would have
    learned — the plan analyzer's dense-decision model stays exact. The
    empty case returns min/max sentinels with count 0; the host maps
    count 0 to the (0, 0, False) no-live-rows seed."""
    import jax.numpy as jnp
    from jax import lax

    from .collectives import _bucket_by_pid

    gather_idx, slot_valid, overflow = _bucket_by_pid(
        pids, row_mask, num_out, quota)

    def xchg(blocks):
        recv = lax.all_to_all(blocks, axis, split_axis=0, concat_axis=0,
                              tiled=False)
        return recv.reshape(num_out * quota)

    outs = [None if a is None
            else xchg(jnp.take(a, gather_idx).reshape(num_out, quota))
            for a in arrays]
    new_mask = xchg(slot_valid)
    count = jnp.sum(new_mask.astype(jnp.int64)).reshape(1)
    total_overflow = lax.psum(overflow, axis)
    stats = None
    if stat_spec:
        big = jnp.int64(1) << 62
        rows = []
        for di, vi in stat_spec:
            d = outs[di].astype(jnp.int64)
            live = new_mask if vi < 0 else (new_mask & outs[vi])
            rows.append(jnp.stack([
                jnp.min(jnp.where(live, d, big)),
                jnp.max(jnp.where(live, d, -big)),
                jnp.sum(live.astype(jnp.int64))]))
        stats = jnp.stack(rows)  # [n_stat, 3] per shard
    return outs, new_mask, count, total_overflow, stats


def _embed_block(x, shard_cap: int):
    """Per-shard re-layout of a BASE plane block (quota-retry restaging):
    the shard's geometry-independent [base_rows] data block embeds at
    offset 0 of a zero-padded [shard_cap] send plane — the device-side
    equivalent of _pad_shards, so a retry never re-crosses the host."""
    import jax.numpy as jnp

    if x is None:
        return None
    out = jnp.zeros((shard_cap,), dtype=x.dtype)
    return out.at[: x.shape[0]].set(x)


def build_plain_stage(mesh, axis: str, quota: int, num_out: int,
                      n_keys: int, key_valid_sig: tuple,
                      n_payloads: int, donate: bool,
                      base_rows: "int | None" = None,
                      stat_spec: tuple = ()):
    """Jitted mesh stage for PRE-MATERIALIZED batches: pids from staged
    key arrays + all-to-all, payload/mask send buffers donated. Signature:
    f(key_eqs, key_valids, payloads, row_mask) ->
    (out_payloads, new_mask, counts[P], overflow[, stats]).

    With `base_rows`, inputs are PERSISTED base planes ([P*base_rows]
    row-sharded, geometry-independent): each shard embeds its block into
    the [shard_cap] send layout in-program, nothing is donated (the base
    planes survive for the next quota retry), and a retry pays only the
    recompile — not the host->device restage.

    With `stat_spec` (indices into the payloads list), the program also
    reduces each listed integral column's received rows to per-reduce-
    partition (min, max, live count) — the in-program column stats that
    seed the dense-range memo so reduce tiles stop krange3-probing
    (the MapStatus col-stats role on the ICI path)."""
    import jax

    from ..ops.hashing import hash_columns, partition_ids
    from ._shard_map_compat import shard_map

    layout = MeshSpecLayout(axis)
    rows = layout.rows()
    shard_cap = num_out * quota

    def local_fn(key_eqs, key_valids, payloads, row_mask):
        if base_rows is not None:
            key_eqs = [_embed_block(k, shard_cap) for k in key_eqs]
            key_valids = [_embed_block(v, shard_cap) for v in key_valids]
            payloads = [_embed_block(p, shard_cap) for p in payloads]
            row_mask = _embed_block(row_mask, shard_cap)
        h = hash_columns(key_eqs, list(key_valids))
        pids = partition_ids(h, num_out)
        outs, new_mask, count, overflow, stats = _exchange_tail(
            payloads, pids, row_mask, num_out, quota, axis, stat_spec)
        if stat_spec:
            return outs, new_mask, count, overflow, stats
        return outs, new_mask, count, overflow

    def sharded(key_eqs, key_valids, payloads, row_mask):
        in_specs = (
            [rows] * n_keys,
            [None if not has else rows for has in key_valid_sig],
            [rows] * n_payloads,
            rows,
        )
        out_specs = ([rows] * n_payloads, rows, rows,
                     layout.replicated())
        if stat_spec:
            # stats are per-shard [n_stat, 3] blocks sharded over the
            # leading axis: the host pull reshapes to [P, n_stat, 3]
            out_specs = out_specs + (rows,)
        f = shard_map(local_fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
        return f(key_eqs, key_valids, payloads, row_mask)

    # built exclusively through GLOBAL_KERNEL_CACHE.get_or_build
    # (mesh_exchange) — launches ride the dispatch counters
    return jax.jit(sharded,  # tpulint: ignore[raw-jit]
                   donate_argnums=(2, 3) if donate and base_rows is None
                   else ())


def build_fused_stage(mesh, axis: str, shard_cap: int, quota: int,
                      num_out: int, seed: int, input_attrs,
                      filters, outputs, key_idx: tuple, key_bool: tuple,
                      out_valid_sig: tuple, donate: bool,
                      base_rows: "int | None" = None,
                      stat_spec: tuple = (), dict_pos: tuple = ()):
    """Jitted mesh stage for a FUSED shuffle stage: the filter/project
    pipeline traces per shard, partition ids derive from the traced key
    outputs, and the all-to-all ships the pipeline OUTPUT columns — the
    whole stage is one SPMD dispatch. Signature:
    f(datas, valids, row_mask, aux, kluts) ->
    (out_datas, out_valids, new_mask, counts[P], overflow[, stats]),
    where the input planes (datas/valids/row_mask) are the donated send
    buffers. `stat_spec` indexes the pipeline OUTPUT columns whose
    per-reduce-partition (min, max, live count) the program reduces
    in-program (see build_plain_stage). `dict_pos` lists the
    dictionary-encoded partition-key positions (pipe-output indices, in
    key_idx order) whose eq domain is a padded codes→value-hash lut
    shipped in `kluts` as a REPLICATED aux plane: the key hash computes
    over dictionary-independent value hashes inside the shard_map, so
    string-key exchanges fuse instead of materializing the pipeline
    before the collective."""
    import jax
    import jax.numpy as jnp

    from ..physical.compile import trace_pipeline
    from ..ops.hashing import hash_columns, partition_ids
    from ._shard_map_compat import shard_map

    layout = MeshSpecLayout(axis)
    rows = layout.rows()
    rep = layout.replicated()
    n_in = len(input_attrs)
    lut_of = {i: j for j, i in enumerate(dict_pos)}

    def local_fn(datas, valids, row_mask, aux, kluts):
        if base_rows is not None:
            # quota-retry restaging: geometry-independent base planes
            # re-lay out to the attempt's [shard_cap] send layout
            # in-program (no host->device restage on retries)
            datas = [_embed_block(d, shard_cap) for d in datas]
            valids = [_embed_block(v, shard_cap) for v in valids]
            row_mask = _embed_block(row_mask, shard_cap)
        out_datas, out_valids, mask = trace_pipeline(
            input_attrs, filters, outputs, datas, valids, row_mask, aux,
            shard_cap)
        eqs = []
        for i, is_bool in zip(key_idx, key_bool):
            kd = out_datas[i]
            if is_bool:
                kd = kd.astype(jnp.int32)
            if i in lut_of:
                lut = kluts[lut_of[i]]
                kd = jnp.take(lut, jnp.clip(kd.astype(jnp.int32), 0,
                                            lut.shape[0] - 1))
            eqs.append(kd)
        kvs = [out_valids[i] for i in key_idx]
        pids = partition_ids(hash_columns(eqs, kvs, seed=seed), num_out)
        planes = list(out_datas) + list(out_valids)
        outs, new_mask, count, overflow, stats = _exchange_tail(
            planes, pids, mask, num_out, quota, axis, stat_spec)
        n = len(out_datas)
        if stat_spec:
            return outs[:n], outs[n:], new_mask, count, overflow, stats
        return outs[:n], outs[n:], new_mask, count, overflow

    def sharded(datas, valids, row_mask, aux, kluts):
        in_specs = (
            [rows] * n_in,
            [None if v is None else rows for v in valids],
            rows,
            [rep] * len(aux),
            [rep] * len(kluts),
        )
        out_specs = ([rows] * len(outputs),
                     [rows if has else None for has in out_valid_sig],
                     rows, rows, rep)
        if stat_spec:
            # per-shard [n_stat, 3] stat blocks, sharded on the leading
            # axis (host reshape → [P, n_stat, 3])
            out_specs = out_specs + (rows,)
        f = shard_map(local_fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
        return f(datas, valids, row_mask, aux, kluts)

    # built exclusively through GLOBAL_KERNEL_CACHE.get_or_build
    # (mesh_exchange) — launches ride the dispatch counters
    return jax.jit(sharded,  # tpulint: ignore[raw-jit]
                   donate_argnums=(0, 1, 2) if donate and base_rows is None
                   else ())
