"""On-device distributed broadcast hash join over a mesh.

The second flagship SPMD step (with mesh_agg's distributed group-by): the
probe side stays row-sharded over the 'data' axis, the build side is
REPLICATED (the BroadcastExchangeExec pattern — on real hardware the
all-gather rides ICI), and every shard probes its rows against the dense
build table in one program. SURVEY.md §2.5 'Broadcast replication'.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def make_broadcast_join_sum(mesh, axis_name: str = "data"):
    """Returns jitted fn(probe_keys, probe_vals, probe_mask,
                         build_keys, build_vals, build_mask)
    -> (matched_mask, joined_vals) both row-sharded like the probe side.

    Semantics: inner equi join probe.key = build.key (unique build keys),
    joined_vals = probe_val * build_val for matched rows — the
    scan→broadcast-join→project spine of a TPC-DS star query."""
    from jax.sharding import PartitionSpec as P

    from ._shard_map_compat import shard_map

    def local_fn(pk, pv, pm, bk, bv, bm):
        # build side is replicated: dense direct-address table per shard
        bcap = bk.shape[0]
        tcap = bcap * 2
        big = jnp.iinfo(jnp.int64).max
        kmin = jnp.min(jnp.where(bm, bk, big))
        slot = jnp.where(bm, (bk - kmin), tcap)
        rowidx = jnp.full((tcap,), 0, jnp.int32).at[slot].set(
            lax.iota(jnp.int32, bcap), mode="drop")
        present = jnp.zeros((tcap,), bool).at[slot].set(True, mode="drop")

        k = pk - kmin
        in_range = (k >= 0) & (k < tcap)
        s = jnp.clip(k, 0, tcap - 1)
        matched = pm & in_range & jnp.take(present, s)
        bval = jnp.take(bv, jnp.take(rowidx, s))
        joined = jnp.where(matched, pv * bval, jnp.zeros_like(pv))
        return matched, joined

    def sharded(pk, pv, pm, bk, bv, bm):
        f = shard_map(
            local_fn, mesh=mesh,
            in_specs=(P(axis_name), P(axis_name), P(axis_name),
                      P(), P(), P()),
            out_specs=(P(axis_name), P(axis_name)),
            check_vma=False)
        return f(pk, pv, pm, bk, bv, bm)

    return jax.jit(sharded)
