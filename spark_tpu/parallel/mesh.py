"""Device mesh management.

Role of the reference's cluster topology layer (SchedulerBackend knowing its
executors, core/scheduler/cluster/CoarseGrainedSchedulerBackend.scala) —
TPU-native: the "cluster" inside a slice is a jax.sharding.Mesh and the
workers are devices; partition-parallelism maps to the 'data' mesh axis
(SURVEY.md §2.5 row 1).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def get_mesh(n_devices: int | None = None, axis_name: str = "data"):
    """1-D mesh over the first n devices (all by default).

    Raises when fewer than ``n_devices`` exist — silently truncating hides
    topology bugs (a "mesh of 8" that is secretly 1 device computes wrong
    ownership and masks broken multi-chip code paths). Callers that can
    degrade (e.g. mesh_exchange.mesh_for) check device count themselves.
    """
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"get_mesh({n_devices}): only {len(devs)} jax device(s) "
                f"visible on backend '{jax.default_backend()}'. For a "
                f"virtual CPU mesh set JAX_PLATFORMS=cpu and XLA_FLAGS="
                f"--xla_force_host_platform_device_count={n_devices}.")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def row_sharding(mesh, axis_name: str = "data"):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(axis_name))


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def shard_rows(arr, mesh, axis_name: str = "data"):
    """Place a [n]-row array row-sharded over the mesh (n % P == 0)."""
    import jax

    return jax.device_put(arr, row_sharding(mesh, axis_name))
