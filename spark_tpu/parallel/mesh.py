"""Device mesh management.

Role of the reference's cluster topology layer (SchedulerBackend knowing its
executors, core/scheduler/cluster/CoarseGrainedSchedulerBackend.scala) —
TPU-native: the "cluster" inside a slice is a jax.sharding.Mesh and the
workers are devices; partition-parallelism maps to the 'data' mesh axis
(SURVEY.md §2.5 row 1).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def get_mesh(n_devices: int | None = None, axis_name: str = "data"):
    """1-D mesh over the first n devices (all by default)."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def row_sharding(mesh, axis_name: str = "data"):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(axis_name))


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def shard_rows(arr, mesh, axis_name: str = "data"):
    """Place a [n]-row array row-sharded over the mesh (n % P == 0)."""
    import jax

    return jax.device_put(arr, row_sharding(mesh, axis_name))
