"""Intra-slice shuffle bucketing primitives: the per-shard leg of the
ICI data plane.

Role of the reference's shuffle data plane (Netty block transfer,
core/storage/ShuffleBlockFetcherIterator.scala:86) WITHIN a TPU slice: rows
never leave the devices — each shard buckets its rows by destination with the
same hash/sort kernel the host shuffle uses (ops/partition.py) and lays them
out as [P, quota] blocks for `lax.all_to_all` (SURVEY.md §2.5 'Communication
backend': data plane = XLA collectives over ICI; the host/DCN path in
exec/shuffle.py covers cross-slice). The stage-level programs that wrap
these primitives under `shard_map` — exchange tail, traced-pipeline fusion,
donation — live in parallel/mesh_fusion.py.

Static shapes: each (src→dst) pair gets a fixed `quota` of rows; a scalar
`overflow` flag reports rows that did not fit so the caller can retry at a
bigger quota (same capacity-bucket discipline as the join kernel).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..ops.hashing import hash_columns, partition_ids


def _bucket_local(key_eqs, key_valids, row_mask, num_partitions: int,
                  quota: int):
    """Per-shard: group rows by destination pid into a [P, quota] layout.

    Returns (perm int32[P*quota] gather indices into local rows (clipped),
             valid bool[P, quota], overflow int32)."""
    h = hash_columns(key_eqs, list(key_valids))
    pids = partition_ids(h, num_partitions)
    return _bucket_by_pid(pids, row_mask, num_partitions, quota)


def _bucket_by_pid(pids, row_mask, num_partitions: int, quota: int):
    """_bucket_local over PRECOMPUTED partition ids — the fused mesh
    stage program (parallel/mesh_fusion.py) derives pids from its traced
    pipeline outputs instead of hashing staged key arrays."""
    cap = row_mask.shape[0]
    key = jnp.where(row_mask, pids, num_partitions)
    skey, perm = lax.sort((key, lax.iota(jnp.int32, cap)), num_keys=1,
                          is_stable=True)
    # position of each sorted row within its pid run
    pos = lax.iota(jnp.int32, cap)
    run_start = jnp.searchsorted(skey, jnp.arange(num_partitions,
                                                  dtype=skey.dtype),
                                 side="left").astype(jnp.int32)
    within = pos - jnp.take(run_start, jnp.minimum(skey, num_partitions - 1))
    live = skey < num_partitions
    fits = live & (within < quota)
    overflow = jnp.sum((live & ~fits).astype(jnp.int32))
    # scatter sorted rows into [P, quota] slots
    slot = jnp.where(fits, skey * quota + within, num_partitions * quota)
    gather_idx = jnp.full((num_partitions * quota,), 0, dtype=jnp.int32)
    gather_idx = gather_idx.at[slot].set(perm, mode="drop")
    slot_valid = jnp.zeros((num_partitions * quota,), dtype=bool)
    slot_valid = slot_valid.at[slot].set(fits, mode="drop")
    return gather_idx, slot_valid.reshape(num_partitions, quota), overflow


