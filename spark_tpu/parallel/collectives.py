"""Intra-slice shuffle over ICI: shard_map + lax.all_to_all.

Role of the reference's shuffle data plane (Netty block transfer,
core/storage/ShuffleBlockFetcherIterator.scala:86) WITHIN a TPU slice: rows
never leave the devices — each shard buckets its rows by destination with the
same hash/sort kernel the host shuffle uses (ops/partition.py), lays them out
as [P, quota] blocks, and one `lax.all_to_all` swaps blocks across the mesh
(SURVEY.md §2.5 'Communication backend': data plane = XLA collectives over
ICI; the host/DCN path in exec/shuffle.py covers cross-slice).

Static shapes: each (src→dst) pair gets a fixed `quota` of rows; a scalar
`overflow` flag reports rows that did not fit so the caller can retry at a
bigger quota (same capacity-bucket discipline as the join kernel).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.hashing import hash_columns, partition_ids


def _bucket_local(key_eqs, key_valids, row_mask, num_partitions: int,
                  quota: int):
    """Per-shard: group rows by destination pid into a [P, quota] layout.

    Returns (perm int32[P*quota] gather indices into local rows (clipped),
             valid bool[P, quota], overflow int32)."""
    cap = row_mask.shape[0]
    h = hash_columns(key_eqs, list(key_valids))
    pids = partition_ids(h, num_partitions)
    key = jnp.where(row_mask, pids, num_partitions)
    skey, perm = lax.sort((key, lax.iota(jnp.int32, cap)), num_keys=1,
                          is_stable=True)
    # position of each sorted row within its pid run
    pos = lax.iota(jnp.int32, cap)
    run_start = jnp.searchsorted(skey, jnp.arange(num_partitions,
                                                  dtype=skey.dtype),
                                 side="left").astype(jnp.int32)
    within = pos - jnp.take(run_start, jnp.minimum(skey, num_partitions - 1))
    live = skey < num_partitions
    fits = live & (within < quota)
    overflow = jnp.sum((live & ~fits).astype(jnp.int32))
    # scatter sorted rows into [P, quota] slots
    slot = jnp.where(fits, skey * quota + within, num_partitions * quota)
    gather_idx = jnp.full((num_partitions * quota,), 0, dtype=jnp.int32)
    gather_idx = gather_idx.at[slot].set(perm, mode="drop")
    slot_valid = jnp.zeros((num_partitions * quota,), dtype=bool)
    slot_valid = slot_valid.at[slot].set(fits, mode="drop")
    return gather_idx, slot_valid.reshape(num_partitions, quota), overflow


def make_all_to_all_exchange(mesh, quota: int, axis_name: str = "data"):
    """Build a jitted shard_map exchange.

    Inputs (all row-sharded over `axis_name`, per-shard capacity = cap):
      key_eqs: list of eq-domain arrays, key_valids (or None), payload arrays,
      row_mask.
    Output: payload arrays + row_mask re-sharded so equal keys land on the
    same device; per-shard capacity becomes P*quota. overflow scalar summed
    across shards."""
    from jax.sharding import PartitionSpec as P

    n_part = mesh.shape[axis_name]

    def local_fn(key_eqs, key_valids, payloads, row_mask):
        gather_idx, slot_valid, overflow = _bucket_local(
            key_eqs, key_valids, row_mask, n_part, quota)
        out_payloads = []
        for p in payloads:
            blocks = jnp.take(p, gather_idx).reshape(n_part, quota)
            recv = lax.all_to_all(blocks, axis_name, split_axis=0,
                                  concat_axis=0, tiled=False)
            out_payloads.append(recv.reshape(n_part * quota))
        vrecv = lax.all_to_all(slot_valid, axis_name, split_axis=0,
                               concat_axis=0, tiled=False)
        new_mask = vrecv.reshape(n_part * quota)
        total_overflow = lax.psum(overflow, axis_name)
        return out_payloads, new_mask, total_overflow

    def sharded(key_eqs, key_valids, payloads, row_mask):
        from ._shard_map_compat import shard_map

        in_specs = (
            [P(axis_name)] * len(key_eqs),
            [None if v is None else P(axis_name) for v in key_valids],
            [P(axis_name)] * len(payloads),
            P(axis_name),
        )
        out_specs = ([P(axis_name)] * len(payloads), P(axis_name), P())
        f = shard_map(local_fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
        return f(key_eqs, key_valids, payloads, row_mask)

    return jax.jit(sharded)
