"""Planner-integrated mesh exchange: ShuffleExchangeExec on the ICI.

Role of the reference's exchange-to-shuffle lowering
(sqlx/exchange/ShuffleExchangeExec.scala:344 — partition-id computation
feeding the core shuffle writer) re-designed for a TPU slice: when a hash
exchange's partition count matches a device mesh, the whole redistribution
runs as ONE XLA program — per-shard bucket-by-destination (hash + lax.sort)
followed by `lax.all_to_all` over the mesh axis — so the redistribution
itself rides the ICI, not a host loop (SURVEY.md §2.5 'Communication
backend'). Staging still crosses the host once on entry (dictionary merge +
re-sharding of arbitrary input tiles); keeping resident mesh output sharded
end-to-end is the planned next step. The host sort-shuffle
(exec/shuffle.py) remains the fallback for non-mesh shapes and the
cross-slice/DCN path.

Static-shape discipline: each (src→dst) pair gets a fixed row `quota`; the
program psums an overflow count and the host retries with a doubled quota —
the same capacity-bucket contract as the join/aggregate kernels.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..columnar.batch import (
    Column, ColumnarBatch, EMPTY_DICT, bucket_capacity, merge_string_dicts,
)
from ..types import StructType, dict_encoded

_MESH_CACHE: dict = {}


def _get_mesh(n: int, axis: str):
    from .mesh import get_mesh

    key = (n, axis)
    m = _MESH_CACHE.get(key)
    if m is None:
        m = _MESH_CACHE[key] = get_mesh(n, axis)
    return m


def mesh_for(num_out: int, conf, schema: StructType):
    """The mesh to run this exchange on, or None → host shuffle path.

    Conditions: mesh enabled, ≥2 devices, power-of-two partition count that
    fits the device count. All dict-encoded payloads (strings, arrays,
    maps, structs) travel as recoded int32 codes against a merged global
    dictionary (merge_string_dicts canonicalizes nested values)."""
    from ..config import MESH_ENABLED, DEVICE_MESH_AXIS

    if not conf.get(MESH_ENABLED):
        return None
    if num_out < 2 or (num_out & (num_out - 1)) != 0:
        return None
    import jax

    if len(jax.devices()) < num_out:
        return None
    return _get_mesh(num_out, conf.get(DEVICE_MESH_AXIS))


def _stage_inputs(partitions, key_positions, schema: StructType):
    """Flatten input partitions into host arrays + merged dictionaries.

    Returns (key_eqs, key_valids, payload_datas, payload_valids, row_mask,
    merged_dicts, total_cap). Strings are recoded to a global dictionary so
    codes are comparable across shards after the exchange."""
    batches = [b for part in partitions for b in part]
    ncols = len(schema.fields)

    merged_dicts: list = [None] * ncols
    recodes: list = [None] * ncols  # per col: list of per-batch LUTs
    for i, f in enumerate(schema.fields):
        if dict_encoded(f.dataType):
            dicts = [b.columns[i].dictionary or EMPTY_DICT
                     for b in batches]
            if batches and all(d is dicts[0] for d in dicts):
                merged_dicts[i] = dicts[0]
            else:
                md, luts = merge_string_dicts(dicts)
                merged_dicts[i] = md
                recodes[i] = luts

    datas = [[] for _ in range(ncols)]
    valids = [[] for _ in range(ncols)]
    has_valid = [False] * ncols
    masks = []
    key_eq_chunks = [[] for _ in key_positions]
    for bi, b in enumerate(batches):
        masks.append(np.asarray(b.row_mask))
        for i, c in enumerate(b.columns):
            d = np.asarray(c.data)
            if recodes[i] is not None:
                lut = recodes[i][bi]
                d = lut[np.clip(d, 0, len(lut) - 1)]
            datas[i].append(d)
            if c.validity is not None:
                has_valid[i] = True
            valids[i].append(None if c.validity is None
                             else np.asarray(c.validity))
        for ki, kp in enumerate(key_positions):
            key_eq_chunks[ki].append(np.asarray(b.columns[kp].eq_keys()))

    if not batches:
        return None
    row_mask = np.concatenate(masks)
    total_cap = int(row_mask.shape[0])
    payload_datas = [np.concatenate(ds) for ds in datas]
    payload_valids = []
    for i in range(ncols):
        if has_valid[i]:
            vs = [v if v is not None else np.ones(len(d), bool)
                  for v, d in zip(valids[i], datas[i])]
            payload_valids.append(np.concatenate(vs))
        else:
            payload_valids.append(None)
    key_eqs = [np.concatenate(ch) for ch in key_eq_chunks]
    key_valids = [payload_valids[kp] for kp in key_positions]
    return (key_eqs, key_valids, payload_datas, payload_valids, row_mask,
            merged_dicts, total_cap)


def _exchange_program(mesh, axis: str, cap: int, quota: int,
                      n_keys: int, key_valid_sig: tuple,
                      payload_dtypes: tuple, payload_valid_sig: tuple):
    """Build (cached) the jitted shard_map exchange for this structure."""
    from ..physical.compile import GLOBAL_KERNEL_CACHE
    from .collectives import make_all_to_all_exchange

    kkey = ("mesh_exchange", id(mesh), axis, cap, quota, n_keys,
            key_valid_sig, payload_dtypes, payload_valid_sig)
    return GLOBAL_KERNEL_CACHE.get_or_build(
        kkey,
        lambda: make_all_to_all_exchange(mesh, quota, axis_name=axis))


def mesh_shuffle_hash(partitions, key_positions: Sequence[int], num_out: int,
                      schema: StructType, ctx, stats, mesh) -> list:
    """Hash exchange over the mesh; output partition i lives on device i."""
    import jax
    import jax.numpy as jnp

    from ..config import DEVICE_MESH_AXIS
    from jax.sharding import NamedSharding, PartitionSpec

    axis = ctx.conf.get(DEVICE_MESH_AXIS)
    staged = _stage_inputs(partitions, key_positions, schema)
    if staged is None:
        out = [[ColumnarBatch.empty(schema)] for _ in range(num_out)]
        for i in range(num_out):
            stats[i] = 0
        return out
    (key_eqs, key_valids, payload_datas, payload_valids, row_mask,
     merged_dicts, total_cap) = staged

    P = num_out
    shard_cap = bucket_capacity(max((total_cap + P - 1) // P, 64))
    cap = shard_cap * P

    def pad(arr, fill=0):
        if arr is None:
            return None
        out = np.zeros(cap, dtype=arr.dtype)
        out[: len(arr)] = arr
        return out

    sharding = NamedSharding(mesh, PartitionSpec(axis))
    put = lambda a: jax.device_put(jnp.asarray(a), sharding)

    d_key_eqs = [put(pad(k)) for k in key_eqs]
    d_key_valids = [None if v is None else put(pad(v)) for v in key_valids]
    d_mask = put(pad(row_mask))
    # payloads: every column's data, then the validity planes, then row_mask
    payloads = [put(pad(d)) for d in payload_datas]
    vplanes = [put(pad(v)) for v in payload_valids if v is not None]
    vmap_idx = [i for i, v in enumerate(payload_valids) if v is not None]

    quota = max(16, 2 * shard_cap // P)
    for _ in range(8):
        prog = _exchange_program(
            mesh, axis, shard_cap, quota, len(key_eqs),
            tuple(v is not None for v in key_valids),
            tuple(str(d.dtype) for d in payloads),
            tuple(v is not None for v in payload_valids))
        out_payloads, new_mask, overflow = prog(
            d_key_eqs, d_key_valids, payloads + vplanes, d_mask)
        if int(overflow) == 0:
            ctx.metrics.add("exchange.mesh")
            break
        quota *= 2
    else:
        # pathological skew past every retry: the host sort-shuffle has no
        # quota to overflow — degrade instead of failing the query
        from ..exec import shuffle as S

        ctx.metrics.add("exchange.mesh_fallback")
        return S.shuffle_hash(partitions, list(key_positions), num_out,
                              schema, ctx, stats)

    out_cap = P * quota
    col_arrays = out_payloads[: len(payload_datas)]
    valid_arrays = out_payloads[len(payload_datas):]

    def shards_of(arr):
        """Per-device shard views ordered by partition id."""
        out = [None] * P
        for s in arr.addressable_shards:
            out[s.index[0].start // out_cap] = s.data
        return out

    mask_shards = shards_of(new_mask)
    data_shards = [shards_of(a) for a in col_arrays]
    valid_shards = {}
    for vi, a in zip(vmap_idx, valid_arrays):
        valid_shards[vi] = shards_of(a)

    out = []
    for p in range(P):
        cols = []
        for i, f in enumerate(schema.fields):
            v = valid_shards[i][p] if i in valid_shards else None
            cols.append(Column(f.dataType, data_shards[i][p], v,
                               merged_dicts[i]))
        n = int(np.asarray(mask_shards[p]).sum())
        stats[p] = n
        out.append([ColumnarBatch(schema, cols, mask_shards[p], num_rows=n)])
    return out
