"""Planner-integrated mesh exchange: ShuffleExchangeExec on the ICI.

Role of the reference's exchange-to-shuffle lowering
(sqlx/exchange/ShuffleExchangeExec.scala:344 — partition-id computation
feeding the core shuffle writer) re-designed for a TPU slice: when a hash
exchange's partition count matches a device mesh, the whole shuffle STAGE
runs as ONE XLA program (parallel/mesh_fusion.py) — for a fused exchange
the traced filter/project pipeline, the partition-id computation, the
per-shard bucket-by-destination and the `lax.all_to_all` all execute
under a single `shard_map` dispatch per step; pre-materialized batches
take the same program without the pipeline leg. Staging crosses the host
once on entry (dictionary merge + re-sharding of arbitrary input tiles);
the send buffers are donated so the all-to-all reuses their HBM in-place,
and outputs stay shard-resident — reduce partition i's batch wraps
device i's shard directly for the downstream consumer (agg partial /
join build feed). The host sort-shuffle (exec/shuffle.py) remains the
fallback for non-mesh shapes and the cross-slice/DCN path.

Static-shape discipline: each (src→dst) pair gets a fixed row `quota`;
the program psums an overflow count and the host retries with a doubled
quota — the same capacity-bucket contract as the join/aggregate kernels.
The plan analyzer (analysis/plan_lint.py) mirrors the staging geometry
and the retry loop exactly, so mesh-path launch counts predict exactly
whenever the key values trace.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..columnar.batch import (
    Column, ColumnarBatch, EMPTY_DICT, merge_string_dicts,
)
from ..types import StructType, dict_encoded
from . import mesh_fusion as MF
from .mesh_fusion import (
    MeshSpecLayout, StagedBuffers, build_fused_stage, build_plain_stage,
    mesh_stage_geometry,
)

_MESH_CACHE: dict = {}
_MAX_QUOTA_RETRIES = 8
# gang-failure budget (JAMPI barrier-mode semantics: one shard fails ⇒
# the WHOLE sharded dispatch failed): one full-gang retry with fresh
# staging, then degrade to the host sort-shuffle — the same terminal
# fallback the skew path takes
_MAX_GANG_RETRIES = 1


def _get_mesh(n: int, axis: str):
    from .mesh import get_mesh

    key = (n, axis)
    m = _MESH_CACHE.get(key)
    if m is None:
        m = _MESH_CACHE[key] = get_mesh(n, axis)
    return m


def mesh_for(num_out: int, conf, schema: StructType):
    """The mesh to run this exchange on, or None → host shuffle path.

    Conditions: mesh enabled, ≥2 devices, power-of-two partition count that
    fits the device count. All dict-encoded payloads (strings, arrays,
    maps, structs) travel as recoded int32 codes against a merged global
    dictionary (merge_string_dicts canonicalizes nested values)."""
    from ..config import MESH_ENABLED, DEVICE_MESH_AXIS

    if not conf.get(MESH_ENABLED):
        return None
    if num_out < 2 or (num_out & (num_out - 1)) != 0:
        return None
    import jax

    if len(jax.devices()) < num_out:
        return None
    return _get_mesh(num_out, conf.get(DEVICE_MESH_AXIS))


def _stage_payloads(batches: list, schema: StructType):
    """Flatten batches into host payload arrays + merged dictionaries.

    Returns (payload_datas, payload_valids, row_mask, merged_dicts,
    total_cap) or None when there are no batches. Strings are recoded to
    a global dictionary so codes are comparable across shards after the
    exchange."""
    ncols = len(schema.fields)

    merged_dicts: list = [None] * ncols
    recodes: list = [None] * ncols  # per col: list of per-batch LUTs
    for i, f in enumerate(schema.fields):
        if dict_encoded(f.dataType):
            dicts = [b.columns[i].dictionary or EMPTY_DICT
                     for b in batches]
            if batches and all(d is dicts[0] for d in dicts):
                merged_dicts[i] = dicts[0]
            else:
                md, luts = merge_string_dicts(dicts)
                merged_dicts[i] = md
                recodes[i] = luts

    if not batches:
        return None
    datas = [[] for _ in range(ncols)]
    valids = [[] for _ in range(ncols)]
    has_valid = [False] * ncols
    masks = []
    for bi, b in enumerate(batches):
        masks.append(np.asarray(b.row_mask))
        for i, c in enumerate(b.columns):
            d = np.asarray(c.data)
            if recodes[i] is not None:
                lut = recodes[i][bi]
                d = lut[np.clip(d, 0, len(lut) - 1)]
            datas[i].append(d)
            if c.validity is not None:
                has_valid[i] = True
            valids[i].append(None if c.validity is None
                             else np.asarray(c.validity))
    row_mask = np.concatenate(masks)
    total_cap = int(row_mask.shape[0])
    payload_datas = [np.concatenate(ds) for ds in datas]
    payload_valids = []
    for i in range(ncols):
        if has_valid[i]:
            vs = [v if v is not None else np.ones(len(d), bool)
                  for v, d in zip(valids[i], datas[i])]
            payload_valids.append(np.concatenate(vs))
        else:
            payload_valids.append(None)
    return payload_datas, payload_valids, row_mask, merged_dicts, total_cap


def _pad_shards(arr, num_out: int, rows_per_shard: int, shard_cap: int):
    """Lay a [total_cap] host array out as [P * shard_cap] with each
    shard's row block at its shard offset — every device gets its slice
    of the data plus its own padding (a tail-padded layout would starve
    the high shards and overflow the low ones)."""
    if arr is None:
        return None
    out = np.zeros(num_out * shard_cap, dtype=arr.dtype)
    for s in range(num_out):
        src = arr[s * rows_per_shard: (s + 1) * rows_per_shard]
        if len(src):
            out[s * shard_cap: s * shard_cap + len(src)] = src
    return out


def _pad_base(arr, num_out: int, rows_per_shard: int):
    """Geometry-INDEPENDENT base layout of a [total_cap] host array:
    flat [P * rows_per_shard] with each shard's row block contiguous at
    its natural offset (blocks are contiguous in the input, so this is a
    tail-pad). Staged device-side ONCE at the first quota overflow and
    reused across every retry — the retry program embeds each shard's
    block into that attempt's [shard_cap] send layout in-program
    (mesh_fusion._embed_block), so retries pay only the recompile, never
    the host->device restage."""
    if arr is None:
        return None
    want = num_out * rows_per_shard
    if len(arr) == want:
        return arr
    out = np.zeros(want, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


def _planes_alive(arrays) -> bool:
    """True when every device plane in `arrays` is still resident (not
    deleted/donated) — the liveness proof a gang retry needs before it
    reuses quota-retry base planes instead of defensively restaging from
    host. Base planes are UNDONATED by contract (build_*_stage passes
    donate_argnums=() when base_rows is set), so a runtime gang fault
    cannot have consumed them; this check is the assertion of that
    contract, not a heuristic."""
    return all(not getattr(a, "is_deleted", lambda: False)()
               for a in arrays if a is not None)


def _shards_by_partition(arr, out_cap: int, num_out: int) -> list:
    """Per-device shard views of a program output, ordered by reduce
    partition id."""
    out = [None] * num_out
    for s in arr.addressable_shards:
        out[s.index[0].start // out_cap] = s.data
    return out


def _empty_result(num_out: int, schema: StructType, stats: dict) -> list:
    out = [[ColumnarBatch.empty(schema)] for _ in range(num_out)]
    for i in range(num_out):
        stats[i] = 0
    return out


def _stat_candidates(schema: StructType, stat_cols) -> list:
    """Column positions whose per-reduce min/max the stage program
    accumulates in-program: integral non-dictionary columns (the only
    ones dense_range_stats reads), intersected with the exchange's
    plan-reachable stat_cols annotation when present — the same
    restriction exec/shuffle._OutBuffer applies on the host path."""
    integral = [i for i, f in enumerate(schema.fields)
                if np.dtype(f.dataType.device_dtype).kind == "i"
                and not dict_encoded(f.dataType)]
    if stat_cols is None:
        return integral
    allow = set(stat_cols)
    return [i for i in integral if i in allow]


def _seed_mesh_stats(result: list, stat_idx: list, stats_np, num_out: int,
                     col_stats) -> None:
    """Seed each reduce partition's dense-range memo from the program's
    in-program column stats ([P, n_stat, 3] — min/max/live-count per
    shard) — the mesh analog of _OutBuffer.seed_stats: post-shuffle
    dense agg/join decisions never launch the krange3 probe. Per-shard
    stats equal exactly what the probe would have measured (same rows),
    so the plan analyzer's dense-decision spans stay exact. The union
    also lands in the exchange's col_stats for the obs layer's
    key-span stage stats."""
    from ..utils.device_memo import seed_dense_range_memo

    union: dict = {}
    for p in range(num_out):
        batch = result[p][0]
        for j, ci in enumerate(stat_idx):
            lo, hi, cnt = (int(x) for x in stats_np[p, j])
            st = (lo, hi, True) if cnt > 0 else (0, 0, False)
            seed_dense_range_memo(batch.columns[ci], batch.row_mask, st)
            if cnt > 0:
                cur = union.get(ci)
                union[ci] = ((min(cur[0], lo), max(cur[1], hi), True)
                             if cur else (lo, hi, True))
    if col_stats is not None and union:
        col_stats["mesh"] = union


def _build_result(schema: StructType, col_arrays: list, valid_arrays: list,
                  new_mask, counts_np, dicts: list, num_out: int,
                  out_cap: int, stats: dict) -> list:
    """Wrap each device's received shard as that reduce partition's batch
    — shard-resident: the downstream consumer reads the device array the
    all-to-all delivered, no host round-trip."""
    mask_shards = _shards_by_partition(new_mask, out_cap, num_out)
    data_shards = [_shards_by_partition(a, out_cap, num_out)
                   for a in col_arrays]
    valid_shards = [None if a is None
                    else _shards_by_partition(a, out_cap, num_out)
                    for a in valid_arrays]
    out = []
    for p in range(num_out):
        cols = []
        for i, f in enumerate(schema.fields):
            v = valid_shards[i][p] if valid_shards[i] is not None else None
            cols.append(Column(f.dataType, data_shards[i][p], v, dicts[i]))
        n = int(counts_np[p])
        stats[p] = n
        out.append([ColumnarBatch(schema, cols, mask_shards[p],
                                  num_rows=n)])
    return out


def _skew_split_merge(batches, num_out, ctx, stats, col_stats, recurse):
    """Pathological skew past every quota retry: split the batch list in
    half and re-plan each half as its own (smaller) mesh exchange instead
    of degrading straight to the host shuffle. Each half stages with
    roughly half the volume, so its quota geometry restarts small; the
    per-reducer outputs concatenate — hash partitioning is
    batch-decomposable. Returns None (caller degrades to host) when the
    split is off or there is nothing left to split."""
    from ..config import ADAPTIVE_SKEW_REPARTITION

    if len(batches) < 2 or not ctx.conf.get(ADAPTIVE_SKEW_REPARTITION):
        return None
    ctx.metrics.add("adaptive.skew_repartitions")
    mid = len(batches) // 2
    halves = []
    for chunk in (batches[:mid], batches[mid:]):
        st: dict = {}
        cs: dict | None = {} if col_stats is not None else None
        halves.append((recurse(chunk, st, cs), st, cs))
    merged = [[b for (res, _, _) in halves for b in res[i]]
              for i in range(num_out)]
    for i in range(num_out):
        stats[i] = sum(st.get(i, 0) for (_, st, _) in halves)
    if col_stats is not None:
        union: dict = {}
        for (_, _, cs) in halves:
            for ci, (lo, hi, _ok) in ((cs or {}).get("mesh")
                                      or {}).items():
                cur = union.get(ci)
                union[ci] = ((min(cur[0], lo), max(cur[1], hi), True)
                             if cur else (lo, hi, True))
        if union:
            col_stats["mesh"] = union
    return merged


def mesh_shuffle_hash(partitions, key_positions: Sequence[int],
                      num_out: int, schema: StructType, ctx, stats,
                      mesh, fusion=None, col_stats=None,
                      stat_cols=None) -> list:
    """Hash exchange over the mesh; output partition i lives on device i.

    With `fusion` (physical/fusion.ExchangeFusion bound to this hash
    partitioning) and spark.tpu.fusion.mesh on, the WHOLE stage —
    pipeline, partition ids, all-to-all — is one SPMD dispatch per step;
    otherwise the pipeline (if any) materializes per batch and the
    pre-materialized batches take the plain stage program."""
    from ..config import DEVICE_MESH_AXIS, FUSION_MESH

    axis = ctx.conf.get(DEVICE_MESH_AXIS)
    if fusion is not None and not ctx.conf.get(FUSION_MESH):
        # legacy composition: materialize the pipeline per batch, then
        # redistribute the materialized batches
        partitions = [[fusion.run_pipeline(b) for b in part]
                      for part in partitions]
        fusion = None
    if fusion is not None:
        return _mesh_shuffle_fused(partitions, fusion, num_out, schema,
                                   ctx, stats, mesh, axis, col_stats,
                                   stat_cols)
    return _mesh_shuffle_plain(partitions, key_positions, num_out, schema,
                               ctx, stats, mesh, axis, col_stats,
                               stat_cols)


def _mesh_shuffle_plain(partitions, key_positions, num_out, schema, ctx,
                        stats, mesh, axis, col_stats=None,
                        stat_cols=None) -> list:
    """Pre-materialized batches: keys staged in their eq domains, one
    stage program per step (pids + bucket + all-to-all)."""
    import jax

    from ..physical.compile import GLOBAL_KERNEL_CACHE

    batches = [b for part in partitions for b in part]
    staged = _stage_payloads(batches, schema)
    if staged is None:
        return _empty_result(num_out, schema, stats)
    (payload_datas, payload_valids, row_mask, merged_dicts,
     total_cap) = staged
    key_eqs = []
    for kp in key_positions:
        chunks = [np.asarray(b.columns[kp].eq_keys()) for b in batches]
        key_eqs.append(np.concatenate(chunks))
    key_valids = [payload_valids[kp] for kp in key_positions]

    P = num_out
    layout = MeshSpecLayout(axis)
    sharding = layout.row_sharding(mesh)
    vmap_idx = [i for i, v in enumerate(payload_valids) if v is not None]
    rows_per_shard, shard_cap, quota = mesh_stage_geometry(total_cap, P)
    donate = MF.DONATE_DEFAULT  # module switch: tests A/B the HBM win
    key_sig = tuple(v is not None for v in key_valids)
    pay_sig = tuple(str(d.dtype) for d in payload_datas) \
        + ("bool",) * len(vmap_idx)
    # in-program column stats: payload index + its validity plane's
    # position in the combined payloads list (-1 = no validity plane)
    stat_idx = _stat_candidates(schema, stat_cols)
    stat_spec = tuple(
        (i, len(payload_datas) + vmap_idx.index(i)
         if i in vmap_idx else -1)
        for i in stat_idx)
    # persistent warm start (exec/persist_cache.py): a prior same-
    # fingerprint run's FINAL quota for this exchange seeds the first
    # attempt, so a restarted process compiles the final program
    # directly (served by the XLA disk cache) instead of replaying the
    # quota-doubling ladder. shard_cap scales with it (the P*quota
    # staging invariant). plan_lint mirrors the same lookup.
    from ..exec.persist_cache import mesh_quota_key_plain

    quota0 = quota
    mkey = mesh_quota_key_plain(
        P, rows_per_shard, key_positions,
        [str(f.dataType) for f in schema.fields])
    seed_q = ((getattr(ctx, "persist_seed", None) or {})
              .get("mesh_quotas") or {}).get(mkey)
    if seed_q and int(seed_q) > quota:
        quota = int(seed_q)
        shard_cap = P * quota
        ctx.metrics.add("cache.mesh_quota_seeded")
    base = None        # device-resident base planes (set at 1st overflow)
    base_ledger = None
    gang_failures = 0
    try:
        for attempt in range(_MAX_QUOTA_RETRIES):
            out_cap = P * quota
            if base is None:
                pad = lambda a: _pad_shards(a, P, rows_per_shard, shard_cap)  # noqa: E731
                # device_put the HOST array straight against the
                # canonical spec: jnp.asarray first would land whole on
                # device 0 and reshard
                put = lambda a: jax.device_put(a, sharding)  # noqa: E731
                d_keys = [put(pad(k)) for k in key_eqs]
                d_kvalids = [None if v is None else put(pad(v))
                             for v in key_valids]
                d_payloads = [put(pad(d)) for d in payload_datas]
                d_vplanes = [put(pad(payload_valids[i]))
                             for i in vmap_idx]
                d_mask = put(pad(row_mask))
                sent = d_payloads + d_vplanes + [d_mask]
                ledger = StagedBuffers(
                    sent + d_keys + [v for v in d_kvalids
                                     if v is not None])
                kkey = ("mesh_stage", "p", id(mesh), axis, P, quota,
                        len(key_eqs), key_sig, pay_sig, stat_spec,
                        donate)
                prog = GLOBAL_KERNEL_CACHE.get_or_build(
                    kkey, lambda: build_plain_stage(
                        mesh, axis, quota, P, len(key_eqs), key_sig,
                        len(d_payloads) + len(d_vplanes), donate,
                        stat_spec=stat_spec))
            else:
                # retry: the persisted base planes feed a program that
                # re-lays them out in-program — zero host->device restage
                d_keys, d_kvalids, d_payloads, d_vplanes, d_mask = base
                ledger = None
                kkey = ("mesh_stage", "p", id(mesh), axis, P, quota,
                        len(key_eqs), key_sig, pay_sig, stat_spec,
                        donate, "base", rows_per_shard)
                prog = GLOBAL_KERNEL_CACHE.get_or_build(
                    kkey, lambda: build_plain_stage(
                        mesh, axis, quota, P, len(key_eqs), key_sig,
                        len(d_payloads) + len(d_vplanes), donate,
                        base_rows=rows_per_shard, stat_spec=stat_spec))
            try:
                with MF.expected_donation_residue():
                    res = prog(d_keys, d_kvalids,
                               d_payloads + d_vplanes, d_mask)
                if stat_spec:
                    (out_payloads, new_mask, counts, overflow,
                     stats_arr) = res
                else:
                    out_payloads, new_mask, counts, overflow = res
                    stats_arr = None
                # the shuffle's ONE intended sync point per attempt: the
                # overflow verdict gates the retry loop
                flow = int(overflow)  # tpulint: ignore[host-sync]
            except Exception as e:
                from ..utils.faults import is_runtime_fault

                if not is_runtime_fault(e):
                    raise
                # GANG failure (barrier semantics): one shard dying at
                # runtime fails the whole sharded dispatch. Retry the
                # gang once, then degrade to the host shuffle. The
                # donated send buffers may already be consumed and are
                # restaged; the UNDONATED quota-retry base planes are
                # provably still resident (liveness-checked) and are
                # reused — a gang retry never re-crosses the host for
                # data a prior attempt already staged.
                if ledger is not None:
                    ledger.release_all()
                if base is not None:
                    if _planes_alive(base[0] + base[1] + base[2]
                                     + base[3] + [base[4]]):
                        ctx.metrics.add("exchange.mesh_gang_base_reused")
                    else:
                        if base_ledger is not None:
                            base_ledger.release_all()
                            base_ledger = None
                        base = None
                gang_failures += 1
                ctx.metrics.add("exchange.mesh_gang_failures")
                if gang_failures > _MAX_GANG_RETRIES:
                    break       # → host-shuffle fallback below
                ctx.metrics.add("exchange.mesh_gang_retries")
                continue
            if ledger is not None:
                ledger.release_consumed()  # donated buffers died at call
            if flow == 0:
                ctx.metrics.add("exchange.mesh")
                if quota != quota0:
                    # final quota outcome for the warm-start manifest
                    pmq = getattr(ctx, "persist_mesh_quotas", None) or {}
                    pmq[mkey] = quota
                    ctx.persist_mesh_quotas = pmq
                counts_np = np.asarray(counts)  # tpulint: ignore[host-sync]
                valid_arrays: list = [None] * len(payload_datas)
                for j, i in enumerate(vmap_idx):
                    valid_arrays[i] = out_payloads[len(payload_datas) + j]
                result = _build_result(
                    schema, out_payloads[: len(payload_datas)],
                    valid_arrays, new_mask, counts_np, merged_dicts, P,
                    out_cap, stats)
                if stats_arr is not None:
                    # in-program column stats → dense-range memo seeds
                    # (one tiny [P, n_stat, 3] pull beside the counts)
                    stats_np = np.asarray(stats_arr).reshape(  # tpulint: ignore[host-sync]
                        P, len(stat_idx), 3)
                    _seed_mesh_stats(result, stat_idx, stats_np, P,
                                     col_stats)
                if ledger is not None:
                    ledger.release_all()
                return result
            if ledger is not None:
                ledger.release_all()
            if base is None:
                # first overflow: persist the staged host arrays
                # device-side ONCE — every further retry reuses them
                pb = lambda a: _pad_base(a, P, rows_per_shard)  # noqa: E731
                putb = lambda a: jax.device_put(a, sharding)  # noqa: E731
                base = ([putb(pb(k)) for k in key_eqs],
                        [None if v is None else putb(pb(v))
                         for v in key_valids],
                        [putb(pb(d)) for d in payload_datas],
                        [putb(pb(payload_valids[i])) for i in vmap_idx],
                        putb(pb(row_mask)))
                base_ledger = StagedBuffers(
                    base[0] + [v for v in base[1] if v is not None]
                    + base[2] + base[3] + [base[4]])
                ctx.metrics.add("exchange.mesh_retry_restage_saved")
            shard_cap, quota = 2 * shard_cap, 2 * quota
    finally:
        if base_ledger is not None:
            base_ledger.release_all()
    # pathological skew past every retry — or a mesh gang that kept
    # dying at runtime: the host sort-shuffle has no quota to overflow
    # and no gang to fail — degrade instead of failing the query
    from ..exec import shuffle as S

    if gang_failures <= _MAX_GANG_RETRIES:
        # quota exhaustion (data skew), not a dying gang: split the
        # oversized batch set and re-plan each half on the mesh
        split = _skew_split_merge(
            batches, num_out, ctx, stats, col_stats,
            lambda chunk, st, cs: _mesh_shuffle_plain(
                [chunk], key_positions, num_out, schema, ctx, st, mesh,
                axis, cs, stat_cols))
        if split is not None:
            return split
    ctx.metrics.add("exchange.mesh_fallback")
    if gang_failures > _MAX_GANG_RETRIES:
        ctx.metrics.add("exchange.mesh_runtime_fallback")
    return S.shuffle_hash(partitions, list(key_positions), num_out,
                          schema, ctx, stats, col_stats=col_stats,
                          stat_cols=stat_cols)


class _StagedView:
    """Column shim over the staged host arrays: enough surface for
    pipeline_host_pass / pipeline_signature (dtype, validity presence,
    dictionary) without constructing a ColumnarBatch (which would charge
    HOST numpy planes to the device ledger)."""

    def __init__(self, fields, datas, valids, dicts):
        self.columns = [Column(f.dataType, d, v, sd)
                        for f, d, v, sd in zip(fields, datas, valids,
                                               dicts)]


def _mesh_shuffle_fused(partitions, fusion, num_out, schema, ctx, stats,
                        mesh, axis, col_stats=None,
                        stat_cols=None) -> list:
    """ONE SPMD dispatch for the whole fused shuffle stage: raw input
    batches stage onto the mesh, the program traces the pipeline per
    shard, derives partition ids from the traced keys, and all-to-alls
    the pipeline output columns."""
    import jax

    from ..physical.compile import (
        GLOBAL_KERNEL_CACHE, pipeline_host_pass, pipeline_signature,
    )
    from ..physical.operators import attrs_schema

    input_attrs = fusion.input_attrs
    in_schema = attrs_schema(input_attrs)
    batches = [b for part in partitions for b in part]
    staged = _stage_payloads(batches, in_schema)
    if staged is None:
        return _empty_result(num_out, schema, stats)
    (in_datas, in_valids, row_mask, in_dicts, total_cap) = staged

    from ..columnar.batch import EMPTY_DICT as _ED
    from ..types import BooleanType, StringType

    filters, outputs = fusion.filters, fusion.pipe_outputs
    key_idx = fusion._key_idx
    seed = fusion._seed
    key_bool = tuple(isinstance(fusion.pipe_attrs[i].dtype, BooleanType)
                     for i in key_idx)
    staged_view = _StagedView(in_schema.fields, in_datas, in_valids,
                              in_dicts)
    hctx, host_outs, aux = pipeline_host_pass(input_attrs, filters,
                                              outputs, staged_view)
    out_valid_sig = tuple(h.validity is not None for h in host_outs)
    out_fields = schema.fields
    out_dicts = [host_outs[i].sdict if dict_encoded(f.dataType) else None
                 for i, f in enumerate(out_fields)]
    # string partition keys fuse too: padded codes→value-hash luts ride
    # the dispatch as replicated aux planes, so the in-program key hash
    # is dictionary-independent across shards (PR 9 compressed-execution
    # carry-over — the pipeline no longer materializes before the
    # collective for dict-encoded keys)
    dict_pos = tuple(i for i in key_idx
                     if isinstance(fusion.pipe_attrs[i].dtype, StringType))
    kluts = [(host_outs[i].sdict or _ED).device_hash_lut()
             for i in dict_pos]

    P = num_out
    layout = MeshSpecLayout(axis)
    sharding = layout.row_sharding(mesh)
    rep_sharding = layout.replicated_sharding(mesh)
    d_aux = [jax.device_put(a, rep_sharding) for a in aux]
    d_kluts = [jax.device_put(l, rep_sharding) for l in kluts]
    lut_lens = tuple(int(l.shape[0]) for l in kluts)
    rows_per_shard, shard_cap, quota = mesh_stage_geometry(total_cap, P)
    donate = MF.DONATE_DEFAULT  # module switch: tests A/B the HBM win
    # in-program column stats over the pipeline OUTPUT columns (planes =
    # out_datas + out_valids inside the program)
    stat_idx = _stat_candidates(schema, stat_cols)
    stat_spec = tuple(
        (i, len(out_fields) + i if out_valid_sig[i] else -1)
        for i in stat_idx)
    # persistent warm start: the fused exchange's final quota from a
    # prior same-fingerprint run (see the plain path for the contract)
    from ..exec.persist_cache import mesh_quota_key_fused

    quota0 = quota
    mkey = mesh_quota_key_fused(
        P, rows_per_shard, key_idx, len(out_fields),
        [str(f.dataType) for f in out_fields])
    seed_q = ((getattr(ctx, "persist_seed", None) or {})
              .get("mesh_quotas") or {}).get(mkey)
    if seed_q and int(seed_q) > quota:
        quota = int(seed_q)
        shard_cap = P * quota
        ctx.metrics.add("cache.mesh_quota_seeded")
    base = None        # device-resident base planes (set at 1st overflow)
    base_ledger = None
    gang_failures = 0
    try:
        for attempt in range(_MAX_QUOTA_RETRIES):
            out_cap = P * quota
            if base is None:
                pad = lambda a: _pad_shards(a, P, rows_per_shard, shard_cap)  # noqa: E731
                # device_put the HOST array straight against the
                # canonical spec: jnp.asarray first would land whole on
                # device 0 and reshard
                put = lambda a: jax.device_put(a, sharding)  # noqa: E731
                d_datas = [put(pad(d)) for d in in_datas]
                d_valids = [None if v is None else put(pad(v))
                            for v in in_valids]
                d_mask = put(pad(row_mask))
                ledger = StagedBuffers(
                    d_datas + [v for v in d_valids
                               if v is not None] + [d_mask])
                kkey = ("mesh_stage", "f", id(mesh), axis, P, quota, seed,
                        fusion._struct_key, key_idx, key_bool,
                        out_valid_sig, pipeline_signature(staged_view),
                        hctx.signature(), stat_spec, dict_pos,
                        lut_lens, donate)
                prog = GLOBAL_KERNEL_CACHE.get_or_build(
                    kkey, lambda: build_fused_stage(
                        mesh, axis, shard_cap, quota, P, seed,
                        input_attrs, filters, outputs, key_idx, key_bool,
                        out_valid_sig, donate, stat_spec=stat_spec,
                        dict_pos=dict_pos))
            else:
                # retry: persisted base planes, in-program re-layout —
                # the retry pays the recompile only, never the restage
                d_datas, d_valids, d_mask = base
                ledger = None
                kkey = ("mesh_stage", "f", id(mesh), axis, P, quota, seed,
                        fusion._struct_key, key_idx, key_bool,
                        out_valid_sig, pipeline_signature(staged_view),
                        hctx.signature(), stat_spec, dict_pos,
                        lut_lens, donate, "base", rows_per_shard)
                prog = GLOBAL_KERNEL_CACHE.get_or_build(
                    kkey, lambda: build_fused_stage(
                        mesh, axis, shard_cap, quota, P, seed,
                        input_attrs, filters, outputs, key_idx, key_bool,
                        out_valid_sig, donate, base_rows=rows_per_shard,
                        stat_spec=stat_spec, dict_pos=dict_pos))
            try:
                with MF.expected_donation_residue():
                    res = prog(d_datas, d_valids, d_mask, d_aux, d_kluts)
                if stat_spec:
                    (g_datas, g_valids, new_mask, counts, overflow,
                     stats_arr) = res
                else:
                    g_datas, g_valids, new_mask, counts, overflow = res
                    stats_arr = None
                # the shuffle's ONE intended sync point per attempt
                flow = int(overflow)  # tpulint: ignore[host-sync]
            except Exception as e:
                from ..utils.faults import is_runtime_fault

                if not is_runtime_fault(e):
                    raise
                # gang failure: retry the whole sharded dispatch once,
                # then degrade to the host shuffle. Undonated base
                # planes are liveness-checked and reused (see the plain
                # path) — only the donated attempt buffers restage.
                if ledger is not None:
                    ledger.release_all()
                if base is not None:
                    if _planes_alive(base[0] + base[1] + [base[2]]):
                        ctx.metrics.add("exchange.mesh_gang_base_reused")
                    else:
                        if base_ledger is not None:
                            base_ledger.release_all()
                            base_ledger = None
                        base = None
                gang_failures += 1
                ctx.metrics.add("exchange.mesh_gang_failures")
                if gang_failures > _MAX_GANG_RETRIES:
                    break       # → host-shuffle fallback below
                ctx.metrics.add("exchange.mesh_gang_retries")
                continue
            if ledger is not None:
                ledger.release_consumed()  # donated buffers died at call
            if flow == 0:
                ctx.metrics.add("exchange.mesh")
                ctx.metrics.add("exchange.mesh_fused")
                if quota != quota0:
                    pmq = getattr(ctx, "persist_mesh_quotas", None) or {}
                    pmq[mkey] = quota
                    ctx.persist_mesh_quotas = pmq
                counts_np = np.asarray(counts)  # tpulint: ignore[host-sync]
                result = _build_result(schema, g_datas, list(g_valids),
                                       new_mask, counts_np, out_dicts, P,
                                       out_cap, stats)
                if stats_arr is not None:
                    stats_np = np.asarray(stats_arr).reshape(  # tpulint: ignore[host-sync]
                        P, len(stat_idx), 3)
                    _seed_mesh_stats(result, stat_idx, stats_np, P,
                                     col_stats)
                if ledger is not None:
                    ledger.release_all()
                return result
            if ledger is not None:
                ledger.release_all()
            if base is None:
                pb = lambda a: _pad_base(a, P, rows_per_shard)  # noqa: E731
                putb = lambda a: jax.device_put(a, sharding)  # noqa: E731
                base = ([putb(pb(d)) for d in in_datas],
                        [None if v is None else putb(pb(v))
                         for v in in_valids],
                        putb(pb(row_mask)))
                base_ledger = StagedBuffers(
                    base[0] + [v for v in base[1] if v is not None]
                    + [base[2]])
                ctx.metrics.add("exchange.mesh_retry_restage_saved")
            shard_cap, quota = 2 * shard_cap, 2 * quota
    finally:
        if base_ledger is not None:
            base_ledger.release_all()
    from ..exec import shuffle as S

    if gang_failures <= _MAX_GANG_RETRIES:
        # quota exhaustion (data skew), not a dying gang: split the
        # oversized batch set and re-plan each half on the mesh
        split = _skew_split_merge(
            batches, num_out, ctx, stats, col_stats,
            lambda chunk, st, cs: _mesh_shuffle_fused(
                [chunk], fusion, num_out, schema, ctx, st, mesh, axis,
                cs, stat_cols))
        if split is not None:
            return split
    ctx.metrics.add("exchange.mesh_fallback")
    if gang_failures > _MAX_GANG_RETRIES:
        ctx.metrics.add("exchange.mesh_runtime_fallback")
    return S.shuffle_fused(partitions, fusion, num_out, schema, ctx,
                           stats, col_stats, stat_cols)
