"""Fully on-device distributed group-by over a mesh: the flagship SPMD step.

This is the TPU-native replacement for the reference's whole
partial-agg → shuffle → final-agg stage pipeline (HashAggregateExec +
ShuffleExchangeExec + HashAggregateExec, SURVEY.md §3.2/§3.3) compiled into
ONE XLA program over a jax.sharding.Mesh:

  1. each shard partially aggregates its rows (sort + segment_sum),
  2. partial groups are exchanged by key hash with `lax.all_to_all`
     (ICI, no host involvement),
  3. each shard merges the groups it owns.

Used by __graft_entry__.dryrun_multichip and (future) the mesh execution
backend of the planner.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..ops import grouping as G
from ..ops.hashing import hash_columns, partition_ids
from .collectives import _bucket_local


def make_distributed_groupby_sum(mesh, axis_name: str = "data",
                                 quota: int | None = None):
    """Returns jitted fn(keys, values, row_mask) -> (out_keys, out_sums,
    out_counts, out_mask), all row-sharded over `axis_name`.

    keys int64[n], values float64/int64[n], row_mask bool[n]; n divisible by
    mesh size. Per-shard group count is bounded by shard capacity, so the
    exchange quota defaults to shard_cap // P (retryable upward by caller)."""
    from jax.sharding import PartitionSpec as P

    from ._shard_map_compat import shard_map

    n_part = mesh.shape[axis_name]

    def local_fn(keys, values, row_mask):
        cap = row_mask.shape[0]
        q = quota or max(cap // n_part, 8)

        # --- 1. local partial aggregation ---
        layout = G.group_rows([keys], [None], row_mask)
        sums, cnts = G.seg_sum(layout, values)
        gkeys, _ = G.scatter_group_keys(layout, keys, None)
        gmask = G.group_output_mask(layout)

        # --- 2. exchange partial groups by hash(key) ---
        gather_idx, slot_valid, _overflow = _bucket_local(
            [gkeys], [None], gmask, n_part, q)

        def xchg(arr):
            blocks = jnp.take(arr, gather_idx).reshape(n_part, q)
            recv = lax.all_to_all(blocks, axis_name, split_axis=0,
                                  concat_axis=0, tiled=False)
            return recv.reshape(n_part * q)

        rkeys = xchg(gkeys)
        rsums = xchg(sums)
        rcnts = xchg(cnts)
        rmask = lax.all_to_all(slot_valid, axis_name, split_axis=0,
                               concat_axis=0, tiled=False).reshape(n_part * q)

        # --- 3. merge: group again, sum the partial sums/counts ---
        mlayout = G.group_rows([rkeys], [None], rmask)
        msums, _ = G.seg_sum(mlayout, rsums)
        mcnts, _ = G.seg_sum(mlayout, rcnts)
        mkeys, _ = G.scatter_group_keys(mlayout, rkeys, None)
        mmask = G.group_output_mask(mlayout)
        return mkeys, msums, mcnts, mmask

    def sharded(keys, values, row_mask):
        f = shard_map(local_fn, mesh=mesh,
                      in_specs=(P(axis_name), P(axis_name), P(axis_name)),
                      out_specs=(P(axis_name), P(axis_name), P(axis_name),
                                 P(axis_name)),
                      check_vma=False)
        return f(keys, values, row_mask)

    return jax.jit(sharded)
