"""shard_map version shim.

jax moved shard_map out of experimental and renamed the replication-check
kwarg (check_rep -> check_vma) across releases; the mesh kernels target the
new surface. The supported kwarg is FEATURE-DETECTED once per process from
the resolved function's signature and cached; a jax release that renames
the kwarg again (or hides the signature) raises immediately with the
detected surface in the message instead of silently dropping the check —
version skew must fail loudly (tests/test_shard_map_compat.py).
"""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # jax<0.6 keeps it in experimental
    from jax.experimental.shard_map import shard_map as _shard_map

_check_kwarg: str | None = None  # detected lazily, once per process


def _detect_check_kwarg(fn) -> str:
    """The replication-check kwarg this jax's shard_map accepts
    (check_vma on current jax, check_rep before the rename). Raises on
    an unrecognized surface."""
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        raise RuntimeError(
            "jax shard_map signature is not introspectable — the "
            "version-skew shim (parallel/_shard_map_compat.py) cannot "
            "verify which replication-check kwarg this jax accepts; "
            "update the shim for this jax release")
    for name in ("check_vma", "check_rep"):
        if name in params:
            return name
    if any(p.kind is inspect.Parameter.VAR_KEYWORD
           for p in params.values()):
        # **kwargs hides the real surface: passing a guessed name would
        # either work or blow up deep inside jax — refuse loudly instead
        raise RuntimeError(
            "jax shard_map accepts **kwargs but neither check_vma nor "
            "check_rep is a named parameter — jax renamed the "
            "replication-check kwarg again; update "
            "parallel/_shard_map_compat.py for this jax release")
    raise RuntimeError(
        "jax shard_map exposes no replication-check kwarg "
        f"(parameters: {sorted(params)}) — update "
        "parallel/_shard_map_compat.py for this jax release")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if check_vma is not None:
        global _check_kwarg
        if _check_kwarg is None:
            _check_kwarg = _detect_check_kwarg(_shard_map)
        kwargs[_check_kwarg] = check_vma
    return _shard_map(f, **kwargs)
