"""shard_map version shim.

jax moved shard_map out of experimental and renamed the replication-check
kwarg (check_rep -> check_vma) across releases; the mesh kernels target the
new surface. This shim resolves the import and translates the kwarg so the
same call sites run on either jax generation.
"""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map
except ImportError:  # jax<0.6 keeps it in experimental
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if check_vma is not None:
        try:
            return _shard_map(f, **kwargs, check_vma=check_vma)
        except TypeError:
            return _shard_map(f, **kwargs, check_rep=check_vma)
    return _shard_map(f, **kwargs)
