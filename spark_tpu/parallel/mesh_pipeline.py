"""Composable mesh-resident query pipeline.

The round-2 building block for planner-level mesh execution: a full
filter → project → partial-aggregate → ICI all-to-all → final-merge pipeline
compiled as ONE XLA program over a jax.sharding.Mesh, with the quota-retry
discipline the host engine uses for capacity overflows (SURVEY.md §7
'Hard parts' (1)) applied to the exchange: the program reports dropped rows
via psum, and the host retries with a doubled quota — same contract as the
join kernel's `needed` scalar.
"""

from __future__ import annotations

from typing import Callable

import numpy as np


def make_mesh_groupby_pipeline(mesh, axis_name: str = "data"):
    """Returns run(keys, values, row_mask, *, filter_fn=None,
    project_fn=None, quota=None) executing

        filter → project → local partial group-sum → all-to-all by key hash
        → final merge

    entirely on the mesh. filter_fn(keys, values)->bool mask and
    project_fn(values)->values trace into the same program. Overflowing
    exchange quotas retry doubled (host loop, fresh compile per quota
    bucket)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ._shard_map_compat import shard_map

    from ..ops import grouping as G
    from .collectives import _bucket_local

    n_part = mesh.shape[axis_name]

    def build(quota: int, filter_fn, project_fn):
        def local_fn(keys, values, row_mask):
            mask = row_mask
            if filter_fn is not None:
                mask = mask & filter_fn(keys, values)
            vals = project_fn(values) if project_fn is not None else values

            layout = G.group_rows([keys], [None], mask)
            sums, _ = G.seg_sum(layout, vals)
            cnts = G.seg_count(layout)
            gkeys, _ = G.scatter_group_keys(layout, keys, None)
            gmask = G.group_output_mask(layout)

            gather_idx, slot_valid, overflow = _bucket_local(
                [gkeys], [None], gmask, n_part, quota)

            def xchg(arr):
                blocks = jnp.take(arr, gather_idx).reshape(n_part, quota)
                recv = lax.all_to_all(blocks, axis_name, split_axis=0,
                                      concat_axis=0, tiled=False)
                return recv.reshape(n_part * quota)

            rkeys = xchg(gkeys)
            rsums = xchg(sums)
            rcnts = xchg(cnts)
            rmask = lax.all_to_all(slot_valid, axis_name, split_axis=0,
                                   concat_axis=0,
                                   tiled=False).reshape(n_part * quota)
            total_overflow = lax.psum(overflow, axis_name)

            mlayout = G.group_rows([rkeys], [None], rmask)
            msums, _ = G.seg_sum(mlayout, rsums)
            mcnts, _ = G.seg_sum(mlayout, rcnts)
            mkeys, _ = G.scatter_group_keys(mlayout, rkeys, None)
            mmask = G.group_output_mask(mlayout)
            return mkeys, msums, mcnts, mmask, total_overflow

        def sharded(keys, values, row_mask):
            f = shard_map(
                local_fn, mesh=mesh,
                in_specs=(P(axis_name), P(axis_name), P(axis_name)),
                out_specs=(P(axis_name), P(axis_name), P(axis_name),
                           P(axis_name), P()),
                check_vma=False)
            return f(keys, values, row_mask)

        return jax.jit(sharded)

    compiled: dict = {}

    def run(keys, values, row_mask, *, filter_fn=None, project_fn=None,
            quota: int | None = None, max_retries: int = 8):
        per_shard = keys.shape[0] // n_part
        q = quota or max(per_shard // n_part, 8)
        for _ in range(max_retries):
            key = (q, id(filter_fn), id(project_fn))
            fn = compiled.get(key)
            if fn is None:
                fn = compiled[key] = build(q, filter_fn, project_fn)
            mk, ms, mc, mm, overflow = fn(keys, values, row_mask)
            if int(overflow) == 0:
                return mk, ms, mc, mm
            q *= 2  # exchange quota too small — retry doubled
        raise RuntimeError(
            f"mesh exchange quota still overflowing at {q}")

    return run
