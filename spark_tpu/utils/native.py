"""ctypes bindings to the native (C++) host runtime in native/.

Role of the reference's [NATIVE-ROLE] Java off-heap layer
(common/unsafe/.../Platform.java, Murmur3_x86_32.java, RadixSort.java):
host-side hot loops — string hashing at dictionary build, radix partitioning
for shuffle — implemented in C++ and loaded via ctypes. Every entry point has
a pure-Python/numpy fallback; callers catch ImportError/OSError.
"""

from __future__ import annotations

import ctypes
import os
from functools import lru_cache

import numpy as np

_LIB_NAMES = ("libsparktpu_native.so",)


@lru_cache(maxsize=1)
def _load():
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    candidates = [os.path.join(here, "..", "native", "build", n) for n in _LIB_NAMES]
    candidates += [os.path.join(here, "native", n) for n in _LIB_NAMES]
    for c in candidates:
        if os.path.exists(c):
            lib = ctypes.CDLL(c)
            lib.spark_tpu_hash_strings.restype = None
            lib.spark_tpu_hash_strings.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
            lib.spark_tpu_radix_partition.restype = None
            lib.spark_tpu_radix_partition.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
                ctypes.c_void_p, ctypes.c_void_p]
            return lib
    raise ImportError("native library not built")


def available() -> bool:
    try:
        _load()
        return True
    except Exception:
        return False


def hash_strings(values: list[str]) -> np.ndarray:
    """64-bit hashes for a list of strings via the C++ xxhash64 kernel."""
    lib = _load()
    blob = b"".join(v.encode("utf-8") for v in values)
    offsets = np.zeros(len(values) + 1, dtype=np.int64)
    np.cumsum([len(v.encode("utf-8")) for v in values], out=offsets[1:])
    out = np.empty(len(values), dtype=np.int64)
    buf = ctypes.create_string_buffer(blob, len(blob))
    lib.spark_tpu_hash_strings(
        buf, offsets.ctypes.data_as(ctypes.c_void_p), len(values),
        out.ctypes.data_as(ctypes.c_void_p))
    return out


def radix_partition(pids: np.ndarray, num_partitions: int):
    """Counting-sort row indices by partition id.

    Returns (order int64[n] — row indices grouped by pid, counts int64[p]).
    Python fallback: np.argsort."""
    lib = _load()
    pids = np.ascontiguousarray(pids, dtype=np.int32)
    order = np.empty(len(pids), dtype=np.int64)
    counts = np.zeros(num_partitions, dtype=np.int64)
    lib.spark_tpu_radix_partition(
        pids.ctypes.data_as(ctypes.c_void_p), len(pids), num_partitions,
        order.ctypes.data_as(ctypes.c_void_p),
        counts.ctypes.data_as(ctypes.c_void_p))
    return order, counts
