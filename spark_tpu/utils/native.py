"""ctypes bindings to the native (C++) host runtime in native/.

Role of the reference's [NATIVE-ROLE] Java off-heap layer
(common/unsafe/.../Platform.java, Murmur3_x86_32.java, RadixSort.java):
host-side hot loops — string hashing at dictionary build, counting-sort
partitioning, dictionary merge — implemented in C++ and loaded via ctypes
(no pybind11 in the image). Auto-builds with g++ on first use; every entry
point has a numpy fallback so callers catch ImportError/OSError.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from functools import lru_cache

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "libsparktpu_native.so")


def _try_build() -> None:
    src = os.path.join(_NATIVE_DIR, "sparktpu_native.cpp")
    if not os.path.exists(src):
        raise ImportError("native source missing")
    os.makedirs(os.path.dirname(_SO_PATH), exist_ok=True)
    subprocess.run(
        ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-o", _SO_PATH, src],
        check=True, capture_output=True, timeout=120)


@lru_cache(maxsize=1)
def _load():
    if not os.path.exists(_SO_PATH):
        _try_build()
    lib = ctypes.CDLL(_SO_PATH)
    lib.spark_tpu_hash_strings.restype = None
    lib.spark_tpu_hash_strings.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
    lib.spark_tpu_radix_partition.restype = None
    lib.spark_tpu_radix_partition.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_void_p, ctypes.c_void_p]
    lib.spark_tpu_merge_dicts.restype = ctypes.c_int64
    lib.spark_tpu_merge_dicts.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p]
    return lib


def available() -> bool:
    try:
        _load()
        return True
    except Exception:
        return False


def _pack(values: list[str]) -> tuple[bytes, np.ndarray]:
    encoded = [v.encode("utf-8") for v in values]
    blob = b"".join(encoded)
    offsets = np.zeros(len(values) + 1, dtype=np.int64)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    return blob, offsets


def hash_strings(values: list[str]) -> np.ndarray:
    """64-bit hashes for a list of strings via the C++ xxhash64 kernel."""
    lib = _load()
    if not values:
        return np.zeros(0, dtype=np.int64)
    blob, offsets = _pack(values)
    out = np.empty(len(values), dtype=np.int64)
    buf = ctypes.create_string_buffer(blob, max(len(blob), 1))
    lib.spark_tpu_hash_strings(
        buf, offsets.ctypes.data_as(ctypes.c_void_p), len(values),
        out.ctypes.data_as(ctypes.c_void_p))
    return out


def radix_partition(pids: np.ndarray, num_partitions: int):
    """Counting-sort row indices by partition id.

    Returns (order int64[n] — row indices grouped by pid, counts int64[p])."""
    lib = _load()
    pids = np.ascontiguousarray(pids, dtype=np.int32)
    order = np.empty(len(pids), dtype=np.int64)
    counts = np.zeros(num_partitions, dtype=np.int64)
    lib.spark_tpu_radix_partition(
        pids.ctypes.data_as(ctypes.c_void_p), len(pids), num_partitions,
        order.ctypes.data_as(ctypes.c_void_p),
        counts.ctypes.data_as(ctypes.c_void_p))
    return order, counts


def merge_dicts(value_lists: list[list[str]]):
    """Union several string dictionaries.

    Returns (merged values list, [recode int32 array per input dict])."""
    lib = _load()
    all_values = [v for vals in value_lists for v in vals]
    if not all_values:
        return [], [np.zeros(0, np.int32) for _ in value_lists]
    blob, offsets = _pack(all_values)
    recode = np.empty(len(all_values), dtype=np.int32)
    morder = np.empty(len(all_values), dtype=np.int64)
    buf = ctypes.create_string_buffer(blob, max(len(blob), 1))
    n = lib.spark_tpu_merge_dicts(
        buf, offsets.ctypes.data_as(ctypes.c_void_p), len(all_values),
        recode.ctypes.data_as(ctypes.c_void_p),
        morder.ctypes.data_as(ctypes.c_void_p))
    merged = [all_values[morder[i]] for i in range(n)]
    out = []
    pos = 0
    for vals in value_lists:
        out.append(recode[pos:pos + len(vals)].copy())
        pos += len(vals)
    return merged, out
