"""Process-global memo of host-synced scalars derived from device arrays.

One device→host sync stalls the async dispatch pipeline; on
transfer-bound transports each is a permanent tax. This memo, keyed by
the IDENTITY of the source device arrays, makes such reads once-per-array
instead of once-per-batch-per-run: identity survives re-wrapping the same
device columns into fresh ColumnarBatches (device-cached scans re-executed
per query, reorder projections, repeated broadcast probes). Entries hold
weakrefs and verify identity: id() values recycle after GC, and serving
another array's cached value would silently corrupt results.

Users: the dense-range aggregate/join fast-path decision
(physical/operators.dense_range_stats), the dense-join duplicate-key
verdict, range-exchange and external-sort key sampling. dev/tpulint.py's
host-sync rule sanctions reads wrapped in this helper.
"""

from __future__ import annotations

import collections
import sys
import threading

from . import lockwatch

__all__ = ["memo_device_scalars", "seed_dense_range_memo",
           "peek_dense_range", "DENSE_RANGE_KIND"]

_MEMO: "collections.OrderedDict" = collections.OrderedDict()
_LOCK = threading.Lock()
lockwatch.register("utils.device_memo._LOCK",
                   sys.modules[__name__], "_LOCK")
_MAX = 4096

# cache-key kind shared by dense_range_stats and the arrow-ingest seeding
DENSE_RANGE_KIND = ("dense_range",)


def memo_device_scalars(kind: tuple, arrays: tuple, compute):
    """Memoized `compute()` keyed by `kind` + identity of `arrays` (None
    entries allowed). Falls back to plain computation when an array does
    not support weakrefs. Treat returned values as immutable."""
    import weakref

    live = tuple(a for a in arrays if a is not None)
    key = (kind, tuple(id(a) if a is not None else None for a in arrays))
    with _LOCK:
        ent = _MEMO.get(key)
        if ent is not None:
            refs, value = ent
            if all(r() is a for r, a in zip(refs, live)):
                _MEMO.move_to_end(key)
                return value
            del _MEMO[key]
    value = compute()
    try:
        refs = tuple(weakref.ref(a) for a in live)
    except TypeError:
        return value
    with _LOCK:
        _MEMO[key] = (refs, value)
        while len(_MEMO) > _MAX:
            _MEMO.popitem(last=False)
    return value


def peek_dense_range(col, row_mask):
    """Memo lookup WITHOUT compute: the seeded (kmin, kmax, any_live)
    for this column under this row mask, or None on a miss. Never
    launches a kernel and never syncs — callers that only want to act
    when the answer is already free (runtime-filter batch skip) use
    this instead of memo_device_scalars."""
    arrays = (col.data, col.validity, row_mask)
    live = tuple(a for a in arrays if a is not None)
    key = (DENSE_RANGE_KIND,
           tuple(id(a) if a is not None else None for a in arrays))
    with _LOCK:
        ent = _MEMO.get(key)
        if ent is None:
            return None
        refs, value = ent
        if all(r() is a for r, a in zip(refs, live)):
            _MEMO.move_to_end(key)
            return value
        del _MEMO[key]
        return None


def seed_dense_range_memo(col, row_mask, value: tuple) -> None:
    """Pre-populate the dense-range memo from stats computed host-side
    while the column was still a numpy array (scan ingest,
    columnar/arrow.record_batch_to_columnar): the dense aggregate/join
    fast-path decision then never launches its range-probe kernel nor
    syncs, even on a cold first run. `value` = (kmin, kmax, any_live)
    under the batch's row mask ∧ validity — the dense_range_stats
    contract."""
    memo_device_scalars(DENSE_RANGE_KIND,
                        (col.data, col.validity, row_mask), lambda: value)
