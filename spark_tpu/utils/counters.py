"""Locked process-global counters: the shared-mutation fix race_lint
demands for bare module-level tallies.

A bare ``COUNTER += 1`` (or ``STATS["k"] += 1``) is a read-modify-write:
two par_map lanes, a heartbeat flusher, and an RPC retry loop bumping it
concurrently lose updates. The KernelCache solved this with an internal
lock years of PRs ago; this module is the same discipline packaged for
the small module-level counters that grew up without one
(net/transport.RETRY_STATS, exec/worker_main.FLUSH_OVERFLOWS).

Contracts race_lint and lockwatch rely on:

  * every mutation runs under the counter's own lock — the static
    analyzer treats ``NAME = LockedCounter(...)`` globals as internally
    guarded state and stops flagging their call-site bumps;
  * when lockwatch is enabled, every bump validates its own guard
    (``check_guard`` inside the critical section) and the lock slot is
    registered for acquisition-order recording — the counters ARE the
    flagged mutation sites the --race gate cross-checks;
  * reads return plain ints (``.value`` / ``[]``), so heartbeat
    payloads and test assertions keep working on host data;
  * ``reset()`` is the per-worker re-init path the worker-reinit rule
    looks for.

Pure host bookkeeping; the critical sections are a few instructions.
"""

from __future__ import annotations

import threading

from . import lockwatch

__all__ = ["LockedCounter", "LockedCounterMap"]


class LockedCounter:
    """A single process-global integer tally with an internal lock.

    `name` doubles as the lockwatch identity: the lock slot registers as
    ``counter.<name>`` so the --race gate sees its acquisitions, and
    every bump self-checks that guard when watching is live."""

    __slots__ = ("name", "_lock_name", "_lock", "_value")

    def __init__(self, name: str, initial: int = 0):
        self.name = name
        self._lock_name = f"counter.{name}"
        self._lock = threading.Lock()
        self._value = int(initial)
        # module-global counters live for the process: register the slot
        # so enable()/disable() can swap watching in and out at any time
        lockwatch.register(self._lock_name, self, "_lock")

    def bump(self, n: int = 1) -> int:
        """Atomically add `n`; returns the new value."""
        with self._lock:
            if lockwatch.ENABLED:
                lockwatch.check_guard(self.name, self._lock_name)
            self._value += int(n)
            return self._value

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def reset(self) -> None:
        """Per-worker / per-test re-init path (worker-reinit rule)."""
        with self._lock:
            self._value = 0

    def __int__(self) -> int:
        return self.value

    def __index__(self) -> int:
        return self.value

    def __repr__(self) -> str:
        return f"LockedCounter({self.name!r}, {self.value})"


class LockedCounterMap:
    """A fixed-key family of tallies behind ONE lock (the
    RETRY_STATS shape: {"absorbed": n, "gave_up": m}).

    Reads via ``stats["k"]`` return plain ints so existing assertions
    (tests, the chaos gate) keep reading it like the dict it replaced;
    writes go through ``bump`` only — there is deliberately no
    ``__setitem__``, so the racy ``STATS["k"] += 1`` pattern is
    unexpressible against it."""

    __slots__ = ("name", "_lock_name", "_lock", "_values")

    def __init__(self, name: str, keys):
        self.name = name
        self._lock_name = f"counter.{name}"
        self._lock = threading.Lock()
        self._values = {k: 0 for k in keys}
        lockwatch.register(self._lock_name, self, "_lock")

    def bump(self, key: str, n: int = 1) -> int:
        with self._lock:
            if lockwatch.ENABLED:
                lockwatch.check_guard(f"{self.name}[{key}]",
                                      self._lock_name)
            v = self._values[key] + int(n)
            self._values[key] = v
            return v

    def __getitem__(self, key: str) -> int:
        with self._lock:
            return self._values[key]

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._values)

    def reset(self) -> None:
        with self._lock:
            for k in self._values:
                self._values[k] = 0

    def __repr__(self) -> str:
        return f"LockedCounterMap({self.name!r}, {self.snapshot()})"
