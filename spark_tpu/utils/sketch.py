"""Probabilistic sketches: BloomFilter and CountMinSketch.

Role of the reference's common/sketch module (BloomFilter.java:45,
CountMinSketch.java) — used by runtime join filters, approx distinct
counts, and DataFrameStatFunctions. TPU-native design: the backing state
is a flat numpy/uint bit array whose probe/insert positions come from the
same splitmix64 hash family the device kernels use (ops/hashing.py), so a
filter BUILT on device (scatter into a bitset) and one built on host are
interchangeable; `device_bits()` hands the bitset to jitted kernels for
vectorized membership tests.
"""

from __future__ import annotations

import math

import numpy as np

_M1 = 0xBF58476D1CE4E5B9
_M2 = 0x94D049BB133111EB
_GOLDEN = 0x9E3779B97F4A7C15


def _mix64_np(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer over uint64 lanes (matches ops/hashing.mix64)."""
    x = x.astype(np.uint64, copy=True)
    with np.errstate(over="ignore"):
        x ^= x >> np.uint64(30)
        x *= np.uint64(_M1)
        x ^= x >> np.uint64(27)
        x *= np.uint64(_M2)
        x ^= x >> np.uint64(31)
    return x


def bloom_position_offsets(k: int) -> tuple:
    """The shared probe-position hash family: position j of hash h is
    mix64(h + (2j+1)*GOLDEN) & (num_bits-1). Returned as SIGNED 64-bit
    offsets so device kernels can add them to int64 hash lanes; host code
    (BloomFilter._positions) uses the same constants mod 2^64 — a filter
    built on device over `hash_columns` output and one built on host via
    put_hashes() are interchangeable."""
    out = []
    for j in range(k):
        off = (2 * j + 1) * _GOLDEN & ((1 << 64) - 1)
        out.append(off - (1 << 64) if off >= (1 << 63) else off)
    return tuple(out)


def _to_u64(values) -> np.ndarray:
    arr = np.asarray(values)
    if arr.dtype == object or arr.dtype.kind in ("U", "S"):
        import hashlib

        out = np.empty(len(arr), np.uint64)
        for i, v in enumerate(arr):
            d = hashlib.blake2b(str(v).encode("utf-8"), digest_size=8).digest()
            out[i] = int.from_bytes(d, "little")
        return out
    if arr.dtype.kind == "f":
        arr = np.where(arr == 0, np.zeros_like(arr), arr)
        return arr.astype(np.float64).view(np.uint64)
    return arr.astype(np.int64).view(np.uint64)


class BloomFilter:
    """Blocked bloom filter over a power-of-two bit array.

    k probe positions are derived from one 64-bit hash by mixing with k
    odd constants — one memory word per probe, no byte loops (reference:
    BloomFilterImpl.putLong's double hashing)."""

    def __init__(self, expected_items: int, fpp: float = 0.03,
                 num_bits: int | None = None):
        if num_bits is None:
            n = max(expected_items, 1)
            m = int(-n * math.log(fpp) / (math.log(2) ** 2))
            num_bits = 1 << max(10, (m - 1).bit_length())
        assert num_bits & (num_bits - 1) == 0
        self.num_bits = num_bits
        self.num_hashes = max(1, min(8, int(round(
            num_bits / max(expected_items, 1) * math.log(2)))))
        self.bits = np.zeros(num_bits // 64, dtype=np.uint64)

    # --- hashing ----------------------------------------------------------
    def _positions(self, values_u64: np.ndarray) -> np.ndarray:
        """[n, k] bit positions (raw values mix once into the shared hash
        domain, then the common position family applies)."""
        return self._hash_positions(_mix64_np(values_u64))

    # --- API --------------------------------------------------------------
    def put_hashes(self, hashes) -> None:
        """Insert pre-computed 64-bit hashes (the device `hash_columns`
        domain) — positions match a device-built bitset bit for bit."""
        self._set_bits(self._hash_positions(
            np.asarray(hashes).view(np.uint64)))

    def might_contain_hashes(self, hashes) -> np.ndarray:
        return self._test_bits(self._hash_positions(
            np.asarray(hashes).view(np.uint64)))

    def _set_bits(self, pos: np.ndarray) -> None:
        pos = pos.ravel()
        word = (pos >> np.uint64(6)).astype(np.int64)
        bit = np.uint64(1) << (pos & np.uint64(63))
        np.bitwise_or.at(self.bits, word, bit)

    def _test_bits(self, pos: np.ndarray) -> np.ndarray:
        word = (pos >> np.uint64(6)).astype(np.int64)
        bit = np.uint64(1) << (pos & np.uint64(63))
        return ((self.bits[word] & bit) != 0).all(axis=1)

    def _hash_positions(self, h: np.ndarray) -> np.ndarray:
        pos = np.empty((len(h), self.num_hashes), np.uint64)
        mask = np.uint64(self.num_bits - 1)
        offs = bloom_position_offsets(self.num_hashes)
        for j, off in enumerate(offs):
            with np.errstate(over="ignore"):
                pos[:, j] = _mix64_np(h + np.uint64(off & ((1 << 64) - 1))) \
                    & mask
        return pos

    def put_many(self, values) -> None:
        self._set_bits(self._positions(_to_u64(values)))

    def put(self, value) -> None:
        self.put_many([value])

    def might_contain_many(self, values) -> np.ndarray:
        return self._test_bits(self._positions(_to_u64(values)))

    def might_contain(self, value) -> bool:
        return bool(self.might_contain_many([value])[0])

    def merge(self, other: "BloomFilter") -> "BloomFilter":
        assert self.num_bits == other.num_bits \
            and self.num_hashes == other.num_hashes, "incompatible filters"
        self.bits |= other.bits
        return self

    def device_bits(self):
        """uint32[num_bits/32] device view for jitted membership kernels
        (uint64 is awkward on TPU lanes; 32-bit words gather cleanly)."""
        import jax.numpy as jnp

        return jnp.asarray(self.bits.view(np.uint32))

    # --- (de)serialization -------------------------------------------------
    def to_bytes(self) -> bytes:
        head = np.array([self.num_bits, self.num_hashes], np.int64).tobytes()
        return head + self.bits.tobytes()

    @staticmethod
    def from_bytes(data: bytes) -> "BloomFilter":
        head = np.frombuffer(data[:16], np.int64)
        bf = BloomFilter(1, num_bits=int(head[0]))
        bf.num_hashes = int(head[1])
        bf.bits = np.frombuffer(data[16:], np.uint64).copy()
        return bf


class CountMinSketch:
    """Count-min sketch: [depth, width] counters, point updates, min-query
    (reference: CountMinSketch.java — same eps/confidence sizing)."""

    def __init__(self, eps: float = 0.001, confidence: float = 0.99,
                 depth: int | None = None, width: int | None = None):
        self.depth = depth or max(1, int(math.ceil(-math.log(1 - confidence))))
        w = width or int(math.ceil(2.0 / eps))
        self.width = 1 << max(4, (w - 1).bit_length())
        self.table = np.zeros((self.depth, self.width), np.int64)
        self.total = 0

    def _cols(self, values_u64: np.ndarray) -> np.ndarray:
        h = _mix64_np(values_u64)
        cols = np.empty((self.depth, len(h)), np.int64)
        mask = np.uint64(self.width - 1)
        for d in range(self.depth):
            with np.errstate(over="ignore"):
                hd = _mix64_np(h + np.uint64((2 * d + 1) * _GOLDEN & ((1 << 64) - 1)))
            cols[d] = (hd & mask).astype(np.int64)
        return cols

    def add_many(self, values, counts=None) -> None:
        u = _to_u64(values)
        cols = self._cols(u)
        cnt = np.ones(len(u), np.int64) if counts is None \
            else np.asarray(counts, np.int64)
        for d in range(self.depth):
            np.add.at(self.table[d], cols[d], cnt)
        self.total += int(cnt.sum())

    def add(self, value, count: int = 1) -> None:
        self.add_many([value], [count])

    def estimate_count_many(self, values) -> np.ndarray:
        cols = self._cols(_to_u64(values))
        ests = np.stack([self.table[d][cols[d]] for d in range(self.depth)])
        return ests.min(axis=0)

    def estimate_count(self, value) -> int:
        return int(self.estimate_count_many([value])[0])

    def merge(self, other: "CountMinSketch") -> "CountMinSketch":
        assert self.table.shape == other.table.shape, "incompatible sketches"
        self.table += other.table
        self.total += other.total
        return self

    def to_bytes(self) -> bytes:
        head = np.array([self.depth, self.width, self.total], np.int64).tobytes()
        return head + self.table.tobytes()

    @staticmethod
    def from_bytes(data: bytes) -> "CountMinSketch":
        head = np.frombuffer(data[:24], np.int64)
        cms = CountMinSketch(depth=int(head[0]), width=int(head[1]))
        cms.total = int(head[2])
        cms.table = np.frombuffer(data[24:], np.int64).reshape(
            cms.depth, cms.width).copy()
        return cms
