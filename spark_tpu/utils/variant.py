"""VARIANT: binary semi-structured values.

Role of the reference's common/variant (Variant.java:43,
VariantBuilder/VariantUtil — the open binary encoding for
semi-structured data shared with Delta/Iceberg): a value encodes as two
byte strings, `metadata` (a sorted field-name dictionary, so repeated
keys across a column compress and field lookup is a binary search) and
`value` (a tagged tree). This implementation keeps the same
metadata/value split and dictionary-sorted field ids; the byte-level
tags are this engine's own (documented below) since only our
encoder/decoder touches them.

Value encoding (1 tag byte + payload, little-endian):
  0x00 null            0x01 true        0x02 false
  0x03 int64 (8B)      0x04 float64 (8B)
  0x05 string: u32 len + utf-8
  0x06 array:  u32 count + count * (u32 size + value)
  0x07 object: u32 count + count * (u32 field_id + u32 size + value)
  0x08 decimal: u8 scale + u32 len + unscaled int (signed, LE)
"""

from __future__ import annotations

import json
import struct
from decimal import Decimal
from typing import Any


def _collect_keys(v: Any, keys: set) -> None:
    if isinstance(v, dict):
        for k, sub in v.items():
            keys.add(k)
            _collect_keys(sub, keys)
    elif isinstance(v, (list, tuple)):
        for sub in v:
            _collect_keys(sub, keys)


def _encode_value(v: Any, key_ids: dict) -> bytes:
    if v is None:
        return b"\x00"
    if v is True:
        return b"\x01"
    if v is False:
        return b"\x02"
    if isinstance(v, int):
        return b"\x03" + struct.pack("<q", v)
    if isinstance(v, float):
        return b"\x04" + struct.pack("<d", v)
    if isinstance(v, str):
        raw = v.encode()
        return b"\x05" + struct.pack("<I", len(raw)) + raw
    if isinstance(v, (list, tuple)):
        parts = [_encode_value(x, key_ids) for x in v]
        out = b"\x06" + struct.pack("<I", len(parts))
        for p in parts:
            out += struct.pack("<I", len(p)) + p
        return out
    if isinstance(v, dict):
        items = sorted(v.items(), key=lambda kv: key_ids[kv[0]])
        out = b"\x07" + struct.pack("<I", len(items))
        for k, sub in items:
            p = _encode_value(sub, key_ids)
            out += struct.pack("<II", key_ids[k], len(p)) + p
        return out
    if isinstance(v, Decimal):
        sign, digits, exponent = v.as_tuple()
        scale = -exponent if exponent < 0 else 0
        unscaled = int(v.scaleb(scale))
        raw = unscaled.to_bytes((unscaled.bit_length() + 8) // 8,
                                "little", signed=True)
        return b"\x08" + struct.pack("<BI", scale, len(raw)) + raw
    raise TypeError(f"cannot encode {type(v).__name__} as variant")


class Variant:
    """One encoded value: (metadata, value) byte strings."""

    __slots__ = ("metadata", "value")

    def __init__(self, metadata: bytes, value: bytes):
        self.metadata = metadata
        self.value = value

    # -- construction ----------------------------------------------------
    @staticmethod
    def of(obj: Any) -> "Variant":
        keys: set = set()
        _collect_keys(obj, keys)
        ordered = sorted(keys)
        key_ids = {k: i for i, k in enumerate(ordered)}
        meta = struct.pack("<I", len(ordered))
        for k in ordered:
            raw = k.encode()
            meta += struct.pack("<I", len(raw)) + raw
        return Variant(meta, _encode_value(obj, key_ids))

    @staticmethod
    def parse_json(text: str) -> "Variant":
        return Variant.of(json.loads(text, parse_float=float))

    # -- decoding --------------------------------------------------------
    def _keys(self) -> list[str]:
        n, = struct.unpack_from("<I", self.metadata, 0)
        off = 4
        out = []
        for _ in range(n):
            ln, = struct.unpack_from("<I", self.metadata, off)
            off += 4
            out.append(self.metadata[off:off + ln].decode())
            off += ln
        return out

    def to_python(self) -> Any:
        keys = self._keys()

        def dec(buf: bytes) -> Any:
            tag = buf[0]
            if tag == 0x00:
                return None
            if tag == 0x01:
                return True
            if tag == 0x02:
                return False
            if tag == 0x03:
                return struct.unpack_from("<q", buf, 1)[0]
            if tag == 0x04:
                return struct.unpack_from("<d", buf, 1)[0]
            if tag == 0x05:
                ln, = struct.unpack_from("<I", buf, 1)
                return buf[5:5 + ln].decode()
            if tag == 0x06:
                n, = struct.unpack_from("<I", buf, 1)
                off = 5
                out = []
                for _ in range(n):
                    ln, = struct.unpack_from("<I", buf, off)
                    off += 4
                    out.append(dec(buf[off:off + ln]))
                    off += ln
                return out
            if tag == 0x07:
                n, = struct.unpack_from("<I", buf, 1)
                off = 5
                out = {}
                for _ in range(n):
                    kid, ln = struct.unpack_from("<II", buf, off)
                    off += 8
                    out[keys[kid]] = dec(buf[off:off + ln])
                    off += ln
                return out
            if tag == 0x08:
                scale, ln = struct.unpack_from("<BI", buf, 1)
                unscaled = int.from_bytes(buf[6:6 + ln], "little",
                                          signed=True)
                return Decimal(unscaled).scaleb(-scale)
            raise ValueError(f"bad variant tag {tag:#x}")

        return dec(self.value)

    def to_json(self) -> str:
        return json.dumps(self.to_python(), default=str)

    # -- path access (variant_get role) ----------------------------------
    def get(self, path: str) -> Any:
        """`$.a.b[2]`-style extraction (VariantGet expression role)."""
        cur = self.to_python()
        if path.startswith("$"):
            path = path[1:]
        import re

        for part in re.findall(r"\.([A-Za-z_][\w]*)|\[(\d+)\]", path):
            name, idx = part
            if name:
                if not isinstance(cur, dict) or name not in cur:
                    return None
                cur = cur[name]
            else:
                i = int(idx)
                if not isinstance(cur, list) or i >= len(cur):
                    return None
                cur = cur[i]
        return cur

    def __eq__(self, other):
        return isinstance(other, Variant) and \
            self.to_python() == other.to_python()

    def __repr__(self):
        return f"Variant({self.to_json()})"
