"""Structured logging (role of common/utils/.../internal/Logging.scala)."""

from __future__ import annotations

import logging
import os

# race-lint: ignore[worker-reinit] — once-per-process latch: every
# process (driver or worker) configures its OWN logging on first use,
# so starting fresh at False in a worker is the intended semantics
_CONFIGURED = False


def get_logger(name: str) -> logging.Logger:
    global _CONFIGURED
    if not _CONFIGURED:
        level = os.environ.get("SPARK_TPU_LOG", "WARNING").upper()
        logging.basicConfig(
            format="%(asctime)s %(levelname)s %(name)s: %(message)s",
            level=getattr(logging, level, logging.WARNING))
        _CONFIGURED = True
    return logging.getLogger(name)
