"""Runtime lock-discipline validation: the dynamic half of race_lint.

The static analyzer (analysis/race_lint.py) builds a whole-repo model of
shared mutable state, the locks guarding it, and the lock-acquisition
nesting graph — but a static model is only a claim. This module proves
the claims at runtime, under the real concurrent loads CI already runs
(the 8-session serve load, the 2-worker cluster chaos leg):

  * **acquisition orders** — every acquire of a watched lock while other
    watched locks are held records a (held → acquired) edge. The gate
    (dev/validate_trace.py --race) unions the observed edges with the
    static nesting graph and fails on any cycle the static model missed
    (a deadlock hazard that only manifests under a rare interleaving is
    still a hazard).

  * **held-lock sets at flagged mutation sites** — instrumented sites
    (the utils/counters.py locked counters, plus explicit `check_guard`
    probes at `# guarded-by:` annotated sites) record whether the lock
    the static model claims guards the mutation was ACTUALLY held.
    Every annotation must be held where claimed or the gate fails.

Zero overhead when idle — by construction, not by measurement:

  * Watched locks are NOT proxies installed unconditionally. Modules
    `register()` the (owner, attribute) slot their lock lives in;
    `enable()` swaps a `WatchedLock` into the slot and `disable()` swaps
    the raw lock back. An idle process runs raw `threading.Lock`s with
    no wrapper frame on any acquire.
  * Per-instance locks created after `enable()` go through
    `maybe_wrap()`, which returns the raw lock untouched when idle.
  * Instrumented mutation sites gate on the module bool `ENABLED`
    (one attribute read — the same fast-path discipline utils/faults.py
    uses for its injection points).

Activation: `enable()` / `disable()` (the gate and tests), the
`SPARK_TPU_LOCKWATCH=1` environment variable (covers module-import-time
lock creation and ships to cluster workers through the inherited
environment), or `spark.tpu.lockwatch.enabled` via `configure(conf)`
(per-session, the config.py-registered surface).

Pure host bookkeeping: never launches a kernel, never touches a device
array, and the observation structures are guarded by a dedicated leaf
lock (`_OBS_LOCK`) that is only ever acquired last — the watcher cannot
introduce the deadlocks it exists to find.
"""

from __future__ import annotations

import os
import threading

__all__ = ["ENABLED", "WatchedLock", "acquire_counts", "check_guard",
           "configure", "disable", "enable", "find_cycle", "guard_checks",
           "held_locks", "maybe_wrap", "order_edges", "register",
           "registered_names", "reset_observations", "violations"]

# fast-path flag: instrumented sites check this module bool before doing
# anything else, so an idle process pays one attribute read per probe
ENABLED = os.environ.get("SPARK_TPU_LOCKWATCH", "") == "1"

# observation state: a dedicated LEAF lock — acquired only momentarily
# inside record paths and never while calling out, so watching locks can
# never deadlock against the watcher itself
_OBS_LOCK = threading.Lock()
_REGISTRY: dict[str, tuple] = {}       # name -> (owner, attr)
_EDGES: dict[tuple, int] = {}          # (held_name, acquired_name) -> n
_ACQUIRES: dict[str, int] = {}         # name -> successful acquires
_GUARD_CHECKS: dict[tuple, int] = {}   # (site, lock_name) -> n held-ok
_VIOLATIONS: list[dict] = []           # {site, lock, held} guard misses
_MAX_VIOLATIONS = 256                  # bound the list on a broken run

# per-thread stack of held watched-lock names, innermost last
_HELD = threading.local()


def _stack() -> list:
    st = getattr(_HELD, "stack", None)
    if st is None:
        st = _HELD.stack = []
    return st


class WatchedLock:
    """Proxy around a raw lock recording acquisition order and held
    sets. Same blocking semantics as the wrapped lock — the record step
    happens after a successful acquire and before release, under the
    leaf observation lock only."""

    __slots__ = ("_raw", "name")

    def __init__(self, name: str, raw):
        self.name = name
        self._raw = raw

    # -- lock protocol ---------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._raw.acquire(blocking, timeout)
        if ok:
            self._record_acquired()
        return ok

    def release(self) -> None:
        self._record_released()
        self._raw.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._raw.locked()

    # -- recording -------------------------------------------------------
    def _record_acquired(self) -> None:
        st = _stack()
        with _OBS_LOCK:
            _ACQUIRES[self.name] = _ACQUIRES.get(self.name, 0) + 1
            for held in st:
                # one edge per held lock (not just the innermost): a
                # cycle through any pair of simultaneously-held locks
                # is a deadlock hazard
                e = (held, self.name)
                _EDGES[e] = _EDGES.get(e, 0) + 1
        st.append(self.name)

    def _record_released(self) -> None:
        st = _stack()
        # remove the LAST occurrence — watched locks release LIFO on the
        # happy path, but a try/finally unwind may release out of order
        for i in range(len(st) - 1, -1, -1):
            if st[i] == self.name:
                del st[i]
                break


# ---------------------------------------------------------------------------
# Registration and activation
# ---------------------------------------------------------------------------

def register(name: str, owner, attr: str) -> None:
    """Declare that `getattr(owner, attr)` is a lock worth watching
    (`owner` is a module or a long-lived singleton). Cheap at import
    time: one dict insert. When lockwatch is (or becomes) enabled the
    slot is swapped to a WatchedLock; `disable()` swaps the raw lock
    back, so the idle process always runs unwrapped locks."""
    with _OBS_LOCK:
        _REGISTRY[name] = (owner, attr)
    if ENABLED:
        _swap_in(name, owner, attr)


def maybe_wrap(name: str, lock):
    """Wrap a freshly created per-instance lock when lockwatch is live;
    return it untouched (zero overhead, no proxy) when idle. For locks
    on objects created after `enable()` — module-level locks should use
    `register()` so they can be swapped at any time."""
    if not ENABLED:
        return lock
    return WatchedLock(name, lock)


def _swap_in(name: str, owner, attr: str) -> None:
    cur = getattr(owner, attr, None)
    if cur is None or isinstance(cur, WatchedLock):
        return
    setattr(owner, attr, WatchedLock(name, cur))


def _swap_out(owner, attr: str) -> None:
    cur = getattr(owner, attr, None)
    if isinstance(cur, WatchedLock):
        setattr(owner, attr, cur._raw)


def enable() -> None:
    """Turn watching on and swap every registered lock slot to its
    watched proxy. Safe to call at any point; locks acquired before the
    swap simply record nothing for that holding."""
    global ENABLED
    ENABLED = True
    with _OBS_LOCK:
        items = list(_REGISTRY.items())
    for name, (owner, attr) in items:
        _swap_in(name, owner, attr)


def disable() -> None:
    """Swap raw locks back into every registered slot and stop
    recording. Observations are kept until reset_observations()."""
    global ENABLED
    ENABLED = False
    with _OBS_LOCK:
        items = list(_REGISTRY.items())
    for _name, (owner, attr) in items:
        _swap_out(owner, attr)


def configure(conf) -> None:
    """Per-session switch through the registered config surface
    (spark.tpu.lockwatch.enabled). Never turns an env-var-enabled
    process off — the gate exports SPARK_TPU_LOCKWATCH=1 so cluster
    workers inherit watching through their spawn environment."""
    from ..config import LOCKWATCH_ENABLED

    want = bool(conf.get(LOCKWATCH_ENABLED))
    if want and not ENABLED:
        enable()
    elif not want and ENABLED \
            and os.environ.get("SPARK_TPU_LOCKWATCH", "") != "1":
        disable()


# ---------------------------------------------------------------------------
# Instrumented mutation sites
# ---------------------------------------------------------------------------

def check_guard(site: str, lock_name: str) -> bool:
    """Record whether `lock_name` is held by the current thread at the
    flagged mutation site `site`. Instrumented sites call this INSIDE
    their critical section, gated on the `ENABLED` fast path:

        if lockwatch.ENABLED:
            lockwatch.check_guard("net.transport.RETRY_STATS",
                                  "counter.net.transport.RETRY_STATS")

    A miss lands in `violations()` — the --race gate fails on any."""
    held = tuple(_stack())
    ok = lock_name in held
    with _OBS_LOCK:
        if ok:
            k = (site, lock_name)
            _GUARD_CHECKS[k] = _GUARD_CHECKS.get(k, 0) + 1
        elif len(_VIOLATIONS) < _MAX_VIOLATIONS:
            _VIOLATIONS.append({"site": site, "lock": lock_name,
                                "held": held})
    return ok


def held_locks() -> tuple:
    """Watched-lock names the current thread holds, outermost first."""
    return tuple(_stack())


# ---------------------------------------------------------------------------
# Observations (the gate's read surface)
# ---------------------------------------------------------------------------

def order_edges() -> dict[tuple, int]:
    """(held, acquired) watched-lock name pairs observed, with counts."""
    with _OBS_LOCK:
        return dict(_EDGES)


def acquire_counts() -> dict[str, int]:
    with _OBS_LOCK:
        return dict(_ACQUIRES)


def guard_checks() -> dict[tuple, int]:
    """(site, lock) -> times the guard was verified held."""
    with _OBS_LOCK:
        return dict(_GUARD_CHECKS)


def violations() -> list[dict]:
    """Guard checks that found the claimed lock NOT held."""
    with _OBS_LOCK:
        return list(_VIOLATIONS)


def registered_names() -> list[str]:
    with _OBS_LOCK:
        return sorted(_REGISTRY)


def reset_observations() -> None:
    """Drop recorded edges/checks/violations (registry stays)."""
    with _OBS_LOCK:
        _EDGES.clear()
        _ACQUIRES.clear()
        _GUARD_CHECKS.clear()
        del _VIOLATIONS[:]


# ---------------------------------------------------------------------------
# Cycle detection (shared shape with race_lint's static check)
# ---------------------------------------------------------------------------

def find_cycle(edges) -> list | None:
    """First directed cycle in an iterable of (src, dst) name pairs, as
    a node path [a, b, ..., a]; None when acyclic. Self-loops are
    ignored: same-NAME edges come from distinct per-instance locks of
    one class (the watcher buckets by name), which cannot deadlock a
    single holder."""
    adj: dict[str, list] = {}
    for a, b in edges:
        if a == b:
            continue
        adj.setdefault(a, []).append(b)
    WHITE, GREY, BLACK = 0, 1, 2
    color: dict[str, int] = {}
    path: list[str] = []

    def dfs(u: str):
        color[u] = GREY
        path.append(u)
        for v in sorted(adj.get(u, ())):
            c = color.get(v, WHITE)
            if c == GREY:
                return path[path.index(v):] + [v]
            if c == WHITE:
                found = dfs(v)
                if found:
                    return found
        path.pop()
        color[u] = BLACK
        return None

    for node in sorted(adj):
        if color.get(node, WHITE) == WHITE:
            found = dfs(node)
            if found:
                return found
    return None
