"""Flock-safe bounded-ring JSONL stores shared by every on-disk metadata
surface of the engine.

Factored out of obs/history.ProfileStore (PR 12) the moment a second
consumer appeared (the persistent-cache manifest, exec/persist_cache.py):
one locking implementation, not two. The contract:

  * one JSONL file, each line one JSON object;
  * appends are process-safe: an exclusive flock is taken on a SIDECAR
    lockfile (never on the data file itself — locking the data file
    would race compaction: a writer blocked on the pre-compaction inode
    would append to the orphaned file after the os.replace and silently
    lose its record);
  * the file is a bounded ring: once it doubles `ring` lines it compacts
    to the newest `ring` — a long-lived server's store stays O(ring);
  * reads take NO lock: JSONL lines are self-delimiting, and a torn tail
    line from a concurrent append is skipped.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading

__all__ = ["JsonlRing"]


def _flock(f) -> None:
    try:
        import fcntl

        fcntl.flock(f.fileno(), fcntl.LOCK_EX)
    except Exception:
        pass  # non-posix: best-effort append (still one write call)


# flock associates with the OPEN FILE DESCRIPTION: a second open() of the
# same lockfile in the same process blocks against the first, so a
# compound operation holding `locked()` that then calls append() would
# self-deadlock. Re-entrancy is tracked per (thread, path) host-side.
_HELD = threading.local()


class JsonlRing:
    """One bounded-ring JSONL file with flock-sidecar writes."""

    def __init__(self, path: str, ring: int = 32):
        self.path = path
        self.ring = max(int(ring), 1)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    @contextlib.contextmanager
    def locked(self):
        """Exclusive sidecar lock for compound read-modify-write
        operations (e.g. the result cache's evict-then-append).
        Re-entrant per thread: appends inside a locked() block ride the
        already-held flock instead of deadlocking against it."""
        held = getattr(_HELD, "paths", None)
        if held is None:
            held = _HELD.paths = set()
        if self.path in held:
            yield
            return
        with open(self.path + ".lock", "a") as lockf:
            _flock(lockf)
            held.add(self.path)
            try:
                yield
            finally:
                held.discard(self.path)

    def append(self, obj: dict) -> None:
        line = json.dumps(obj, default=str) + "\n"
        with self.locked():
            with open(self.path, "ab") as f:
                # a writer that died mid-line leaves a torn tail with no
                # newline; appending straight after it would concatenate
                # and poison THIS record too — terminate the torn line
                # first (readers skip it as unparseable either way)
                if f.tell() > 0:
                    with open(self.path, "rb") as r:
                        r.seek(-1, os.SEEK_END)
                        if r.read(1) != b"\n":
                            f.write(b"\n")
                f.write(line.encode("utf-8"))
            self._compact_locked()

    def _compact_locked(self) -> None:
        """Ring compaction; caller holds the sidecar lock."""
        try:
            with open(self.path, encoding="utf-8") as f:
                lines = f.readlines()
        except FileNotFoundError:
            return
        if len(lines) > 2 * self.ring:
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as out:
                out.writelines(lines[-self.ring:])
            os.replace(tmp, self.path)

    def load(self) -> list[dict]:
        """All records, oldest first. Lockless (see module docstring)."""
        out: list[dict] = []
        try:
            with open(self.path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue  # torn tail of a concurrent append
        except FileNotFoundError:
            pass
        return out
