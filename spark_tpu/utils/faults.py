"""Deterministic fault injection: named fault points with seeded rules.

Role of the reference's chaos/fault-injection test hooks (the
DistributedSuite kill-executor tests, FailureSuite's deterministic task
failures, and the excludeOnFailure/HealthTracker suites all hand-roll
their faults) generalized into one seeded, process-local registry the
chaos suite (tests/test_chaos.py, dev/validate_trace.py --chaos) drives
through regular session conf:

  spark.tpu.faults.enabled  master switch (default off)
  spark.tpu.faults.seed     deterministic seed for probabilistic rules
  spark.tpu.faults.points   ';'-separated rules, each
                            point=trigger[:arg][:action[:arg]][@scope]

Named points are threaded through the stack at the seams where real
deployments fail:

  rpc.call         control-plane unary call about to be issued
  block.fetch      shuffle-block fetch about to stream
  worker.task      cluster stage task body (worker process)
  heartbeat.flush  executor heartbeat about to be sent
  kernel.compile   KernelCache miss about to build/compile
  kernel.dispatch  cached kernel about to launch
  shuffle.write    map output block about to be stored

Triggers: `once` (first matching call), `nth:N` (exactly the Nth,
1-based), `first:N` (calls 1..N), `after:N` (every call past the Nth —
the blackout shape: let N through, then fail forever), `prob:P`
(seeded coin per call), `always`. Actions: default raises the site's
transport/fault error;
`kill` hard-exits the process (os._exit — the worker-death chaos mode);
`sleep:S` injects S seconds of latency and returns (the straggler
chaos mode). An optional `@scope` suffix restricts the rule to
processes whose host label matches OR to calls whose detail string
contains the scope (e.g. `kernel.dispatch=once@whole_query`).

Contract: with the registry disabled (the default) every fault point is
a single module-bool check — zero kernel launches, zero syncs, no
allocation — so the obs layer's zero-overhead guards hold with the
layer compiled in but idle. Counters are process-local; rules ship to
worker processes with the rest of the session conf and are installed by
exec/worker_main.begin_stage_obs, exactly like the encoding/resource
switches.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import zlib

from . import lockwatch

__all__ = ["ENABLED", "InjectedFault", "configure", "maybe_fail",
           "fire_counts", "reset", "is_transient_marker",
           "is_runtime_fault"]

# fast-path flag: fault points check this module bool before anything
# else, so a healthy run pays one attribute read per instrumented call
ENABLED = False

# process identity for @scope matching: the worker's host label (set
# from SPARK_TPU_WORKER_HOST when rules install), "driver" elsewhere
HOST_LABEL = "driver"

_LOCK = threading.Lock()
lockwatch.register("utils.faults._LOCK", sys.modules[__name__], "_LOCK")
_RULES: dict[str, "_Rule"] = {}
_FIRED: dict[str, int] = {}
_SEED = 0
# last-installed (enabled, seed, spec): configure() is called per stage
# task on workers, and an unchanged spec must NOT reset the per-rule
# call counters (nth/first count over the process lifetime, not per
# task — resetting would make `nth:1` fire on every task)
_INSTALLED: tuple | None = None


class InjectedFault(RuntimeError):
    """A deterministic injected failure. The MARKER survives pickling
    and cross-process traceback stringification, so the driver can
    classify a worker-side injected fault as TRANSIENT (retry the task
    elsewhere, count the executor failure) rather than deterministic."""

    MARKER = "SPARK_TPU_INJECTED_FAULT"
    # markers the cluster retry loop treats as transient task failures
    # (retried on another executor up to max_task_failures, counted
    # against the executor's excludeOnFailure window). A real runtime
    # RESOURCE_EXHAUSTED on a worker is the same class of event.
    TRANSIENT_MARKERS = (MARKER, "RESOURCE_EXHAUSTED")

    def __init__(self, point: str, detail: str = ""):
        suffix = f" ({detail})" if detail else ""
        super().__init__(f"{self.MARKER}[{point}]{suffix}")
        self.point = point


def is_runtime_fault(e: BaseException) -> bool:
    """Is this a RUNTIME failure of a compiled program (XLA runtime
    error, device resource exhaustion, injected dispatch/compile chaos)
    rather than a logic error? Runtime faults are recoverable by
    degrading to a smaller execution granularity — the whole-query tier
    re-executes stage-at-a-time, a mesh gang retries then falls back to
    the host shuffle. Logic errors must keep propagating: re-executing
    a deterministic bug elsewhere hides it."""
    if isinstance(e, InjectedFault):
        return True
    name = type(e).__name__
    if name in ("XlaRuntimeError", "JaxRuntimeError", "InternalError",
                "ResourceExhaustedError"):
        return True
    text = str(e)
    return ("RESOURCE_EXHAUSTED" in text or "XlaRuntimeError" in text
            or InjectedFault.MARKER in text)


def is_transient_marker(text: str) -> bool:
    """Does an error's text identify a TRANSIENT task failure (worth
    retrying on another executor) rather than a deterministic one?
    Callers must check FetchFailed FIRST (lineage regen, not task
    retry) — this helper only knows the transient markers."""
    return any(m in text for m in InjectedFault.TRANSIENT_MARKERS)


class _Rule:
    __slots__ = ("point", "trigger", "arg", "action", "action_arg",
                 "scope", "calls", "rng")

    def __init__(self, point: str, trigger: str, arg: float,
                 action: str, action_arg: float, scope: str):
        self.point = point
        self.trigger = trigger      # once|nth|first|prob|always
        self.arg = arg
        self.action = action        # raise|kill|sleep
        self.action_arg = action_arg
        self.scope = scope
        self.calls = 0              # matching (in-scope) calls so far
        # per-rule deterministic stream: same seed + same call order →
        # same fault schedule, independent of other points' traffic
        import random

        self.rng = random.Random(_SEED ^ zlib.crc32(point.encode()))

    def should_fire(self) -> bool:
        self.calls += 1
        n = self.calls
        if self.trigger == "once":
            return n == 1
        if self.trigger == "nth":
            return n == int(self.arg)
        if self.trigger == "first":
            return n <= int(self.arg)
        if self.trigger == "after":
            return n > int(self.arg)
        if self.trigger == "prob":
            return self.rng.random() < self.arg
        return True  # always


def _parse_rule(spec: str) -> _Rule:
    spec = spec.strip()
    point, _, rhs = spec.partition("=")
    if not rhs:
        raise ValueError(f"bad fault rule {spec!r} (want point=trigger)")
    rhs, _, scope = rhs.partition("@")
    toks = rhs.split(":")
    trigger = toks.pop(0).strip().lower()
    if trigger not in ("once", "nth", "first", "after", "prob", "always"):
        raise ValueError(f"unknown fault trigger {trigger!r} in {spec!r}")
    arg = 1.0
    if trigger in ("nth", "first", "after", "prob"):
        if not toks:
            raise ValueError(f"trigger {trigger!r} needs an argument "
                             f"in {spec!r}")
        arg = float(toks.pop(0))
    action, action_arg = "raise", 0.0
    if toks:
        action = toks.pop(0).strip().lower()
        if action not in ("kill", "sleep", "raise"):
            raise ValueError(f"unknown fault action {action!r} in {spec!r}")
        if action == "sleep":
            if not toks:
                raise ValueError(f"sleep action needs seconds in {spec!r}")
            action_arg = float(toks.pop(0))
    if toks:
        raise ValueError(f"trailing tokens {toks} in fault rule {spec!r}")
    return _Rule(point.strip(), trigger, arg, action, action_arg,
                 scope.strip())


def configure(conf) -> None:
    """(Re)install the registry from session conf. Called per session on
    the driver (TpuSession.__init__) and per stage task on workers
    (exec/worker_main.begin_stage_obs) — the same shipping path every
    other process-global switch takes. Idempotent on an UNCHANGED spec
    (per-rule call counters keep counting across tasks); a changed spec
    reinstalls with fresh counters, so one test's consumed `once` rule
    never leaks into the next."""
    global ENABLED, HOST_LABEL, _SEED, _INSTALLED

    from ..config import FAULTS_ENABLED, FAULTS_POINTS, FAULTS_SEED

    # conf values are host data — never a device read
    enabled = bool(conf.get(FAULTS_ENABLED))  # tpulint: ignore[host-sync]
    seed = int(conf.get(FAULTS_SEED))  # tpulint: ignore[host-sync]
    spec = str(conf.get(FAULTS_POINTS) or "")
    with _LOCK:
        want = (enabled, seed, spec)
        if want == _INSTALLED:
            return
        _INSTALLED = want
        if not enabled:
            ENABLED = False
            _RULES.clear()
            _FIRED.clear()
            return
        _SEED = seed
        HOST_LABEL = os.environ.get("SPARK_TPU_WORKER_HOST", "driver")
        _RULES.clear()
        _FIRED.clear()
        for part in spec.replace(",", ";").split(";"):
            if not part.strip():
                continue
            rule = _parse_rule(part)
            _RULES[rule.point] = rule
        ENABLED = bool(_RULES)


def reset() -> None:
    """Disable the registry and drop all rules/counters (test teardown)."""
    global ENABLED, _INSTALLED
    with _LOCK:
        ENABLED = False
        _INSTALLED = None
        _RULES.clear()
        _FIRED.clear()


def fire_counts() -> dict[str, int]:
    with _LOCK:
        return dict(_FIRED)


def maybe_fail(point: str, detail: str = "", exc=None) -> None:
    """Evaluate one fault point. No-op unless a rule for `point` is
    installed and in scope; otherwise fires per the rule's trigger:
    raises `exc(message)` (default InjectedFault), kills the process, or
    sleeps. Call sites guard with `if faults.ENABLED:` so the idle cost
    is one module-bool read."""
    if not ENABLED:
        return
    with _LOCK:
        rule = _RULES.get(point)
        if rule is None:
            return
        if rule.scope and rule.scope != HOST_LABEL \
                and rule.scope not in detail:
            return
        fire = rule.should_fire()
        if fire:
            _FIRED[point] = _FIRED.get(point, 0) + 1
            action, action_arg = rule.action, rule.action_arg
    if not fire:
        return
    if action == "kill":
        os._exit(17)
    if action == "sleep":
        time.sleep(action_arg)
        return
    if exc is not None:
        raise exc(f"{InjectedFault.MARKER}[{point}] injected "
                  f"({detail or 'no detail'})")
    raise InjectedFault(point, detail)
