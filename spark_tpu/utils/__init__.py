from .logging import get_logger  # noqa: F401
