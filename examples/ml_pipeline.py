"""ML pipeline quickstart: scaling + logistic regression + evaluation.

Run: python examples/ml_pipeline.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import pyarrow as pa

from spark_tpu import SparkSession
from spark_tpu.ml import (
    BinaryClassificationEvaluator, LogisticRegression,
    MulticlassClassificationEvaluator, Pipeline, StandardScaler,
    VectorAssembler,
)


def main():
    spark = SparkSession.builder.appName("ml").getOrCreate()

    rng = np.random.default_rng(0)
    n = 2000
    x1 = rng.normal(50, 20, n)
    x2 = rng.normal(-3, 1.5, n)
    label = ((x1 - 50) / 20 + (x2 + 3) / 1.5 > 0).astype(np.float64)
    df = spark.createDataFrame(pa.table({"x1": x1, "x2": x2, "label": label}))

    pipeline = Pipeline(stages=(
        VectorAssembler(inputCols=["x1", "x2"], outputCol="raw"),
        StandardScaler(inputCol="raw", outputCol="features"),
        LogisticRegression(maxIter=300),
    ))
    model = pipeline.fit(df)
    scored = model.transform(df)

    acc = MulticlassClassificationEvaluator().evaluate(scored)
    auc = BinaryClassificationEvaluator().evaluate(scored)
    print(f"accuracy={acc:.4f}  auc={auc:.4f}")
    scored.select("x1", "x2", "label", "prediction").limit(5).show()


if __name__ == "__main__":
    main()
