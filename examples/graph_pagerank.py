"""Graph quickstart: PageRank + connected components via Pregel.

Run: python examples/graph_pagerank.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from spark_tpu import SparkSession
from spark_tpu.graph import Graph


def main():
    SparkSession.builder.appName("graph").getOrCreate()

    # two communities bridged by one edge
    src = [1, 2, 3, 1, 10, 11, 12, 3]
    dst = [2, 3, 1, 3, 11, 12, 10, 10]
    g = Graph.from_edges(src, dst)

    pr = g.page_rank(num_iter=30)
    print("pagerank:", {k: round(v, 3) for k, v in sorted(pr.items())})

    cc = g.connected_components()
    print("components:", cc)

    tc = g.triangle_count()
    print("triangles:", tc)


if __name__ == "__main__":
    main()
