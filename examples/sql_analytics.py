"""SQL analytics quickstart: star-schema joins, aggregation, windows.

Run: python examples/sql_analytics.py   (CPU or TPU)
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))

import numpy as np
import pyarrow as pa

from spark_tpu import SparkSession
import spark_tpu.api.functions as F


def main():
    spark = SparkSession.builder.appName("sql-analytics").getOrCreate()

    from tpcds_mini import register_tpcds

    register_tpcds(spark)

    print("== Star-schema join + aggregation ==")
    spark.sql("""
        SELECT dt.d_year, item.i_category,
               SUM(ss_ext_sales_price) AS revenue,
               COUNT(*) AS n_sales
        FROM store_sales
        JOIN date_dim dt ON ss_sold_date_sk = dt.d_date_sk
        JOIN item ON ss_item_sk = item.i_item_sk
        WHERE dt.d_moy = 11
        GROUP BY dt.d_year, item.i_category
        ORDER BY revenue DESC
        LIMIT 10""").show()

    print("== Window functions: top items per store ==")
    spark.sql("""
        SELECT ss_store_sk, ss_item_sk, rev, rnk FROM (
            SELECT ss_store_sk, ss_item_sk, rev,
                   rank() OVER (PARTITION BY ss_store_sk
                                ORDER BY rev DESC) AS rnk
            FROM (SELECT ss_store_sk, ss_item_sk,
                         SUM(ss_ext_sales_price) AS rev
                  FROM store_sales GROUP BY ss_store_sk, ss_item_sk))
        WHERE rnk <= 3 ORDER BY ss_store_sk, rnk LIMIT 9""").show()

    print("== Correlated subquery: above-average sales ==")
    spark.sql("""
        SELECT ss_store_sk, COUNT(*) AS big_sales
        FROM store_sales s1
        WHERE ss_ext_sales_price > (
            SELECT 2 * AVG(ss_ext_sales_price) FROM store_sales s2
            WHERE s2.ss_store_sk = s1.ss_store_sk)
        GROUP BY ss_store_sk ORDER BY ss_store_sk LIMIT 5""").show()

    print("== DataFrame API ==")
    (spark.table("store_sales")
     .groupBy("ss_store_sk")
     .agg(F.sum("ss_net_profit").alias("profit"),
          F.countDistinct("ss_item_sk").alias("items"))
     .orderBy(F.col("profit").desc())
     .limit(5).show())


if __name__ == "__main__":
    main()
