"""Local-cluster quickstart: multi-process executors with failure recovery.

Run: python examples/local_cluster.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from spark_tpu.exec.cluster import LocalCluster
from spark_tpu.rdd import RDDContext


def main():
    cluster = LocalCluster(num_workers=3)
    try:
        print(f"executors alive: {cluster.num_alive()}")

        sc = RDDContext(parallelism=6, cluster=cluster)
        rdd = sc.parallelize(range(1_000), 6)

        # tasks ship to worker processes (cloudpickle over local sockets)
        pids = set(rdd.mapPartitions(
            lambda it: iter([os.getpid()])).collect())
        print(f"driver pid {os.getpid()}; task pids: {sorted(pids)}")

        total = rdd.map(lambda x: x * x).sum()
        print(f"sum of squares: {total}")

        by_mod = dict(rdd.map(lambda x: (x % 3, 1))
                      .reduceByKey(lambda a, b: a + b).collect())
        print(f"counts by x % 3: {by_mod}")

        # kill one executor mid-flight: tasks retry on survivors
        victim = next(iter(cluster._workers.values()))
        victim.proc.kill()
        total2 = rdd.map(lambda x: x + 1).sum()
        print(f"after executor loss, alive={cluster.num_alive()}, "
              f"sum={total2}")
    finally:
        cluster.stop()


if __name__ == "__main__":
    main()
