"""Structured streaming quickstart: stateful aggregation over a memory
stream with checkpointing.

Run: python examples/streaming_wordcount.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import pyarrow as pa

from spark_tpu import SparkSession
import spark_tpu.api.functions as F


def main():
    spark = SparkSession.builder.appName("streaming").getOrCreate()
    ckpt = tempfile.mkdtemp(prefix="stream-ckpt-")

    source, events = spark.memory_stream(pa.schema([
        ("user", pa.string()), ("clicks", pa.int64())]))

    query = (events.groupBy("user")
             .agg(F.sum("clicks").alias("total"),
                  F.count("*").alias("events"))
             .writeStream.format("memory").queryName("click_totals")
             .outputMode("complete")
             .option("checkpointLocation", ckpt)
             .start())

    source.add_data({"user": ["ann", "bob", "ann"], "clicks": [1, 2, 3]})
    query.processAllAvailable()
    print("after batch 1:")
    spark.sql("SELECT * FROM click_totals ORDER BY user").show()

    source.add_data({"user": ["bob", "cyd"], "clicks": [10, 5]})
    query.processAllAvailable()
    print("after batch 2 (state merged):")
    spark.sql("SELECT * FROM click_totals ORDER BY user").show()

    print("progress:", query.lastProgress())
    query.stop()


if __name__ == "__main__":
    main()
