// Native host runtime kernels.
//
// Role of the reference's [NATIVE-ROLE] Java off-heap layer
// (common/unsafe/src/main/java/org/apache/spark/unsafe/Platform.java,
// hash/Murmur3_x86_32.java, corej/util/collection/unsafe/sort/RadixSort.java):
// the host-side hot loops that sit outside the XLA compute path —
// dictionary hashing at Arrow ingest and counting-sort partitioning for the
// DCN shuffle plane. Exposed as a plain C ABI for ctypes (no pybind11 in
// the image).
//
// Build: make -C native   (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// 64-bit string hashing (xxhash64-inspired mixing, public-domain constants).
// Per dictionary entry — row-level hashing rides jnp.take on device.
// ---------------------------------------------------------------------------

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static const uint64_t P1 = 0x9E3779B185EBCA87ULL;
static const uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
static const uint64_t P3 = 0x165667B19E3779F9ULL;
static const uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
static const uint64_t P5 = 0x27D4EB2F165667C5ULL;

static uint64_t hash_bytes64(const uint8_t* data, int64_t len) {
  uint64_t h;
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  if (len >= 32) {
    uint64_t v1 = P1 + P2, v2 = P2, v3 = 0, v4 = (uint64_t)0 - P1;
    const uint8_t* limit = end - 32;
    do {
      uint64_t k;
      std::memcpy(&k, p, 8);
      v1 = rotl64(v1 + k * P2, 31) * P1;
      std::memcpy(&k, p + 8, 8);
      v2 = rotl64(v2 + k * P2, 31) * P1;
      std::memcpy(&k, p + 16, 8);
      v3 = rotl64(v3 + k * P2, 31) * P1;
      std::memcpy(&k, p + 24, 8);
      v4 = rotl64(v4 + k * P2, 31) * P1;
      p += 32;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
  } else {
    h = P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    uint64_t k;
    std::memcpy(&k, p, 8);
    h ^= rotl64(k * P2, 31) * P1;
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    uint32_t k;
    std::memcpy(&k, p, 4);
    h ^= (uint64_t)k * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * P5;
    h = rotl64(h, 11) * P1;
    p++;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

// blob: concatenated UTF-8 bytes; offsets: int64[n+1]; out: int64[n]
void spark_tpu_hash_strings(const void* blob, const void* offsets_v,
                            int64_t n, void* out_v) {
  const uint8_t* bytes = (const uint8_t*)blob;
  const int64_t* offsets = (const int64_t*)offsets_v;
  int64_t* out = (int64_t*)out_v;
  for (int64_t i = 0; i < n; i++) {
    out[i] = (int64_t)hash_bytes64(bytes + offsets[i],
                                   offsets[i + 1] - offsets[i]);
  }
}

// ---------------------------------------------------------------------------
// Counting-sort partitioning: group row indices by partition id.
// (RadixSort.java role for the host shuffle plane.)
// pids: int32[n]; order_out: int64[n] — row indices grouped by pid;
// counts_out: int64[p].
// ---------------------------------------------------------------------------

void spark_tpu_radix_partition(const void* pids_v, int64_t n, int32_t p,
                               void* order_v, void* counts_v) {
  const int32_t* pids = (const int32_t*)pids_v;
  int64_t* order = (int64_t*)order_v;
  int64_t* counts = (int64_t*)counts_v;
  for (int32_t i = 0; i < p; i++) counts[i] = 0;
  for (int64_t i = 0; i < n; i++) {
    int32_t pid = pids[i];
    if (pid >= 0 && pid < p) counts[pid]++;
  }
  // prefix offsets
  int64_t* cursor = new int64_t[p];
  int64_t acc = 0;
  for (int32_t i = 0; i < p; i++) {
    cursor[i] = acc;
    acc += counts[i];
  }
  for (int64_t i = 0; i < n; i++) {
    int32_t pid = pids[i];
    if (pid >= 0 && pid < p) order[cursor[pid]++] = i;
  }
  delete[] cursor;
}

// ---------------------------------------------------------------------------
// Dictionary merge: union string dictionaries with an open-addressing map.
// (role of UTF8String interning in the shuffle read path.)
// Returns the merged size; recode[i] = merged code of input value i.
// The caller passes values for several dictionaries concatenated; `starts`
// gives per-dictionary value ranges so codes stay per-dictionary.
// ---------------------------------------------------------------------------

int64_t spark_tpu_merge_dicts(const void* blob, const void* offsets_v,
                              int64_t n_values, void* recode_v,
                              void* merged_order_v) {
  const uint8_t* bytes = (const uint8_t*)blob;
  const int64_t* offsets = (const int64_t*)offsets_v;
  int32_t* recode = (int32_t*)recode_v;
  int64_t* merged_order = (int64_t*)merged_order_v;  // first-occurrence idx

  // open addressing, power-of-two capacity >= 2n
  int64_t cap = 16;
  while (cap < n_values * 2) cap <<= 1;
  int64_t* slots = new int64_t[cap];  // value index or -1
  for (int64_t i = 0; i < cap; i++) slots[i] = -1;

  int64_t merged_n = 0;
  for (int64_t i = 0; i < n_values; i++) {
    const uint8_t* s = bytes + offsets[i];
    int64_t len = offsets[i + 1] - offsets[i];
    uint64_t h = hash_bytes64(s, len);
    int64_t slot = (int64_t)(h & (uint64_t)(cap - 1));
    for (;;) {
      int64_t v = slots[slot];
      if (v < 0) {
        slots[slot] = i;
        merged_order[merged_n] = i;
        recode[i] = (int32_t)merged_n;
        merged_n++;
        break;
      }
      int64_t vlen = offsets[v + 1] - offsets[v];
      if (vlen == len && std::memcmp(bytes + offsets[v], s, len) == 0) {
        recode[i] = recode[v];
        break;
      }
      slot = (slot + 1) & (cap - 1);
    }
  }
  delete[] slots;
  return merged_n;
}

}  // extern "C"
