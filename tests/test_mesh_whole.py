"""Mesh whole-query compilation (physical/mesh_whole.py).

Acceptance gates:
  * mesh-whole / whole / stage tiers produce IDENTICAL results on the
    differential suite (repartition+agg, shuffled join+agg, string and
    nullable keys);
  * the mesh tier executes the ENTIRE sharded plan as ONE shard_map
    dispatch per retry round (warm run: {"mesh_whole": 1});
  * plan_lint's mesh mirror predicts the per-kind launch counts EXACTLY,
    including quota-doubling, join-capacity and dense-guard retry rounds,
    fusion on AND off;
  * the warm-start manifest collapses retries across restarts (quota
    seeds) and compiles the dense direct-address probe up front (span
    seeds), with the in-program guard catching seeded-span drift;
  * chaos: a gang fault retries the whole program as a unit, reusing the
    undonated base planes (never re-staging from host), and the device
    ledger stays balanced.
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC
from spark_tpu.utils import faults


def _need_devices(n):
    import jax

    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} virtual devices")


@pytest.fixture()
def tiers(spark):
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    yield spark
    for k in ("spark.tpu.compile.tier", "spark.tpu.fusion.minRows",
              "spark.tpu.fusion.enabled", "spark.tpu.faults.enabled",
              "spark.tpu.faults.points"):
        spark.conf.unset(k)
    faults.reset()


@pytest.fixture()
def data(spark):
    rng = np.random.default_rng(11)
    n = 5000
    spark.createDataFrame(pa.table({
        "k": rng.integers(0, 13, n),
        "v": rng.integers(-50, 100, n),
        "f": rng.random(n),
        "s": [f"cat{i % 5}" for i in range(n)],
    })).createOrReplaceTempView("mw_t")
    spark.createDataFrame(pa.table({
        "dk": np.arange(13, dtype=np.int64),
        "label": [f"lab{i % 3}" for i in range(13)],
    })).createOrReplaceTempView("mw_dim")
    return spark


def _rows(df, by):
    t = df.toArrow().to_pandas()
    return t.sort_values(by).reset_index(drop=True)


def _measured(build):
    build().toArrow()  # warm
    before = dict(KC.launches_by_kind)
    build().toArrow()
    return {k: v - before.get(k, 0) for k, v in KC.launches_by_kind.items()
            if v != before.get(k, 0)}


def _counters(session) -> dict:
    return dict(session._metrics.snapshot()["counters"])


def _q_agg(s):
    return (s.sql("select * from mw_t").repartition(4, "k")
            .groupBy("k").count())


def _q_join_agg(s):
    return (s.sql("select mw_t.k k, v, label from mw_t "
                  "join mw_dim on k = dk where v > 10")
            .repartition(4, "k").groupBy("label").count())


def _q_str(s):
    return (s.sql("select * from mw_t").repartition(4, "s")
            .groupBy("s").count())


QUERIES = [("agg", _q_agg, ["k"]),
           ("join_agg", _q_join_agg, ["label"]),
           ("str_key", _q_str, ["s"])]


# ---------------------------------------------------------------------------
# differential suite: identical results across the tiers
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,q,by", QUERIES,
                         ids=[n for n, _q, _b in QUERIES])
def test_mesh_tier_differential(tiers, data, name, q, by):
    import pandas as pd

    _need_devices(4)
    data.conf.set("spark.tpu.compile.tier", "stage")
    ref = _rows(q(data), by)
    for tier in ("whole", "mesh-whole"):
        data.conf.set("spark.tpu.compile.tier", tier)
        pd.testing.assert_frame_equal(ref, _rows(q(data), by),
                                      check_dtype=False)
    from spark_tpu.physical.mesh_whole import MeshWholeQueryExec

    assert isinstance(q(data).query_execution.physical,
                      MeshWholeQueryExec)


def test_mesh_tier_differential_nullable_key(tiers, data):
    """Nullable join/partition key: null rows hash by the null tag
    through the collective and join to nothing — identical to the
    host-shuffle oracle."""
    import pandas as pd

    _need_devices(4)
    rng = np.random.default_rng(5)
    k = rng.integers(0, 13, 800).astype(object)
    k[::7] = None
    data.createDataFrame(pa.table({
        "nk": pa.array(list(k), type=pa.int64()),
        "nv": np.arange(800),
    })).createOrReplaceTempView("mw_null")

    def q(s):
        return (s.sql("select nk, nv, label from mw_null "
                      "left outer join mw_dim on nk = dk")
                .repartition(4, "nk").groupBy("label").count())

    data.conf.set("spark.tpu.compile.tier", "stage")
    ref = _rows(q(data), ["label"])
    data.conf.set("spark.tpu.compile.tier", "mesh-whole")
    pd.testing.assert_frame_equal(ref, _rows(q(data), ["label"]),
                                  check_dtype=False)


# ---------------------------------------------------------------------------
# ONE dispatch per retry round + exact lint predictions
# ---------------------------------------------------------------------------

def test_mesh_whole_single_dispatch_warm(tiers, data):
    _need_devices(4)
    data.conf.set("spark.tpu.compile.tier", "mesh-whole")
    assert _measured(lambda: _q_agg(data)) == {"mesh_whole": 1}


@pytest.mark.parametrize("name,q,by", QUERIES,
                         ids=[n for n, _q, _b in QUERIES])
def test_mesh_lint_exact(tiers, data, name, q, by):
    _need_devices(4)
    data.conf.set("spark.tpu.compile.tier", "mesh-whole")
    data.conf.set("spark.tpu.fusion.enabled", "true")
    df = q(data)
    report = df.query_execution.analysis_report()
    assert report.exact, report.inexact_reasons
    measured = _measured(lambda: q(data))
    assert report.predicted_launches == measured, (
        f"predicted {dict(sorted(report.predicted_launches.items()))} != "
        f"measured {dict(sorted(measured.items()))}\n{report.render()}")


@pytest.mark.parametrize("name,q,by", QUERIES,
                         ids=[n for n, _q, _b in QUERIES])
def test_mesh_lint_fusion_off_fallback(tiers, data, name, q, by):
    """Fusion disabled: the whole tiers cannot fuse the plan into one
    program, so mesh-whole falls back tier-by-tier. The analyzer follows
    the same chooser — ZERO mesh_whole launches predicted AND measured —
    and the fallback plan returns identical rows."""
    import pandas as pd

    _need_devices(4)
    data.conf.set("spark.tpu.compile.tier", "mesh-whole")
    ref = _rows(q(data), by)
    data.conf.set("spark.tpu.fusion.enabled", "false")
    from spark_tpu.physical.mesh_whole import MeshWholeQueryExec

    df = q(data)
    assert not isinstance(df.query_execution.physical, MeshWholeQueryExec)
    report = df.query_execution.analysis_report()
    measured = _measured(lambda: q(data))
    assert report.predicted_launches.get("mesh_whole", 0) == 0
    assert measured.get("mesh_whole", 0) == 0
    pd.testing.assert_frame_equal(ref, _rows(q(data), by),
                                  check_dtype=False)


def test_mesh_quota_retry_exact(tiers, spark):
    """A skewed key sends nearly every row to one destination shard: the
    psum'd overflow scalar doubles that exchange's quota and the WHOLE
    program re-dispatches — 2 mesh_whole dispatches, predicted exactly."""
    _need_devices(4)
    skew = np.zeros(4000, dtype=np.int64)
    skew[:32] = np.arange(32)
    spark.createDataFrame(pa.table({"sk": skew, "sv": np.arange(4000)})) \
        .createOrReplaceTempView("mw_skew")
    spark.conf.set("spark.tpu.compile.tier", "mesh-whole")

    def q():
        return (spark.sql("select * from mw_skew").repartition(4, "sk")
                .groupBy("sk").count())

    report = q().query_execution.analysis_report()
    assert report.predicted_launches.get("mesh_whole", 0) >= 2, \
        report.predicted_launches
    before = _counters(spark)
    out = dict(zip(*(c.to_pylist()
                     for c in q().toArrow().columns)))
    after = _counters(spark)
    assert out[0] == 4000 - 31 and out[5] == 1
    assert after.get("mesh_whole.quota_retries", 0) \
        > before.get("mesh_whole.quota_retries", 0)
    measured = _measured(q)
    assert report.predicted_launches == measured, (
        report.predicted_launches, measured, report.render())


def test_mesh_join_cap_retry_exact(tiers, spark):
    """An expanding inner join (8 build rows per key) overflows the
    default join output bucket inside the program: the pmax'd `needed`
    bumps the capacity and the whole program re-dispatches."""
    import pandas as pd

    _need_devices(4)
    rng = np.random.default_rng(3)
    spark.createDataFrame(pa.table({
        "fk": rng.integers(0, 8, 3000),
        "fv": rng.integers(0, 50, 3000),
    })).createOrReplaceTempView("mw_fact")
    spark.createDataFrame(pa.table({
        "bk": np.repeat(np.arange(8, dtype=np.int64), 8),
        "bl": [f"b{i}" for i in range(64)],
    })).createOrReplaceTempView("mw_dup")

    def q(s):
        return (s.sql("select fk, fv, bl from mw_fact "
                      "join mw_dup on fk = bk")
                .repartition(4, "fk").groupBy("fk").count())

    spark.conf.set("spark.tpu.compile.tier", "stage")
    ref = _rows(q(spark), ["fk"])
    spark.conf.set("spark.tpu.compile.tier", "mesh-whole")
    pd.testing.assert_frame_equal(ref, _rows(q(spark), ["fk"]),
                                  check_dtype=False)
    report = q(spark).query_execution.analysis_report()
    assert report.predicted_launches.get("mesh_whole", 0) >= 2, \
        report.predicted_launches
    measured = _measured(lambda: q(spark))
    assert report.predicted_launches == measured, (
        report.predicted_launches, measured, report.render())


# ---------------------------------------------------------------------------
# admission + obs contract
# ---------------------------------------------------------------------------

def test_mesh_admission_fallbacks(tiers, data):
    """Inadmissible shapes fall back to the whole tier with the reason on
    the decision: non-power-of-two partition counts and plans without a
    hash exchange never reach the mesh builder."""
    from spark_tpu.physical.mesh_whole import MeshWholeQueryExec
    from spark_tpu.physical.whole_query import WholeQueryExec

    _need_devices(4)
    data.conf.set("spark.tpu.compile.tier", "mesh-whole")
    # 3 partitions: not a power of two
    p = (data.sql("select * from mw_t").repartition(3, "k")
         .groupBy("k").count()).query_execution.physical
    assert isinstance(p, WholeQueryExec) \
        and not isinstance(p, MeshWholeQueryExec)
    assert "mesh-whole fallback" in p.decision.reason, p.decision.reason
    # single-partition collapse: no hash exchange anywhere in the plan
    p = data.sql("select k, count(*) c from mw_t group by k") \
        .query_execution.physical
    assert isinstance(p, WholeQueryExec) \
        and not isinstance(p, MeshWholeQueryExec)
    assert "mesh-whole fallback" in p.decision.reason, p.decision.reason


def test_mesh_attribution_matches_global(tiers, data):
    """obs contract: the single sharded dispatch attributes to
    MeshWholeQueryExec (re-attributed to members via fused_members) and
    the attributed total equals the global launch counter delta."""
    _need_devices(4)
    data.conf.set("spark.tpu.compile.tier", "mesh-whole")
    _q_agg(data).toArrow()  # warm
    before = KC.launches
    df = _q_agg(data)
    df.toArrow()
    global_delta = KC.launches - before
    graph = df.query_execution.plan_graph()
    attributed = sum(v for nd in graph
                     for v in (nd.get("launches") or {}).values())
    assert attributed == global_delta
    assert global_delta == 1
    from spark_tpu.obs.resources import GLOBAL_LEDGER

    assert GLOBAL_LEDGER.verify() == [], \
        "device ledger unbalanced after mesh whole-query runs"


# ---------------------------------------------------------------------------
# warm-start manifest: quota seeds, dense span seeds, drift guard
# ---------------------------------------------------------------------------

def _session(name, tmp_path):
    from spark_tpu import TpuSession

    return TpuSession(name, {
        "spark.sql.shuffle.partitions": 4,
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.tpu.fusion.minRows": "0",
        "spark.tpu.compile.tier": "mesh-whole",
        "spark.tpu.cache.dir": str(tmp_path),
        # the manifest tests measure real dispatches on the second run;
        # a result-cache hit would answer with zero launches
        "spark.tpu.cache.result.enabled": "false",
    })


def _seed_skew(s):
    skew = np.zeros(4000, dtype=np.int64)
    skew[:32] = np.arange(32)
    s.createDataFrame(pa.table({"sk": skew, "sv": np.arange(4000)})) \
        .createOrReplaceTempView("pm_skew")
    return lambda: (s.sql("select * from pm_skew").repartition(4, "sk")
                    .groupBy("sk").count())


def test_warm_manifest_collapses_quota_retries(tiers, tmp_path):
    """Run 1 learns the doubled quota (2 dispatches) and records it in
    the manifest; a fresh restart seeds it and dispatches ONCE — and the
    analyzer, reading the same manifest, predicts both runs exactly."""
    _need_devices(4)
    s = _session("mw-manifest", tmp_path)
    try:
        q = _seed_skew(s)
        r1 = q()
        assert r1.query_execution.analysis_report() \
                 .predicted_launches == {"mesh_whole": 2}
        first = r1.toArrow()
    finally:
        s.stop()
    s = _session("mw-manifest2", tmp_path)
    try:
        q = _seed_skew(s)
        report = q().query_execution.analysis_report()
        assert report.predicted_launches == {"mesh_whole": 1}, \
            report.render()
        before = _counters(s)
        again = q().toArrow()
        after = _counters(s)
        assert sorted(zip(*(c.to_pylist() for c in again.columns))) \
            == sorted(zip(*(c.to_pylist() for c in first.columns)))
        assert after.get("cache.mesh_quota_seeded", 0) \
            > before.get("cache.mesh_quota_seeded", 0)
        assert _measured(q) == {"mesh_whole": 1}
    finally:
        s.stop()


def _seed_join(s):
    rng = np.random.default_rng(11)
    n = 5000
    s.createDataFrame(pa.table({
        "k": rng.integers(0, 13, n),
        "v": rng.integers(-50, 100, n),
    })).createOrReplaceTempView("pm_t")
    s.createDataFrame(pa.table({
        "dk": np.arange(13, dtype=np.int64),
        "label": [f"lab{i % 3}" for i in range(13)],
    })).createOrReplaceTempView("pm_dim")
    return lambda: (s.sql("select pm_t.k k, v, label from pm_t "
                          "join pm_dim on k = dk where v > 10")
                    .repartition(4, "k").groupBy("label").count())


def test_warm_manifest_dense_probe(tiers, tmp_path):
    """Run 1 observes the build-side key span (dense + unique) through
    the sorted probe; run 2 compiles the dense direct-address probe
    INSIDE the mesh program from the span seed — same results, one
    dispatch, predicted exactly."""
    _need_devices(4)
    s = _session("mw-dense", tmp_path)
    try:
        q = _seed_join(s)
        first = q().toArrow()
        before = _counters(s)
        report = q().query_execution.analysis_report()
        assert report.predicted_launches == {"mesh_whole": 1}
        again = q().toArrow()
        after = _counters(s)
        assert after.get("join.dense_fast_path", 0) \
            > before.get("join.dense_fast_path", 0), \
            "span seed never compiled the dense probe"
        assert after.get("whole_query.dense_probe", 0) \
            > before.get("whole_query.dense_probe", 0)
        assert sorted(zip(*(c.to_pylist() for c in again.columns))) \
            == sorted(zip(*(c.to_pylist() for c in first.columns)))
    finally:
        s.stop()


def test_dense_guard_catches_span_drift(tiers, tmp_path):
    """A manifest span that no longer covers the build keys (data drift
    stand-in: a doctored record) makes the in-program guard fire: the
    round is discarded, dense is disabled for the join, and the retry
    returns the correct result — one extra dispatch, predicted exactly
    by the analyzer reading the SAME lying manifest."""
    import spark_tpu.exec.persist_cache as pc

    _need_devices(4)
    s = _session("mw-drift", tmp_path)
    try:
        q = _seed_join(s)
        oracle = sorted(zip(*(c.to_pylist()
                              for c in q().toArrow().columns)))
        s.stop()
        s = _session("mw-drift2", tmp_path)
        q = _seed_join(s)
        fp = q().query_execution.plan_fingerprint()["fingerprint"]
        rec = pc.manifest_seed(s.conf, fp)
        assert rec and rec.get("join_spans"), \
            "run 1 never recorded a span — dense seeding is dead"
        lying = dict(rec)
        lying["join_spans"] = [[2, 6, 1]] \
            + list(rec["join_spans"][1:])
        pc._manifest(s.conf).append(lying)
        report = q().query_execution.analysis_report()
        assert report.predicted_launches == {"mesh_whole": 2}, \
            report.render()
        before = _counters(s)
        before_k = dict(KC.launches_by_kind)
        got = sorted(zip(*(c.to_pylist()
                           for c in q().toArrow().columns)))
        after = _counters(s)
        delta = {k: v - before_k.get(k, 0)
                 for k, v in KC.launches_by_kind.items()
                 if v != before_k.get(k, 0)}
        assert got == oracle
        assert delta == {"mesh_whole": 2}, delta
        assert after.get("whole_query.dense_guard_retries", 0) \
            > before.get("whole_query.dense_guard_retries", 0)
        # the guarded run re-records the HONEST observed span at close:
        # the manifest self-heals, so the next run (and the analyzer
        # reading the healed record) is back to one dense dispatch
        report = q().query_execution.analysis_report()
        assert report.predicted_launches == {"mesh_whole": 1}, \
            report.render()
        assert _measured(q) == {"mesh_whole": 1}
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# chaos: gang retry reuses the undonated base planes
# ---------------------------------------------------------------------------

def test_mesh_gang_retry_reuses_base_planes(tiers, spark):
    """A runtime fault on the retry round's dispatch (after the base
    planes staged) gang-retries the WHOLE program as a unit: the rebuilt
    program proves the undonated base planes resident and reuses them —
    no host restage — and the device ledger stays balanced. Faulted
    dispatches never count, so the launch prediction still holds."""
    _need_devices(4)
    skew = np.zeros(4000, dtype=np.int64)
    skew[:32] = np.arange(32)
    spark.createDataFrame(pa.table({"gk": skew, "gv": np.arange(4000)})) \
        .createOrReplaceTempView("mw_gang")
    spark.conf.set("spark.tpu.compile.tier", "mesh-whole")

    def q():
        return (spark.sql("select * from mw_gang").repartition(4, "gk")
                .groupBy("gk").count())

    q().toArrow()  # warm both retry-round programs, healthy
    spark.conf.set("spark.tpu.faults.enabled", "true")
    spark.conf.set("spark.tpu.faults.points",
                   "kernel.dispatch=nth:2@mesh_whole")
    faults.configure(spark.conf)
    before = _counters(spark)
    out = dict(zip(*(c.to_pylist() for c in q().toArrow().columns)))
    after = _counters(spark)
    spark.conf.set("spark.tpu.faults.enabled", "false")
    spark.conf.unset("spark.tpu.faults.points")
    faults.configure(spark.conf)
    assert out[0] == 4000 - 31
    assert after.get("whole_query.mesh_gang_retries", 0) \
        - before.get("whole_query.mesh_gang_retries", 0) == 1
    assert after.get("whole_query.mesh_gang_base_reused", 0) \
        > before.get("whole_query.mesh_gang_base_reused", 0), \
        "gang retry restaged from host instead of reusing base planes"
    from spark_tpu.obs.resources import GLOBAL_LEDGER

    assert GLOBAL_LEDGER.verify() == [], \
        "device ledger unbalanced after the gang retry"


# ---------------------------------------------------------------------------
# per-stage carry-over: dict-encoded keys fuse into the stage collective
# ---------------------------------------------------------------------------

def test_stage_mesh_fused_string_keys(tiers, data):
    """PR 9 encoding carry-over on the per-stage mesh path: a fused
    filter+shuffle with a dict-encoded partition key ships padded
    codes→value-hash luts as replicated aux planes and hashes inside the
    shard_map — the pipeline no longer materializes before the
    collective, and the launch prediction stays exact."""
    import pandas as pd

    _need_devices(4)
    data.conf.set("spark.tpu.compile.tier", "stage")

    def q():
        return (data.sql("select k, v, s from mw_t where v > 10")
                .repartition(4, "s"))

    ref = _rows(q(), ["k", "v", "s"])
    fused_keys = [k for k in KC._cache
                  if k and k[0] == "mesh_stage" and k[1] == "f"
                  and isinstance(k[-3], tuple) and len(k[-3]) > 0]
    assert fused_keys, \
        "string-key exchange never compiled the fused mesh program"
    data.conf.set("spark.tpu.fusion.enabled", "false")
    pd.testing.assert_frame_equal(ref, _rows(q(), ["k", "v", "s"]),
                                  check_dtype=False)
    data.conf.unset("spark.tpu.fusion.enabled")
    report = q().query_execution.analysis_report()
    measured = _measured(q)
    assert report.predicted_launches == measured, (
        report.predicted_launches, measured, report.render())
