"""BloomFilter / CountMinSketch (role of the reference's common/sketch
suites: BloomFilterSuite.scala, CountMinSketchSuite.scala)."""

import numpy as np
import pytest

from spark_tpu.utils.sketch import BloomFilter, CountMinSketch


def test_bloom_no_false_negatives():
    rng = np.random.default_rng(0)
    items = rng.integers(0, 1 << 40, 5000)
    bf = BloomFilter(expected_items=5000, fpp=0.03)
    bf.put_many(items)
    assert bf.might_contain_many(items).all()


def test_bloom_fpp_reasonable():
    rng = np.random.default_rng(1)
    items = rng.integers(0, 1 << 40, 5000)
    other = rng.integers(1 << 41, 1 << 42, 20000)
    bf = BloomFilter(expected_items=5000, fpp=0.03)
    bf.put_many(items)
    fp = bf.might_contain_many(other).mean()
    assert fp < 0.1, fp


def test_bloom_strings_and_merge():
    a = BloomFilter(expected_items=100, num_bits=1 << 12)
    b = BloomFilter(expected_items=100, num_bits=1 << 12)
    b.num_hashes = a.num_hashes
    a.put_many(["x", "y"])
    b.put_many(["z"])
    a.merge(b)
    assert a.might_contain("x") and a.might_contain("z")


def test_bloom_roundtrip():
    bf = BloomFilter(expected_items=10)
    bf.put_many([1, 2, 3])
    bf2 = BloomFilter.from_bytes(bf.to_bytes())
    assert bf2.might_contain_many([1, 2, 3]).all()
    assert bf2.num_hashes == bf.num_hashes


def test_bloom_incompatible_merge():
    a = BloomFilter(1, num_bits=1 << 10)
    b = BloomFilter(1, num_bits=1 << 11)
    with pytest.raises(AssertionError):
        a.merge(b)


def test_cms_counts():
    cms = CountMinSketch(eps=0.001, confidence=0.99)
    rng = np.random.default_rng(2)
    vals = rng.integers(0, 50, 10000)
    cms.add_many(vals)
    true = np.bincount(vals, minlength=50)
    est = cms.estimate_count_many(np.arange(50))
    # CMS never undercounts; overcount bounded by eps * total
    assert (est >= true).all()
    assert (est - true).max() <= 0.01 * cms.total + 1
    assert cms.total == 10000


def test_cms_merge_roundtrip():
    a = CountMinSketch(depth=4, width=1 << 10)
    b = CountMinSketch(depth=4, width=1 << 10)
    a.add("k", 3)
    b.add("k", 2)
    a.merge(b)
    assert a.estimate_count("k") >= 5
    c = CountMinSketch.from_bytes(a.to_bytes())
    assert c.estimate_count("k") >= 5
    assert c.total == a.total


# ---------------------------------------------------------------------------
# VARIANT binary type (common/variant Variant.java role)
# ---------------------------------------------------------------------------

def test_variant_roundtrip():
    from decimal import Decimal

    from spark_tpu.utils.variant import Variant

    obj = {"name": "spark", "n": 42, "pi": 3.5, "ok": True,
           "tags": ["a", "b", {"deep": None}],
           "price": Decimal("12.34")}
    v = Variant.of(obj)
    assert v.to_python() == obj
    assert isinstance(v.metadata, bytes) and isinstance(v.value, bytes)


def test_variant_parse_json_and_get():
    from spark_tpu.utils.variant import Variant

    v = Variant.parse_json(
        '{"a": {"b": [10, 20, {"c": "x"}]}, "z": false}')
    assert v.get("$.a.b[1]") == 20
    assert v.get("$.a.b[2].c") == "x"
    assert v.get("$.z") is False
    assert v.get("$.missing") is None
    assert v.get("$.a.b[9]") is None


def test_variant_metadata_dictionary_shares_keys():
    from spark_tpu.utils.variant import Variant

    v = Variant.of([{"k": 1}, {"k": 2}, {"k": 3}])
    # one dictionary entry regardless of repetitions
    assert v.metadata.count(b"k") == 1
    assert v.to_python() == [{"k": 1}, {"k": 2}, {"k": 3}]
