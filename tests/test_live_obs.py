"""Live telemetry (spark_tpu/obs/live.py + worker_main heartbeat flush).

The contract under test: worker stage tasks stream incremental obs
partials on the executor heartbeat BEFORE any task returns; the driver's
LiveObs merges them monotonically (final task-return record supersedes,
late heartbeats drop); the straggler detector flags slowed tasks in live
status AND EXPLAIN ANALYZE; and the whole layer preserves the obs
invariants — zero extra kernel launches, no mid-query device syncs,
contextvars into every new flush thread."""

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_tpu.obs.live import (
    ConsoleProgressReporter, LiveObs, start_query_flusher,
)
from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC


def _delta(qid="q1", stage="s.1.1", task=0, seq=1, rows=0, batches=0,
           launches=0, **kw):
    return {"query": qid, "stage": stage, "task": task, "seq": seq,
            "rows": rows, "batches": batches, "launches": launches,
            "compile_ms": 0.0, "kernel_kinds": kw.pop("kernel_kinds", {}),
            "op_records": kw.pop("op_records", {}),
            "spans_closed": kw.pop("spans_closed", []),
            "open_spans": kw.pop("open_spans", []), **kw}


# ---------------------------------------------------------------------------
# merge semantics: monotonic partials, final supersedes, late drops
# ---------------------------------------------------------------------------

def test_partials_merge_monotonically_and_final_supersedes():
    live = LiveObs()
    live.on_heartbeat("exec-a", [_delta(seq=1, rows=10, batches=1)])
    live.on_heartbeat("exec-a", [_delta(seq=3, rows=30, batches=3,
                                        launches=5)])
    # stale/reordered snapshot must not regress the counters
    live.on_heartbeat("exec-a", [_delta(seq=2, rows=20, batches=2)])
    t = live.task_record("q1", "s.1.1", 0)
    assert t["rows"] == 30 and t["batches"] == 3 and t["launches"] == 5
    assert t["partials"] == 2 and not t["done"]
    assert live.partials_seen == 2

    final = {"op_records": {7: {"rows": 44, "rows_exact": True,
                                "batches": 4}},
             "kernel_launches": 6, "kernel_compile_ms": 1.5,
             "kernel_kinds": {"pipeline": 6}}
    live.task_finished("q1", "s.1.1", 0, final)
    t = live.task_record("q1", "s.1.1", 0)
    assert t["done"] and t["rows"] == 44 and t["launches"] == 6
    assert t["kernel_kinds"] == {"pipeline": 6}
    # partials arrived and the final extends them monotonically
    assert t["reconciled"] is True

    # a late heartbeat after completion is DROPPED, not merged
    live.on_heartbeat("exec-a", [_delta(seq=9, rows=999)])
    t = live.task_record("q1", "s.1.1", 0)
    assert t["rows"] == 44 and live.late_dropped == 1


def test_query_progress_rolls_up_stages_and_heartbeat_age():
    live = LiveObs()
    live.on_heartbeat("e1", [_delta(task=0, seq=1, rows=5, batches=1),
                             _delta(task=1, seq=1, rows=7, batches=2)])
    live.task_finished("q1", "s.1.1", 1, None, rows=7)
    p = live.query_progress("q1")
    st = p["stages"]["s.1.1"]
    assert st["tasks_total"] == 2 and st["tasks_done"] == 1
    assert st["rows"] == 12 and st["partials"] == 2
    assert st["tasks"][0]["heartbeat_age_s"] >= 0
    assert st["tasks"][1]["done"]


# ---------------------------------------------------------------------------
# straggler detection
# ---------------------------------------------------------------------------

class _Conf:
    """Minimal conf shim (LiveObs only calls .get(entry))."""

    def __init__(self, **over):
        self.over = over

    def get(self, entry):
        return self.over.get(entry.key, entry.default)


def test_straggler_rate_detection_and_healthy_runs_stay_clean():
    conf = _Conf(**{"spark.tpu.straggler.minSeconds": 0.05,
                    "spark.tpu.straggler.rateFraction": 0.5})
    live = LiveObs(conf=conf)
    # fast peer completes with a healthy rate; slow task makes no progress
    live.on_heartbeat("e1", [_delta(task=0, seq=1, rows=0),
                             _delta(task=1, seq=1, rows=500, batches=4)])
    live.task_finished("q1", "s.1.1", 1, None, rows=500)
    time.sleep(0.1)
    live.on_heartbeat("e1", [_delta(task=0, seq=2, rows=0)])
    active = live.check_stragglers()
    assert [(f["stage"], f["task"]) for f in active] == [("s.1.1", 0)]
    assert all(f["kind"] == "obs.straggler" and f["severity"] == "warning"
               for f in active)
    # findings persist for the query (EXPLAIN ANALYZE reads them later)
    assert live.findings_for("q1")
    assert live.active_stragglers() == [("q1", "s.1.1", 0)]

    # healthy: equal-progress peers never flag
    live2 = LiveObs(conf=conf)
    live2.on_heartbeat("e1", [_delta(qid="q2", task=0, seq=1, rows=100),
                              _delta(qid="q2", task=1, seq=1, rows=110)])
    time.sleep(0.1)
    live2.on_heartbeat("e1", [_delta(qid="q2", task=0, seq=2, rows=200),
                              _delta(qid="q2", task=1, seq=2, rows=210)])
    assert live2.check_stragglers() == []
    assert live2.findings_for("q2") == []


def test_straggler_silence_detection():
    conf = _Conf(**{"spark.tpu.straggler.heartbeatDeadline": 0.05,
                    "spark.tpu.straggler.minSeconds": 10_000})
    live = LiveObs(conf=conf)
    live.on_heartbeat("e1", [_delta(task=0, seq=1, rows=5)])
    time.sleep(0.12)
    active = live.check_stragglers()
    assert active and "silent" in active[0]["msg"]
    # a finished query stops being scanned
    live.query_finished("q1")
    assert live.check_stragglers() == []


def test_fast_task_without_partials_gets_real_duration():
    """A task can finish before its first heartbeat ever reaches the
    driver; without the scheduler-provided start time its duration would
    collapse to ~0 and its completed-peer rate would explode, flagging
    every healthy sibling as a straggler."""
    conf = _Conf(**{"spark.tpu.straggler.minSeconds": 0.05,
                    "spark.tpu.straggler.rateFraction": 0.5})
    live = LiveObs(conf=conf)
    # sibling still running, healthy progress
    live.on_heartbeat("e1", [_delta(task=0, seq=1, rows=90)])
    # peer finishes WITHOUT any partials; the scheduler knows it started
    # 1s ago → rate ~100 rows/s, same ballpark as the running sibling
    live.task_finished("q1", "s.1.1", 1, None, rows=100,
                       started=time.time() - 1.0)
    t = live.task_record("q1", "s.1.1", 1)
    assert t["duration"] >= 0.9          # real duration, not ~0
    time.sleep(0.1)
    live.on_heartbeat("e1", [_delta(task=0, seq=2, rows=110)])
    assert live.check_stragglers() == [] # healthy sibling stays clean


def test_stage_abandoned_drops_failed_attempt_entries():
    """A failed stage attempt retries under a new shuffle id; its live
    entries must not sit open forever tripping the heartbeat-silence
    deadline (a permanently-truthy straggler signal)."""
    conf = _Conf(**{"spark.tpu.straggler.heartbeatDeadline": 0.05,
                    "spark.tpu.straggler.minSeconds": 10_000})
    live = LiveObs(conf=conf)
    live.on_heartbeat("e1", [_delta(stage="run.1.1", task=0, seq=1,
                                    rows=5)])
    live.stage_abandoned("q1", "run.1.1")
    # a heartbeat straggling in AFTER abandonment must not resurrect
    # the entry (nothing would ever close it again)
    live.on_heartbeat("e1", [_delta(stage="run.1.1", task=0, seq=2,
                                    rows=9)])
    # nor may a late final record of the failed attempt
    live.task_finished("q1", "run.1.1", 0, None, rows=9)
    time.sleep(0.12)                     # past the silence deadline
    assert live.check_stragglers() == []
    assert live.active_stragglers() == []
    p = live.query_progress("q1")
    assert p is not None and "run.1.1" not in p["stages"]
    assert live.late_dropped >= 1


def test_speculative_copies_merge_per_executor():
    """Speculation races two copies of one task on the same key, each
    with an independent seq counter: per-executor seq tracking accepts
    both streams (no interleave-drops), the further-along copy owns the
    displayed counters, and reconciliation compares the final record
    against the WINNING copy's own partials."""
    live = LiveObs()
    live.on_heartbeat("e1", [_delta(seq=1, rows=100, batches=2)])
    live.on_heartbeat("e2", [_delta(seq=1, rows=10, batches=1)])
    t = live.task_record("q1", "s.1.1", 0)
    assert t["partials"] == 2            # laggard's stream not dropped
    assert t["rows"] == 100 and t["executor"] == "e1"  # leader displays
    # the laggard catches up past the leader and takes over the display
    live.on_heartbeat("e2", [_delta(seq=2, rows=300, batches=4)])
    t = live.task_record("q1", "s.1.1", 0)
    assert t["rows"] == 300 and t["executor"] == "e2"
    assert t["rows_by"] == {"e1": 100, "e2": 300}
    # e1 wins the race: reconciliation is against e1's OWN partials
    # (100 <= 120), not the displayed 300 from the losing copy
    live.task_finished("q1", "s.1.1", 0, None, rows=120, executor="e1")
    t = live.task_record("q1", "s.1.1", 0)
    assert t["reconciled"] is True and t["executor"] == "e1"


def test_straggler_signal_scoped_to_flagged_task():
    """The live straggler signal is the hook the speculative-execution
    path consumes — polled during the wait for the primary, SCOPED to
    the waiting task's key, so one flagged straggler launches ITS
    backup immediately without collapsing the speculation threshold for
    every other in-flight task."""
    from spark_tpu.exec.cluster import LocalCluster

    c = LocalCluster.__new__(LocalCluster)     # no worker spawn
    c.speculation_interval = None
    c.speculation_multiplier = 1.5
    c._durations = []
    c._lock = threading.Lock()
    c.speculation_signal = None
    assert c._speculation_threshold() is None  # no history, no interval
    assert c._signal_flags(("s.1", 0)) is False

    flagged = [("q1", "s.1", 0)]               # active_stragglers() shape
    c.speculation_signal = (
        lambda key=None: any(key is None or (f[1], f[2]) == key
                             for f in flagged))
    assert c._signal_flags(("s.1", 0)) is True   # this task is flagged
    assert c._signal_flags(("s.1", 1)) is False  # siblings unaffected
    # a KEYLESS task never consumes the signal — 'any straggler
    # anywhere' would double-launch every unrelated task
    assert c._signal_flags(None) is False
    # bare (no-arg) signals keep the legacy any-straggler semantics
    c.speculation_signal = lambda: True
    assert c._signal_flags(("s.9", 3)) is True
    # the duration-history threshold itself no longer consults the
    # signal — the poll inside _run_speculative owns that decision
    assert c._speculation_threshold() is None


# ---------------------------------------------------------------------------
# no-sync guard: partial export never touches a device array
# ---------------------------------------------------------------------------

def test_partial_export_leaves_parked_masks_parked():
    from spark_tpu.obs import metrics as OM

    class Grenade:
        """Parked mask stand-in: ANY array access mid-query is a sync."""

        def __array__(self, *a, **k):
            raise AssertionError("live flush resolved a parked mask")

        @property
        def nbytes(self):
            raise AssertionError("live flush touched a parked mask")

    rec = {}
    ent = rec[1] = OM.new_op_record()
    ent["rows"] = 7
    ent["batches"] = 2
    ent["pending"].append(Grenade())
    snap = OM.export_op_records_partial(rec)
    # host counters ship; the pending mask is untouched and still parked
    assert snap[1]["rows"] == 7 and snap[1]["batches"] == 2
    assert snap[1]["rows_exact"] is False      # lower bound until task end
    assert len(ent["pending"]) == 1
    assert "pending" not in snap[1]


def test_worker_collect_live_obs_is_pure_host(spark):
    """collect_live_obs over a registered recorder launches nothing and
    ships cumulative snapshots with monotonic seq + incremental spans."""
    from spark_tpu.config import SQLConf
    from spark_tpu.exec import worker_main as WM
    from spark_tpu.obs.metrics import new_op_record

    conf = SQLConf({})
    state = WM.begin_stage_obs(conf, query_id="qx", stage_id="st.1.1",
                               task_id=2)
    try:
        assert state is not None
        tracer = state["tracer"]
        state["rec"][5] = new_op_record()
        state["rec"][5]["rows"] = 11
        with tracer.span("op-a", cat="operator"):
            pass
        before = KC.launches
        d1 = WM.collect_live_obs()
        # the heartbeat carrying d1 FAILED: spans must be re-sent, not
        # silently lost from the live stream
        d_retry = WM.collect_live_obs()
        WM.ack_live_obs()                      # this beat reached the driver
        d2 = WM.collect_live_obs()
        assert KC.launches == before
        mine = [d for d in d1 if d["query"] == "qx"]
        assert len(mine) == 1 and mine[0]["task"] == 2
        assert mine[0]["rows"] == 11
        assert any(s["name"] == "op-a" for s in mine[0]["spans_closed"])
        retry = [d for d in d_retry if d["query"] == "qx"][0]
        assert any(s["name"] == "op-a" for s in retry["spans_closed"]), \
            "unacked closed spans dropped from the live stream"
        mine2 = [d for d in d2 if d["query"] == "qx"][0]
        assert mine2["seq"] == mine[0]["seq"] + 2
        assert mine2["spans_closed"] == []     # acked: shipped exactly once
    finally:
        WM.finish_stage_obs(state)
    assert all(d.get("query") != "qx" for d in WM.collect_live_obs()), \
        "finished task still registered for live flushing"


def test_open_spans_visible_while_in_flight(spark):
    from spark_tpu.obs.tracing import Tracer

    t = Tracer(enabled=True)
    with t.span("long-running", cat="operator"):
        open_now = t.open_spans()
        assert any(s["name"] == "long-running" and s["elapsed_ms"] >= 0
                   for s in open_now)
    assert all(s["name"] != "long-running" for s in t.open_spans())


# ---------------------------------------------------------------------------
# flush-thread contextvar propagation (satellite regression)
# ---------------------------------------------------------------------------

def test_flush_thread_carries_query_scope_via_scoped_submit():
    """start_query_flusher hands its loop to the pool through
    scoped_submit: the flush thread sees the caller's query scope and
    publishes under the right qid. A bare pool.submit (negative
    control) starts from an empty context and would publish untagged."""
    from concurrent.futures import ThreadPoolExecutor

    from spark_tpu.exec.context import ExecContext
    from spark_tpu.obs import metrics as OM
    from spark_tpu.obs.tracing import current_query, pop_query, push_query

    live = LiveObs()
    ctx = ExecContext()
    ctx.plan_metrics = {3: OM.new_op_record()}
    ctx.plan_metrics[3]["rows"] = 42
    tok = push_query("q-flush")
    try:
        stop = start_query_flusher(live, ctx, interval=0.02)
        time.sleep(0.1)
        stop()
        with ThreadPoolExecutor(1) as pool:
            bare_qid = pool.submit(current_query).result()
    finally:
        pop_query(tok)
    assert bare_qid is None         # the hazard scoped_submit prevents
    p = live.query_progress("q-flush")
    assert p is not None, "flush thread lost the query scope"
    st = p["stages"]["local"]
    assert st["rows"] == 42 and st["partials"] >= 1


# ---------------------------------------------------------------------------
# zero-launch guard: live telemetry (flusher + console) adds no dispatch
# ---------------------------------------------------------------------------

def test_local_live_telemetry_zero_launch_overhead(spark):
    import io

    rng = np.random.default_rng(5)
    spark.createDataFrame(pa.table({
        "k": rng.integers(0, 9, 4000),
        "v": rng.integers(-10, 50, 4000)})) \
        .createOrReplaceTempView("live_t")
    sql = "select k, sum(v) s, count(*) c from live_t where v > 0 group by k"

    def delta():
        spark.sql(sql).toArrow()   # warm
        before = dict(KC.launches_by_kind)
        spark.sql(sql).toArrow()
        after = dict(KC.launches_by_kind)
        return {k: v - before.get(k, 0) for k, v in after.items()
                if v != before.get(k, 0)}

    baseline = delta()
    # console progress ON routes every query through the live flusher +
    # reporter; pre-install a reporter on a throwaway stream so the test
    # terminal stays clean
    spark._progress_reporter = ConsoleProgressReporter(
        spark.live_obs, stream=io.StringIO(), interval=0.02).start()
    spark.conf.set("spark.tpu.progress.console", "true")
    try:
        with_live = delta()
    finally:
        spark.conf.unset("spark.tpu.progress.console")
        spark._progress_reporter.stop()
        spark._progress_reporter = None
    assert with_live == baseline, (
        f"live telemetry changed dispatches: {with_live} vs {baseline}")


def test_console_reporter_renders_stage_bars():
    import io

    live = LiveObs()
    live.on_heartbeat("e1", [_delta(task=0, seq=1, rows=100, launches=3),
                             _delta(task=1, seq=1, rows=50)])
    live.task_finished("q1", "s.1.1", 1, None, rows=50)
    rep = ConsoleProgressReporter(live, stream=io.StringIO())
    line = rep.render_line()
    assert "1/2 tasks" in line and "rows=150" in line
    assert "launches=3" in line


# ---------------------------------------------------------------------------
# cluster integration: a deliberately slow worker streams partials
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def live_cluster_spark():
    """2-worker cluster heartbeating every 0.1s — slow stage tasks emit
    several live deltas before returning."""
    from spark_tpu.api.session import TpuSession
    from spark_tpu.exec.cluster import LocalCluster

    s = TpuSession("live-cluster", {
        "spark.sql.shuffle.partitions": "2",
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.adaptive.enabled": "false",
    })
    cluster = LocalCluster(num_workers=2, heartbeat_interval=0.1)
    s.attachSqlCluster(cluster)
    rng = np.random.default_rng(17)
    n = 4000
    s.createDataFrame(pa.table({
        "k": rng.integers(0, 8, n),
        "v": rng.integers(-20, 60, n)})) \
        .createOrReplaceTempView("lc_t")
    yield s
    s.stop()


def _slow_df(spark, sleep_s=0.25, slow_key=None):
    """Map stage containing a sleeping UDF: slow_key=None sleeps every
    batch; an int sleeps only in batches containing that key (after the
    hash repartition, exactly the map task holding that key's partition
    stalls)."""
    import spark_tpu.api.functions as F
    from spark_tpu.types import int64

    @F.udf(returnType=int64)
    def crawl(k):
        if slow_key is None or (np.asarray(k) == slow_key).any():
            time.sleep(sleep_s)
        return k * 2

    base = spark.table("lc_t")
    if slow_key is not None:
        base = base.repartition(2, "k")
    return base.withColumn("kk", crawl("k")).repartition(2)


def test_slow_worker_streams_partials_before_any_task_returns(
        live_cluster_spark):
    spark = live_cluster_spark
    live = spark.live_obs
    df = _slow_df(spark, sleep_s=0.3)
    base_partials = live.partials_seen

    seen_running = []
    done = threading.Event()

    def poll():
        while not done.is_set():
            snap = live.snapshot()
            for qid, q in snap["running"].items():
                for stage, st in q["stages"].items():
                    if stage != "local" and st["partials"] > 0 and \
                            st["tasks_done"] < st["tasks_total"]:
                        seen_running.append((qid, stage, dict(st)))
            time.sleep(0.05)

    poller = threading.Thread(target=poll, daemon=True)
    poller.start()
    try:
        df.toArrow()
    finally:
        done.set()
        poller.join(5)
    # acceptance: incremental worker deltas were visible on the driver
    # BEFORE the map task returned
    assert seen_running, "no mid-stage heartbeat partial reached the driver"
    assert live.partials_seen > base_partials
    qid, stage, st = seen_running[-1]
    # after completion the final record superseded and reconciled
    p = live.query_progress(qid)
    final_st = p["stages"][stage]
    assert final_st["tasks_done"] == final_st["tasks_total"]
    for t in final_st["tasks"].values():
        assert t["done"] and t["reconciled"] is True
    # healthy run: zero straggler findings
    assert p["findings"] == []


def test_cluster_attribution_intact_with_live_telemetry(live_cluster_spark):
    """Streaming partials must not perturb the ground truth: attributed
    per-operator launches still equal driver + worker measured totals
    (the PR 4 invariant) with heartbeat obs flowing."""
    import spark_tpu.api.functions as F

    spark = live_cluster_spark

    def q():
        return (spark.table("lc_t").repartition(2)
                .groupBy("k").agg(F.sum("v").alias("s")))

    q().toArrow()   # warm worker caches
    before = KC.launches
    df = q()
    df.toArrow()
    driver_delta = KC.launches - before
    ctx = df.query_execution._last_ctx
    worker_kinds = ctx.worker_kernel_kinds or {}
    assert worker_kinds, "workers shipped no kernel deltas"
    graph = df.query_execution.plan_graph()
    attributed = sum(v for nd in graph
                     for v in (nd.get("launches") or {}).values())
    assert attributed == driver_delta + sum(worker_kinds.values())


def test_straggler_flagged_in_live_status_and_explain_analyze(
        live_cluster_spark):
    """Acceptance: an artificially slowed map task (sleeping UDF pinned
    to one hash partition, 2 map tasks racing) is flagged while running
    and the obs.straggler finding surfaces in live status and EXPLAIN
    ANALYZE."""
    spark = live_cluster_spark
    spark.conf.set("spark.tpu.shuffle.mapParallelism", "2")
    spark.conf.set("spark.tpu.straggler.minSeconds", "0.3")
    spark.conf.set("spark.tpu.straggler.rateFraction", "0.5")
    qids = []
    listener = lambda ev: qids.append(ev.query_id)  # noqa: E731
    spark.listener_bus.register(listener)
    try:
        # the stall must dominate the task: completed peers now carry
        # REAL durations (scheduler start time), so the bar is a
        # realistic rate, not the inflated ~0-duration artifact —
        # a marginal slowdown would make this assertion timing-flaky
        df = _slow_df(spark, sleep_s=3.0, slow_key=3)
        report = df.query_execution.analyzed_report()
        spark.listener_bus.wait_empty()
    finally:
        spark.listener_bus.unregister(listener)
        spark.conf.unset("spark.tpu.shuffle.mapParallelism")
        spark.conf.unset("spark.tpu.straggler.minSeconds")
        spark.conf.unset("spark.tpu.straggler.rateFraction")
    stragglers = [f for f in report.findings
                  if f.get("kind") == "obs.straggler"]
    assert stragglers, \
        f"no straggler finding in EXPLAIN ANALYZE: {report.findings}"
    # and the same finding lives in the query's live status
    flagged_q = stragglers[0]["query"]
    assert flagged_q in qids
    p = spark.live_obs.query_progress(flagged_q)
    assert p is not None and any(f["kind"] == "obs.straggler"
                                 for f in p["findings"])
    # drift gates stay green: stragglers are warnings, not errors
    assert not report.has_unexplained_drift, report.render()


def test_live_ui_summary_includes_live_snapshot(live_cluster_spark):
    from spark_tpu.exec.ui import LiveStatusStore

    spark = live_cluster_spark
    store = LiveStatusStore("live-ui", live_obs=spark.live_obs)
    spark.listener_bus.register(store)
    try:
        _slow_df(spark, sleep_s=0.05).toArrow()
        spark.listener_bus.wait_empty()
    finally:
        spark.listener_bus.unregister(store)
    s = store.summary("live-ui")
    assert "live" in s
    assert s["live"]["partials_seen"] > 0


# ---------------------------------------------------------------------------
# push-merge flow arrows (satellite): merged chunks have a producing span
# ---------------------------------------------------------------------------

def test_push_merge_exchange_edges_flow_through_merge_span():
    import importlib.util
    import os

    from spark_tpu.api.session import TpuSession
    from spark_tpu.exec.cluster import LocalCluster
    from tests.test_observability import _flow_edges

    s = TpuSession("push-flow", {
        "spark.sql.shuffle.partitions": "2",
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.adaptive.enabled": "false",
    })
    try:
        cluster = LocalCluster(num_workers=2, push_shuffle=True)
        s.attachSqlCluster(cluster)
        rng = np.random.default_rng(3)
        s.createDataFrame(pa.table({
            "k": rng.integers(0, 5, 3000),
            "v": rng.integers(0, 40, 3000)})) \
            .createOrReplaceTempView("pm_t")
        import spark_tpu.api.functions as F

        (s.table("pm_t").repartition(2)
         .groupBy("k").agg(F.sum("v").alias("sv"))).toArrow()
        merged = s._metrics.snapshot()["counters"].get(
            "shuffle.merged_chunks_fetched", 0)
        assert merged > 0, "query never consumed a push-merged chunk"
        doc = s.tracer.to_chrome_trace()
    finally:
        s.stop()
    evs = doc["traceEvents"]
    complete = [e for e in evs if e.get("ph") == "X"]
    merge_spans = [e for e in complete if e["name"].startswith("merge[")]
    assert merge_spans, "push-merge finalize recorded no producing span"
    assert all((e.get("args") or {}).get("flow_id", "").endswith("#merged")
               for e in merge_spans)
    # every arrow resolves (no dangling endpoints), and at least one
    # lands merge span → reduce-side fetch: the exchange edge no longer
    # stops at the fetch
    edges = _flow_edges(doc)
    assert all(srd is not None and dst is not None for srd, dst in edges)
    assert any(srd["name"].startswith("merge[")
               and dst["name"].startswith("fetch[")
               for srd, dst in edges), \
        "no merge → reduce-fetch flow arrow"
    # and a map task feeds the merge span (map → merge → fetch chain)
    assert any(srd["cat"] == "worker" and dst["name"].startswith("merge[")
               for srd, dst in edges), "no map-task → merge flow arrow"
    # the CI validator's referential-integrity check agrees
    spec = importlib.util.spec_from_file_location(
        "validate_trace", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "dev", "validate_trace.py"))
    vt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(vt)
    assert vt._check_flows(evs, complete) > 0


# ---------------------------------------------------------------------------
# map-side stat restriction (satellite): only plan-reachable candidates
# ---------------------------------------------------------------------------

def test_exchange_stat_cols_restricted_to_dense_candidates(spark):
    rng = np.random.default_rng(9)
    spark.createDataFrame(pa.table({
        "k": rng.integers(0, 7, 3000),
        "v": rng.integers(0, 100, 3000),
        "w": rng.integers(0, 100, 3000)})) \
        .createOrReplaceTempView("sc_t")
    from spark_tpu.physical.exchange import ShuffleExchangeExec

    # k is a downstream single-int grouping key → the exchange
    # accumulates stats ONLY for k, not for v/w (historically every
    # integral column paid the per-append host min/max)
    df = (spark.table("sc_t").repartition(3, "k")
          .groupBy("k").count())
    plan = df.query_execution.physical
    ex = [n for n in plan.iter_nodes()
          if isinstance(n, ShuffleExchangeExec)]
    assert ex
    kpos = [i for i, a in enumerate(ex[0].output) if a.name == "k"]
    assert ex[0].stat_cols == kpos, ex[0].stat_cols
    df.toArrow()
    stats = ex[0].last_col_stats
    assert stats and all(set(cols) <= set(kpos)
                         for cols in stats.values()), stats

    # no downstream dense consumer → no stat accumulation at all
    df2 = spark.table("sc_t").repartition(3)
    plan2 = df2.query_execution.physical
    ex2 = [n for n in plan2.iter_nodes()
           if isinstance(n, ShuffleExchangeExec)]
    assert ex2 and ex2[0].stat_cols == []
    df2.toArrow()
    assert all(cols == {} for cols in ex2[0].last_col_stats.values())
