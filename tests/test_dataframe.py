"""DataFrame API tests (role of the reference's DataFrameSuite /
sql/core/src/test — pandas/numpy as oracle)."""

import numpy as np
import pyarrow as pa
import pytest

import spark_tpu.api.functions as F


def _dict(df):
    return df.toArrow().to_pydict()


def test_select_filter(people):
    out = _dict(people.filter(F.col("age") > 25).select("name", "age")
                .orderBy("name"))
    assert out["name"] == ["bob", "eve"]
    assert out["age"] == [32, 41]


def test_filter_string_condition(people):
    out = _dict(people.filter("age = 25 AND dept = 'eng'").select("name")
                .orderBy("name"))
    assert out["name"] == ["alice", "carol"]


def test_with_column_arithmetic(people):
    out = _dict(people.withColumn("double_sal", F.col("salary") * 2)
                .filter(F.col("name") == "alice")
                .select("name", "double_sal"))
    assert out["double_sal"] == [200.0]


def test_nulls_filtered_by_comparison(people):
    # age NULL rows drop from age>0 filter (3-valued logic)
    assert people.filter(F.col("age") > 0).count() == 5


def test_is_null(people):
    out = _dict(people.filter(F.col("age").isNull()).select("name"))
    assert out["name"] == ["dave"]


def test_groupby_agg(people):
    out = _dict(people.groupBy("dept").agg(
        F.count("*").alias("n"),
        F.sum("age").alias("sa"),
        F.avg("salary").alias("avg_sal"),
        F.min("age").alias("mn"),
        F.max("age").alias("mx"),
    ).orderBy("dept"))
    assert out["dept"] == ["eng", "hr", "sales"]
    assert out["n"] == [3, 1, 2]
    assert out["sa"] == [50, 41, 57]  # null age excluded
    assert out["mn"] == [25, 41, 25]
    assert out["mx"] == [25, 41, 32]
    assert abs(out["avg_sal"][0] - 105.0) < 1e-9


def test_global_agg(people):
    out = _dict(people.agg(F.count("*").alias("n"),
                           F.sum("age").alias("s")))
    assert out["n"] == [6]
    assert out["s"] == [148]


def test_sum_all_null_group(spark):
    df = spark.createDataFrame(pa.table({
        "k": [1, 1, 2], "v": pa.array([None, None, 5], pa.int64())}))
    out = _dict(df.groupBy("k").agg(F.sum("v").alias("s"),
                                    F.count("v").alias("c")).orderBy("k"))
    assert out["s"] == [None, 5]
    assert out["c"] == [0, 1]


def test_distinct(people):
    assert people.select("dept").distinct().count() == 3


def test_order_by_desc_nulls(people):
    out = _dict(people.orderBy(F.col("age").desc()).select("age"))
    assert out["age"] == [41, 32, 25, 25, 25, None]
    out2 = _dict(people.orderBy(F.col("age").asc()).select("age"))
    assert out2["age"] == [None, 25, 25, 25, 32, 41]


def test_limit_offset(people):
    df = people.filter(F.col("name").isNotNull()).orderBy("name")
    assert _dict(df.limit(2).select("name"))["name"] == ["alice", "bob"]


def test_join_inner(spark):
    a = spark.createDataFrame(pa.table({"id": [1, 2, 3], "v": [10, 20, 30]}))
    b = spark.createDataFrame(pa.table({"id": [2, 3, 4], "w": [200, 300, 400]}))
    out = _dict(a.join(b, on="id").orderBy("id"))
    assert out["id"] == [2, 3]
    assert out["v"] == [20, 30]
    assert out["w"] == [200, 300]


def test_join_left(spark):
    a = spark.createDataFrame(pa.table({"id": [1, 2], "v": [10, 20]}))
    b = spark.createDataFrame(pa.table({"id": [2], "w": [200]}))
    out = _dict(a.join(b, on="id", how="left").orderBy("id"))
    assert out["w"] == [None, 200]


def test_self_join(spark):
    df = spark.createDataFrame(pa.table({"id": [1, 2, 3], "v": [5, 6, 7]}))
    a = df.alias("a")
    b = df.alias("b")
    out = a.join(b, F.col("a.id") == F.col("b.id")).select(
        F.col("a.id").alias("id"), F.col("b.v").alias("bv")).orderBy("id")
    assert _dict(out)["id"] == [1, 2, 3]


def test_union(spark):
    a = spark.createDataFrame(pa.table({"x": [1, 2]}))
    b = spark.createDataFrame(pa.table({"x": [3]}))
    assert _dict(a.union(b).orderBy("x"))["x"] == [1, 2, 3]


def test_cross_join(spark):
    a = spark.createDataFrame(pa.table({"x": [1, 2]}))
    b = spark.createDataFrame(pa.table({"y": ["p", "q"]}))
    assert a.crossJoin(b).count() == 4


def test_string_functions(people):
    out = _dict(people.filter(F.col("name").isNotNull()).select(
        F.upper("name").alias("u"),
        F.length("name").alias("l"),
        F.col("name").substr(1, 2).alias("s2"),
    ).orderBy("u"))
    assert out["u"][0] == "ALICE"
    assert out["l"][0] == 5
    assert out["s2"][0] == "al"


def test_string_predicates(people):
    assert people.filter(F.col("name").like("%a%")).count() == 3
    assert people.filter(F.col("name").startswith("a")).count() == 1
    assert people.filter(F.col("dept").isin("eng", "hr")).count() == 4


def test_case_when(people):
    out = _dict(people.select(
        F.when(F.col("age") > 30, "old").otherwise("young").alias("grp")))
    # NULL age → condition unknown → ELSE branch (SQL CASE semantics)
    assert sorted(out["grp"]) == ["old", "old", "young", "young", "young",
                                  "young"]


def test_cast(spark):
    df = spark.createDataFrame(pa.table({"s": ["1", "2", "x"]}))
    out = _dict(df.select(F.col("s").cast("int").alias("i")))
    assert out["i"] == [1, 2, None]


def test_range(spark):
    df = spark.range(10)
    assert df.count() == 10
    assert _dict(df.agg(F.sum("id").alias("s")))["s"] == [45]


def test_repartition_preserves_data(spark):
    df = spark.range(100).repartition(5)
    assert df.count() == 100
    out = df.groupBy((F.col("id") % 3).alias("m")).count()
    assert sorted(_dict(out)["count"]) == [33, 33, 34]


def test_dropduplicates(spark):
    df = spark.createDataFrame(pa.table({"a": [1, 1, 2], "b": [9, 9, 8]}))
    assert df.dropDuplicates().count() == 2


def test_with_column_renamed(people):
    assert "renamed" in people.withColumnRenamed("age", "renamed").columns


def test_stddev(spark):
    df = spark.createDataFrame(pa.table({"v": [2.0, 4.0, 4.0, 4.0, 5.0, 5.0,
                                               7.0, 9.0]}))
    out = _dict(df.agg(F.stddev_pop("v").alias("sd")))
    assert abs(out["sd"][0] - 2.0) < 1e-9


def test_date_functions(spark):
    import datetime

    df = spark.createDataFrame(pa.table({
        "d": pa.array([datetime.date(2020, 2, 29), datetime.date(1999, 12, 31)],
                      pa.date32())}))
    out = _dict(df.select(F.year("d").alias("y"), F.month("d").alias("m"),
                          F.dayofmonth("d").alias("dd"),
                          F.quarter("d").alias("q"),
                          F.dayofweek("d").alias("dw")))
    assert out["y"] == [2020, 1999]
    assert out["m"] == [2, 12]
    assert out["dd"] == [29, 31]
    assert out["q"] == [1, 4]
    assert out["dw"] == [7, 6]  # Sat=7, Fri=6


def test_show_and_explain(people, capsys):
    people.show(2)
    people.explain()
    out = capsys.readouterr().out
    assert "Physical Plan" in out


def test_count_multi_partition(spark):
    df = spark.range(0, 10000, 1, 8)
    assert df.count() == 10000
    out = _dict(df.groupBy((F.col("id") % 7).alias("m")).agg(
        F.count("*").alias("c")).orderBy("m"))
    assert sum(out["c"]) == 10000


def test_string_min_max_aggregate(spark):
    df = spark.createDataFrame(pa.table({
        "g": [1, 1, 2, 2, 2],
        "s": ["banana", "apple", "zebra", None, "mango"]}))
    out = (df.groupBy("g").agg(F.min("s").alias("mn"),
                               F.max("s").alias("mx"))
           .orderBy("g").toArrow().to_pydict())
    assert out["mn"] == ["apple", "mango"]
    assert out["mx"] == ["banana", "zebra"]
    # global + multi-partition merge
    out2 = (df.repartition(3).agg(F.min("s").alias("mn"),
                                  F.max("s").alias("mx"))
            .toArrow().to_pydict())
    assert out2["mn"] == ["apple"]
    assert out2["mx"] == ["zebra"]
