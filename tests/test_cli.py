"""CLI layer tests (reference: bin/spark-submit, bin/spark-sql,
launcher/ — SURVEY.md §1 layer 14)."""

import io
import os

import pyarrow as pa

from spark_tpu.cli.submit import build_parser, parse_conf
from spark_tpu.cli.sql_shell import render_table, run_statement


def test_parse_conf():
    assert parse_conf(["a.b=1", "c = x=y "]) == {"a.b": "1", "c": "x=y"}


def test_submit_parser_app_args():
    args = build_parser().parse_args(
        ["--name", "n", "--conf", "k=v", "app.py", "--flag", "7"])
    assert args.name == "n"
    assert args.conf == ["k=v"]
    assert args.app == "app.py"
    assert args.app_args == ["--flag", "7"]


def test_render_table():
    t = pa.table({"a": [1, None], "name": ["xx", "y"]})
    out = render_table(t)
    assert "| a    | name |" in out
    assert "| NULL | y    |" in out


def test_run_statement(spark):
    buf = io.StringIO()
    run_statement(spark, "SELECT 1 AS one", out=buf)
    s = buf.getvalue()
    assert "| one |" in s and "1 row(s)" in s


def test_submit_runs_app(tmp_path, spark):
    app = tmp_path / "app.py"
    app.write_text(
        "import json, os\n"
        "from spark_tpu.cli.submit import get_session\n"
        "s = get_session()\n"
        "out = s.sql('SELECT 40 + 2 AS v').toArrow().to_pydict()\n"
        "open(os.environ['CLI_TEST_OUT'], 'w').write(json.dumps(out))\n")
    marker = tmp_path / "out.json"
    os.environ["CLI_TEST_OUT"] = str(marker)
    try:
        import spark_tpu.cli.submit as sub

        old = sub._SESSION
        sub._SESSION = None
        try:
            sub.main(["--name", "t", "--conf",
                      "spark.sql.shuffle.partitions=2", str(app)])
        finally:
            sub._SESSION = old
        assert marker.read_text() == '{"v": [42]}'
    finally:
        del os.environ["CLI_TEST_OUT"]
