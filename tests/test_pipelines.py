"""Declarative pipelines tests (reference: sql/pipelines graph suites +
python/pyspark/pipelines/tests)."""

import pyarrow as pa
import pytest

from spark_tpu.pipelines import Pipeline, PipelineError


def test_dependency_order_and_counts(spark):
    spark.createDataFrame(pa.table({
        "id": [1, 2, 3, 4], "spend": [50.0, 150.0, 300.0, 20.0]})) \
        .createOrReplaceTempView("pl_src")

    p = Pipeline(spark)

    # declared out of dependency order on purpose
    @p.materialized_view()
    def big_spenders():
        return p.read("pl_customers").filter("spend > 100")

    @p.materialized_view(name="pl_customers")
    def customers():
        return spark.table("pl_src")

    counts = p.run()
    assert counts == {"big_spenders": 2, "pl_customers": 4}
    out = spark.sql("SELECT id FROM big_spenders ORDER BY id").toArrow()
    assert out.column("id").to_pylist() == [2, 3]
    assert any("materialized" in e for e in p.events)


def test_cycle_detection(spark):
    p = Pipeline(spark)

    @p.materialized_view()
    def a():
        return p.read("b")

    @p.materialized_view()
    def b():
        return p.read("a")

    with pytest.raises(PipelineError, match="cycle"):
        p.run()


def test_append_flows_feed_table(spark):
    spark.createDataFrame(pa.table({"x": [1, 2]})) \
        .createOrReplaceTempView("pl_feed1")
    spark.createDataFrame(pa.table({"x": [3]})) \
        .createOrReplaceTempView("pl_feed2")

    p = Pipeline(spark)

    @p.table(name="pl_sink")
    def sink():
        return None

    @p.append_flow(target="pl_sink")
    def from_one():
        return spark.table("pl_feed1")

    @p.append_flow(target="pl_sink")
    def from_two():
        return spark.table("pl_feed2")

    counts = p.run()
    assert counts["pl_sink"] == 3
    vals = sorted(spark.table("pl_sink").toArrow().column("x").to_pylist())
    assert vals == [1, 2, 3]
    assert p.run()["pl_sink"] == 3  # full refresh is idempotent


def test_module_level_decorators(spark):
    import spark_tpu.pipelines as plm

    spark.createDataFrame(pa.table({"n": [10, 20]})) \
        .createOrReplaceTempView("pl_m_src")
    p = Pipeline(spark)
    with p:
        @plm.materialized_view(name="pl_m_out")
        def out():
            return spark.table("pl_m_src").selectExpr("n * 2 AS n2")

    assert p.run()["pl_m_out"] == 2
    assert sorted(spark.table("pl_m_out").toArrow()
                  .column("n2").to_pylist()) == [20, 40]


def test_append_flow_requires_table_target(spark):
    p = Pipeline(spark)
    with pytest.raises(PipelineError, match="not a declared table"):
        @p.append_flow(target="nope")
        def f():
            pass
