"""Mesh-native SPMD stage fusion (parallel/mesh_fusion.py +
mesh_exchange.py): MULTICHIP differential tests against the unfused mesh
path and the host shuffle oracle, the one-dispatch-per-stage regression
guard, the donated-send-buffer HBM watermark, and obs attribution under
shard_map.

The tier-1 harness runs 8 virtual CPU devices (conftest), so the
8-device tests run in CI; they skip gracefully on smaller device counts
while the 2-device variant keeps coverage."""

import gc

import jax
import numpy as np
import pyarrow as pa
import pytest

import spark_tpu.api.functions as F
from spark_tpu.obs.resources import GLOBAL_LEDGER
from spark_tpu.parallel import mesh_fusion as MF
from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC


def _need_devices(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} virtual devices")


@pytest.fixture()
def mesh_spark(spark):
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    yield spark
    for k in ("spark.tpu.fusion.enabled", "spark.tpu.fusion.minRows",
              "spark.tpu.fusion.mesh", "spark.tpu.mesh.enabled"):
        spark.conf.unset(k)


@pytest.fixture()
def mdata(mesh_spark):
    spark = mesh_spark
    rng = np.random.default_rng(17)
    n = 6000
    v = rng.integers(-50, 100, n)
    spark.createDataFrame(pa.table({
        "k": rng.integers(0, 13, n),
        "v": v,
        # nullable column: validity planes must survive the all-to-all
        "nv": pa.array([None if i % 7 == 0 else int(x)
                        for i, x in enumerate(v)], type=pa.int64()),
        "s": [f"cat{i % 5}" for i in range(n)],
    })).createOrReplaceTempView("mf_t")
    spark.createDataFrame(pa.table({
        "dk": np.arange(13, dtype=np.int64),
        "label": [f"lab{i % 3}" for i in range(13)],
    })).createOrReplaceTempView("mf_dim")
    return spark


def _modes(spark, build, sort_cols):
    """The same query in four modes: mesh-fused, mesh-legacy
    (materialize-then-collective), fusion-off mesh, and the host shuffle
    oracle — all must agree row-for-row."""
    outs = {}
    for mode, confs in (
            ("mesh_fused", {}),
            ("mesh_legacy", {"spark.tpu.fusion.mesh": "false"}),
            ("mesh_unfused", {"spark.tpu.fusion.enabled": "false"}),
            ("host", {"spark.tpu.mesh.enabled": "false"})):
        for k, val in confs.items():
            spark.conf.set(k, val)
        try:
            outs[mode] = (build().toPandas().sort_values(sort_cols)
                          .reset_index(drop=True))
        finally:
            for k in confs:
                spark.conf.unset(k)
    want = outs.pop("mesh_fused")
    for mode, got in outs.items():
        assert want.equals(got), f"{mode} diverged from mesh_fused"
    return want


# ---------------------------------------------------------------------------
# differentials: fused mesh vs unfused mesh vs host oracle
# ---------------------------------------------------------------------------

def test_mesh_fused_agg_differential(mdata):
    _need_devices(8)
    spark = mdata
    out = _modes(
        spark,
        lambda: (spark.sql("select k, v * 2 as v2, nv, s from mf_t "
                           "where v > 0")
                 .repartition(8, "k").groupBy("k")
                 .agg(F.sum("v2").alias("sv"), F.count("*").alias("c"),
                      F.sum("nv").alias("snv"))),
        ["k"])
    assert len(out) == 13


def test_mesh_fused_join_differential(mdata):
    """Shuffled hash join: BOTH sides redistribute over mesh exchanges
    (broadcast disabled) and the reduce-side join build/probe consumes
    the shard-resident exchange output."""
    _need_devices(4)
    spark = mdata
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", "-1")
    try:
        _modes(
            spark,
            lambda: spark.sql(
                "select label, sum(v) sv, count(*) c from mf_t "
                "join mf_dim on k = dk where v > 10 group by label"),
            ["label"])
    finally:
        spark.conf.unset("spark.sql.autoBroadcastJoinThreshold")


def test_mesh_fused_tpcds_q3_sharded_differential(mesh_spark, spark):
    """Sharded TPC-DS mini q3: the fact table redistributes over the
    8-device mesh before the join spine (the acceptance query)."""
    _need_devices(8)
    from tpcds_mini import register_tpcds

    register_tpcds(spark)
    spark.sql("select * from store_sales") \
        .repartition(8, "ss_item_sk") \
        .createOrReplaceTempView("mf_store_sales")
    q3 = """
        SELECT dt.d_year, item.i_brand_id AS brand_id,
               SUM(ss_ext_sales_price) AS sum_agg
        FROM date_dim dt, mf_store_sales store_sales, item
        WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
          AND store_sales.ss_item_sk = item.i_item_sk
          AND item.i_manufact_id = 28 AND dt.d_moy = 11
        GROUP BY dt.d_year, item.i_brand_id"""
    out = _modes(spark, lambda: spark.sql(q3), ["d_year", "brand_id"])
    assert len(out) > 0


def test_mesh_two_device_variant(mdata):
    """2-device CPU-mesh variant: the smallest mesh keeps tier-1
    coverage even when the harness runs under 8 devices."""
    _need_devices(2)
    spark = mdata
    _modes(
        spark,
        lambda: (spark.sql("select k, v + 1 as v1, s from mf_t "
                           "where v != 7")
                 .repartition(2, "k").groupBy("k")
                 .agg(F.sum("v1").alias("sv"))),
        ["k"])


# ---------------------------------------------------------------------------
# one sharded dispatch per stage per step
# ---------------------------------------------------------------------------

def _kind_delta(run):
    before = dict(KC.launches_by_kind)
    run()
    after = dict(KC.launches_by_kind)
    return {k: v - before.get(k, 0) for k, v in after.items()
            if v != before.get(k, 0)}


def test_mesh_single_dispatch_per_stage(mdata):
    """Acceptance: a scan→filter→project→mesh-shuffle stage executes as
    ONE SPMD dispatch per step — regardless of the input batch count —
    with no separate pipeline launch and no per-batch partition kernel."""
    _need_devices(4)
    spark = mdata
    q = lambda: (spark.sql("select k, v * 3 as v3 from mf_t "  # noqa: E731
                           "where v > 25").repartition(4, "k").toArrow())
    q()  # warm: compile the stage program, device-cache the scan
    delta = _kind_delta(q)
    assert delta.get("mesh_stage", 0) == 1, delta
    assert delta.get("pipeline", 0) == 0, delta
    assert sum(delta.values()) == 1, delta

    # the legacy composition pays a pipeline dispatch per map batch on
    # top of the collective (6000 rows / 4096-capacity tiles = 2)
    spark.conf.set("spark.tpu.fusion.mesh", "false")
    q()  # warm the legacy kernels
    legacy = _kind_delta(q)
    assert legacy.get("mesh_stage", 0) == 1, legacy
    assert legacy.get("pipeline", 0) == 2, legacy


def test_mesh_quota_retry_counts_as_extra_dispatch(mesh_spark, spark):
    """Pathological skew overflows the per-(src,dst) quota: the stage
    re-dispatches with a doubled quota and the KernelCache counts every
    attempt (the plan analyzer predicts the same count — see
    test_plan_analysis.test_mesh_exchange_prediction_exact)."""
    _need_devices(4)
    n = 6000
    spark.createDataFrame(pa.table({
        "k": np.ones(n, np.int64) * 5,  # every live row → one reducer
        "v": np.arange(n, dtype=np.int64),
    })).createOrReplaceTempView("mf_skew")
    q = lambda: (spark.sql("select k, v from mf_skew")  # noqa: E731
                 .repartition(4, "k").toArrow())
    q()
    delta = _kind_delta(q)
    report = (spark.sql("select k, v from mf_skew").repartition(4, "k")
              .query_execution.analysis_report())
    assert delta.get("mesh_stage", 0) >= 2, delta
    assert report.predicted_launches.get("mesh_stage") == \
        delta["mesh_stage"], (report.predicted_launches, delta)


# ---------------------------------------------------------------------------
# donated send buffers: the DeviceLedger watermark is the scoreboard
# ---------------------------------------------------------------------------

def test_mesh_stage_program_donates_send_buffers(mdata, monkeypatch):
    """donate_argnums rides the mesh stage program (cache key carries the
    donation flag) and the donated run's per-window HBM watermark sits
    BELOW the undonated oracle's: donated staging buffers release at
    dispatch (the arrays are invalidated), undonated ones overlap the
    received output tiles."""
    _need_devices(4)
    spark = mdata
    rng = np.random.default_rng(23)
    n = 40000
    spark.createDataFrame(pa.table({
        "k": rng.integers(0, 1 << 12, n),
        "v": rng.integers(0, 1000, n),
    })).createOrReplaceTempView("mf_big")
    q = lambda: (spark.sql("select k, v * 2 as v2 from mf_big "  # noqa: E731
                           "where v > 10").repartition(4, "k").toArrow())

    q()  # warm donated program
    donated_keys = [k for k in KC._cache
                    if k and k[0] == "mesh_stage" and k[-1] is True]
    assert donated_keys, "no mesh stage program compiled with donation"

    monkeypatch.setattr(MF, "DONATE_DEFAULT", False)
    q()  # warm undonated program
    undonated_keys = [k for k in KC._cache
                      if k and k[0] == "mesh_stage" and k[-1] is False]
    assert undonated_keys, "undonated oracle program never compiled"

    def window_peak():
        gc.collect()
        GLOBAL_LEDGER.begin_window()
        q()
        return GLOBAL_LEDGER.window_peak()

    peak_undonated = window_peak()
    monkeypatch.setattr(MF, "DONATE_DEFAULT", True)
    peak_donated = window_peak()
    # staged send planes: 2 int64 columns + mask over ≥P*shard_cap slots
    assert peak_undonated - peak_donated >= 1 << 19, \
        (peak_undonated, peak_donated)


# ---------------------------------------------------------------------------
# obs: the single SPMD dispatch attributes like the single-device path
# ---------------------------------------------------------------------------

def test_mesh_dispatch_attribution_total_matches_counter(mdata):
    """The mesh stage's launches re-bucket to the dispatching exchange
    (fused_members re-attribution included) and the per-operator
    attribution total equals the global KernelCache delta — no dispatch
    escapes the operator scope under shard_map."""
    _need_devices(4)
    spark = mdata

    def build():
        return (spark.sql("select k, v * 2 as v2 from mf_t where v > 0")
                .repartition(4, "k").groupBy("k")
                .agg(F.sum("v2").alias("sv")))

    build().toArrow()  # warm
    before = KC.launches
    df = build()
    df.toArrow()
    global_delta = KC.launches - before
    graph = df.query_execution.plan_graph()
    attributed = sum(v for nd in graph
                     for v in (nd.get("launches") or {}).values())
    assert attributed == global_delta
    mesh_attr = [nd for nd in graph
                 if (nd.get("launches") or {}).get("mesh_stage")]
    assert mesh_attr, "mesh_stage dispatch not attributed to any operator"


def test_mesh_zero_launch_obs_overhead(mdata):
    """The obs contract holds under shard_map: metrics + tracing add
    ZERO kernel launches to a mesh-fused query."""
    _need_devices(4)
    spark = mdata
    q = lambda: (spark.sql("select k, v * 2 as v2 from mf_t "  # noqa: E731
                           "where v > 0").repartition(4, "k").toArrow())

    def delta():
        q()  # warm
        return _kind_delta(q)

    spark.conf.set("spark.tpu.ui.operatorMetrics", "true")
    spark.conf.set("spark.tpu.trace.enabled", "true")
    try:
        with_obs = delta()
        spark.conf.set("spark.tpu.ui.operatorMetrics", "false")
        spark.conf.set("spark.tpu.trace.enabled", "false")
        without = delta()
        assert with_obs == without
    finally:
        spark.conf.unset("spark.tpu.ui.operatorMetrics")
        spark.conf.unset("spark.tpu.trace.enabled")
