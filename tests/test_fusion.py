"""Whole-stage kernel fusion (physical/fusion.py): differential tests
against the unfused operator-at-a-time oracle
(spark.tpu.fusion.enabled=false), plus dispatch-count regressions over the
KernelCache launch counters — the reference gates WholeStageCodegen the
same way (codegen on/off differential suites + codegen-metrics checks)."""

import numpy as np
import pyarrow as pa
import pytest

import spark_tpu.api.functions as F
from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC


@pytest.fixture()
def fusion_spark(spark):
    """Session fixture forcing the FUSED runtime path (the size gate
    `spark.tpu.fusion.minRows` would otherwise route test-sized partitions
    to the shared unfused kernels); restores conf after each test."""
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    yield spark
    spark.conf.unset("spark.tpu.fusion.enabled")
    spark.conf.unset("spark.tpu.fusion.minRows")


def _differential(spark, build_query, sort_cols):
    """Run the same query fused and unfused; compare row-for-row."""
    outs = {}
    for enabled in (True, False):
        spark.conf.set("spark.tpu.fusion.enabled", str(enabled).lower())
        outs[enabled] = build_query().toPandas() \
            .sort_values(sort_cols).reset_index(drop=True)
    spark.conf.unset("spark.tpu.fusion.enabled")
    got, want = outs[True], outs[False]
    assert list(got.columns) == list(want.columns)
    assert len(got) == len(want), f"{len(got)} vs {len(want)} rows"
    for c in got.columns:
        g, w = got[c].to_numpy(), want[c].to_numpy()
        if np.issubdtype(np.asarray(w).dtype, np.floating):
            # the fused path merges per-batch partials (associative
            # reordering of float adds); everything else must be identical
            np.testing.assert_allclose(g.astype(float), w.astype(float),
                                       rtol=1e-12, atol=1e-12)
        else:
            assert list(g) == list(w), f"column {c} differs"


@pytest.fixture()
def data(spark):
    rng = np.random.default_rng(7)
    n = 5000
    spark.createDataFrame(pa.table({
        "k": rng.integers(0, 13, n),
        "v": rng.integers(-50, 100, n),
        "f": rng.random(n),
        "s": [f"cat{i % 5}" for i in range(n)],
    })).createOrReplaceTempView("fu_t")
    dim = pa.table({
        "dk": np.arange(13, dtype=np.int64),
        "label": [f"lab{i % 3}" for i in range(13)],
    })
    spark.createDataFrame(dim).createOrReplaceTempView("fu_dim")
    return spark


def test_filter_project_agg_differential(fusion_spark, data):
    spark = data
    _differential(
        spark,
        lambda: spark.sql(
            "select k, sum(v * 2) sv, count(*) c, min(v) mn, max(v+1) mx, "
            "avg(f) af from fu_t where v > 0 group by k"),
        ["k"])


def test_ungrouped_agg_differential(fusion_spark, data):
    spark = data
    _differential(
        spark,
        lambda: spark.sql(
            "select count(*) c, sum(v) sv, min(v) mn from fu_t "
            "where v % 3 = 0"),
        ["c"])


def test_string_group_keys_differential(fusion_spark, data):
    spark = data
    _differential(
        spark,
        lambda: spark.sql(
            "select s, k, count(*) c, sum(v) sv from fu_t "
            "where v != 7 group by s, k"),
        ["s", "k"])


def test_join_plus_agg_differential(fusion_spark, data):
    spark = data
    _differential(
        spark,
        lambda: spark.sql(
            "select label, sum(v) sv, count(*) c from fu_t "
            "join fu_dim on k = dk where v > 10 group by label"),
        ["label"])


def test_limit_differential(fusion_spark, data):
    spark = data
    # deterministic limit: values are unique per row position
    _differential(
        spark,
        lambda: spark.sql(
            "select k + v * 100 as key2 from fu_t where v > 95 "
            "order by key2 limit 17"),
        ["key2"])


def test_tpcds_mini_q3_q7_differential(fusion_spark, spark):
    from tpcds_mini import register_tpcds

    register_tpcds(spark)
    q3 = """
        SELECT dt.d_year, item.i_brand_id AS brand_id,
               SUM(ss_ext_sales_price) AS sum_agg
        FROM date_dim dt, store_sales, item
        WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
          AND store_sales.ss_item_sk = item.i_item_sk
          AND item.i_manufact_id = 28 AND dt.d_moy = 11
        GROUP BY dt.d_year, item.i_brand_id"""
    q7 = """
        SELECT i.i_category, AVG(ss_quantity) AS agg1, COUNT(*) AS cnt
        FROM store_sales ss
        JOIN item i ON ss.ss_item_sk = i.i_item_sk
        JOIN date_dim d ON ss.ss_sold_date_sk = d.d_date_sk
        WHERE d.d_year = 1999
        GROUP BY i.i_category"""
    _differential(spark, lambda: spark.sql(q3), ["d_year", "brand_id"])
    _differential(spark, lambda: spark.sql(q7), ["i_category"])


# ---------------------------------------------------------------------------
# Dispatch-count regressions
# ---------------------------------------------------------------------------

def _kind_delta(run):
    """launches_by_kind delta around `run()`."""
    before = dict(KC.launches_by_kind)
    run()
    after = dict(KC.launches_by_kind)
    return {k: v - before.get(k, 0) for k, v in after.items()
            if v != before.get(k, 0)}


def test_fused_stage_single_launch_per_batch(fusion_spark, spark):
    """Acceptance: a scan→filter→project→partial-agg stage executes as ONE
    cached jitted program per input batch."""
    cap = 1 << 12  # the session fixture's spark.tpu.batch.capacity
    n_batches = 4
    rng = np.random.default_rng(3)
    t = pa.table({"k": rng.integers(0, 8, cap * n_batches),
                  "v": rng.integers(0, 100, cap * n_batches)})
    spark.conf.set("spark.tpu.fusion.enabled", "true")
    df = spark.createDataFrame(t)
    q = lambda: (df.filter(F.col("v") > 25)  # noqa: E731
                 .withColumn("v2", F.col("v") * 3)
                 .groupBy("k").agg(F.sum("v2").alias("s"))
                 .toArrow())
    q()  # warm: compile kernels, device-cache the scan
    delta = _kind_delta(q)
    # the fused stage: exactly one launch per input batch, and NO separate
    # pipeline launches for the stage's filter/project (the only pipeline
    # kernel left is the buffer→result finishing projection)
    assert delta.get("fused_agg", 0) == n_batches, delta
    assert delta.get("pipeline", 0) <= 1, delta
    # merge of per-batch partials + finish: small constant overhead
    total = sum(delta.values())
    assert total <= n_batches + 4, delta


def test_fusion_reduces_dispatches_vs_oracle(fusion_spark, spark):
    rng = np.random.default_rng(4)
    t = pa.table({"k": rng.integers(0, 8, 3000),
                  "v": rng.integers(0, 100, 3000)})
    df = spark.createDataFrame(t)

    def run():
        (df.filter(F.col("v") > 25).withColumn("v2", F.col("v") * 3)
         .groupBy("k").agg(F.sum("v2").alias("s")).toArrow())

    counts = {}
    for enabled in ("true", "false"):
        spark.conf.set("spark.tpu.fusion.enabled", enabled)
        run()  # warm this mode's kernels
        counts[enabled] = sum(_kind_delta(run).values())
    assert counts["true"] < counts["false"], counts


def test_structurally_identical_queries_share_kernels(fusion_spark, spark):
    """Two plans with the same shape (different attribute ids/tables) hit
    the same cache entries — zero compile misses on the second query."""
    rng = np.random.default_rng(5)

    def make(seed):
        t = pa.table({"a": rng.integers(0, 9, 2000),
                      "b": rng.integers(0, 50, 2000)})
        return spark.createDataFrame(t)

    spark.conf.set("spark.tpu.fusion.enabled", "true")

    def q(df):
        return (df.filter(F.col("b") > 5).groupBy("a")
                .agg(F.sum("b").alias("s")).toArrow())

    q(make(1))  # compiles
    misses_before = KC.misses
    q(make(2))  # structurally identical: every kernel is a cache hit
    assert KC.misses == misses_before


def test_adjacent_computes_collapse(fusion_spark, spark):
    """A ComputeExec over a ComputeExec must merge into one pipeline."""
    from spark_tpu.physical.operators import ComputeExec

    rng = np.random.default_rng(6)
    t = pa.table({"x": rng.integers(0, 100, 500)})
    df = (spark.createDataFrame(t)
          .withColumn("y", F.col("x") * 2)
          .filter(F.col("y") > 10)
          .select((F.col("y") + 1).alias("z")))
    plan = df.query_execution.physical
    for node in plan.iter_nodes():
        if isinstance(node, ComputeExec):
            assert not isinstance(node.child, ComputeExec), \
                plan.tree_string()
    out = df.toPandas()
    want = t.to_pandas()
    want["y"] = want.x * 2
    want = want[want.y > 10]
    assert sorted(out["z"]) == sorted((want.y + 1).tolist())


# ---------------------------------------------------------------------------
# Exchange map-side fusion: shuffle writes consume the fused stage
# ---------------------------------------------------------------------------
# Partition counts are deliberately NON-powers-of-two (3/5): the test env
# runs 8 virtual devices, so a power-of-two hash exchange would take the
# mesh all-to-all instead of the host shuffle path under test.

@pytest.fixture()
def xdata(spark):
    rng = np.random.default_rng(11)
    n = 6000
    spark.createDataFrame(pa.table({
        "k": rng.integers(0, 13, n),
        "v": rng.integers(-50, 100, n),
        "s": [f"cat{i % 5}" for i in range(n)],
    })).createOrReplaceTempView("ex_t")
    return spark


def test_exchange_fusion_hash_differential(fusion_spark, xdata):
    spark = xdata
    _differential(
        spark,
        lambda: (spark.sql("select k, v * 2 as v2, s from ex_t "
                           "where v > 0").repartition(5, "k")),
        ["k", "v2", "s"])


def test_exchange_fusion_rr_differential(fusion_spark, xdata):
    spark = xdata
    _differential(
        spark,
        lambda: (spark.sql("select k + 1 as k2, v from ex_t where v != 7")
                 .repartition(3)),
        ["k2", "v"])


def test_exchange_fusion_range_differential(fusion_spark, spark):
    import spark_tpu.api.functions as F

    def q():
        return (spark.range(0, 30000, 1, 3)
                .filter(F.col("id") % 7 != 0)
                .withColumn("y", F.col("id") * 3)
                .orderBy("id"))

    outs = {}
    for enabled in (True, False):
        spark.conf.set("spark.tpu.fusion.enabled", str(enabled).lower())
        outs[enabled] = q().toPandas().reset_index(drop=True)
    spark.conf.unset("spark.tpu.fusion.enabled")
    # global sort: row-for-row ordered equality, not just multiset
    assert outs[True].equals(outs[False])


def test_fused_range_bounds_sample_post_pipeline(fusion_spark, spark):
    """Fused range-exchange bounds sample the POST-pipeline key column:
    a selective filter no longer skews partition balance (pre-pipeline
    sampling saw the full input domain, so every surviving row landed in
    the top partition). Balance is read from the exchange's per-reducer
    stats BEFORE AQE coalescing can mask the skew."""
    from spark_tpu.physical.exchange import ShuffleExchangeExec

    df = (spark.range(0, 30000, 1, 3)
          .filter(F.col("id") >= 27000)
          .withColumn("y", F.col("id") * 2)
          .orderBy("id"))
    plan = df.query_execution.physical
    ex = next(n for n in plan.iter_nodes()
              if isinstance(n, ShuffleExchangeExec))
    assert ex.pipe_fusion is not None, plan.tree_string()
    df.query_execution.execute()
    sizes = [ex.last_stats[i] for i in sorted(ex.last_stats)]
    assert sum(sizes) == 3000
    # post-pipeline bounds split the SURVIVING domain: every reducer
    # gets a share, none hoards the whole filtered range (pre-pipeline
    # sampling put all 3000 rows in the last reducer)
    assert all(s > 0 for s in sizes), sizes
    assert max(sizes) <= 2 * (sum(sizes) / len(sizes)), sizes
    # and the global sort is still correct
    out = df.toPandas()
    assert list(out["id"]) == list(range(27000, 30000))


def test_fused_range_computed_key_fuses(fusion_spark, spark):
    """A COMPUTED sort key no longer blocks exchange fusion: bounds
    sample the pipeline output, so no pass-through input column is
    needed — and the fused plan matches the unfused oracle."""
    from spark_tpu.physical.exchange import ShuffleExchangeExec

    def q():
        return (spark.range(0, 20000, 1, 3)
                .filter(F.col("id") % 3 != 0)
                .select((F.col("id") * 2 + 1).alias("key2"))
                .orderBy("key2"))

    spark.conf.set("spark.tpu.fusion.enabled", "true")
    plan = q().query_execution.physical
    ex = next(n for n in plan.iter_nodes()
              if isinstance(n, ShuffleExchangeExec))
    assert ex.pipe_fusion is not None, plan.tree_string()
    outs = {}
    for enabled in (True, False):
        spark.conf.set("spark.tpu.fusion.enabled", str(enabled).lower())
        outs[enabled] = q().toPandas().reset_index(drop=True)
    spark.conf.unset("spark.tpu.fusion.enabled")
    assert outs[True].equals(outs[False])


def test_exchange_fused_single_dispatch_per_map_batch(fusion_spark, spark):
    """Acceptance: a scan→filter→project→shuffle-write map stage executes
    as ONE fused dispatch per input batch — no separate pipeline launch,
    no separate partition-id kernel."""
    cap = 1 << 12  # the session fixture's spark.tpu.batch.capacity
    n_batches = 4
    rng = np.random.default_rng(12)
    t = pa.table({"k": rng.integers(0, 9, cap * n_batches),
                  "v": rng.integers(0, 100, cap * n_batches)})
    df_base = spark.createDataFrame(t)
    spark.conf.set("spark.tpu.fusion.enabled", "true")
    q = lambda: (df_base.filter(F.col("v") > 25)  # noqa: E731
                 .withColumn("v2", F.col("v") * 3)
                 .repartition(5, "k").toArrow())
    q()  # warm: compile kernels, device-cache the scan
    delta = _kind_delta(q)
    assert delta.get("fused_shuffle", 0) == n_batches, delta
    assert delta.get("pipeline", 0) == 0, delta
    assert sum(delta.values()) == n_batches, delta

    # the oracle pays >=2 dispatches per map batch for the same work
    spark.conf.set("spark.tpu.fusion.exchange", "false")
    try:
        q()  # warm the unfused kernels
        unfused = _kind_delta(q)
        assert unfused.get("fused_shuffle", 0) == 0, unfused
        assert unfused.get("pipeline", 0) == n_batches, unfused
        assert sum(unfused.values()) >= 2 * n_batches, unfused
    finally:
        spark.conf.unset("spark.tpu.fusion.exchange")


def test_exchange_fusion_minrows_gate(fusion_spark, spark):
    """Partitions under spark.tpu.fusion.minRows take the shared unfused
    kernels at runtime even though the PLAN carries the fused exchange."""
    rng = np.random.default_rng(13)
    t = pa.table({"k": rng.integers(0, 9, 3000),
                  "v": rng.integers(0, 100, 3000)})
    df = spark.createDataFrame(t)
    spark.conf.set("spark.tpu.fusion.enabled", "true")
    spark.conf.set("spark.tpu.fusion.minRows", str(1 << 17))
    q = lambda: (df.filter(F.col("v") > 25)  # noqa: E731
                 .repartition(5, "k").toArrow())
    q()
    delta = _kind_delta(q)
    assert delta.get("fused_shuffle", 0) == 0, delta
    assert delta.get("pipeline", 0) == 1, delta


def test_shuffle_read_batches_seed_dense_range_memo(fusion_spark, xdata):
    """Map-side column stats seed the dense-range memo at build time for
    the PLAN-REACHABLE dense candidates (annotate_exchange_stat_cols):
    the downstream aggregate's single-int grouping key never launches
    the krange3 probe on shuffle-READ batches, even though the arrays
    are fresh every run — while columns no dense decision can consult
    stop paying the per-append host min/max entirely."""
    from spark_tpu.exec.context import ExecContext
    from spark_tpu.physical.exchange import ShuffleExchangeExec
    from spark_tpu.physical.operators import dense_range_stats

    spark = xdata
    spark.conf.set("spark.tpu.fusion.enabled", "true")
    df = (spark.sql("select k, v from ex_t where v > 0")
          .repartition(5, "k").groupBy("k").agg(F.sum("v").alias("sv")))
    plan = df.query_execution.physical
    ex = next(n for n in plan.iter_nodes()
              if isinstance(n, ShuffleExchangeExec))
    kpos = [i for i, a in enumerate(ex.output) if a.name == "k"]
    assert ex.stat_cols == kpos, ex.stat_cols
    # execute the exchange subtree: its output IS the shuffle-read side
    parts = ex.execute(ExecContext(conf=spark.conf))
    before = KC.launches_by_kind.get("krange3", 0)
    for part in parts:
        for b in part:
            kmin, kmax, any_live = dense_range_stats(
                b.columns[kpos[0]], b.row_mask, b.capacity)
            live = np.asarray(
                b.columns[kpos[0]].data)[np.asarray(b.row_mask)]
            if len(live):
                assert any_live
                assert kmin <= int(live.min()) <= int(live.max()) <= kmax
    assert KC.launches_by_kind.get("krange3", 0) == before


def test_exchange_fusion_cluster_differential(fusion_spark, spark):
    """The cluster worker runs the SAME fused map program: fused vs
    unfused cluster runs agree, and the worker ships fused_shuffle
    launch deltas back to the driver."""
    import spark_tpu.api.functions as F
    from spark_tpu.api.session import TpuSession
    from spark_tpu.exec.cluster import LocalCluster

    rng = np.random.default_rng(14)
    t = pa.table({"k": rng.integers(0, 11, 6000),
                  "v": rng.integers(-20, 80, 6000)})
    outs = {}
    worker_kinds = {}
    for enabled in ("true", "false"):
        s = TpuSession(f"fuse-cluster-{enabled}", {
            "spark.sql.shuffle.partitions": "3",
            "spark.tpu.batch.capacity": 1 << 12,
            "spark.sql.adaptive.enabled": "false",
            "spark.tpu.fusion.enabled": enabled,
            "spark.tpu.fusion.minRows": "0",
        })
        cluster = LocalCluster(num_workers=2)
        s.attachSqlCluster(cluster)
        try:
            s.createDataFrame(t).createOrReplaceTempView("xc_t")
            df = (s.sql("select k, v * 2 as v2 from xc_t where v > 0")
                  .repartition(3, "k")
                  .groupBy("k").agg(F.sum("v2").alias("sv")))
            outs[enabled] = (df.toPandas().sort_values("k")
                             .reset_index(drop=True))
            remote = s._metrics.snapshot()["counters"].get(
                "scheduler.stages_remote", 0)
            assert remote >= 1, "map stage never shipped to a worker"
            worker_kinds[enabled] = dict(
                df.query_execution._last_ctx.worker_kernel_kinds or {})
        finally:
            s.stop()
    assert outs["true"].equals(outs["false"])
    assert worker_kinds["true"].get("fused_shuffle", 0) >= 1, worker_kinds
    assert worker_kinds["false"].get("fused_shuffle", 0) == 0, worker_kinds


def test_string_minmax_fused_differential(fusion_spark, xdata):
    """String MIN/MAX no longer falls back to the unfused path: the fused
    kernel reduces in rank space with the inverse-rank lut as an aux
    input, and results match the oracle exactly."""
    from spark_tpu.physical.fusion import FusedAggregateExec

    spark = xdata
    q = ("select k, min(s) mn, max(s) mx, count(*) c from ex_t "
         "where v > 0 group by k")
    spark.conf.set("spark.tpu.fusion.enabled", "true")
    plan = spark.sql(q).query_execution.physical
    assert any(isinstance(n, FusedAggregateExec) for n in plan.iter_nodes()), \
        plan.tree_string()
    _differential(spark, lambda: spark.sql(q), ["k"])
    # ungrouped variant exercises the whole-tile reduce
    _differential(
        spark,
        lambda: spark.sql("select min(s) mn, max(s) mx from ex_t "
                          "where v % 3 = 0"),
        ["mn"])


def test_dense_range_sync_memoized_across_batches(fusion_spark, spark):
    """Repeated executions over device-cached scan batches must not re-sync
    the dense-range scalars: the krange kernel fires once per distinct
    column identity, not once per run."""
    rng = np.random.default_rng(8)
    t = pa.table({"k": rng.integers(0, 16, 4000),
                  "v": rng.integers(0, 10, 4000)})
    spark.conf.set("spark.tpu.fusion.enabled", "true")
    df = spark.createDataFrame(t)

    def run():
        df.groupBy("k").agg(F.count("*").alias("c")).toArrow()

    run()  # warm: scan batches device-cached, ranges memoized
    delta = _kind_delta(run)
    assert delta.get("krange3", 0) == 0, delta
