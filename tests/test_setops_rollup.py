"""Set operations and grouping analytics tests."""

import pyarrow as pa
import pytest


@pytest.fixture()
def nums(spark):
    spark.createDataFrame(pa.table({"x": [1, 2, 3, 4]})) \
        .createOrReplaceTempView("ta")
    spark.createDataFrame(pa.table({"x": [3, 4, 5]})) \
        .createOrReplaceTempView("tb")
    spark.createDataFrame(pa.table({
        "region": ["w", "w", "e", "e", "e"],
        "product": ["p1", "p2", "p1", "p1", "p2"],
        "amount": [10, 20, 30, 40, 50],
    })).createOrReplaceTempView("sales_r")
    return spark


def q(spark, text):
    return spark.sql(text).toArrow().to_pydict()


def test_intersect(nums):
    out = q(nums, "SELECT x FROM ta INTERSECT SELECT x FROM tb ORDER BY x")
    assert out["x"] == [3, 4]


def test_except(nums):
    out = q(nums, "SELECT x FROM ta EXCEPT SELECT x FROM tb ORDER BY x")
    assert out["x"] == [1, 2]


def test_minus_alias(nums):
    out = q(nums, "SELECT x FROM ta MINUS SELECT x FROM tb ORDER BY x")
    assert out["x"] == [1, 2]


def test_rollup(nums):
    out = q(nums, """
        SELECT region, product, sum(amount) AS s
        FROM sales_r GROUP BY ROLLUP(region, product)
        ORDER BY region NULLS LAST, product NULLS LAST""")
    rows = list(zip(out["region"], out["product"], out["s"]))
    assert (None, None, 150) in rows           # grand total
    assert ("e", None, 120) in rows            # region subtotal
    assert ("w", None, 30) in rows
    assert ("e", "p1", 70) in rows             # leaf
    assert len(rows) == 4 + 2 + 1              # leaves + regions + total


def test_cube(nums):
    out = q(nums, """
        SELECT region, product, sum(amount) AS s
        FROM sales_r GROUP BY CUBE(region, product)""")
    rows = set(zip(out["region"], out["product"], out["s"]))
    assert (None, "p1", 80) in rows            # product subtotal (cube only)
    assert (None, "p2", 70) in rows
    assert (None, None, 150) in rows
    assert len(rows) == 4 + 2 + 2 + 1


def test_grouping_sets(nums):
    out = q(nums, """
        SELECT region, product, sum(amount) AS s
        FROM sales_r GROUP BY GROUPING SETS ((region), (product))""")
    rows = set(zip(out["region"], out["product"], out["s"]))
    assert ("w", None, 30) in rows
    assert (None, "p1", 80) in rows
    assert len(rows) == 2 + 2


def test_union_type_widening(nums):
    out = q(nums, "SELECT 1 AS v UNION ALL SELECT 2.5 UNION ALL SELECT x FROM ta WHERE x = 1")
    assert sorted(out["v"]) == [1.0, 1.0, 2.5]


def test_intersect_type_widening(nums):
    out = q(nums, "SELECT CAST(3 AS BIGINT) AS v INTERSECT SELECT 3")
    assert out["v"] == [3]


def test_grouping_function(nums):
    out = q(nums, """
        SELECT region, grouping(region) AS gr, grouping(product) AS gp,
               sum(amount) AS s
        FROM sales_r GROUP BY ROLLUP(region, product)""")
    rows = set(zip(out["region"], out["gr"], out["gp"], out["s"]))
    assert (None, 1, 1, 150) in rows        # grand total: both rolled up
    assert ("e", 0, 1, 120) in rows         # region subtotal
    assert ("e", 0, 0, 70) in rows          # leaf row (e, p1)


def test_grouping_id(nums):
    out = q(nums, """
        SELECT region, product, grouping_id() AS gid, sum(amount) AS s
        FROM sales_r GROUP BY CUBE(region, product)""")
    rows = set(zip(out["region"], out["product"], out["gid"], out["s"]))
    assert ("e", "p1", 0, 70) in rows       # fully grouped
    assert ("e", None, 1, 120) in rows      # product rolled up → bit 0
    assert (None, "p1", 2, 80) in rows      # region rolled up → bit 1
    assert (None, None, 3, 150) in rows


def test_rollup_dataframe_api(nums):
    from spark_tpu.api import functions as F

    df = nums.table("sales_r")
    out = df.rollup(df["region"], df["product"]) \
            .agg(F.sum(df["amount"]).alias("s"),
                 F.grouping_id().alias("gid")).toArrow().to_pydict()
    rows = set(zip(out["region"], out["product"], out["gid"], out["s"]))
    assert (None, None, 3, 150) in rows
    assert ("w", None, 1, 30) in rows
    assert len(rows) == 4 + 2 + 1
