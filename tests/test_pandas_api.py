"""pandas-API shim tests (reference: pyspark.pandas suites, reduced)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest


@pytest.fixture()
def psdf(spark):
    import spark_tpu.pandas as ps

    pdf = pd.DataFrame({
        "city": ["sf", "sf", "nyc", "nyc", "la"],
        "pop": [10, 20, 30, 40, 50],
        "area": [1.0, 2.0, 3.0, 4.0, 5.0],
    })
    return ps.from_pandas(pdf)


def test_select_filter_len(psdf):
    assert psdf.shape == (5, 3)
    big = psdf[psdf["pop"] > 25]
    assert len(big) == 3
    assert set(big[["city"]].to_pandas()["city"]) == {"nyc", "la"}


def test_assign_and_arithmetic(psdf):
    out = psdf.assign(density=psdf["pop"] / psdf["area"]).to_pandas()
    assert list(out["density"]) == [10.0] * 5


def test_groupby_agg(psdf):
    out = (psdf.groupby("city").agg({"pop": "sum", "area": "mean"})
           .sort_values("city").to_pandas())
    assert list(out["city"]) == ["la", "nyc", "sf"]
    assert list(out["pop"]) == [50, 70, 30]


def test_series_reductions(psdf):
    assert psdf["pop"].sum() == 150
    assert psdf["pop"].mean() == 30
    assert psdf["city"].nunique() == 3


def test_merge(psdf, spark):
    import spark_tpu.pandas as ps

    other = ps.from_pandas(pd.DataFrame({
        "city": ["sf", "nyc"], "state": ["CA", "NY"]}))
    out = psdf.merge(other, on="city").sort_values(["city", "pop"]).to_pandas()
    assert len(out) == 4
    assert set(out["state"]) == {"CA", "NY"}


def test_value_counts_dropna(psdf):
    vc = psdf.value_counts("city")
    assert vc.iloc[0]["count"] == 2
    import spark_tpu.pandas as ps

    pdf = pd.DataFrame({"x": [1.0, None, 3.0]})
    assert len(ps.from_pandas(pdf).dropna()) == 2


# ---------------------------------------------------------------------------
# r4 breadth
# ---------------------------------------------------------------------------

def test_str_accessor_and_astype(spark):
    import spark_tpu.pandas as ps

    df = ps.from_pandas(pd.DataFrame({
        "s": ["Alpha", "beta ", "Gamma"], "v": [1.5, 2.5, 3.5]}))
    up = df["s"].str.upper().to_pandas()
    assert list(up) == ["ALPHA", "BETA ", "GAMMA"]
    assert list(df["s"].str.strip().str.len().to_pandas()) == [5, 4, 5]
    assert list(df["s"].str.contains("et").to_pandas()) == \
        [False, True, False]
    assert list(df["v"].astype(int).to_pandas()) == [1, 2, 3]
    assert list(df["v"].round().to_pandas()) == [2.0, 3.0, 4.0]  # SQL HALF_UP


def test_series_apply_and_stats(spark):
    import spark_tpu.pandas as ps

    df = ps.from_pandas(pd.DataFrame({"x": [1.0, 2.0, 3.0, 4.0]}))
    assert list(df["x"].apply(lambda v: v * 10).to_pandas()) == \
        [10.0, 20.0, 30.0, 40.0]
    assert df["x"].std() == pd.Series([1.0, 2, 3, 4]).std()
    assert sorted(df["x"].unique()) == [1.0, 2.0, 3.0, 4.0]


def test_frame_query_pivot_and_io(spark, tmp_path):
    import spark_tpu.pandas as ps

    pdf = pd.DataFrame({
        "k": ["a", "a", "b", "b"], "grp": ["x", "y", "x", "y"],
        "v": [1.0, 2.0, 3.0, 4.0]})
    df = ps.from_pandas(pdf)
    q = df.query("v > 1.5").to_pandas()
    assert len(q) == 3
    piv = df.pivot_table(values="v", index="k", columns="grp",
                         aggfunc="sum").to_pandas()
    assert set(piv.columns) >= {"k", "x", "y"}
    big = df.nlargest(1, "v").to_pandas()
    assert big["v"].iloc[0] == 4.0
    p = str(tmp_path / "ps.parquet")
    df.to_parquet(p)
    back = ps.read_parquet(p).to_pandas()
    assert len(back) == 4
    two = ps.concat([df, df]).to_pandas()
    assert len(two) == 8
    nn = df.nunique()
    assert nn["k"] == 2 and nn["grp"] == 2 and nn["v"] == 4
