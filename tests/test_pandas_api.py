"""pandas-API shim tests (reference: pyspark.pandas suites, reduced)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest


@pytest.fixture()
def psdf(spark):
    import spark_tpu.pandas as ps

    pdf = pd.DataFrame({
        "city": ["sf", "sf", "nyc", "nyc", "la"],
        "pop": [10, 20, 30, 40, 50],
        "area": [1.0, 2.0, 3.0, 4.0, 5.0],
    })
    return ps.from_pandas(pdf)


def test_select_filter_len(psdf):
    assert psdf.shape == (5, 3)
    big = psdf[psdf["pop"] > 25]
    assert len(big) == 3
    assert set(big[["city"]].to_pandas()["city"]) == {"nyc", "la"}


def test_assign_and_arithmetic(psdf):
    out = psdf.assign(density=psdf["pop"] / psdf["area"]).to_pandas()
    assert list(out["density"]) == [10.0] * 5


def test_groupby_agg(psdf):
    out = (psdf.groupby("city").agg({"pop": "sum", "area": "mean"})
           .sort_values("city").to_pandas())
    assert list(out["city"]) == ["la", "nyc", "sf"]
    assert list(out["pop"]) == [50, 70, 30]


def test_series_reductions(psdf):
    assert psdf["pop"].sum() == 150
    assert psdf["pop"].mean() == 30
    assert psdf["city"].nunique() == 3


def test_merge(psdf, spark):
    import spark_tpu.pandas as ps

    other = ps.from_pandas(pd.DataFrame({
        "city": ["sf", "nyc"], "state": ["CA", "NY"]}))
    out = psdf.merge(other, on="city").sort_values(["city", "pop"]).to_pandas()
    assert len(out) == 4
    assert set(out["state"]) == {"CA", "NY"}


def test_value_counts_dropna(psdf):
    vc = psdf.value_counts("city")
    assert vc.iloc[0]["count"] == 2
    import spark_tpu.pandas as ps

    pdf = pd.DataFrame({"x": [1.0, None, 3.0]})
    assert len(ps.from_pandas(pdf).dropna()) == 2
