"""Kafka-contract segment-log source (reference: connector/kafka-0-10-sql
KafkaMicroBatchStream / KafkaSourceOffset): per-partition offsets,
arbitrary replay, partition discovery mid-stream, exactly-once recovery
from a checkpoint."""

import pyarrow as pa
import pytest

from spark_tpu.streaming.segment_log import SegmentLogSource, SegmentLogWriter


def _sink_rows(spark, name):
    t = spark.sql(f"select * from {name}").toArrow()
    return t.to_pylist()


class TestLogPrimitives:
    def test_writer_offsets_and_segment_roll(self, tmp_path):
        w = SegmentLogWriter(str(tmp_path), segment_max_records=2)
        offs = [w.send(0, f"v{i}") for i in range(5)]
        assert offs == [0, 1, 2, 3, 4]
        src = SegmentLogSource(str(tmp_path))
        assert src.latest_offset() == {"0": 5}
        # three segments: 0-1, 2-3, 4
        assert len(src._segments(0)) == 3

    def test_replay_arbitrary_range(self, tmp_path):
        w = SegmentLogWriter(str(tmp_path), segment_max_records=3)
        for i in range(10):
            w.send(0, f"v{i}", key=f"k{i}")
        src = SegmentLogSource(str(tmp_path))
        t = src.get_batch({"0": 4}, {"0": 8})
        rows = t.to_pylist()
        assert [r["offset"] for r in rows] == [4, 5, 6, 7]
        assert [r["value"] for r in rows] == ["v4", "v5", "v6", "v7"]

    def test_starting_offsets_modes(self, tmp_path):
        w = SegmentLogWriter(str(tmp_path))
        for i in range(4):
            w.send(0, f"v{i}")
        assert SegmentLogSource(str(tmp_path)).initial_offset() == {}
        assert SegmentLogSource(str(tmp_path),
                                "latest").initial_offset() == {"0": 4}
        assert SegmentLogSource(
            str(tmp_path), '{"0": 2}').initial_offset() == {"0": 2}

    def test_writer_resumes_existing_log(self, tmp_path):
        w1 = SegmentLogWriter(str(tmp_path), segment_max_records=2)
        for i in range(3):
            w1.send(0, f"a{i}")
        # a NEW writer process continues at the right offset
        w2 = SegmentLogWriter(str(tmp_path), segment_max_records=2)
        assert w2.send(0, "b0") == 3


class TestStreaming:
    def test_stream_two_partitions(self, spark, tmp_path):
        w = SegmentLogWriter(str(tmp_path / "topic"))
        for i in range(3):
            w.send(0, f"p0-{i}")
        for i in range(2):
            w.send(1, f"p1-{i}")
        df = spark.readStream.format("segment-log").load(
            str(tmp_path / "topic"))
        q = (df.writeStream.format("memory").queryName("sl1")
             .outputMode("append").start())
        try:
            q.processAllAvailable()
            rows = _sink_rows(spark, "sl1")
            got = sorted((r["partition"], r["offset"], r["value"])
                         for r in rows)
            assert got == [(0, 0, "p0-0"), (0, 1, "p0-1"), (0, 2, "p0-2"),
                           (1, 0, "p1-0"), (1, 1, "p1-1")]
        finally:
            q.stop()

    def test_partition_added_mid_stream(self, spark, tmp_path):
        """Partition discovery between batches: a partition created
        AFTER the query started is picked up from its earliest offset
        (the Kafka rebalance-on-discovery contract)."""
        root = str(tmp_path / "topic")
        w = SegmentLogWriter(root)
        w.send(0, "first")
        df = spark.readStream.format("segment-log").load(root)
        q = (df.writeStream.format("memory").queryName("sl2")
             .outputMode("append").start())
        try:
            q.processAllAvailable()
            assert len(_sink_rows(spark, "sl2")) == 1
            # new partition + more data on the old one, mid-stream
            w.send(2, "late-part-0")
            w.send(2, "late-part-1")
            w.send(0, "second")
            q.processAllAvailable()
            rows = _sink_rows(spark, "sl2")
            got = sorted((r["partition"], r["offset"], r["value"])
                         for r in rows)
            assert got == [(0, 0, "first"), (0, 1, "second"),
                           (2, 0, "late-part-0"), (2, 1, "late-part-1")]
        finally:
            q.stop()

    def test_checkpoint_recovery_no_loss_no_dupes(self, spark, tmp_path):
        """The exactly-once bar: stop after committed batches, write
        more (including a brand-new partition), restart from the
        checkpoint — every record delivered exactly once across the two
        runs."""
        root = str(tmp_path / "topic")
        ck = str(tmp_path / "ckpt")
        w = SegmentLogWriter(root)
        for i in range(3):
            w.send(0, f"a{i}")

        seen: list[tuple] = []

        def sink(batch_df, epoch):
            seen.extend((r["partition"], r["offset"], r["value"])
                        for r in batch_df.collect())

        df = spark.readStream.format("segment-log").load(root)
        q = (df.writeStream.foreachBatch(sink)
             .option("checkpointLocation", ck).start())
        q.processAllAvailable()
        q.stop()
        assert sorted(seen) == [(0, 0, "a0"), (0, 1, "a1"), (0, 2, "a2")]

        # while the query is DOWN: more data + a new partition
        w.send(0, "a3")
        w2 = SegmentLogWriter(root)
        w2.send(1, "b0")

        df2 = spark.readStream.format("segment-log").load(root)
        q2 = (df2.writeStream.foreachBatch(sink)
              .option("checkpointLocation", ck).start())
        try:
            q2.processAllAvailable()
        finally:
            q2.stop()
        assert sorted(seen) == [
            (0, 0, "a0"), (0, 1, "a1"), (0, 2, "a2"), (0, 3, "a3"),
            (1, 0, "b0")], seen

    def test_starting_offsets_replay_in_query(self, spark, tmp_path):
        root = str(tmp_path / "topic")
        w = SegmentLogWriter(root)
        for i in range(6):
            w.send(0, f"v{i}")
        df = (spark.readStream.format("segment-log")
              .option("startingOffsets", '{"0": 4}').load(root))
        q = (df.writeStream.format("memory").queryName("sl4")
             .outputMode("append").start())
        try:
            q.processAllAvailable()
            rows = _sink_rows(spark, "sl4")
            assert sorted(r["value"] for r in rows) == ["v4", "v5"]
        finally:
            q.stop()
