"""Observability tests: listener bus, query events, event log replay."""

import os

import pyarrow as pa
import pytest

from spark_tpu.exec.listener import (
    EventLoggingListener, HistoryReader, QueryExecutionListener,
)


def test_query_listener(spark):
    seen = []

    class L(QueryExecutionListener):
        def on_success(self, ev):
            seen.append(ev)

        def on_failure(self, ev):
            seen.append(ev)

    l = L()
    spark.listener_bus.register(l)
    try:
        df = spark.createDataFrame(pa.table({"x": [1, 2, 3]}))
        df.toArrow()
        spark.listener_bus.wait_empty()
        assert any(e.event == "querySucceeded" for e in seen)
        ok = [e for e in seen if e.event == "querySucceeded"][0]
        assert ok.duration_ms is not None
        assert "execution" in ok.phases
        assert "LocalTableScan" in ok.plan
    finally:
        spark.listener_bus.unregister(l)


def test_failure_event(spark):
    seen = []
    spark.listener_bus.register(lambda ev: seen.append(ev))
    try:
        with pytest.raises(Exception):
            spark.sql("SELECT missing_col FROM nonexistent_xyz").toArrow()
        spark.listener_bus.wait_empty()
        assert any(e.event == "queryFailed" for e in seen)
    finally:
        spark.listener_bus._listeners.clear()


def test_event_log_and_history(spark, tmp_path):
    log_dir = str(tmp_path / "events")
    el = EventLoggingListener(log_dir, app_id="testapp")
    spark.listener_bus.register(el)
    try:
        spark.createDataFrame(pa.table({"x": [1]})).toArrow()
        spark.createDataFrame(pa.table({"x": [2]})).toArrow()
        spark.listener_bus.wait_empty()
        h = HistoryReader(log_dir)
        apps = h.applications()
        assert apps == ["app-testapp.jsonl"]
        summary = h.summary(apps[0])
        assert summary["queries"] >= 2
        assert summary["total_duration_ms"] > 0
    finally:
        spark.listener_bus.unregister(el)
