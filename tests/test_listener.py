"""Observability tests: listener bus, query events, event log replay."""

import os

import pyarrow as pa
import pytest

from spark_tpu.exec.listener import (
    EventLoggingListener, HistoryReader, QueryExecutionListener,
)


def test_query_listener(spark):
    seen = []

    class L(QueryExecutionListener):
        def on_success(self, ev):
            seen.append(ev)

        def on_failure(self, ev):
            seen.append(ev)

    l = L()
    spark.listener_bus.register(l)
    try:
        df = spark.createDataFrame(pa.table({"x": [1, 2, 3]}))
        df.toArrow()
        spark.listener_bus.wait_empty()
        assert any(e.event == "querySucceeded" for e in seen)
        ok = [e for e in seen if e.event == "querySucceeded"][0]
        assert ok.duration_ms is not None
        assert "execution" in ok.phases
        assert "LocalTableScan" in ok.plan
    finally:
        spark.listener_bus.unregister(l)


def test_failure_event(spark):
    seen = []
    spark.listener_bus.register(lambda ev: seen.append(ev))
    try:
        with pytest.raises(Exception):
            spark.sql("SELECT missing_col FROM nonexistent_xyz").toArrow()
        spark.listener_bus.wait_empty()
        assert any(e.event == "queryFailed" for e in seen)
    finally:
        spark.listener_bus._listeners.clear()


def test_event_log_and_history(spark, tmp_path):
    log_dir = str(tmp_path / "events")
    el = EventLoggingListener(log_dir, app_id="testapp")
    spark.listener_bus.register(el)
    try:
        spark.createDataFrame(pa.table({"x": [1]})).toArrow()
        spark.createDataFrame(pa.table({"x": [2]})).toArrow()
        spark.listener_bus.wait_empty()
        h = HistoryReader(log_dir)
        apps = h.applications()
        assert apps == ["app-testapp.jsonl"]
        summary = h.summary(apps[0])
        assert summary["queries"] >= 2
        assert summary["total_duration_ms"] > 0
    finally:
        spark.listener_bus.unregister(el)


def test_history_server_ui(tmp_path):
    import json
    import urllib.request

    import pyarrow as pa

    from spark_tpu.api.session import TpuSession
    from spark_tpu.exec.history_server import HistoryServer

    log_dir = str(tmp_path / "events")
    s = TpuSession("hsui", {"spark.eventLog.enabled": "true",
                            "spark.eventLog.dir": log_dir})
    s.createDataFrame(pa.table({"x": [1, 2, 3]})) \
        .createOrReplaceTempView("hs_t")
    s.sql("SELECT sum(x) AS s FROM hs_t").collect()
    s.listener_bus.wait_empty()

    hs = HistoryServer(log_dir, port=0).start()
    try:
        base = f"http://127.0.0.1:{hs.port}"
        apps = json.loads(urllib.request.urlopen(
            base + "/api/applications", timeout=10).read())
        assert len(apps) == 1 and apps[0]["queries"] >= 1
        app_id = apps[0]["id"]
        index = urllib.request.urlopen(base + "/", timeout=10).read()
        assert app_id.encode() in index
        app_page = urllib.request.urlopen(
            base + f"/app?id={app_id}", timeout=10).read()
        assert b"OK" in app_page
        qpage = urllib.request.urlopen(
            base + f"/query?id={app_id}&n=0", timeout=10).read()
        assert b"Phases" in qpage and b"HashAggregate" in qpage
    finally:
        hs.stop()


def test_live_ui_serves_session_queries(spark):
    """Live SparkUI (exec/ui.py): bus events render without event-log
    files (AppStatusListener/SparkUI roles)."""
    import json
    import urllib.request

    import pyarrow as pa

    from spark_tpu.exec.ui import SparkUI

    ui = SparkUI(spark).start()
    try:
        spark.createDataFrame(pa.table({"x": [1, 2, 3]})) \
            .createOrReplaceTempView("ui_t")
        spark.sql("SELECT sum(x) AS s FROM ui_t").toArrow()
        spark.listener_bus.wait_empty()
        api = json.loads(urllib.request.urlopen(
            ui.url + "api/applications", timeout=10).read())
        assert api and api[0]["queries"] >= 1
        index = urllib.request.urlopen(ui.url, timeout=10).read().decode()
        assert "Application" in index
        app = urllib.request.urlopen(
            ui.url + f"app?id={api[0]['id']}", timeout=10).read().decode()
        assert "OK" in app
        detail = urllib.request.urlopen(
            ui.url + f"query?id={api[0]['id']}&n=0", timeout=10) \
            .read().decode()
        assert "Phases" in detail and "Plan" in detail
    finally:
        ui.stop()
