"""Avro/XML sources + the thriftserver-role SQL endpoint (reference:
connector/avro/AvroFileFormat.scala, connector/xml XmlFileFormat,
sql/hive-thriftserver HiveThriftServer2 + the JDBC/ODBC role)."""

import numpy as np
import pyarrow as pa
import pytest


class TestAvro:
    def _table(self):
        return pa.table({
            "i": pa.array([1, 2, None], pa.int64()),
            "f": pa.array([1.5, None, -2.25], pa.float64()),
            "s": pa.array(["a", "b''c", None], pa.string()),
            "b": pa.array([True, None, False], pa.bool_()),
        })

    def test_roundtrip_codec(self, tmp_path):
        from spark_tpu.io.avro import read_avro, write_avro

        t = self._table()
        for codec in ("null", "deflate"):
            p = str(tmp_path / f"t_{codec}.avro")
            write_avro(p, t, codec=codec)
            back = read_avro(p)
            assert back.to_pylist() == t.to_pylist()

    def test_multi_block(self, tmp_path):
        from spark_tpu.io.avro import read_avro, write_avro

        n = 10_000
        rng = np.random.default_rng(0)
        t = pa.table({"x": rng.integers(0, 1 << 40, n),
                      "y": rng.random(n)})
        p = str(tmp_path / "big.avro")
        write_avro(p, t, block_rows=512)
        back = read_avro(p)
        assert back.num_rows == n
        assert back.column("x").to_pylist() == t.column("x").to_pylist()

    def test_reader_writer_through_session(self, spark, tmp_path):
        t = self._table()
        df = spark.createDataFrame(t)
        out = str(tmp_path / "sess.avro")
        df.write.avro(out)
        back = spark.read.format("avro").load(out)
        assert sorted(map(str, back.toArrow().to_pylist())) == \
            sorted(map(str, t.to_pylist()))
        # SQL over the avro relation
        back.createOrReplaceTempView("av")
        n = spark.sql("select count(*) c from av where i is not null") \
            .toArrow().to_pylist()[0]["c"]
        assert n == 2

    def test_date_timestamp_logical_types(self, tmp_path):
        import datetime

        from spark_tpu.io.avro import read_avro, write_avro

        t = pa.table({
            "d": pa.array([datetime.date(2020, 1, 2), None],
                          pa.date32()),
            "ts": pa.array([datetime.datetime(2021, 3, 4, 5, 6, 7,
                                              500000), None],
                           pa.timestamp("us")),
        })
        p = str(tmp_path / "lt.avro")
        write_avro(p, t)
        assert read_avro(p).to_pylist() == t.to_pylist()

    def test_reversed_union_null_branch(self):
        """A union written as [T, \"null\"] encodes null as branch 1 —
        the reader must honor the actual index, not assume 0."""
        import io as _io
        import json

        from spark_tpu.io import avro as A

        raw = json.dumps({"type": "record", "name": "r", "fields": [
            {"name": "x", "type": ["long", "null"]}]})
        fts = A._field_types(raw)
        assert fts[0].null_branch == 1
        body = bytearray()
        body += A._zigzag_encode(0)         # branch 0 = the value
        A._encode_value(body, "long", 7)
        body += A._zigzag_encode(1)         # branch 1 = null
        b = _io.BytesIO(bytes(body))
        vals = []
        for _ in range(2):
            br = A._zigzag_decode(b)
            vals.append(None if br == fts[0].null_branch
                        else A._decode_value(b, "long"))
        assert vals == [7, None]

    def test_corrupt_sync_detected(self, tmp_path):
        from spark_tpu.io.avro import read_avro, write_avro

        p = str(tmp_path / "c.avro")
        write_avro(p, pa.table({"x": [1, 2, 3]}))
        raw = bytearray(open(p, "rb").read())
        raw[-1] ^= 0xFF     # flip a sync byte
        open(p, "wb").write(bytes(raw))
        with pytest.raises(ValueError, match="sync"):
            read_avro(p)


class TestXML:
    def test_schema_spans_all_files(self, spark, tmp_path):
        (tmp_path / "a.xml").write_text(
            "<d><r><x>1</x></r></d>")
        (tmp_path / "b.xml").write_text(
            "<d><r><x>2</x><extra>late</extra></r></d>")
        df = spark.read.format("xml").option("rowTag", "r") \
            .load(str(tmp_path))
        rows = df.toArrow().to_pylist()
        assert {r.get("extra") for r in rows} == {None, "late"}

    def test_like_percent_with_params(self, spark):
        from spark_tpu.connect.sql_endpoint import SQLEndpoint, connect

        spark.createDataFrame(pa.table({"s": ["abc", "xyz"],
                                        "k": [1, 2]})) \
            .createOrReplaceTempView("likep")
        ep = SQLEndpoint(spark).start()
        try:
            with connect("127.0.0.1", ep.port) as c:
                cur = c.cursor()
                cur.execute("select s from likep where s like 'a%' "
                            "and k = %s", (1,))
                assert cur.fetchall() == [("abc",)]
        finally:
            ep.stop()

    def test_read_rows(self, spark, tmp_path):
        p = tmp_path / "books.xml"
        p.write_text("""<catalog>
          <book id="1"><title>Dune</title><price>9.99</price></book>
          <book id="2"><title>Foundation</title><price>7.50</price></book>
        </catalog>""")
        df = spark.read.format("xml").option("rowTag", "book") \
            .load(str(p))
        rows = df.toArrow().to_pylist()
        assert {r["title"] for r in rows} == {"Dune", "Foundation"}
        assert {r["_id"] for r in rows} == {"1", "2"}
        # strings cast downstream, like the reference's schema-less mode
        df.createOrReplaceTempView("books")
        s = spark.sql("select sum(cast(price as double)) s from books") \
            .toArrow().to_pylist()[0]["s"]
        assert abs(s - 17.49) < 1e-9


class TestSQLEndpoint:
    def test_dbapi_roundtrip(self, spark):
        from spark_tpu.connect.sql_endpoint import SQLEndpoint, connect

        spark.createDataFrame(pa.table({
            "k": ["a", "a", "b"], "v": [1, 2, 5]})) \
            .createOrReplaceTempView("ept")
        ep = SQLEndpoint(spark).start()
        try:
            with connect("127.0.0.1", ep.port) as conn:
                cur = conn.cursor()
                cur.execute("select k, sum(v) as s from ept "
                            "group by k order by k")
                assert [d[0] for d in cur.description] == ["k", "s"]
                assert cur.fetchall() == [("a", 3), ("b", 5)]
                # parameters + fetchone/iteration
                cur.execute("select * from ept where k = %s order by v",
                            ("a",))
                assert cur.fetchone() == ("a", 1)
                assert list(cur) == [("a", 2)]
                # errors surface as DB-API Error, connection stays alive
                from spark_tpu.connect.sql_endpoint import Error

                with pytest.raises(Error):
                    cur.execute("select * from no_such_table")
                cur.execute("select 1 one")
                assert cur.fetchall() == [(1,)]
        finally:
            ep.stop()

    def test_concurrent_clients(self, spark):
        from concurrent.futures import ThreadPoolExecutor

        from spark_tpu.connect.sql_endpoint import SQLEndpoint, connect

        ep = SQLEndpoint(spark).start()
        try:
            def one(i):
                with connect("127.0.0.1", ep.port) as c:
                    cur = c.cursor()
                    cur.execute(f"select {i} * 2 as r")
                    return cur.fetchall()[0][0]

            with ThreadPoolExecutor(4) as pool:
                out = list(pool.map(one, range(8)))
            assert out == [i * 2 for i in range(8)]
        finally:
            ep.stop()
