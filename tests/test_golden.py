"""Golden-file SQL tests.

Role of the reference's SQLQueryTestSuite (sql/core/src/test/.../
SQLQueryTestSuite.scala): `.sql` inputs under tests/sql-tests/inputs/ run
against committed results under tests/sql-tests/results/; regenerate with
SPARK_GENERATE_GOLDEN_FILES=1 (same env-var workflow as the reference).
"""

import glob
import os

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
INPUTS = os.path.join(HERE, "sql-tests", "inputs")
RESULTS = os.path.join(HERE, "sql-tests", "results")
REGEN = os.environ.get("SPARK_GENERATE_GOLDEN_FILES") == "1"


def _setup(spark):
    import pyarrow as pa

    from tpcds_mini import register_tpcds

    register_tpcds(spark)
    nested = pa.table({
        "id": [1, 2, 3],
        "person": pa.array(
            [{"name": "ann", "age": 31}, {"name": "bob", "age": 25}, None],
            pa.struct([("name", pa.string()), ("age", pa.int64())])),
        "tags": pa.array([[("x", 1), ("y", 2)], [("x", 9)], []],
                         pa.map_(pa.string(), pa.int64())),
        "nums": pa.array([[3, 1, 2], [5], None], pa.list_(pa.int64())),
    })
    spark.createDataFrame(nested).createOrReplaceTempView("nested")


def _render(table) -> str:
    """Stable text rendering of a result table."""
    cols = table.column_names
    lines = ["-- " + "\t".join(cols)]
    pylists = [c.to_pylist() for c in table.columns]
    for row in zip(*pylists) if cols else []:
        lines.append("\t".join(_fmt(v) for v in row))
    return "\n".join(lines) + "\n"


def _fmt(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, float):
        return f"{v:.6g}"
    if isinstance(v, bool):
        return str(v).lower()
    return str(v)


def _split_statements(text: str) -> list[str]:
    """Split on ';' outside single-quoted strings ('' escapes a quote,
    like the lexer)."""
    out, buf, i, n = [], [], 0, len(text)
    in_str = False
    while i < n:
        c = text[i]
        if not in_str and c == "-" and i + 1 < n and text[i + 1] == "-":
            # '--' comment runs to end of line (apostrophes inside it
            # must not open a string)
            while i < n and text[i] != "\n":
                buf.append(text[i])
                i += 1
            continue
        if in_str:
            buf.append(c)
            if c == "'":
                if i + 1 < n and text[i + 1] == "'":
                    buf.append("'")
                    i += 1
                else:
                    in_str = False
        elif c == "'":
            in_str = True
            buf.append(c)
        elif c == ";":
            out.append("".join(buf))
            buf = []
        else:
            buf.append(c)
        i += 1
    if buf:
        out.append("".join(buf))
    return out


def _cases():
    return sorted(glob.glob(os.path.join(INPUTS, "*.sql")))


@pytest.mark.parametrize("path", _cases(),
                         ids=[os.path.basename(p) for p in _cases()])
def test_golden(spark, path):
    _setup(spark)
    name = os.path.splitext(os.path.basename(path))[0]
    out_path = os.path.join(RESULTS, name + ".out")
    with open(path) as f:
        text = f.read()

    chunks = [q.strip() for q in _split_statements(text) if q.strip()
              and not q.strip().startswith("--")]
    rendered = []
    for q in chunks:
        table = spark.sql(q).toArrow()
        rendered.append(f"-- !query\n{q}\n-- !result\n{_render(table)}")
    got = "\n".join(rendered)

    if REGEN:
        os.makedirs(RESULTS, exist_ok=True)
        with open(out_path, "w") as f:
            f.write(got)
        pytest.skip("regenerated golden file")
    assert os.path.exists(out_path), \
        f"golden file missing — regenerate with SPARK_GENERATE_GOLDEN_FILES=1"
    with open(out_path) as f:
        want = f.read()
    assert got == want, f"golden mismatch for {name}"
