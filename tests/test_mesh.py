"""Mesh / collectives tests over the 8-virtual-device CPU mesh
(SURVEY.md §4: the local-cluster analog)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_tpu.parallel.mesh import get_mesh, replicated_sharding, shard_rows
from spark_tpu.parallel.mesh_agg import make_distributed_groupby_sum


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return get_mesh(8)


def test_distributed_groupby_matches_oracle(mesh):
    n = 8 * 128
    rng = np.random.default_rng(7)
    keys = rng.integers(0, 23, n).astype(np.int64)
    vals = rng.integers(-50, 100, n).astype(np.int64)
    mask = np.ones(n, bool)
    mask[::13] = False

    f = make_distributed_groupby_sum(mesh)
    ok, osum, ocnt, om = f(shard_rows(jnp.asarray(keys), mesh),
                           shard_rows(jnp.asarray(vals), mesh),
                           shard_rows(jnp.asarray(mask), mesh))
    ok, osum, ocnt, om = map(np.asarray, (ok, osum, ocnt, om))

    got = {}
    for kk, ss, cc in zip(ok[om], osum[om], ocnt[om]):
        assert int(kk) not in got, "key owned by two shards"
        got[int(kk)] = (int(ss), int(cc))
    want = {}
    for kk, vv, mm in zip(keys, vals, mask):
        if mm:
            s, c = want.get(int(kk), (0, 0))
            want[int(kk)] = (s + int(vv), c + 1)
    assert got == want


def test_keys_land_on_owner_shard(mesh):
    """Each distinct key must end up on exactly one shard — the clustering
    contract the final aggregation relies on."""
    n = 8 * 64
    keys = np.arange(n, dtype=np.int64) % 11
    vals = np.ones(n, dtype=np.int64)
    mask = np.ones(n, bool)
    f = make_distributed_groupby_sum(mesh)
    ok, osum, ocnt, om = f(shard_rows(jnp.asarray(keys), mesh),
                           shard_rows(jnp.asarray(vals), mesh),
                           shard_rows(jnp.asarray(mask), mesh))
    ok, om = np.asarray(ok), np.asarray(om)
    per_shard = ok.shape[0] // 8
    owners = {}
    for shard in range(8):
        sl = slice(shard * per_shard, (shard + 1) * per_shard)
        for kk in ok[sl][om[sl]]:
            assert int(kk) not in owners
            owners[int(kk)] = shard
    assert len(owners) == 11


def test_mesh_pipeline_filter_project_groupby(mesh):
    import jax.numpy as jnp

    from spark_tpu.parallel.mesh_pipeline import make_mesh_groupby_pipeline

    n = 8 * 128
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 19, n).astype(np.int64)
    vals = rng.integers(1, 50, n).astype(np.int64)
    mask = np.ones(n, bool)

    run = make_mesh_groupby_pipeline(mesh)
    mk, ms, mc, mm = run(
        shard_rows(jnp.asarray(keys), mesh),
        shard_rows(jnp.asarray(vals), mesh),
        shard_rows(jnp.asarray(mask), mesh),
        filter_fn=lambda k, v: v > 10,          # WHERE v > 10
        project_fn=lambda v: v * 2)             # SELECT v * 2
    mk, ms, mc, mm = map(np.asarray, (mk, ms, mc, mm))

    got = {int(k): (int(s), int(c)) for k, s, c in
           zip(mk[mm], ms[mm], mc[mm])}
    want = {}
    for k, v in zip(keys, vals):
        if v > 10:
            s, c = want.get(int(k), (0, 0))
            want[int(k)] = (s + 2 * int(v), c + 1)
    assert got == want


def test_mesh_pipeline_quota_retry(mesh):
    """Skewed keys overflow the per-destination quota; the host retries
    with a doubled quota until the exchange fits."""
    import jax.numpy as jnp

    from spark_tpu.parallel.mesh_pipeline import make_mesh_groupby_pipeline

    n = 8 * 256
    # many distinct keys on each shard that all hash to few destinations?
    # simpler: huge distinct-key count per shard → partial outputs exceed a
    # tiny starting quota
    keys = np.arange(n, dtype=np.int64)
    vals = np.ones(n, dtype=np.int64)
    mask = np.ones(n, bool)

    run = make_mesh_groupby_pipeline(mesh)
    mk, ms, mc, mm = run(
        shard_rows(jnp.asarray(keys), mesh),
        shard_rows(jnp.asarray(vals), mesh),
        shard_rows(jnp.asarray(mask), mesh),
        quota=4)  # deliberately too small → retries
    mk, ms, mm = np.asarray(mk), np.asarray(ms), np.asarray(mm)
    assert int(mm.sum()) == n           # every key survives
    assert set(ms[mm]) == {1}


# ---------------------------------------------------------------------------
# Planner-integrated mesh execution: whole SQL queries through the mesh
# exchange (spark_tpu/parallel/mesh_exchange.py), results bit-identical to
# the host shuffle path.
# ---------------------------------------------------------------------------

def _rows(df):
    out = [tuple(r) for r in df.collect()]
    return sorted(out, key=lambda t: tuple((x is None, x) for x in t))


@pytest.fixture()
def mesh_session():
    from spark_tpu import TpuSession

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    s = TpuSession("mesh-sql", {"spark.sql.shuffle.partitions": 8,
                                "spark.tpu.batch.capacity": 1 << 10})
    yield s
    s.stop()


def _mk_tables(s, seed=11, n=3000):
    import pyarrow as pa

    rng = np.random.default_rng(seed)
    t1 = pa.table({
        "k": rng.integers(0, 40, n),
        "g": rng.choice(["a", "b", "c", None], n).tolist(),
        "v": rng.standard_normal(n),
    })
    t2 = pa.table({
        "k": rng.integers(0, 60, n // 2),
        "w": rng.integers(-5, 5, n // 2),
    })
    # repartition: LocalRelation scans are single-partition, which would
    # satisfy every clustering requirement and elide the exchange under test
    s.createDataFrame(t1).repartition(8).createOrReplaceTempView("t1")
    s.createDataFrame(t2).repartition(8).createOrReplaceTempView("t2")


def _run_both(mesh_session, sql):
    """Run once with the mesh exchange, once with the host shuffle."""
    _mk_tables(mesh_session)
    mesh_session.conf.set("spark.tpu.mesh.enabled", "true")
    got_mesh = _rows(mesh_session.sql(sql))
    mesh_session.conf.set("spark.tpu.mesh.enabled", "false")
    got_host = _rows(mesh_session.sql(sql))
    mesh_session.conf.set("spark.tpu.mesh.enabled", "true")
    assert got_mesh == got_host, sql
    return got_mesh


def test_mesh_sql_groupby_agg(mesh_session):
    out = _run_both(mesh_session,
                    "SELECT k, g, count(*) c, sum(v) s, min(v) mn "
                    "FROM t1 GROUP BY k, g")
    assert len(out) > 40


def test_mesh_sql_join(mesh_session):
    out = _run_both(mesh_session,
                    "SELECT t1.k, count(*) c, sum(t2.w) sw FROM t1 "
                    "JOIN t2 ON t1.k = t2.k GROUP BY t1.k ORDER BY t1.k")
    assert len(out) > 10


def test_mesh_sql_distinct_and_semi(mesh_session):
    _run_both(mesh_session, "SELECT DISTINCT g, k % 7 FROM t1")
    _run_both(mesh_session,
              "SELECT k, g FROM t1 WHERE k IN (SELECT k FROM t2 WHERE w > 0)")


def test_mesh_exchange_fires(mesh_session):
    """The metric proves the ICI path actually ran (not the host fallback)."""
    _mk_tables(mesh_session)
    df = mesh_session.sql("SELECT k, sum(v) FROM t1 GROUP BY k")
    df.collect()
    m = mesh_session._metrics.snapshot()["counters"]
    assert m.get("exchange.mesh", 0) >= 1
