"""Mesh / collectives tests over the 8-virtual-device CPU mesh
(SURVEY.md §4: the local-cluster analog)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_tpu.parallel.mesh import get_mesh, replicated_sharding, shard_rows
from spark_tpu.parallel.mesh_agg import make_distributed_groupby_sum


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return get_mesh(8)


def test_distributed_groupby_matches_oracle(mesh):
    n = 8 * 128
    rng = np.random.default_rng(7)
    keys = rng.integers(0, 23, n).astype(np.int64)
    vals = rng.integers(-50, 100, n).astype(np.int64)
    mask = np.ones(n, bool)
    mask[::13] = False

    f = make_distributed_groupby_sum(mesh)
    ok, osum, ocnt, om = f(shard_rows(jnp.asarray(keys), mesh),
                           shard_rows(jnp.asarray(vals), mesh),
                           shard_rows(jnp.asarray(mask), mesh))
    ok, osum, ocnt, om = map(np.asarray, (ok, osum, ocnt, om))

    got = {}
    for kk, ss, cc in zip(ok[om], osum[om], ocnt[om]):
        assert int(kk) not in got, "key owned by two shards"
        got[int(kk)] = (int(ss), int(cc))
    want = {}
    for kk, vv, mm in zip(keys, vals, mask):
        if mm:
            s, c = want.get(int(kk), (0, 0))
            want[int(kk)] = (s + int(vv), c + 1)
    assert got == want


def test_keys_land_on_owner_shard(mesh):
    """Each distinct key must end up on exactly one shard — the clustering
    contract the final aggregation relies on."""
    n = 8 * 64
    keys = np.arange(n, dtype=np.int64) % 11
    vals = np.ones(n, dtype=np.int64)
    mask = np.ones(n, bool)
    f = make_distributed_groupby_sum(mesh)
    ok, osum, ocnt, om = f(shard_rows(jnp.asarray(keys), mesh),
                           shard_rows(jnp.asarray(vals), mesh),
                           shard_rows(jnp.asarray(mask), mesh))
    ok, om = np.asarray(ok), np.asarray(om)
    per_shard = ok.shape[0] // 8
    owners = {}
    for shard in range(8):
        sl = slice(shard * per_shard, (shard + 1) * per_shard)
        for kk in ok[sl][om[sl]]:
            assert int(kk) not in owners
            owners[int(kk)] = shard
    assert len(owners) == 11


def test_mesh_pipeline_filter_project_groupby(mesh):
    import jax.numpy as jnp

    from spark_tpu.parallel.mesh_pipeline import make_mesh_groupby_pipeline

    n = 8 * 128
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 19, n).astype(np.int64)
    vals = rng.integers(1, 50, n).astype(np.int64)
    mask = np.ones(n, bool)

    run = make_mesh_groupby_pipeline(mesh)
    mk, ms, mc, mm = run(
        shard_rows(jnp.asarray(keys), mesh),
        shard_rows(jnp.asarray(vals), mesh),
        shard_rows(jnp.asarray(mask), mesh),
        filter_fn=lambda k, v: v > 10,          # WHERE v > 10
        project_fn=lambda v: v * 2)             # SELECT v * 2
    mk, ms, mc, mm = map(np.asarray, (mk, ms, mc, mm))

    got = {int(k): (int(s), int(c)) for k, s, c in
           zip(mk[mm], ms[mm], mc[mm])}
    want = {}
    for k, v in zip(keys, vals):
        if v > 10:
            s, c = want.get(int(k), (0, 0))
            want[int(k)] = (s + 2 * int(v), c + 1)
    assert got == want


def test_mesh_pipeline_quota_retry(mesh):
    """Skewed keys overflow the per-destination quota; the host retries
    with a doubled quota until the exchange fits."""
    import jax.numpy as jnp

    from spark_tpu.parallel.mesh_pipeline import make_mesh_groupby_pipeline

    n = 8 * 256
    # many distinct keys on each shard that all hash to few destinations?
    # simpler: huge distinct-key count per shard → partial outputs exceed a
    # tiny starting quota
    keys = np.arange(n, dtype=np.int64)
    vals = np.ones(n, dtype=np.int64)
    mask = np.ones(n, bool)

    run = make_mesh_groupby_pipeline(mesh)
    mk, ms, mc, mm = run(
        shard_rows(jnp.asarray(keys), mesh),
        shard_rows(jnp.asarray(vals), mesh),
        shard_rows(jnp.asarray(mask), mesh),
        quota=4)  # deliberately too small → retries
    mk, ms, mm = np.asarray(mk), np.asarray(ms), np.asarray(mm)
    assert int(mm.sum()) == n           # every key survives
    assert set(ms[mm]) == {1}
