"""Python UDF tests (reference: pyspark UDF suites / ArrowEvalPython)."""

import numpy as np
import pyarrow as pa
import pytest

import spark_tpu.api.functions as F
from spark_tpu.types import int64, string


def test_vectorized_udf(spark):
    @F.udf(returnType=int64)
    def plus_one(x):
        return x + 1  # numpy vectorized

    df = spark.createDataFrame(pa.table({"x": [1, 2, 3]}))
    out = df.select(plus_one("x").alias("y")).toArrow().to_pydict()
    assert out["y"] == [2, 3, 4]


def test_udf_two_args_in_filter(spark):
    @F.udf(returnType="double")
    def ratio(a, b):
        return a / b

    df = spark.createDataFrame(pa.table({"a": [10.0, 4.0, 9.0],
                                         "b": [2.0, 4.0, 3.0]}))
    out = (df.withColumn("r", ratio("a", "b"))
           .filter(F.col("r") > 2.0)
           .select("a").toArrow().to_pydict())
    assert out["a"] == [10.0, 9.0]


def test_scalar_fallback_udf(spark):
    @F.udf(returnType=string)
    def spell(x):
        return {1: "one", 2: "two"}.get(x, "many")  # not numpy-vectorizable

    df = spark.createDataFrame(pa.table({"x": [1, 2, 5]}))
    out = df.select(spell("x").alias("s")).toArrow().to_pydict()
    assert out["s"] == ["one", "two", "many"]


def test_udf_nulls(spark):
    @F.udf(returnType=int64)
    def maybe(x):
        return None if x == 2 else int(x * 10)

    df = spark.createDataFrame(pa.table({"x": [1, 2, 3]}))
    out = df.select(maybe("x").alias("y")).toArrow().to_pydict()
    assert out["y"] == [10, None, 30]


def test_udf_after_shuffle(spark):
    @F.udf(returnType=int64)
    def double(x):
        return x * 2

    df = spark.range(0, 100, 1, 4).repartition(3)
    out = df.select(double("id").alias("d")).agg(
        F.sum("d").alias("s")).toArrow().to_pydict()
    assert out["s"] == [2 * sum(range(100))]


def test_map_in_pandas(spark):
    import pandas as pd

    from spark_tpu.types import StructField, StructType, float64, int64

    df = spark.range(0, 100, 1, 4)

    def double(pdf: "pd.DataFrame") -> "pd.DataFrame":
        return pd.DataFrame({"twice": pdf["id"] * 2})

    schema = StructType([StructField("twice", int64, False)])
    out = df.mapInPandas(double, schema)
    assert out.agg(F.sum("twice").alias("s")).toArrow().to_pydict()["s"] == \
        [2 * sum(range(100))]


def test_apply_in_pandas(spark):
    import pandas as pd
    import pyarrow as pa

    df = spark.createDataFrame(pa.table({
        "g": ["a", "a", "b", "b", "b"],
        "v": [1.0, 3.0, 2.0, 4.0, 9.0]}))

    def demean(pdf: "pd.DataFrame") -> "pd.DataFrame":
        pdf = pdf.copy()
        pdf["v"] = pdf["v"] - pdf["v"].mean()
        return pdf

    out = (df.groupBy("g").applyInPandas(demean)
           .orderBy("g", "v").toArrow().to_pydict())
    assert out["v"] == [-1.0, 1.0, -3.0, -1.0, 4.0]


def test_correlated_scalar_in_select(spark):
    import pyarrow as pa

    spark.createDataFrame(pa.table({
        "g": ["a", "a", "b"], "v": [1.0, 3.0, 10.0]})) \
        .createOrReplaceTempView("sel_corr")
    out = spark.sql("""
        SELECT g, v, (SELECT avg(v) FROM sel_corr i WHERE i.g = o.g) AS ga
        FROM sel_corr o ORDER BY g, v""").toArrow().to_pydict()
    assert out["ga"] == [2.0, 2.0, 10.0]
