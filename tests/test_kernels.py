"""Kernel unit tests (the reference tests expression eval both interpreted
and codegen'd — here numpy is the oracle for every jitted kernel;
SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_tpu.ops import (
    SortKeySpec, build_index, cross_join, group_rows, group_output_mask,
    hash_columns, hash_partition, limit_mask, mix64, partition_ids,
    probe_join, scatter_group_keys, seg_count, seg_first, seg_max, seg_min,
    seg_sum, sort_permutation,
)


def test_mix64_deterministic_and_spread():
    x = jnp.arange(1000, dtype=jnp.int64)
    h1 = np.asarray(mix64(x))
    h2 = np.asarray(mix64(x))
    assert (h1 == h2).all()
    assert len(np.unique(h1)) == 1000
    # partition balance
    pids = np.asarray(partition_ids(jnp.asarray(h1), 8))
    counts = np.bincount(pids, minlength=8)
    assert counts.min() > 60  # roughly uniform


def test_group_rows_numpy_oracle():
    rng = np.random.default_rng(0)
    n, cap = 900, 1024
    keys = rng.integers(0, 50, n)
    vals = rng.integers(-100, 100, n)
    k = np.zeros(cap, np.int64)
    v = np.zeros(cap, np.int64)
    k[:n] = keys
    v[:n] = vals
    mask = np.arange(cap) < n

    layout = group_rows([jnp.asarray(k)], [None], jnp.asarray(mask))
    sums, cnts = seg_sum(layout, jnp.asarray(v))
    out_k, _ = scatter_group_keys(layout, jnp.asarray(k), None)
    om = np.asarray(group_output_mask(layout))

    got = {}
    for kk, s in zip(np.asarray(out_k)[om], np.asarray(sums)[om]):
        got[int(kk)] = int(s)
    want = {}
    for kk, vv in zip(keys, vals):
        want[int(kk)] = want.get(int(kk), 0) + int(vv)
    assert got == want

    mins, has = seg_min(layout, jnp.asarray(v))
    gotm = {int(kk): int(m) for kk, m in
            zip(np.asarray(out_k)[om], np.asarray(mins)[om])}
    wantm = {}
    for kk, vv in zip(keys, vals):
        wantm[int(kk)] = min(wantm.get(int(kk), 10**9), int(vv))
    assert gotm == wantm


def test_group_rows_null_keys_group_together():
    k = jnp.asarray([1, 2, 1, 99, 99], dtype=jnp.int64)
    valid = jnp.asarray([True, True, True, False, False])
    mask = jnp.ones(5, dtype=bool)
    layout = group_rows([k], [valid], mask)
    assert int(layout.num_groups) == 3  # {1}, {2}, {null}


def test_sort_permutation_desc_nulls():
    k = jnp.asarray([3, 1, 2, 0, 0], dtype=jnp.int64)
    valid = jnp.asarray([True, True, True, False, True])
    mask = jnp.asarray([True, True, True, True, False])
    perm = sort_permutation([k], [valid], [SortKeySpec(ascending=False)], mask)
    out = np.asarray(jnp.take(k, perm))
    vout = np.asarray(jnp.take(valid, perm))
    mout = np.asarray(jnp.take(mask, perm))
    # live rows: 3,2,1 then null last (desc → nulls last by default)
    assert list(out[mout][:3]) == [3, 2, 1]
    assert not vout[mout][3]


def test_sort_stability():
    k = jnp.asarray([1, 1, 1, 1], dtype=jnp.int64)
    mask = jnp.ones(4, dtype=bool)
    perm = sort_permutation([k], [None], [SortKeySpec()], mask)
    assert list(np.asarray(perm)) == [0, 1, 2, 3]


def test_join_inner_oracle():
    rng = np.random.default_rng(1)
    bn, pn = 300, 500
    bcap, pcap = 512, 512
    bk = np.zeros(bcap, np.int64)
    pk = np.zeros(pcap, np.int64)
    bk[:bn] = rng.integers(0, 100, bn)
    pk[:pn] = rng.integers(0, 100, pn)
    bmask = np.arange(bcap) < bn
    pmask = np.arange(pcap) < pn

    bi = build_index([jnp.asarray(bk)], [None], jnp.asarray(bmask))
    r = probe_join(bi, [jnp.asarray(bk)], [None], [jnp.asarray(pk)], [None],
                   jnp.asarray(pmask), out_capacity=1 << 14)
    om = np.asarray(r.out_mask)
    pi = np.asarray(r.probe_idx)[om]
    bi_idx = np.asarray(r.build_idx)[om]
    got = sorted(zip(pi.tolist(), bi_idx.tolist()))
    want = sorted((i, j) for i in range(pn) for j in range(bn)
                  if pk[i] == bk[j])
    assert got == want


def test_join_left_outer_and_anti():
    bk = jnp.asarray([1, 2, 0, 0], dtype=jnp.int64)
    bmask = jnp.asarray([True, True, False, False])
    pk = jnp.asarray([1, 5, 2, 2], dtype=jnp.int64)
    pmask = jnp.ones(4, dtype=bool)
    bi = build_index([bk], [None], bmask)
    r = probe_join(bi, [bk], [None], [pk], [None], pmask, 16, "left_outer")
    om = np.asarray(r.out_mask)
    rows = sorted(zip(np.asarray(r.probe_idx)[om].tolist(),
                      np.asarray(r.matched)[om].tolist()))
    assert rows == [(0, True), (1, False), (2, True), (3, True)]
    r2 = probe_join(bi, [bk], [None], [pk], [None], pmask, 16, "left_anti")
    om2 = np.asarray(r2.out_mask)
    assert np.asarray(r2.probe_idx)[om2].tolist() == [1]


def test_join_null_keys_never_match():
    bk = jnp.asarray([1, 1], dtype=jnp.int64)
    bvalid = jnp.asarray([True, False])
    bmask = jnp.ones(2, dtype=bool)
    pk = jnp.asarray([1], dtype=jnp.int64)
    pvalid = jnp.asarray([False])
    pmask = jnp.ones(1, dtype=bool)
    bi = build_index([bk], [bvalid], bmask)
    r = probe_join(bi, [bk], [bvalid], [pk], [pvalid], pmask, 8, "inner")
    assert int(np.asarray(r.out_mask).sum()) == 0


def test_join_overflow_reports_needed():
    bk = jnp.zeros(8, dtype=jnp.int64)
    bmask = jnp.ones(8, dtype=bool)
    pk = jnp.zeros(8, dtype=jnp.int64)
    pmask = jnp.ones(8, dtype=bool)
    bi = build_index([bk], [None], bmask)
    r = probe_join(bi, [bk], [None], [pk], [None], pmask, out_capacity=16)
    assert int(r.needed) == 64  # 8x8 matches, capacity 16 → host must retry


def test_hash_partition_counts():
    k = jnp.arange(1000, dtype=jnp.int64)
    mask = jnp.ones(1000, dtype=bool)
    pr = hash_partition([k], [None], mask, 7)
    counts = np.asarray(pr.counts)
    assert counts.sum() == 1000
    pids = np.asarray(pr.pids)
    # grouped ascending
    live = pids[pids < 7]
    assert (np.diff(live) >= 0).all()


def test_limit_mask():
    mask = jnp.asarray([True, False, True, True, True])
    out = np.asarray(limit_mask(mask, 2))
    assert out.tolist() == [True, False, True, False, False]


def test_cross_join():
    pmask = jnp.asarray([True, True, False])
    bmask = jnp.asarray([True, False, True])
    r = cross_join(pmask, bmask, 16)
    om = np.asarray(r.out_mask)
    assert int(om.sum()) == 4  # 2 live probe x 2 live build


def test_batch_validation_mode(spark):
    import pyarrow as pa

    spark.conf.set("spark.tpu.debug.validateBatches", "true")
    try:
        df = spark.createDataFrame(pa.table({
            "k": ["a", "b", "a"], "v": [1, 2, 3]}))
        import spark_tpu.api.functions as F

        out = (df.repartition(3).groupBy("k")
               .agg(F.sum("v").alias("s")).orderBy("k")
               .toArrow().to_pydict())
        assert out["s"] == [4, 2]
    finally:
        spark.conf.unset("spark.tpu.debug.validateBatches")


def test_validate_batch_catches_bad_codes():
    import jax.numpy as jnp
    import pytest as _pt

    from spark_tpu.columnar.batch import Column, ColumnarBatch, StringDict
    from spark_tpu.columnar.validate import validate_batch
    from spark_tpu.errors import ExecutionError
    from spark_tpu.types import StructField, StructType, string

    schema = StructType([StructField("s", string, False)])
    bad = ColumnarBatch(
        schema,
        [Column(string, jnp.asarray(np.array([5, 0], np.int32)), None,
                StringDict(["only"]))],
        jnp.asarray(np.array([True, True])), num_rows=2)
    with _pt.raises(ExecutionError, match="out of range"):
        validate_batch(bad, "test")
