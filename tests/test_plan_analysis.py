"""Plan/trace analyzer (spark_tpu/analysis/plan_lint.py).

Acceptance gate: on the fusion differential suite (agg, join+agg, limit,
TPC-DS mini q3/q7), `explain("analysis")`'s predicted per-kind kernel
launch counts must equal the measured KernelCache launch counters EXACTLY
— fusion on and off. The prediction models one warm execution; the test
warms once (compiles + device-cached scans + memo priming) and measures a
second run, the same steady-state discipline the fusion dispatch tests
use (the reference gates EXPLAIN CODEGEN with codegen-metrics checks the
same way)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC


@pytest.fixture()
def fusion_conf(spark):
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    yield spark
    spark.conf.unset("spark.tpu.fusion.enabled")
    spark.conf.unset("spark.tpu.fusion.minRows")


@pytest.fixture()
def data(spark):
    rng = np.random.default_rng(7)
    n = 5000
    spark.createDataFrame(pa.table({
        "k": rng.integers(0, 13, n),
        "v": rng.integers(-50, 100, n),
        "f": rng.random(n),
        "s": [f"cat{i % 5}" for i in range(n)],
    })).createOrReplaceTempView("an_t")
    dim = pa.table({
        "dk": np.arange(13, dtype=np.int64),
        "label": [f"lab{i % 3}" for i in range(13)],
    })
    spark.createDataFrame(dim).createOrReplaceTempView("an_dim")
    return spark


Q_AGG = ("select k, sum(v * 2) sv, count(*) c, min(v) mn, max(v+1) mx, "
         "avg(f) af from an_t where v > 0 group by k")
Q_JOIN_AGG = ("select label, sum(v) sv, count(*) c from an_t "
              "join an_dim on k = dk where v > 10 group by label")
Q_LIMIT = ("select k + v * 100 as key2 from an_t where v > 95 "
           "order by key2 limit 17")
Q3 = """
    SELECT dt.d_year, item.i_brand_id AS brand_id,
           SUM(ss_ext_sales_price) AS sum_agg
    FROM date_dim dt, store_sales, item
    WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
      AND store_sales.ss_item_sk = item.i_item_sk
      AND item.i_manufact_id = 28 AND dt.d_moy = 11
    GROUP BY dt.d_year, item.i_brand_id"""
Q7 = """
    SELECT i.i_category, AVG(ss_quantity) AS agg1, COUNT(*) AS cnt
    FROM store_sales ss
    JOIN item i ON ss.ss_item_sk = i.i_item_sk
    JOIN date_dim d ON ss.ss_sold_date_sk = d.d_date_sk
    WHERE d.d_year = 1999
    GROUP BY i.i_category"""


def _predicted_vs_measured_df(build):
    """(analysis report, measured by-kind launch delta of one warm run)
    for a DataFrame builder (fresh DataFrame per run)."""
    df = build()
    report = df.query_execution.analysis_report()
    df.toArrow()  # warm: compile kernels, device-cache scans, prime memos
    before = dict(KC.launches_by_kind)
    build().toArrow()
    after = dict(KC.launches_by_kind)
    measured = {k: v - before.get(k, 0) for k, v in after.items()
                if v != before.get(k, 0)}
    return report, measured


def _predicted_vs_measured(spark, sql):
    return _predicted_vs_measured_df(lambda: spark.sql(sql))


def _assert_exact_df(build):
    report, measured = _predicted_vs_measured_df(build)
    assert report.exact, report.inexact_reasons
    assert report.predicted_launches == measured, (
        f"predicted {dict(sorted(report.predicted_launches.items()))} != "
        f"measured {dict(sorted(measured.items()))}\n{report.render()}")


def _assert_exact(spark, sql):
    _assert_exact_df(lambda: spark.sql(sql))


# ---------------------------------------------------------------------------
# acceptance: predicted == measured, fusion on AND off
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("enabled", ["true", "false"])
def test_agg_launch_prediction_exact(fusion_conf, data, enabled):
    data.conf.set("spark.tpu.fusion.enabled", enabled)
    _assert_exact(data, Q_AGG)


@pytest.mark.parametrize("enabled", ["true", "false"])
def test_join_agg_launch_prediction_exact(fusion_conf, data, enabled):
    data.conf.set("spark.tpu.fusion.enabled", enabled)
    _assert_exact(data, Q_JOIN_AGG)


@pytest.mark.parametrize("enabled", ["true", "false"])
def test_limit_launch_prediction_exact(fusion_conf, data, enabled):
    data.conf.set("spark.tpu.fusion.enabled", enabled)
    _assert_exact(data, Q_LIMIT)


@pytest.mark.parametrize("enabled", ["true", "false"])
def test_tpcds_q3_q7_launch_prediction_exact(fusion_conf, spark, enabled):
    from tpcds_mini import register_tpcds

    register_tpcds(spark)
    spark.conf.set("spark.tpu.fusion.enabled", enabled)
    _assert_exact(spark, Q3)
    _assert_exact(spark, Q7)


def test_total_matches_kernel_launch_metric(fusion_conf, data):
    """The report's total equals the per-query kernel.launches SQLMetric
    delta the scheduler records (same ground truth, metric plumbing)."""
    data.conf.set("spark.tpu.fusion.enabled", "true")
    df = data.sql(Q_AGG)
    report = df.query_execution.analysis_report()
    df.toArrow()  # warm
    before = data._metrics.snapshot()["counters"].get("kernel.launches", 0)
    data.sql(Q_AGG).toArrow()
    after = data._metrics.snapshot()["counters"].get("kernel.launches", 0)
    assert report.total == after - before


# ---------------------------------------------------------------------------
# minRows runtime gate: fused PLAN, unfused runtime kernels — still exact
# ---------------------------------------------------------------------------

def test_min_rows_gate_prediction_exact(spark, data):
    spark.conf.set("spark.tpu.fusion.enabled", "true")
    try:
        # default minRows (128k tile rows) far exceeds the 5k-row table:
        # the analyzer must predict the UNFUSED runtime kernels under the
        # fused plan, and say why
        report, measured = _predicted_vs_measured(spark, Q_AGG)
        assert report.exact, report.inexact_reasons
        assert report.predicted_launches == measured, report.render()
        assert "fused_agg" not in report.predicted_launches
        assert any("minRows" in n for s in report.stages
                   for n in s["notes"])
    finally:
        spark.conf.unset("spark.tpu.fusion.enabled")


# ---------------------------------------------------------------------------
# explain("analysis") surface + boundary explanations + hazards
# ---------------------------------------------------------------------------

def test_explain_analysis_renders(fusion_conf, data, capsys):
    data.conf.set("spark.tpu.fusion.enabled", "true")
    data.sql(Q_AGG).explain("analysis")
    out = capsys.readouterr().out
    assert "== Plan Analysis ==" in out
    assert "predicted launches" in out
    assert "FUSED" in out
    assert "minRows" in out          # the runtime gate is explained
    assert "fused_agg" in out


def test_sort_consume_boundary_explained(fusion_conf, data):
    data.conf.set("spark.tpu.fusion.enabled", "true")
    report = data.sql(Q_LIMIT).query_execution.analysis_report()
    assert any("Sort" in b and "UNFUSED" in b
               for b in report.fusion_boundaries), report.fusion_boundaries


def test_fusion_off_boundary_explained(fusion_conf, data):
    data.conf.set("spark.tpu.fusion.enabled", "false")
    report = data.sql(Q_AGG).query_execution.analysis_report()
    assert any("spark.tpu.fusion.enabled=false" in b
               for b in report.fusion_boundaries)


def test_string_probe_key_fuses_with_encoding(fusion_conf, data):
    """Compressed execution retires the string-key unfused probe
    fallback: the probe pipeline fuses (padded dictionary-hash lut as a
    kernel aux input), the prediction stays exact, and turning encoding
    OFF restores the historical boundary + reason."""
    data.conf.set("spark.tpu.fusion.enabled", "true")
    sdim = pa.table({"sk": [f"cat{i}" for i in range(5)],
                     "w": np.arange(5, dtype=np.int64)})
    data.createDataFrame(sdim).createOrReplaceTempView("an_sdim")
    q = ("select s, w from an_t join an_sdim on s = sk where v > 0")
    report = data.sql(q).query_execution.analysis_report()
    assert any("FUSED probe" in b for b in report.fusion_boundaries), \
        report.fusion_boundaries
    assert not any("UNFUSED probe" in b for b in report.fusion_boundaries)
    _assert_exact(data, q)
    data.conf.set("spark.tpu.encoding.enabled", "false")
    try:
        report = data.sql(q).query_execution.analysis_report()
        assert any("UNFUSED probe" in b and "string" in b
                   for b in report.fusion_boundaries), \
            report.fusion_boundaries
    finally:
        data.conf.unset("spark.tpu.encoding.enabled")


def test_overflow_risk_flagged_for_int_sum(fusion_conf, data):
    report = data.sql(Q_AGG).query_execution.analysis_report()
    assert any("SUM(" in r and "int64" in r
               for r in report.overflow_risks), report.overflow_risks


def test_dense_recompile_hazard_flagged(fusion_conf, data):
    data.conf.set("spark.tpu.fusion.enabled", "false")
    report = data.sql(Q_AGG).query_execution.analysis_report()
    assert any("value-dependent" in h
               for h in report.recompile_hazards), report.recompile_hazards


def test_report_dict_shape(fusion_conf, data):
    d = data.sql(Q_AGG).query_execution.analysis_report().to_dict()
    for key in ("stages", "predicted_launches", "predicted_total", "exact",
                "fusion_boundaries", "recompile_hazards", "overflow_risks"):
        assert key in d
    assert d["predicted_total"] == sum(d["predicted_launches"].values())


def test_sample_offset_arg_no_recompile_storm(spark):
    """SampleExec keys its kernel by (capacity, seed, fraction) and feeds
    the per-(partition,batch) position base as a kernel ARGUMENT: 12
    batches across 4 partitions compile at most one kernel per capacity
    bucket (the historical per-batch cache key compiled 12), launches
    stay 1/batch, the analyzer predicts them exactly, and the recompile
    hazard is gone from the report."""

    def q():
        return spark.range(0, 40000, 1, 4).sample(0.5, seed=31)

    report = q().query_execution.analysis_report()
    assert report.exact, report.inexact_reasons
    assert report.predicted_launches == {"sample": 12}, \
        report.predicted_launches
    assert not any("SampleExec" in h for h in report.recompile_hazards), \
        report.recompile_hazards
    assert any("kernel argument" in n for s in report.stages
               for n in s["notes"])

    before = KC.counters()
    before_kinds = dict(KC.launches_by_kind)
    q().toArrow()  # cold: compiles happen here
    mid = KC.counters()
    # 10000 rows/partition at 4096-capacity tiles → per partition
    # [4096, 4096, 2048] caps: two distinct buckets → ≤ 2 compiles
    assert mid["kernel_cache.misses"] - before["kernel_cache.misses"] <= 2
    assert KC.launches_by_kind["sample"] \
        - before_kinds.get("sample", 0) == 12

    warm = dict(KC.launches_by_kind)
    q().toArrow()  # warm: predicted == measured, zero further compiles
    after = KC.counters()
    measured = {k: v - warm.get(k, 0) for k, v in
                KC.launches_by_kind.items() if v != warm.get(k, 0)}
    assert measured == report.predicted_launches
    assert after["kernel_cache.misses"] == mid["kernel_cache.misses"]


def test_rr_offset_arg_no_recompile_storm(spark):
    """shuffle_rr keys its kernel by (capacity, num_out) and feeds the
    running row offset as a kernel ARGUMENT: a multi-batch round-robin
    repartition compiles at most one kernel per capacity bucket (the
    historical per-offset cache key compiled one per batch position),
    launches stay 1/batch, and the analyzer's recompile hazard is gone
    — replaced by the kernel-argument note."""

    def rr_keys():
        return [k for k in KC._cache if k and k[0] == "shuffle_rr"]

    def q():
        return spark.range(0, 40000, 1, 4).repartition(3)

    report = q().query_execution.analysis_report()
    assert not any("round-robin" in h for h in report.recompile_hazards), \
        report.recompile_hazards
    assert any("kernel argument" in n for s in report.stages
               for n in s["notes"] if "round-robin" in n), \
        [n for s in report.stages for n in s["notes"]]

    before_keys = set(rr_keys())
    before_kinds = dict(KC.launches_by_kind)
    q().toArrow()  # cold: compiles happen here
    new_keys = set(rr_keys()) - before_keys
    # 10000 rows/partition at 4096-capacity tiles → per partition caps
    # [4096, 4096, 2048]: two distinct buckets → ≤ 2 compiled kernels,
    # each keyed WITHOUT the running offset
    assert len(new_keys) <= 2, new_keys
    assert all(len(k) == 3 for k in new_keys), new_keys
    assert KC.launches_by_kind["shuffle_rr"] \
        - before_kinds.get("shuffle_rr", 0) == 12

    warm_keys = set(rr_keys())
    q().toArrow()  # warm: zero further shuffle_rr compiles
    assert set(rr_keys()) == warm_keys


def test_rr_shuffle_rows_survive_offset_argument(spark):
    """Round-robin output stays balanced and complete with the offset as
    a kernel argument (the offset still advances across batches)."""
    out = spark.range(0, 9999, 1, 4).repartition(3)
    parts = out.query_execution.execute()
    sizes = [sum(b.num_rows() for b in p) for p in parts]
    assert sum(sizes) == 9999
    assert max(sizes) - min(sizes) <= 1, sizes  # strict round-robin


def test_inexact_degrades_honestly(fusion_conf, data):
    """A MESH hash exchange whose key values the analyzer cannot trace
    (a COMPUTED string key — only pass-through columns trace) has
    data-dependent quota retries: the analyzer must NOT claim exactness,
    and must say why. (Traced keys — integers AND plain string columns,
    whose eq-lanes ride the dictionary hashes — now simulate the staging
    + retry loop exactly.)"""
    data.conf.set("spark.tpu.fusion.enabled", "true")
    df = (data.sql("select upper(s) as u, v from an_t")
          .repartition(4, "u").groupBy("u").count())
    report = df.query_execution.analysis_report()
    assert not report.exact
    assert report.inexact_reasons
    assert any("untraced" in r for r in report.inexact_reasons), \
        report.inexact_reasons
    # the mesh stage dispatch itself is still predicted
    assert report.predicted_launches.get("mesh_stage", 0) >= 1, \
        report.predicted_launches


# ---------------------------------------------------------------------------
# multi-stage shuffle plans: host-side hash of traced keys → EXACT
# ---------------------------------------------------------------------------
# Partition counts are non-powers-of-two so the exchanges stay on the host
# shuffle path (the 8-virtual-device env would otherwise go mesh).

@pytest.mark.parametrize("enabled", ["true", "false"])
def test_repartition_agg_prediction_exact(fusion_conf, data, enabled):
    """Acceptance: the value model flows THROUGH the hash exchange
    (host-side splitmix64 of the traced keys decides per-reducer rows and
    values), so repartition+agg predicts exactly — krange3 probes, dense
    vs sorted decisions, and per-batch launches included — fusion on and
    off."""
    data.conf.set("spark.tpu.fusion.enabled", enabled)
    _assert_exact_df(lambda: (data.sql("select * from an_t")
                              .repartition(5, "k").groupBy("k").count()))


@pytest.mark.parametrize("enabled", ["true", "false"])
def test_fused_exchange_prediction_exact(fusion_conf, data, enabled):
    """A shuffle-map stage with a nontrivial pipeline: fused (ONE
    fused_shuffle dispatch per map batch) and unfused (pipeline + shuffle
    kind) launch models both predict exactly, through the downstream
    aggregate."""
    data.conf.set("spark.tpu.fusion.enabled", enabled)
    _assert_exact_df(lambda: (
        data.sql("select k, v * 2 as v2 from an_t where v > 0")
        .repartition(5, "k")))
    _assert_exact_df(lambda: (
        data.sql("select k, v * 2 as v2 from an_t where v > 0")
        .repartition(5, "k").groupBy("k").count()))
    # round-robin keeps its offset-as-kernel-argument model when fused
    _assert_exact_df(lambda: (
        data.sql("select k, v from an_t where v > 0").repartition(3)))


def test_fused_exchange_boundary_and_kind(fusion_conf, data):
    data.conf.set("spark.tpu.fusion.enabled", "true")
    df = (data.sql("select k, v * 2 as v2 from an_t where v > 0")
          .repartition(5, "k"))
    report = df.query_execution.analysis_report()
    assert "fused_shuffle" in report.predicted_launches, \
        report.predicted_launches
    assert any("FUSED map side" in b for b in report.fusion_boundaries), \
        report.fusion_boundaries


def test_string_exchange_key_fuses_with_encoding(fusion_conf, data):
    """Compressed execution fuses string hash-partition keys into the
    map-side program (dict-hash lut aux input): fused_shuffle is
    predicted exactly; encoding off restores the historical boundary."""
    data.conf.set("spark.tpu.fusion.enabled", "true")

    def q():
        return (data.sql("select s, v * 2 as v2 from an_t where v > 0")
                .repartition(5, "s"))

    report = q().query_execution.analysis_report()
    assert "fused_shuffle" in report.predicted_launches, \
        report.predicted_launches
    assert any("FUSED map side" in b for b in report.fusion_boundaries), \
        report.fusion_boundaries
    _assert_exact_df(q)
    data.conf.set("spark.tpu.encoding.enabled", "false")
    try:
        report = q().query_execution.analysis_report()
        assert "fused_shuffle" not in report.predicted_launches, \
            report.predicted_launches
        assert any("UNFUSED exchange" in b and "string" in b
                   for b in report.fusion_boundaries), \
            report.fusion_boundaries
    finally:
        data.conf.unset("spark.tpu.encoding.enabled")


# ---------------------------------------------------------------------------
# mesh SPMD stage: staging + quota-retry simulation → EXACT
# ---------------------------------------------------------------------------
# Partition counts are powers of two on the 8-virtual-device env, so these
# exchanges take the mesh stage program (ONE sharded dispatch per step).

@pytest.mark.parametrize("enabled", ["true", "false"])
def test_mesh_exchange_prediction_exact(fusion_conf, data, enabled):
    """Acceptance: the mesh stage model simulates the staging geometry,
    the splitmix64 partition ids, and the quota-retry loop host-side, so
    mesh-path plans predict EXACTLY — one mesh_stage dispatch per step
    (no per-batch pipeline when fused), krange3/dense decisions on the
    shard-resident reduce tiles included — fusion on and off."""
    data.conf.set("spark.tpu.fusion.enabled", enabled)
    _assert_exact_df(lambda: (
        data.sql("select k, v * 2 as v2 from an_t where v > 0")
        .repartition(4, "k")))
    _assert_exact_df(lambda: (
        data.sql("select k, v * 2 as v2 from an_t where v > 0")
        .repartition(4, "k").groupBy("k").count()))


def test_mesh_fused_single_dispatch_predicted(fusion_conf, data):
    data.conf.set("spark.tpu.fusion.enabled", "true")
    df = (data.sql("select k, v * 2 as v2 from an_t where v > 0")
          .repartition(4, "k"))
    report = df.query_execution.analysis_report()
    assert report.predicted_launches.get("mesh_stage") == 1, \
        report.predicted_launches
    assert "pipeline" not in report.predicted_launches, \
        report.predicted_launches
    assert any("FUSED mesh stage" in n for s in report.stages
               for n in s["notes"]), \
        [n for s in report.stages for n in s["notes"]]


def test_mesh_legacy_mode_prediction_exact(fusion_conf, data):
    """spark.tpu.fusion.mesh=false: the pipeline materializes per batch
    before the collective — the model mirrors that too."""
    data.conf.set("spark.tpu.fusion.enabled", "true")
    data.conf.set("spark.tpu.fusion.mesh", "false")
    try:
        _assert_exact_df(lambda: (
            data.sql("select k, v * 2 as v2 from an_t where v > 0")
            .repartition(4, "k")))
    finally:
        data.conf.unset("spark.tpu.fusion.mesh")


def test_mesh_quota_retry_prediction_exact(fusion_conf, spark):
    """Skewed keys overflow the per-(src,dst) quota: the simulation
    predicts the retry dispatches exactly."""
    n = 6000
    spark.createDataFrame(pa.table({
        "k": np.full(n, 5, np.int64),
        "v": np.arange(n, dtype=np.int64),
    })).createOrReplaceTempView("an_skew")
    spark.conf.set("spark.tpu.fusion.enabled", "true")
    try:
        df = spark.sql("select k, v from an_skew").repartition(4, "k")
        report = df.query_execution.analysis_report()
        assert report.predicted_launches.get("mesh_stage", 0) >= 2, \
            report.predicted_launches
        _assert_exact_df(
            lambda: spark.sql("select k, v from an_skew")
            .repartition(4, "k"))
    finally:
        spark.conf.unset("spark.tpu.fusion.enabled")


@pytest.mark.parametrize("enabled", ["true", "false"])
def test_mesh_sharded_q3_prediction_exact(fusion_conf, spark, enabled):
    """The acceptance query: sharded TPC-DS mini q3 — fact table
    redistributed over the mesh, broadcast join spine, fused partial
    aggregate — predicts exactly, fusion on and off (the join value
    model rides the per-partition mesh reduce traces)."""
    from tpcds_mini import register_tpcds

    register_tpcds(spark)
    spark.sql("select * from store_sales") \
        .repartition(4, "ss_item_sk") \
        .createOrReplaceTempView("an_store_sales_sharded")
    spark.conf.set("spark.tpu.fusion.enabled", enabled)
    _assert_exact(spark, """
        SELECT dt.d_year, item.i_brand_id AS brand_id,
               SUM(ss_ext_sales_price) AS sum_agg
        FROM date_dim dt, an_store_sales_sharded store_sales, item
        WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
          AND store_sales.ss_item_sk = item.i_item_sk
          AND item.i_manufact_id = 28 AND dt.d_moy = 11
        GROUP BY dt.d_year, item.i_brand_id""")


@pytest.mark.parametrize("enabled", ["true", "false"])
def test_string_minmax_fused_prediction_exact(fusion_conf, data, enabled):
    """String MIN/MAX now rides the fused aggregate kernel (rank-space
    reduce, inverse-rank lut as aux input) — and the launch model stays
    exact fusion on and off."""
    data.conf.set("spark.tpu.fusion.enabled", enabled)
    _assert_exact(data, "select k, min(s) mn, max(s) mx, count(*) c "
                        "from an_t where v > 0 group by k")
