"""Scheduler & control-plane tests (reference: DAGSchedulerSuite drives the
event loop with a mock TaskScheduler — here the stage graph + retry logic
are driven directly; SURVEY.md §4)."""

import time

import pyarrow as pa
import pytest

import spark_tpu.api.functions as F
from spark_tpu.exec.context import ExecContext
from spark_tpu.exec.scheduler import (
    BarrierCoordinator, DAGScheduler, ExecutorRegistry, HealthTracker,
    build_stage_graph,
)


def test_stage_graph_cuts_at_exchanges(spark):
    df = (spark.range(0, 1000, 1, 4)
          .groupBy((F.col("id") % 7).alias("m"))
          .agg(F.count("*").alias("c")))
    plan = df.query_execution.physical
    result_stage, stages = build_stage_graph(plan)
    # one shuffle (partial→final agg) + result stage
    assert len(stages) == 2
    assert result_stage.parents[0] in stages


def test_stage_graph_join(spark):
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", -1)
    try:
        a = spark.range(0, 100, 1, 2).withColumn("k", F.col("id") % 10)
        b = spark.range(0, 50, 1, 2).withColumn("k", F.col("id") % 10)
        df = a.join(b, on="k")
        _, stages = build_stage_graph(df.query_execution.physical)
        assert len(stages) == 3  # two shuffle stages + result
    finally:
        spark.conf.unset("spark.sql.autoBroadcastJoinThreshold")


def test_scheduler_results_match_direct(spark):
    df = (spark.range(0, 5000, 1, 8)
          .groupBy((F.col("id") % 13).alias("m"))
          .agg(F.sum("id").alias("s")).orderBy("m"))
    out = df.toArrow().to_pydict()
    assert len(out["m"]) == 13
    assert sum(out["s"]) == sum(range(5000))
    snap = spark._metrics.snapshot()
    assert snap["counters"]["scheduler.stages_completed"] > 0


def test_stage_retry():
    from spark_tpu.physical.operators import PhysicalPlan

    calls = [0]

    class Flaky(PhysicalPlan):
        child_fields = ()

        @property
        def output(self):
            return []

        def execute(self, ctx):
            calls[0] += 1
            if calls[0] == 1:
                raise RuntimeError("transient")
            return [[]]

    sched = DAGScheduler(ExecContext(), max_attempts=2)
    out = sched.run(Flaky())
    assert calls[0] == 2
    assert out == [[]]


def test_stage_retry_exhausted():
    from spark_tpu.physical.operators import PhysicalPlan

    class Broken(PhysicalPlan):
        child_fields = ()

        @property
        def output(self):
            return []

        def execute(self, ctx):
            raise RuntimeError("permanent")

    sched = DAGScheduler(ExecContext(), max_attempts=2)
    with pytest.raises(RuntimeError, match="permanent"):
        sched.run(Broken())


def test_executor_registry_heartbeats():
    reg = ExecutorRegistry(heartbeat_timeout_s=0.05)
    e1 = reg.register("host1", 4)
    e2 = reg.register("host2", 4)
    assert len(reg.alive()) == 2
    time.sleep(0.08)
    reg.heartbeat(e1)
    dead = reg.expire_dead()
    assert dead == [e2]
    assert [e.executor_id for e in reg.alive()] == [e1]
    assert not reg.heartbeat(e2)  # unknown → must re-register


def test_health_tracker_excludes():
    reg = ExecutorRegistry()
    e1 = reg.register("host1")
    ht = HealthTracker(reg, max_failures=2)
    assert not ht.record_failure(e1)
    assert ht.record_failure(e1)
    assert reg.alive() == []


def test_barrier_all_gather():
    import threading

    bc = BarrierCoordinator(3)
    results = {}

    def task(i):
        results[i] = bc.all_gather(i, f"msg{i}")

    ts = [threading.Thread(target=task, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(5)
    assert results[0] == ["msg0", "msg1", "msg2"]
    assert results[1] == results[0]
