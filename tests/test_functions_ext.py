"""Extended function library tests (numpy as oracle)."""

import math

import numpy as np
import pyarrow as pa
import pytest

import spark_tpu.api.functions as F


def q(spark, text):
    return spark.sql(text).toArrow().to_pydict()


def test_trig_and_math(spark):
    out = q(spark, """SELECT sin(0) AS s, cos(0) AS c, atan2(1, 1) AS a,
                             log2(8) AS l2, sign(-3.5) AS sg,
                             degrees(pi()) AS dg, cbrt(27) AS cb""")
    assert out["s"] == [0.0]
    assert out["c"] == [1.0]
    assert abs(out["a"][0] - math.pi / 4) < 1e-12
    assert abs(out["l2"][0] - 3.0) < 1e-9  # XLA log2 is a few ulp off
    assert out["sg"] == [-1.0]
    assert abs(out["dg"][0] - 180.0) < 1e-9
    assert abs(out["cb"][0] - 3.0) < 1e-9


def test_string_extended(spark):
    spark.createDataFrame(pa.table({"s": ["hello world", "aBc"]})) \
        .createOrReplaceTempView("strs")
    out = q(spark, """SELECT initcap(s) AS i, reverse(s) AS r,
                             instr(s, 'o') AS p, ascii(s) AS a,
                             substring_index(s, ' ', 1) AS si
                      FROM strs ORDER BY s""")
    assert out["i"] == ["Abc", "Hello World"]
    assert out["r"] == ["cBa", "dlrow olleh"]
    assert out["p"] == [0, 5]
    assert out["a"] == [ord("a"), ord("h")]
    assert out["si"] == ["aBc", "hello"]


def test_concat_ws_translate_repeat(spark):
    out = q(spark, """SELECT concat_ws('-', 'a', 'b') AS cw,
                             translate('abcba', 'ab', 'xy') AS tr,
                             repeat('ab', 3) AS rp""")
    assert out["cw"] == ["a-b"]
    assert out["tr"] == ["xycyx"]
    assert out["rp"] == ["ababab"]


def test_timestamp_parts(spark):
    out = q(spark, """SELECT hour(TIMESTAMP '2021-03-04 13:45:21') AS h,
                             minute(TIMESTAMP '2021-03-04 13:45:21') AS m,
                             second(TIMESTAMP '2021-03-04 13:45:21') AS s,
                             unix_timestamp(TIMESTAMP '1970-01-01 00:01:00') AS u""")
    assert out["h"] == [13]
    assert out["m"] == [45]
    assert out["s"] == [21]
    assert out["u"] == [60]


def test_month_arithmetic(spark):
    out = q(spark, """SELECT add_months(DATE '2020-01-31', 1) AS feb,
                             last_day(DATE '2020-02-10') AS ld,
                             months_between(DATE '2020-03-15',
                                            DATE '2020-01-15') AS mb""")
    assert str(out["feb"][0]) == "2020-02-29"  # clamped, leap year
    assert str(out["ld"][0]) == "2020-02-29"
    assert abs(out["mb"][0] - 2.0) < 1e-9


def test_corr_covar(spark):
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, 500)
    y = 2 * x + rng.normal(0, 0.1, 500)
    df = spark.createDataFrame(pa.table({"x": x, "y": y}))
    out = df.agg(F.corr("x", "y").alias("c"),
                 F.covar_samp("x", "y").alias("cv")).toArrow().to_pydict()
    assert abs(out["c"][0] - np.corrcoef(x, y)[0, 1]) < 1e-6
    assert abs(out["cv"][0] - np.cov(x, y, ddof=1)[0, 1]) < 1e-6


def test_skew_kurtosis(spark):
    rng = np.random.default_rng(1)
    x = rng.exponential(1.0, 2000)
    df = spark.createDataFrame(pa.table({"x": x}))
    out = df.agg(F.skewness("x").alias("sk"),
                 F.kurtosis("x").alias("ku")).toArrow().to_pydict()
    n = len(x)
    mu = x.mean()
    m2 = ((x - mu) ** 2).mean()
    m3 = ((x - mu) ** 3).mean()
    m4 = ((x - mu) ** 4).mean()
    assert abs(out["sk"][0] - m3 / m2 ** 1.5) < 1e-6
    assert abs(out["ku"][0] - (m4 / m2 ** 2 - 3)) < 1e-6


def test_sum_distinct(spark):
    df = spark.createDataFrame(pa.table({"x": [1, 1, 2, 3, 3]}))
    out = df.agg(F.sum_distinct("x").alias("s")).toArrow().to_pydict()
    assert out["s"] == [6]
    out2 = q(spark, "SELECT sum(DISTINCT x) AS s FROM "
                    "(SELECT col1 AS x FROM (VALUES (1), (1), (5)))")
    assert out2["s"] == [6]


def test_corr_with_nulls(spark):
    df = spark.createDataFrame(pa.table({
        "x": pa.array([1.0, 2.0, None, 4.0], pa.float64()),
        "y": pa.array([2.0, 4.0, 6.0, None], pa.float64())}))
    out = df.agg(F.corr("x", "y").alias("c")).toArrow().to_pydict()
    # only rows (1,2),(2,4) count → perfect correlation... but 2 points
    assert abs(out["c"][0] - 1.0) < 1e-9


def test_interval_date_arithmetic(spark):
    out = q(spark, """SELECT DATE '2000-01-31' + INTERVAL 1 MONTH AS m,
                             DATE '2000-01-01' + INTERVAL 30 DAYS AS d,
                             DATE '2000-03-01' - INTERVAL '1' DAY AS s,
                             TIMESTAMP '2000-01-01 00:00:00' + INTERVAL 2 HOURS AS h""")
    assert str(out["m"][0]) == "2000-02-29"
    assert str(out["d"][0]) == "2000-01-31"
    assert str(out["s"][0]) == "2000-02-29"
    assert "02:00" in str(out["h"][0])


def test_interval_in_predicate(spark):
    import pyarrow as pa
    import datetime

    spark.createDataFrame(pa.table({
        "d": pa.array([datetime.date(2000, 1, 5), datetime.date(2000, 3, 5)],
                      pa.date32())})).createOrReplaceTempView("dts")
    out = q(spark, """SELECT count(*) AS c FROM dts
                      WHERE d BETWEEN DATE '2000-01-01'
                                  AND DATE '2000-01-01' + INTERVAL 60 DAYS""")
    assert out["c"] == [1]


def test_concat_two_string_columns(spark):
    df = spark.createDataFrame(pa.table({
        "a": ["x", "y", None], "b": ["1", "2", "3"]}))
    out = df.select(F.concat("a", "b").alias("c")).toArrow().to_pydict()
    assert out["c"] == ["x1", "y2", None]
    out2 = q(spark, "SELECT first || '-' || last AS full FROM "
                    "(SELECT col1 AS first, col2 AS last FROM "
                    "(VALUES ('ada', 'lovelace')))")
    assert out2["full"] == ["ada-lovelace"]


def test_cast_to_string(spark):
    import datetime

    df = spark.createDataFrame(pa.table({
        "i": [42, 7],
        "d": pa.array([datetime.date(2020, 1, 2)] * 2, pa.date32())}))
    out = df.select(F.col("i").cast("string").alias("s"),
                    F.col("d").cast("string").alias("ds")) \
        .toArrow().to_pydict()
    assert out["s"] == ["42", "7"]
    assert out["ds"] == ["2020-01-02", "2020-01-02"]


def test_date_vs_string_literal_comparison(spark):
    import datetime

    df = spark.createDataFrame(pa.table({
        "d": pa.array([datetime.date(1999, 1, 15),
                       datetime.date(2001, 6, 1)], pa.date32())}))
    df.createOrReplaceTempView("dcmp")
    out = q(spark, "SELECT count(*) AS c FROM dcmp "
                   "WHERE d BETWEEN '1999-01-01' AND '1999-12-31'")
    assert out["c"] == [1]


def test_regexp_extract_and_date_format(spark):
    out = q(spark, """SELECT regexp_extract('abc-123-xyz', '([0-9]+)', 1) AS n,
                             date_format(DATE '2021-07-04', 'yyyy/MM/dd') AS d,
                             date_format(DATE '2021-07-04', 'EEEE') AS w""")
    assert out["n"] == ["123"]
    assert out["d"] == ["2021/07/04"]
    assert out["w"] == ["Sunday"]


def test_decimal_multiply_exact(spark):
    import decimal

    df = spark.createDataFrame(pa.table({
        "qty": pa.array([3, 7], pa.int32()),
        "price": pa.array([decimal.Decimal("19.99"),
                           decimal.Decimal("0.01")],
                          pa.decimal128(7, 2))}))
    out = df.select((F.col("qty") * F.col("price")).alias("amt")) \
        .agg(F.sum("amt").alias("total")).toArrow().to_pydict()
    import decimal as _d

    # exact: 3*19.99 + 7*0.01 = 60.04 — arrives as an exact Decimal
    assert out["total"][0] == _d.Decimal("60.04")


def test_nan_sort_order(spark):
    df = spark.createDataFrame(pa.table({
        "v": [1.0, float("nan"), -5.0]}))
    asc = df.orderBy("v").toArrow().to_pydict()["v"]
    assert asc[0] == -5.0 and asc[1] == 1.0
    import math

    assert math.isnan(asc[2])  # NaN largest → last asc
    desc = df.orderBy(F.col("v").desc()).toArrow().to_pydict()["v"]
    assert math.isnan(desc[0])  # first desc


def test_median_percentile(spark):
    import numpy as np

    rng = np.random.default_rng(12)
    v = rng.permutation(np.arange(1, 102)).astype(np.float64)  # 1..101
    df = spark.createDataFrame(pa.table({"v": v}))
    out = df.agg(F.median("v").alias("m"),
                 F.percentile_approx("v", 0.25).alias("q1")).toArrow() \
        .to_pydict()
    assert out["m"] == [51.0]
    assert out["q1"] == [26.0]


def test_grouped_median_multi_partition(spark):
    import numpy as np

    df = spark.createDataFrame(pa.table({
        "g": ["a"] * 5 + ["b"] * 4,
        "v": [5.0, 1.0, 3.0, 2.0, 4.0, 10.0, 30.0, 20.0, 40.0]}))
    out = (df.repartition(3).groupBy("g")
           .agg(F.median("v").alias("m")).orderBy("g")
           .toArrow().to_pydict())
    assert out["m"] == [3.0, 20.0]  # even count → lower-middle element


def test_percentile_sql(spark):
    out = spark.sql(
        "SELECT percentile(col1, 0.5) AS p FROM "
        "(VALUES (1.0), (2.0), (3.0))").toArrow().to_pydict()
    assert out["p"] == [2.0]


def test_regexp_extract_replace(spark):
    spark.createDataFrame(pa.table(
        {"s": ["user-123-end", "no-digits-here", "x9y"]})) \
        .createOrReplaceTempView("rex")
    out = q(spark, r"""
        SELECT regexp_extract(s, '(\d+)', 1) AS d,
               regexp_extract(s, '([a-z]+)-(\d+)', 2) AS g2,
               regexp_replace(s, '\d+', '#') AS rp
        FROM rex ORDER BY s""")
    assert out["d"] == ["", "123", "9"]
    assert out["g2"] == ["", "123", ""]
    assert out["rp"] == ["no-digits-here", "user-#-end", "x#y"]


def test_regexp_replace_group_refs(spark):
    spark.createDataFrame(pa.table({"s": ["ab", "cd"]})) \
        .createOrReplaceTempView("rex2")
    out = q(spark, r"""
        SELECT regexp_replace(s, '(a)(b)', '$2$1') AS sw FROM rex2
        ORDER BY s""")
    assert out["sw"] == ["ba", "cd"]


def test_collect_list_and_set(spark):
    spark.createDataFrame(pa.table({
        "k": ["a", "a", "b", "a", "b"],
        "v": [1, 2, 1, 2, None],
        "s": ["x", "y", "x", "y", "z"],
    })).createOrReplaceTempView("coll")
    out = q(spark, """
        SELECT k, collect_list(v) AS l, collect_set(v) AS st,
               collect_list(s) AS ls
        FROM coll GROUP BY k ORDER BY k""")
    assert out["l"] == [[1, 2, 2], [1]]       # nulls skipped
    assert out["st"] == [[1, 2], [1]]
    assert out["ls"] == [["x", "y", "y"], ["x", "z"]]


def test_collect_ungrouped_and_df_api(spark):
    df = spark.createDataFrame(pa.table({"v": [3, 1, 3, 2]}))
    rows = df.agg(F.collect_set(df["v"]).alias("s"),
                  F.collect_list(df["v"]).alias("l")).collect()
    assert rows[0]["s"] == [3, 1, 2]
    assert rows[0]["l"] == [3, 1, 3, 2]


def test_array_agg_alias(spark):
    spark.createDataFrame(pa.table({"v": [1, 2]})) \
        .createOrReplaceTempView("aa")
    out = q(spark, "SELECT array_agg(v) AS a FROM aa")
    assert out["a"] == [[1, 2]]


def test_array_functions(spark):
    spark.createDataFrame(pa.table({
        "k": ["a", "a", "b", "b", "c"],
        "v": [3, 1, 2, 2, 7],
    })).createOrReplaceTempView("arr_src")
    spark.sql("""CREATE OR REPLACE TEMP VIEW arrs AS
                 SELECT k, collect_list(v) AS l FROM arr_src GROUP BY k""")
    out = q(spark, """
        SELECT k, size(l) AS n, array_contains(l, 2) AS has2,
               array_min(l) AS lo, array_max(l) AS hi,
               sort_array(l) AS srt, array_distinct(l) AS dst,
               element_at(l, 1) AS first_e, element_at(l, -1) AS last_e
        FROM arrs ORDER BY k""")
    assert out["n"] == [2, 2, 1]
    assert out["has2"] == [False, True, False]
    assert out["lo"] == [1, 2, 7]
    assert out["hi"] == [3, 2, 7]
    assert out["srt"] == [[1, 3], [2, 2], [7]]
    assert out["dst"] == [[3, 1], [2], [7]]
    assert out["first_e"] == [3, 2, 7]
    assert out["last_e"] == [1, 2, 7]


def test_array_functions_strings(spark):
    spark.createDataFrame(pa.table({"s": ["b a c", "z"]})) \
        .createOrReplaceTempView("arrstr_src")
    spark.sql("""CREATE OR REPLACE TEMP VIEW arrstr AS
                 SELECT s, split(s, ' ') AS parts FROM arrstr_src""")
    out = q(spark, """
        SELECT size(parts) AS n, element_at(parts, 2) AS e2,
               sort_array(parts) AS srt
        FROM arrstr ORDER BY s""")
    assert out["n"] == [3, 1]
    assert out["e2"] == ["a", None]  # NULL for out-of-bounds, like the ref
    assert out["srt"] == [["a", "b", "c"], ["z"]]


def test_more_string_functions(spark):
    spark.createDataFrame(pa.table({"s": ["hello", "spark"]})) \
        .createOrReplaceTempView("mstr")
    out = q(spark, """
        SELECT left(s, 2) AS l, right(s, 2) AS r,
               overlay(s, 'XX', 2) AS ov, soundex(s) AS sx,
               levenshtein(s, 'hello') AS lv,
               md5(s) AS m, base64(s) AS b64,
               unbase64(base64(s)) AS rt
        FROM mstr ORDER BY s""")
    assert out["l"] == ["he", "sp"]
    assert out["r"] == ["lo", "rk"]
    assert out["ov"] == ["hXXlo", "sXXrk"]
    assert out["sx"] == ["H400", "S162"]
    assert out["lv"] == [0, 5]
    import hashlib

    assert out["m"][0] == hashlib.md5(b"hello").hexdigest()
    import base64 as b64mod

    assert out["b64"][0] == b64mod.b64encode(b"hello").decode()
    assert out["rt"] == ["hello", "spark"]


def test_format_number_and_try_divide(spark):
    out = q(spark, """SELECT format_number(1234567.891, 2) AS f,
                             try_divide(10, 0) AS t0,
                             try_divide(10, 4) AS t1""")
    assert out["f"] == ["1,234,567.89"]
    assert out["t0"] == [None]
    assert out["t1"] == [2.5]


def test_try_arithmetic_overflow_nulls(spark):
    # try_* return NULL on int64 overflow instead of wrapping (ADVICE r1)
    out = q(spark, """SELECT try_add(9223372036854775807, 1) AS a,
                             try_add(1, 2) AS a2,
                             try_subtract(-9223372036854775808, 1) AS s,
                             try_subtract(5, 3) AS s2,
                             try_multiply(4611686018427387904, 4) AS m,
                             try_multiply(7, 6) AS m2""")
    assert out["a"] == [None]
    assert out["a2"] == [3]
    assert out["s"] == [None]
    assert out["s2"] == [2]
    assert out["m"] == [None]
    assert out["m2"] == [42]


def test_unbase64_sha2_invalid_null(spark):
    # invalid base64 / unsupported sha2 bit length → NULL (ADVICE r1)
    out = q(spark, """SELECT unbase64('!!!bad') AS u,
                             sha2('x', 7) AS s7,
                             sha2('abc', 224) AS s224""")
    assert out["u"] == [None]
    assert out["s7"] == [None]
    import hashlib

    assert out["s224"] == [hashlib.sha224(b"abc").hexdigest()]
