"""Scan pruning: static predicate pushdown (partition dirs + parquet
row-group stats) and dynamic partition pruning from a join's build side
(reference: ParquetFileFormat row-group filter, PartitionPruning.scala,
InjectRuntimeFilter.scala bloom branch)."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest


@pytest.fixture()
def part_dir(tmp_path):
    """Hive-partitioned fact table: part=0..3, plus a dim table."""
    root = tmp_path / "fact"
    rng = np.random.default_rng(3)
    for p in range(4):
        d = root / f"part={p}"
        os.makedirs(d)
        # two row groups per file with disjoint v ranges for stats pruning
        t = pa.table({"v": np.arange(100) + p * 1000,
                      "w": rng.integers(0, 5, 100)})
        pq.write_table(t, d / "f.parquet", row_group_size=50)
    return str(root)


def _fresh_session():
    from spark_tpu import TpuSession

    return TpuSession("pruning", {"spark.tpu.batch.capacity": 1 << 10})


def test_static_partition_pruning(part_dir):
    s = _fresh_session()
    try:
        df = s.read.parquet(part_dir)
        df.createOrReplaceTempView("fact")
        out = s.sql("SELECT count(*) c FROM fact WHERE part = 2") \
            .toArrow().to_pylist()
        assert out == [{"c": 100}]
        m = s._metrics.snapshot()["counters"]
        # only part=2's splits were read
        read = [k for k in m if k.startswith("scan.") and k.endswith(".rows")]
        assert sum(m[k] for k in read) == 100, m
    finally:
        s.stop()


def test_rowgroup_stats_pruning(part_dir):
    s = _fresh_session()
    try:
        df = s.read.parquet(part_dir)
        df.createOrReplaceTempView("fact")
        # v >= 3050 lives in the second row group of part=3 only
        out = s.sql("SELECT count(*) c FROM fact WHERE v >= 3050") \
            .toArrow().to_pylist()
        assert out == [{"c": 50}]
        m = s._metrics.snapshot()["counters"]
        read = [k for k in m if k.startswith("scan.") and k.endswith(".rows")]
        assert sum(m[k] for k in read) == 50, m
    finally:
        s.stop()


def test_in_predicate_pruning(part_dir):
    s = _fresh_session()
    try:
        s.read.parquet(part_dir).createOrReplaceTempView("fact")
        out = s.sql("SELECT count(*) c FROM fact WHERE part IN (0, 3)") \
            .toArrow().to_pylist()
        assert out == [{"c": 200}]
    finally:
        s.stop()


def test_dynamic_partition_pruning(part_dir):
    s = _fresh_session()
    try:
        s.read.parquet(part_dir).createOrReplaceTempView("fact")
        dim = pa.table({"pk": [1, 3], "name": ["a", "b"]})
        s.createDataFrame(dim).createOrReplaceTempView("dim")
        out = s.sql(
            "SELECT count(*) c FROM fact JOIN dim ON fact.part = dim.pk"
        ).toArrow().to_pylist()
        assert out == [{"c": 200}]
        m = s._metrics.snapshot()["counters"]
        assert m.get("scan.dpp_pruned_splits", 0) >= 2, m
    finally:
        s.stop()


def test_dpp_disabled_still_correct(part_dir):
    s = _fresh_session()
    try:
        s.conf.set("spark.sql.dynamicPartitionPruning.enabled", "false")
        s.read.parquet(part_dir).createOrReplaceTempView("fact")
        dim = pa.table({"pk": [1, 3], "name": ["a", "b"]})
        s.createDataFrame(dim).createOrReplaceTempView("dim")
        out = s.sql(
            "SELECT count(*) c FROM fact JOIN dim ON fact.part = dim.pk"
        ).toArrow().to_pylist()
        assert out == [{"c": 200}]
        m = s._metrics.snapshot()["counters"]
        assert m.get("scan.dpp_pruned_splits", 0) == 0, m
    finally:
        s.stop()


def test_bloom_runtime_filter_reduces_probe(part_dir):
    s = _fresh_session()
    try:
        s.conf.set("spark.tpu.join.runtimeFilter.bloom", "true")
        s.conf.set("spark.sql.dynamicPartitionPruning.enabled", "false")
        n = 4000
        rng = np.random.default_rng(5)
        # sparse keys: the dense-build fast path would bypass the bloom
        # stage (it needs no filter), so spread the key domain wide
        fact = pa.table({"k": rng.integers(0, 1000, n) * 999_999_937,
                         "v": rng.standard_normal(n)})
        dim = pa.table({"k": np.arange(0, 10) * 999_999_937,
                        "nm": [str(i) for i in range(10)]})
        s.createDataFrame(fact).createOrReplaceTempView("f")
        s.createDataFrame(dim).createOrReplaceTempView("d")
        out = s.sql("SELECT count(*) c FROM f JOIN d ON f.k = d.k") \
            .toArrow().to_pylist()
        want = int(np.isin(fact["k"].to_numpy(), dim["k"].to_numpy()).sum())
        assert out == [{"c": want}]
        m = s._metrics.snapshot()["counters"]
        assert m.get("join.bloom_filtered_rows", 0) > n // 2, m
    finally:
        s.stop()
