"""Local-cluster (multi-process executor) tests
(reference: core DistributedSuite over local-cluster[n,c,m]).

Task functions are defined inside the tests (closures) so cloudpickle
serializes them by value — module-level functions would be pickled by
reference to a module the workers cannot import (the reference ships user
code via --py-files; closures are its common case too)."""

import os
import time

import pytest

from spark_tpu.exec.cluster import (
    ExecutorLostError, LocalCluster, RemoteTaskError,
)
from spark_tpu.rdd import RDDContext


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(num_workers=3)
    yield c
    c.stop()


def test_tasks_run_in_separate_processes(cluster):
    pids = set(cluster.map(lambda _: os.getpid(), range(6)))
    assert os.getpid() not in pids
    assert len(pids) >= 2  # spread across workers


def test_task_results(cluster):
    assert cluster.map(lambda x: x * x, [1, 2, 3]) == [1, 4, 9]


def test_deterministic_task_error_propagates(cluster):
    def boom(x):
        raise ValueError(f"bad {x}")

    with pytest.raises(RemoteTaskError, match="bad 7"):
        cluster.run_task(boom, 7)
    # cluster still healthy afterwards
    assert cluster.run_task(lambda x: x * x, 4) == 16


def test_executor_loss_retries_elsewhere(cluster):
    n0 = cluster.num_alive()
    with pytest.raises((ExecutorLostError, Exception)):
        # the task kills every executor it lands on; after max failures the
        # driver gives up — but other tasks must still run on survivors
        cluster.run_task(lambda _: os._exit(42), 0)
    assert cluster.num_alive() < n0
    if cluster.num_alive():
        assert cluster.run_task(lambda x: x * x, 5) == 25


def test_rdd_on_cluster():
    c = LocalCluster(num_workers=2)
    try:
        sc = RDDContext(parallelism=4, cluster=c)
        r = sc.parallelize(range(100), 4)
        assert r.map(lambda x: x + 1).filter(lambda x: x % 2 == 0).count() == 50
        out = dict(r.map(lambda x: (x % 3, 1))
                   .reduceByKey(lambda a, b: a + b).collect())
        assert out == {0: 34, 1: 33, 2: 33}
        # tasks really ran off-driver
        pids = set(r.mapPartitions(
            lambda it: iter([os.getpid()])).collect())
        assert os.getpid() not in pids
    finally:
        c.stop()


def test_distributed_sql_stages():
    import numpy as np
    import pyarrow as pa

    from spark_tpu.api.session import TpuSession
    from spark_tpu.exec.cluster import LocalCluster

    s = TpuSession("csql_t", {"spark.sql.shuffle.partitions": "4"})
    s.attachSqlCluster(LocalCluster(num_workers=2))
    try:
        rng = np.random.default_rng(0)
        n = 20000
        keys = rng.integers(0, 40, n)
        vals = rng.random(n)
        s.createDataFrame(pa.table({"k": keys, "v": vals})) \
            .createOrReplaceTempView("cbig")
        # repartition forces a shuffle exchange → a remote map stage
        import spark_tpu.api.functions as F

        df = s.table("cbig").repartition(4) \
            .groupBy("k").agg(F.sum("v").alias("sv"))
        got = {r["k"]: r["sv"] for r in df.collect()}
        exp = {}
        for k, v in zip(keys.tolist(), vals.tolist()):
            exp[k] = exp.get(k, 0.0) + v
        assert set(got) == set(exp)
        for k in exp:
            assert abs(got[k] - exp[k]) < 1e-6
        remote = s._metrics.snapshot()["counters"].get(
            "scheduler.stages_remote", 0)
        assert remote >= 1
    finally:
        s.stop()


def test_distributed_sql_join_and_worker_loss():
    import numpy as np
    import pyarrow as pa

    from spark_tpu.api.session import TpuSession
    from spark_tpu.exec.cluster import LocalCluster

    s = TpuSession("csql_j", {"spark.sql.shuffle.partitions": "3"})
    cluster = LocalCluster(num_workers=2)
    s.attachSqlCluster(cluster)
    try:
        n = 5000
        rng = np.random.default_rng(1)
        s.createDataFrame(pa.table({
            "k": rng.integers(0, 20, n), "v": np.ones(n)})) \
            .createOrReplaceTempView("cfact")
        s.createDataFrame(pa.table({
            "k": np.arange(20), "name": [f"n{i}" for i in range(20)]})) \
            .createOrReplaceTempView("cdim")
        q = ("SELECT d.name, sum(f.v) AS s FROM cfact f "
             "JOIN cdim d ON f.k = d.k GROUP BY d.name")
        out1 = s.sql(q).toArrow().to_pydict()
        assert sum(out1["s"]) == n

        # kill one worker; the next query must still succeed (task retry
        # on the surviving executor)
        w = next(iter(cluster._workers.values()))
        w.proc.kill()
        w.proc.wait(timeout=10)
        out2 = s.sql(q).toArrow().to_pydict()
        assert sum(out2["s"]) == n
    finally:
        s.stop()


def test_fetch_failure_regenerates_lost_map_outputs(monkeypatch):
    """Worker dies AFTER its map stage completed (blocks lost, task ok):
    the consumer's fetch fails and the scheduler re-runs only the lost
    map stage from lineage (reference: DAGScheduler FetchFailed →
    resubmit missing map stages)."""
    import numpy as np
    import pyarrow as pa

    import spark_tpu.exec.cluster_sql as CS
    from spark_tpu.api.session import TpuSession
    from spark_tpu.exec.cluster import LocalCluster

    s = TpuSession("csql_ff", {"spark.sql.shuffle.partitions": "3"})
    cluster = LocalCluster(num_workers=2)
    s.attachSqlCluster(cluster)

    state = {"killed": False}
    orig = CS.ClusterDAGScheduler._run_remote

    def kill_after_first_map(self, stage):
        status = orig(self, stage)
        if not state["killed"]:
            state["killed"] = True
            w = cluster._workers[status.executor_id]
            w.proc.kill()
            w.proc.wait(timeout=10)
        return status

    monkeypatch.setattr(CS.ClusterDAGScheduler, "_run_remote",
                        kill_after_first_map)
    try:
        n = 4000
        rng = np.random.default_rng(7)
        s.createDataFrame(pa.table({
            "k": rng.integers(0, 30, n),
            "v": rng.integers(1, 5, n)})) \
            .createOrReplaceTempView("ffact")
        df = s.table("ffact").repartition(3).groupBy("k").count()
        got = {r["k"]: r["count"] for r in df.collect()}
        import collections

        rng2 = np.random.default_rng(7)
        keys = rng2.integers(0, 30, n)
        exp = collections.Counter(keys.tolist())
        assert got == dict(exp)
        m = s._metrics.snapshot()["counters"]
        assert m.get("scheduler.fetch_failures", 0) >= 1, m
        assert m.get("shuffle.blocks_fetched", 0) >= 3, m
    finally:
        s.stop()
        cluster.stop()


# ---------------------------------------------------------------------------
# Speculation + barrier (r4; TaskSetManager.scala:80-88,
# core/BarrierTaskContext.scala)
# ---------------------------------------------------------------------------

def test_speculative_copy_wins_over_straggler():
    """One executor is made a straggler; the speculative copy launched on
    the other executor finishes first and its result wins."""
    import tempfile

    c = LocalCluster(num_workers=2, speculation=True,
                     speculation_interval=0.5)
    try:
        marker = tempfile.mktemp(prefix="sparktpu-straggle-")

        def straggle_once(path):
            import os as _os
            import time as _time

            # the FIRST executor to run the task stalls; the speculative
            # copy (second executor) sees the marker and returns fast
            if not _os.path.exists(path):
                open(path, "w").close()
                _time.sleep(8.0)
                return "straggler"
            return "fast"

        t0 = time.monotonic()
        out = c.run_task(straggle_once, marker)
        took = time.monotonic() - t0
        assert out == "fast"
        assert took < 6.0, f"straggler was awaited ({took:.1f}s)"
        assert c.stats.get("speculative_launched", 0) >= 1
        assert c.stats.get("speculative_wins", 0) >= 1
    finally:
        c.stop()


def test_speculation_threshold_from_history():
    c = LocalCluster(num_workers=2, speculation=True)
    try:
        assert c._speculation_threshold() is None  # no history yet
        for _ in range(4):
            c.run_task(lambda x: x, 1)
        th = c._speculation_threshold()
        assert th is not None and th >= 0.1
    finally:
        c.stop()


def test_barrier_all_gather_across_executors():
    from spark_tpu.exec.barrier import run_barrier_job

    c = LocalCluster(num_workers=3)
    try:
        def task(ctx):
            import os as _os

            gathered = ctx.allGather((ctx.task_id, _os.getpid()))
            ctx.barrier()
            return gathered

        outs = run_barrier_job(c, task, num_tasks=3)
        assert len(outs) == 3
        # every task saw all three messages, ordered by task id
        for got in outs:
            assert [t for t, _ in got] == [0, 1, 2]
        pids = {p for _, p in outs[0]}
        assert len(pids) == 3  # three distinct executor processes
    finally:
        c.stop()


def test_barrier_times_out_when_gang_incomplete():
    from spark_tpu.exec.barrier import BarrierTaskContext

    c = LocalCluster(num_workers=1)
    try:
        ctx = BarrierTaskContext(c.driver_addr, c.token, "lonely", 0, 2,
                                 timeout=1.0)
        with pytest.raises(Exception, match="barrier"):
            ctx.allGather("only me")
    finally:
        c.stop()


def test_dynamic_allocation_grows_and_shrinks():
    """Backlog of slow tasks grows the pool past its floor; idle
    executors retire back to it (ExecutorAllocationManager.scala:102)."""
    c = LocalCluster(num_workers=1, dynamic_allocation=True,
                     max_workers=3, executor_idle_timeout=2.0)
    try:
        assert c.num_alive() == 1
        # 4 concurrent 3s tasks on 1 worker → sustained backlog → growth
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=4) as pool:
            futs = [pool.submit(c.run_task,
                                lambda _: __import__("time").sleep(3.0),
                                i) for i in range(4)]
            for f in futs:
                f.result(timeout=60)
        assert c.stats.get("executors_added", 0) >= 1
        grown = c.num_alive()
        assert grown >= 2
        # idle: retire back to the floor
        deadline = time.monotonic() + 30
        while c.num_alive() > 1 and time.monotonic() < deadline:
            time.sleep(0.5)
        assert c.num_alive() == 1
        assert c.stats.get("executors_retired", 0) >= grown - 1
        # still functional after scale-in
        assert c.run_task(lambda x: x + 1, 41) == 42
    finally:
        c.stop()


def test_shuffle_service_survives_executor_loss(monkeypatch):
    """With the external shuffle service on, killing the producer AFTER
    its map stage does NOT force recomputation: the consumer fetches the
    persisted blocks from the service (ExternalShuffleService.scala
    role) and zero fetch failures are recorded."""
    import numpy as np
    import pyarrow as pa

    import spark_tpu.exec.cluster_sql as CS
    from spark_tpu.api.session import TpuSession

    s = TpuSession("csql_ess", {"spark.sql.shuffle.partitions": "3"})
    cluster = LocalCluster(num_workers=2, shuffle_service=True)
    s.attachSqlCluster(cluster)

    state = {"killed": False}
    orig = CS.ClusterDAGScheduler._run_remote

    def kill_after_first_map(self, stage):
        status = orig(self, stage)
        if not state["killed"]:
            state["killed"] = True
            w = cluster._workers[status.executor_id]
            w.proc.kill()
            w.proc.wait(timeout=10)
        return status

    monkeypatch.setattr(CS.ClusterDAGScheduler, "_run_remote",
                        kill_after_first_map)
    try:
        n = 4000
        rng = np.random.default_rng(9)
        keys = rng.integers(0, 30, n)
        s.createDataFrame(pa.table({
            "k": keys, "v": rng.integers(1, 5, n)})) \
            .createOrReplaceTempView("essfact")
        df = s.table("essfact").repartition(3).groupBy("k").count()
        got = {r["k"]: r["count"] for r in df.collect()}
        import collections

        assert got == dict(collections.Counter(keys.tolist()))
        assert state["killed"], "the kill hook never fired"
        m = s._metrics.snapshot()["counters"]
        # the whole point: no FetchFailed → no map-stage regeneration
        assert m.get("scheduler.fetch_failures", 0) == 0, m
    finally:
        s.stop()


def test_fair_pools_share_slots():
    """FAIR pools (core/scheduler/Pool.scala): a task from an empty pool
    is offered the next slot ahead of a backlog from another pool."""
    import queue as _q

    c = LocalCluster(num_workers=2)
    try:
        done: _q.Queue = _q.Queue()
        from concurrent.futures import ThreadPoolExecutor

        def slow(tag):
            import time as _t

            _t.sleep(0.8)
            return tag

        with ThreadPoolExecutor(max_workers=9) as pool:
            futs = [pool.submit(
                lambda i=i: done.put(
                    c.run_task(slow, f"bulk{i}", pool="bulk")))
                for i in range(6)]
            import time as _t

            # wait until bulk PROVABLY occupies both slots with a queue
            # behind them (a fixed sleep races machine load)
            deadline = _t.monotonic() + 30
            while _t.monotonic() < deadline and not (
                    c._pool_running.get("bulk", 0) >= 2
                    and c._pool_waiting.get("bulk", 0) >= 2):
                _t.sleep(0.02)
            futs.append(pool.submit(
                lambda: done.put(
                    c.run_task(slow, "interactive", pool="fast"))))
            for f in futs:
                f.result(timeout=60)
        order = []
        while not done.empty():
            order.append(done.get())
        # the interactive task must NOT be last: FAIR lets the empty
        # pool jump the bulk backlog (FIFO would finish all bulk first)
        assert order.index("interactive") < len(order) - 2, order
    finally:
        c.stop()


def test_push_shuffle_survives_executor_loss(monkeypatch):
    """Push-based shuffle (ShuffleBlockPusher → RemoteBlockPushResolver
    role): mappers ship blocks to the shuffle service over the NETWORK
    (no shared filesystem), so a producer lost after its map stage does
    not force recomputation."""
    import numpy as np
    import pyarrow as pa

    import spark_tpu.exec.cluster_sql as CS
    from spark_tpu.api.session import TpuSession

    s = TpuSession("csql_push", {"spark.sql.shuffle.partitions": "3"})
    cluster = LocalCluster(num_workers=2, push_shuffle=True)
    s.attachSqlCluster(cluster)

    state = {"killed": False}
    orig = CS.ClusterDAGScheduler._run_remote

    def kill_after_first_map(self, stage):
        status = orig(self, stage)
        if not state["killed"]:
            state["killed"] = True
            w = cluster._workers[status.executor_id]
            w.proc.kill()
            w.proc.wait(timeout=10)
        return status

    monkeypatch.setattr(CS.ClusterDAGScheduler, "_run_remote",
                        kill_after_first_map)
    try:
        n = 3000
        rng = np.random.default_rng(11)
        keys = rng.integers(0, 25, n)
        s.createDataFrame(pa.table({
            "k": keys, "v": rng.integers(1, 4, n)})) \
            .createOrReplaceTempView("pushfact")
        df = s.table("pushfact").repartition(3).groupBy("k").count()
        got = {r["k"]: r["count"] for r in df.collect()}
        import collections

        assert got == dict(collections.Counter(keys.tolist()))
        assert state["killed"]
        m = s._metrics.snapshot()["counters"]
        assert m.get("scheduler.fetch_failures", 0) == 0, m
        # the blocks really travelled through the service's MERGED
        # chunks (push → merge → fetch-merged), not per-map originals
        assert m.get("shuffle.merged_chunks_fetched", 0) >= 1, m
        # and the query's shuffle state was cleaned up at the service
        import os as _os

        leftovers = sum(len(fs) for _, _, fs in
                        _os.walk(cluster._shuffle_dir))
        assert leftovers == 0, leftovers
    finally:
        s.stop()
