"""Local-cluster (multi-process executor) tests
(reference: core DistributedSuite over local-cluster[n,c,m]).

Task functions are defined inside the tests (closures) so cloudpickle
serializes them by value — module-level functions would be pickled by
reference to a module the workers cannot import (the reference ships user
code via --py-files; closures are its common case too)."""

import os
import time

import pytest

from spark_tpu.exec.cluster import (
    ExecutorLostError, LocalCluster, RemoteTaskError,
)
from spark_tpu.rdd import RDDContext


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(num_workers=3)
    yield c
    c.stop()


def test_tasks_run_in_separate_processes(cluster):
    pids = set(cluster.map(lambda _: os.getpid(), range(6)))
    assert os.getpid() not in pids
    assert len(pids) >= 2  # spread across workers


def test_task_results(cluster):
    assert cluster.map(lambda x: x * x, [1, 2, 3]) == [1, 4, 9]


def test_deterministic_task_error_propagates(cluster):
    def boom(x):
        raise ValueError(f"bad {x}")

    with pytest.raises(RemoteTaskError, match="bad 7"):
        cluster.run_task(boom, 7)
    # cluster still healthy afterwards
    assert cluster.run_task(lambda x: x * x, 4) == 16


def test_executor_loss_retries_elsewhere(cluster):
    n0 = cluster.num_alive()
    with pytest.raises((ExecutorLostError, Exception)):
        # the task kills every executor it lands on; after max failures the
        # driver gives up — but other tasks must still run on survivors
        cluster.run_task(lambda _: os._exit(42), 0)
    assert cluster.num_alive() < n0
    if cluster.num_alive():
        assert cluster.run_task(lambda x: x * x, 5) == 25


def test_rdd_on_cluster():
    c = LocalCluster(num_workers=2)
    try:
        sc = RDDContext(parallelism=4, cluster=c)
        r = sc.parallelize(range(100), 4)
        assert r.map(lambda x: x + 1).filter(lambda x: x % 2 == 0).count() == 50
        out = dict(r.map(lambda x: (x % 3, 1))
                   .reduceByKey(lambda a, b: a + b).collect())
        assert out == {0: 34, 1: 33, 2: 33}
        # tasks really ran off-driver
        pids = set(r.mapPartitions(
            lambda it: iter([os.getpid()])).collect())
        assert os.getpid() not in pids
    finally:
        c.stop()


def test_distributed_sql_stages():
    import numpy as np
    import pyarrow as pa

    from spark_tpu.api.session import TpuSession
    from spark_tpu.exec.cluster import LocalCluster

    s = TpuSession("csql_t", {"spark.sql.shuffle.partitions": "4"})
    s.attachSqlCluster(LocalCluster(num_workers=2))
    try:
        rng = np.random.default_rng(0)
        n = 20000
        keys = rng.integers(0, 40, n)
        vals = rng.random(n)
        s.createDataFrame(pa.table({"k": keys, "v": vals})) \
            .createOrReplaceTempView("cbig")
        # repartition forces a shuffle exchange → a remote map stage
        import spark_tpu.api.functions as F

        df = s.table("cbig").repartition(4) \
            .groupBy("k").agg(F.sum("v").alias("sv"))
        got = {r["k"]: r["sv"] for r in df.collect()}
        exp = {}
        for k, v in zip(keys.tolist(), vals.tolist()):
            exp[k] = exp.get(k, 0.0) + v
        assert set(got) == set(exp)
        for k in exp:
            assert abs(got[k] - exp[k]) < 1e-6
        remote = s._metrics.snapshot()["counters"].get(
            "scheduler.stages_remote", 0)
        assert remote >= 1
    finally:
        s.stop()


def test_distributed_sql_join_and_worker_loss():
    import numpy as np
    import pyarrow as pa

    from spark_tpu.api.session import TpuSession
    from spark_tpu.exec.cluster import LocalCluster

    s = TpuSession("csql_j", {"spark.sql.shuffle.partitions": "3"})
    cluster = LocalCluster(num_workers=2)
    s.attachSqlCluster(cluster)
    try:
        n = 5000
        rng = np.random.default_rng(1)
        s.createDataFrame(pa.table({
            "k": rng.integers(0, 20, n), "v": np.ones(n)})) \
            .createOrReplaceTempView("cfact")
        s.createDataFrame(pa.table({
            "k": np.arange(20), "name": [f"n{i}" for i in range(20)]})) \
            .createOrReplaceTempView("cdim")
        q = ("SELECT d.name, sum(f.v) AS s FROM cfact f "
             "JOIN cdim d ON f.k = d.k GROUP BY d.name")
        out1 = s.sql(q).toArrow().to_pydict()
        assert sum(out1["s"]) == n

        # kill one worker; the next query must still succeed (task retry
        # on the surviving executor)
        w = next(iter(cluster._workers.values()))
        w.proc.kill()
        w.proc.wait(timeout=10)
        out2 = s.sql(q).toArrow().to_pydict()
        assert sum(out2["s"]) == n
    finally:
        s.stop()


def test_fetch_failure_regenerates_lost_map_outputs(monkeypatch):
    """Worker dies AFTER its map stage completed (blocks lost, task ok):
    the consumer's fetch fails and the scheduler re-runs only the lost
    map stage from lineage (reference: DAGScheduler FetchFailed →
    resubmit missing map stages)."""
    import numpy as np
    import pyarrow as pa

    import spark_tpu.exec.cluster_sql as CS
    from spark_tpu.api.session import TpuSession
    from spark_tpu.exec.cluster import LocalCluster

    s = TpuSession("csql_ff", {"spark.sql.shuffle.partitions": "3"})
    cluster = LocalCluster(num_workers=2)
    s.attachSqlCluster(cluster)

    state = {"killed": False}
    orig = CS.ClusterDAGScheduler._run_remote

    def kill_after_first_map(self, stage):
        status = orig(self, stage)
        if not state["killed"]:
            state["killed"] = True
            w = cluster._workers[status.executor_id]
            w.proc.kill()
            w.proc.wait(timeout=10)
        return status

    monkeypatch.setattr(CS.ClusterDAGScheduler, "_run_remote",
                        kill_after_first_map)
    try:
        n = 4000
        rng = np.random.default_rng(7)
        s.createDataFrame(pa.table({
            "k": rng.integers(0, 30, n),
            "v": rng.integers(1, 5, n)})) \
            .createOrReplaceTempView("ffact")
        df = s.table("ffact").repartition(3).groupBy("k").count()
        got = {r["k"]: r["count"] for r in df.collect()}
        import collections

        rng2 = np.random.default_rng(7)
        keys = rng2.integers(0, 30, n)
        exp = collections.Counter(keys.tolist())
        assert got == dict(exp)
        m = s._metrics.snapshot()["counters"]
        assert m.get("scheduler.fetch_failures", 0) >= 1, m
        assert m.get("shuffle.blocks_fetched", 0) >= 3, m
    finally:
        s.stop()
        cluster.stop()
