"""Local-cluster (multi-process executor) tests
(reference: core DistributedSuite over local-cluster[n,c,m]).

Task functions are defined inside the tests (closures) so cloudpickle
serializes them by value — module-level functions would be pickled by
reference to a module the workers cannot import (the reference ships user
code via --py-files; closures are its common case too)."""

import os
import time

import pytest

from spark_tpu.exec.cluster import (
    ExecutorLostError, LocalCluster, RemoteTaskError,
)
from spark_tpu.rdd import RDDContext


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(num_workers=3)
    yield c
    c.stop()


def test_tasks_run_in_separate_processes(cluster):
    pids = set(cluster.map(lambda _: os.getpid(), range(6)))
    assert os.getpid() not in pids
    assert len(pids) >= 2  # spread across workers


def test_task_results(cluster):
    assert cluster.map(lambda x: x * x, [1, 2, 3]) == [1, 4, 9]


def test_deterministic_task_error_propagates(cluster):
    def boom(x):
        raise ValueError(f"bad {x}")

    with pytest.raises(RemoteTaskError, match="bad 7"):
        cluster.run_task(boom, 7)
    # cluster still healthy afterwards
    assert cluster.run_task(lambda x: x * x, 4) == 16


def test_executor_loss_retries_elsewhere(cluster):
    n0 = cluster.num_alive()
    with pytest.raises((ExecutorLostError, Exception)):
        # the task kills every executor it lands on; after max failures the
        # driver gives up — but other tasks must still run on survivors
        cluster.run_task(lambda _: os._exit(42), 0)
    assert cluster.num_alive() < n0
    if cluster.num_alive():
        assert cluster.run_task(lambda x: x * x, 5) == 25


def test_rdd_on_cluster():
    c = LocalCluster(num_workers=2)
    try:
        sc = RDDContext(parallelism=4, cluster=c)
        r = sc.parallelize(range(100), 4)
        assert r.map(lambda x: x + 1).filter(lambda x: x % 2 == 0).count() == 50
        out = dict(r.map(lambda x: (x % 3, 1))
                   .reduceByKey(lambda a, b: a + b).collect())
        assert out == {0: 34, 1: 33, 2: 33}
        # tasks really ran off-driver
        pids = set(r.mapPartitions(
            lambda it: iter([os.getpid()])).collect())
        assert os.getpid() not in pids
    finally:
        c.stop()
