"""Chaos suite: deterministic fault injection end to end.

Every scenario drives a REAL failure mode through the regular conf
surface (spark.tpu.faults.*, utils/faults.py) and asserts the hardening
the fault proves out: bounded RPC/fetch retry absorbing transient flaps
with ZERO stage regenerations, FetchFailed regeneration still producing
correct results, worker death mid-task retried on surviving executors,
window-based executor exclusion with timed re-inclusion
(excludeOnFailure), heartbeat blackout flagged as a straggler and
rescued by speculation, whole-tier runtime faults degrading to the
stage tier with identical results, mesh gang failures retrying then
falling back to the host shuffle, and failed queries releasing their
shuffle state.

Chaos assertions are measured (KernelCache deltas, metrics counters,
result equality against a healthy oracle) — never plan predictions:
healthy-path launch behavior is UNCHANGED and tests/test_plan_analysis
keeps asserting exact counts with the fault layer present but idle.
"""

import pickle
import time

import numpy as np
import pyarrow as pa
import pytest

import spark_tpu.api.functions as F
from spark_tpu import TpuSession
from spark_tpu.config import SQLConf
from spark_tpu.exec.cluster import LocalCluster
from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC
from spark_tpu.utils import faults


# ---------------------------------------------------------------------------
# helpers / fixtures
# ---------------------------------------------------------------------------

@pytest.fixture(autouse=True)
def _reset_faults():
    yield
    faults.reset()


def _set_faults(session, points: str, seed: int = 7) -> None:
    session.conf.set("spark.tpu.faults.enabled", "true")
    session.conf.set("spark.tpu.faults.seed", str(seed))
    session.conf.set("spark.tpu.faults.points", points)
    faults.configure(session.conf)


def _clear_faults(session) -> None:
    session.conf.set("spark.tpu.faults.enabled", "false")
    session.conf.unset("spark.tpu.faults.points")
    faults.configure(session.conf)


def _counters(session) -> dict:
    return dict(session._metrics.snapshot()["counters"])


def _delta(after: dict, before: dict, key: str) -> int:
    return after.get(key, 0) - before.get(key, 0)


def _expected_sums(keys, vals) -> dict:
    exp: dict = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        exp[k] = exp.get(k, 0) + v
    return exp


def _assert_sums(df, exp: dict) -> None:
    got = {r["k"]: r["s"] for r in df.collect()}
    assert set(got) == set(exp)
    for k in exp:
        assert abs(got[k] - exp[k]) < 1e-6, (k, got[k], exp[k])


@pytest.fixture(scope="module")
def chaos_spark():
    s = TpuSession("chaos", {
        "spark.sql.shuffle.partitions": "2",
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.adaptive.enabled": "false",
        "spark.tpu.cluster.enabled": "true",
        "spark.tpu.cluster.workers": "2",
        "spark.tpu.heartbeat.interval": "0.2",
    })
    rng = np.random.default_rng(11)
    n = 6000
    keys = rng.integers(0, 32, n)
    vals = rng.integers(-50, 100, n)
    s.createDataFrame(pa.table({"k": keys, "v": vals})) \
        .createOrReplaceTempView("chaos_t")
    s._chaos_exp = _expected_sums(keys, vals)
    s._chaos_rows = sorted(zip(keys.tolist(), vals.tolist()))
    yield s
    s.stop()


def _agg_df(s):
    return (s.table("chaos_t").repartition(2)
            .groupBy("k").agg(F.sum("v").alias("s")))


def _shuffle_df(s):
    # EXACTLY one exchange → two stages: a remote map stage (shuffle
    # write, no fetches) and a driver-side result stage whose Fetch
    # leaf pulls the blocks — block.fetch rules fire in the DRIVER
    # process only, keeping fetch-path scenarios deterministic
    return s.table("chaos_t").repartition(2)


def _assert_rows(df, s) -> None:
    got = sorted((r["k"], r["v"]) for r in df.collect())
    assert got == s._chaos_rows


# ---------------------------------------------------------------------------
# registry unit behavior
# ---------------------------------------------------------------------------

def test_fault_rules_unit():
    conf = SQLConf({
        "spark.tpu.faults.enabled": "true",
        "spark.tpu.faults.seed": "13",
        "spark.tpu.faults.points":
            "a.nth=nth:2;b.first=first:2;c.after=after:2;"
            "d.prob=prob:0.5;e.scoped=always@nowhere;f.sleep=always:sleep:0",
    })
    faults.configure(conf)
    assert faults.ENABLED

    def fires(point, n, detail=""):
        out = []
        for _ in range(n):
            try:
                faults.maybe_fail(point, detail=detail)
                out.append(False)
            except faults.InjectedFault:
                out.append(True)
        return out

    assert fires("a.nth", 4) == [False, True, False, False]
    assert fires("b.first", 4) == [True, True, False, False]
    assert fires("c.after", 5) == [False, False, True, True, True]
    # scope neither matches the driver host label nor the detail
    assert fires("e.scoped", 3) == [False, False, False]
    assert fires("e.scoped", 1, detail="x/nowhere/y") == [True]
    # seeded prob: identical schedule on reinstall with the same seed
    sched1 = fires("d.prob", 16)
    faults.reset()
    faults.configure(conf)
    assert fires("d.prob", 16) == sched1
    assert any(sched1) and not all(sched1)
    # sleep action returns instead of raising
    faults.maybe_fail("f.sleep")
    # disabled registry short-circuits
    faults.reset()
    faults.maybe_fail("a.nth")


def test_rpc_call_retry_absorbs_flap():
    """Transient UNAVAILABLE on an idempotent control-plane call is
    absorbed by RpcClient's bounded backoff; without a policy the same
    flap surfaces immediately."""
    from spark_tpu.net.transport import (
        RETRY_STATS, RetryPolicy, RpcClient, RpcServer,
        RpcUnavailableError,
    )

    server = RpcServer("tok")
    server.register("echo", lambda p: p)
    addr = server.start()
    try:
        c = RpcClient(addr, "tok")
        conf = SQLConf({"spark.tpu.faults.enabled": "true",
                        "spark.tpu.faults.points": "rpc.call=first:1"})
        faults.configure(conf)
        with pytest.raises(RpcUnavailableError):
            c.call("echo", b"x")          # no policy → flap surfaces
        faults.reset()
        faults.configure(conf)            # fresh first:1
        before = RETRY_STATS["absorbed"]
        out = c.call("echo", b"y",
                     retry=RetryPolicy(attempts=3, base_ms=1.0,
                                       deadline_s=5.0))
        assert out == b"y"
        assert RETRY_STATS["absorbed"] > before
        c.close()
    finally:
        server.stop()


def test_fault_layer_idle_zero_overhead(chaos_spark):
    """Fault layer compiled in but IDLE (enabled with a never-hit
    point): identical measured kernel-launch count as the healthy run —
    the acceptance guard that healthy-path launch behavior is
    unchanged."""
    s = chaos_spark
    _agg_df(s).toArrow()                      # warm
    before = KC.launches
    _agg_df(s).toArrow()
    healthy = KC.launches - before
    _set_faults(s, "never.hit=always")
    before = KC.launches
    _agg_df(s).toArrow()
    idle = KC.launches - before
    _clear_faults(s)
    assert idle == healthy, (idle, healthy)


# ---------------------------------------------------------------------------
# fetch retry / FetchFailed regeneration / regen cap
# ---------------------------------------------------------------------------

def test_rpc_flap_absorbed_by_fetch_retry_zero_regens(chaos_spark):
    """A transient block-fetch flap is absorbed by the bounded fetch
    retry: the query completes correctly with ZERO stage
    regenerations (no FetchFailed ever reaches the scheduler)."""
    s = chaos_spark
    _set_faults(s, "block.fetch=first:2")
    before = _counters(s)
    _assert_rows(_shuffle_df(s), s)
    after = _counters(s)
    fired = faults.fire_counts().get("block.fetch")
    _clear_faults(s)
    assert _delta(after, before, "scheduler.fetch_failures") == 0
    assert _delta(after, before, "scheduler.stage_retries") == 0
    assert _delta(after, before, "shuffle.fetch_retries") >= 1
    assert fired == 2


def test_fetch_exhaustion_regenerates_stage_correctly(chaos_spark):
    """With the fetch retry budget at zero, a lost block surfaces as
    FetchFailed and the scheduler regenerates the map stage from
    lineage — the result is still correct."""
    s = chaos_spark
    s.conf.set("spark.tpu.shuffle.fetch.maxRetries", "0")
    _set_faults(s, "block.fetch=first:1")
    before = _counters(s)
    try:
        _assert_rows(_shuffle_df(s), s)
    finally:
        s.conf.unset("spark.tpu.shuffle.fetch.maxRetries")
        _clear_faults(s)
        s._sql_cluster.health.reset()   # the regen counted a failure
    after = _counters(s)
    assert _delta(after, before, "scheduler.fetch_failures") >= 1


def test_stage_regen_cap_is_classified_and_state_freed(chaos_spark):
    """An executor set that keeps losing map outputs terminates in the
    CLASSIFIED StageRegenerationLimitError (never an infinite
    FetchFailed loop), and the failed query leaves zero shuffle blocks
    on any worker and a balanced device ledger."""
    from spark_tpu.errors import StageRegenerationLimitError
    from spark_tpu.net.transport import RpcClient
    from spark_tpu.obs.resources import GLOBAL_LEDGER

    s = chaos_spark
    s.conf.set("spark.tpu.shuffle.fetch.maxRetries", "0")
    s.conf.set("spark.tpu.scheduler.maxStageRegens", "1")
    # this test targets the regen CAP — keep exclusion out of the way
    # (each regen legitimately counts a failure against the producer)
    s.conf.set("spark.tpu.excludeOnFailure.maxFailures", "100")
    _set_faults(s, "block.fetch=first:100")
    try:
        with pytest.raises(StageRegenerationLimitError) as ei:
            _shuffle_df(s).toArrow()
        assert ei.value.error_class == "STAGE_REGENERATION_LIMIT"
    finally:
        s.conf.unset("spark.tpu.shuffle.fetch.maxRetries")
        s.conf.unset("spark.tpu.scheduler.maxStageRegens")
        s.conf.unset("spark.tpu.excludeOnFailure.maxFailures")
        _clear_faults(s)
        # the repeated FetchFaileds legitimately counted against the
        # producing executors — reset so later tests start clean
        s._sql_cluster.health.reset()
    cluster = s._sql_cluster
    for w in cluster.alive_workers():
        with RpcClient(w.client.addr, cluster.authkey_hex) as c:
            stats = pickle.loads(c.call("block_stats", timeout=10))
        assert stats["blocks"] == 0, \
            f"{w.executor_id} leaked {stats['blocks']} blocks"
    assert GLOBAL_LEDGER.verify() == []
    # the cluster is still healthy for the next query
    _assert_rows(_shuffle_df(s), s)


# ---------------------------------------------------------------------------
# worker death / transient task failures / exclusion
# ---------------------------------------------------------------------------

def test_worker_kill_mid_map_retries_on_survivors():
    """A worker process hard-dying mid-task (kill action) is detected
    as executor loss; the task retries on a survivor, the query is
    correct, and the failure is recorded against the dead executor."""
    s = TpuSession("chaos_kill", {
        "spark.sql.shuffle.partitions": "2",
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.adaptive.enabled": "false",
    })
    cluster = LocalCluster(num_workers=2)
    s.attachSqlCluster(cluster)
    try:
        cluster.add_worker("chaoshost")
        rng = np.random.default_rng(3)
        keys = rng.integers(0, 16, 3000)
        vals = rng.integers(0, 50, 3000)
        s.createDataFrame(pa.table({"k": keys, "v": vals})) \
            .createOrReplaceTempView("kill_t")
        exp = _expected_sums(keys, vals)
        _set_faults(s, "worker.task=always:kill@chaoshost")
        for _ in range(6):   # round-robin eventually offers chaoshost
            df = (s.table("kill_t").repartition(2)
                  .groupBy("k").agg(F.sum("v").alias("s")))
            _assert_sums(df, exp)
            if cluster.stats.get("executor_losses", 0) >= 1:
                break
        assert cluster.stats.get("executor_losses", 0) >= 1, \
            "chaoshost never received (and died on) a task"
        assert cluster.num_alive() == 2   # survivors only
        _clear_faults(s)
    finally:
        s.stop()


def test_flaky_executor_excluded_then_reincluded():
    """excludeOnFailure end to end: an alive-but-flaky executor that
    keeps failing tasks transiently is retried around (queries stay
    correct), accumulates failures in the window, gets EXCLUDED from
    scheduling, surfaces in live status + findings, and rejoins after
    the timed re-inclusion horizon."""
    s = TpuSession("chaos_flaky", {
        "spark.sql.shuffle.partitions": "2",
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.adaptive.enabled": "false",
        "spark.tpu.excludeOnFailure.maxFailures": "2",
        "spark.tpu.excludeOnFailure.windowSecs": "60",
        "spark.tpu.excludeOnFailure.timeoutSecs": "1.0",
    })
    cluster = LocalCluster(num_workers=2)
    s.attachSqlCluster(cluster)
    try:
        cluster.add_worker("flakyhost")
        flaky_eid = next(w.executor_id
                         for w in cluster._workers.values()
                         if w.host == "flakyhost")
        rng = np.random.default_rng(5)
        keys = rng.integers(0, 16, 2000)
        vals = rng.integers(0, 50, 2000)
        s.createDataFrame(pa.table({"k": keys, "v": vals})) \
            .createOrReplaceTempView("flaky_t")
        exp = _expected_sums(keys, vals)
        qids = []
        s.listener_bus.register(lambda ev: qids.append(ev.query_id))
        _set_faults(s, "worker.task=always@flakyhost")
        excluded_at = None
        for _ in range(10):
            df = (s.table("flaky_t").repartition(2)
                  .groupBy("k").agg(F.sum("v").alias("s")))
            _assert_sums(df, exp)   # transient failures retried around
            if flaky_eid in cluster.health.excluded():
                excluded_at = time.time()
                break
        assert excluded_at is not None, \
            f"flaky executor never excluded " \
            f"(failures={cluster.health.failure_count(flaky_eid)})"
        assert cluster.health.failure_count(flaky_eid) >= 2
        # excluded from scheduling NOW
        assert flaky_eid not in [e.executor_id
                                 for e in cluster.registry.alive()]
        # surfaced: live executor row + a query finding
        util = s.live_obs.executor_utilization()
        assert util.get(flaky_eid, {}).get("excluded") is True
        s.listener_bus.wait_empty()
        found = [f for q in qids
                 for f in (s.live_obs.query_progress(q)
                           or {"findings": []})["findings"]
                 if f.get("kind") == "exec.excluded"]
        assert found, "no exec.excluded finding surfaced"
        _clear_faults(s)
        # timed re-inclusion: past the horizon the executor is offered
        # tasks again
        deadline = excluded_at + 1.0
        time.sleep(max(0.0, deadline - time.time()) + 0.3)
        assert flaky_eid in [e.executor_id
                             for e in cluster.registry.alive()]
        _assert_sums(s.table("flaky_t").repartition(2)
                     .groupBy("k").agg(F.sum("v").alias("s")), exp)
    finally:
        s.stop()


def test_shuffle_write_fault_is_transient_task_failure(chaos_spark):
    """An injected shuffle-write failure fails the map task; the driver
    classifies it TRANSIENT (marker), retries on another executor, and
    the query completes correctly."""
    s = chaos_spark
    cluster = s._sql_cluster
    before_t = cluster.stats.get("transient_task_failures", 0)
    _set_faults(s, "shuffle.write=once")
    try:
        _assert_rows(_shuffle_df(s), s)
    finally:
        _clear_faults(s)
        cluster.health.reset()
    assert cluster.stats.get("transient_task_failures", 0) > before_t


# ---------------------------------------------------------------------------
# heartbeat: telemetry error counting, blackout → straggler + speculation
# ---------------------------------------------------------------------------

def test_heartbeat_telemetry_errors_counted(chaos_spark):
    """A throwing heartbeat sink must never fail a liveness beat — but
    every swallowed exception is COUNTED (cluster stats + the sink
    owner's telemetry_errors) instead of disappearing into a bare
    except."""
    s = chaos_spark
    cluster = s._sql_cluster

    class Boom:
        telemetry_errors = 0

        def sink(self, *a, **k):
            raise RuntimeError("sink bug")

    boom = Boom()
    saved = cluster.obs_sink
    cluster.obs_sink = boom.sink
    try:
        deadline = time.time() + 5.0
        while time.time() < deadline and \
                cluster.stats.get("heartbeat.telemetry_errors", 0) == 0:
            time.sleep(0.1)
    finally:
        cluster.obs_sink = saved
    assert cluster.stats.get("heartbeat.telemetry_errors", 0) >= 1
    assert boom.telemetry_errors >= 1
    # the workers are still registered: the beat returned ok
    assert cluster.num_alive() >= 2


def test_heartbeat_blackout_straggler_and_speculation_win():
    """Heartbeat blackout mid-task: the driver flags the silent task as
    a straggler (silence deadline), the speculation signal launches a
    backup on the healthy executor, and the backup's result wins while
    the stalled primary is still asleep."""
    s = TpuSession("chaos_hb", {
        "spark.sql.shuffle.partitions": "2",
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.adaptive.enabled": "false",
        "spark.speculation": "true",
        "spark.tpu.straggler.minSeconds": "0.1",
        "spark.tpu.straggler.heartbeatDeadline": "0.35",
    })
    cluster = LocalCluster(num_workers=1, heartbeat_interval=0.1)
    s.attachSqlCluster(cluster)
    try:
        cluster.add_worker("slowhost")
        rng = np.random.default_rng(9)
        keys = rng.integers(0, 8, 2000)
        vals = rng.integers(0, 40, 2000)
        s.createDataFrame(pa.table({"k": keys, "v": vals})) \
            .createOrReplaceTempView("hb_t")
        exp = _expected_sums(keys, vals)
        qids = []
        s.listener_bus.register(lambda ev: qids.append(ev.query_id))
        # slowhost: task stalls 2.5s AND its busy-phase beats black out
        # after the first two (the entry must exist before it can go
        # silent) — the driver sees a live task fall silent mid-stage
        _set_faults(s, "worker.task=always:sleep:2.5@slowhost;"
                       "heartbeat.flush=after:2@busy")
        t0 = time.time()
        straggled = False
        for _ in range(4):   # round-robin until the primary lands slow
            df = (s.table("hb_t").repartition(2)
                  .groupBy("k").agg(F.sum("v").alias("s")))
            _assert_sums(df, exp)
            s.listener_bus.wait_empty()
            straggled = any(
                f.get("kind") == "obs.straggler"
                for q in qids
                for f in (s.live_obs.query_progress(q)
                          or {"findings": []})["findings"])
            if straggled and cluster.stats.get("speculative_wins", 0):
                break
        _clear_faults(s)
        assert straggled, "blackout never produced a straggler finding"
        assert cluster.stats.get("speculative_launched", 0) >= 1
        assert cluster.stats.get("speculative_wins", 0) >= 1, \
            f"speculation never won (stats={cluster.stats}, " \
            f"{time.time() - t0:.1f}s)"
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# runtime tier degradation
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def local_spark():
    s = TpuSession("chaos_local", {
        "spark.sql.shuffle.partitions": "2",
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.adaptive.enabled": "false",
    })
    rng = np.random.default_rng(21)
    n = 5000
    keys = rng.integers(0, 24, n)
    vals = rng.integers(-30, 80, n)
    s.createDataFrame(pa.table({"k": keys, "v": vals})) \
        .createOrReplaceTempView("deg_t")
    s._chaos_exp = _expected_sums(keys, vals)
    yield s
    s.stop()


def test_whole_tier_dispatch_fault_degrades_to_stage(local_spark):
    """An XLA-runtime-shaped fault at the whole-query program's single
    dispatch degrades the query to the STAGE tier and re-executes with
    identical results; the reason lands on the tier decision. Measured
    KernelCache deltas (not plan predictions) prove the degraded run
    took the stage tier."""
    from spark_tpu.physical.whole_query import WholeQueryExec

    s = local_spark
    s.conf.set("spark.tpu.compile.tier", "whole")

    def q():
        return (s.table("deg_t").repartition(2)
                .groupBy("k").agg(F.sum("v").alias("s")))

    try:
        q().toArrow()                      # warm the whole program
        before_kinds = dict(KC.launches_by_kind)
        _assert_sums(q(), s._chaos_exp)    # healthy whole run
        healthy_kinds = {k: v - before_kinds.get(k, 0)
                         for k, v in KC.launches_by_kind.items()
                         if v != before_kinds.get(k, 0)}
        assert healthy_kinds.get("whole_query", 0) >= 1, healthy_kinds

        _set_faults(s, "kernel.dispatch=once@whole_query")
        before = _counters(s)
        before_kinds = dict(KC.launches_by_kind)
        df = q()
        _assert_sums(df, s._chaos_exp)     # identical results, degraded
        after = _counters(s)
        deg_kinds = {k: v - before_kinds.get(k, 0)
                     for k, v in KC.launches_by_kind.items()
                     if v != before_kinds.get(k, 0)}
        _clear_faults(s)
        assert _delta(after, before, "whole_query.runtime_degraded") == 1
        # the faulted dispatch never counted; the stage tier did the work
        assert deg_kinds.get("whole_query", 0) == 0, deg_kinds
        assert sum(deg_kinds.values()) > 0, deg_kinds
        plan = df.query_execution.physical
        assert isinstance(plan, WholeQueryExec)
        assert "runtime_degraded" in plan.decision.details
        # consumed `once` rule: the next run is whole again
        _assert_sums(q(), s._chaos_exp)
    finally:
        s.conf.unset("spark.tpu.compile.tier")
        _clear_faults(s)


def test_kernel_compile_fault_absorbed_by_stage_retry(local_spark):
    """A one-shot compile-time fault fails the stage attempt; the DAG
    scheduler's deterministic stage retry recompiles and the query
    completes correctly."""
    s = local_spark
    _set_faults(s, "kernel.compile=once")
    before = _counters(s)
    try:
        # a fresh expression structure forces at least one cache miss
        df = (s.table("deg_t")
              .withColumn("w", (F.col("v") * 13 + F.col("k") * 7) % 11)
              .groupBy("k").agg(F.sum("w").alias("s")))
        got = {r["k"]: r["s"] for r in df.collect()}
        fired = faults.fire_counts().get("kernel.compile")
    finally:
        _clear_faults(s)
    after = _counters(s)
    assert fired == 1, "compile fault never fired (no cache miss?)"
    assert _delta(after, before, "scheduler.stage_retries") >= 1
    exp: dict = {}
    for k, v in zip(*(c.to_pylist() for c in
                      s.table("deg_t").toArrow().columns)):
        # engine % is C-style (sign follows the dividend), unlike Python's
        exp[k] = exp.get(k, 0) + int(np.fmod(v * 13 + k * 7, 11))
    assert got == exp


def test_mesh_gang_failure_retries_then_falls_back(local_spark):
    """Mesh gang semantics at runtime: one injected dispatch fault →
    the whole sharded stage retries as a unit and succeeds; repeated
    faults → the exchange degrades to the host shuffle. Results match
    the healthy oracle in both regimes."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    s = local_spark

    def q():
        return (s.table("deg_t").repartition(8, "k")
                .groupBy("k").agg(F.sum("v").alias("s")))

    q().toArrow()                          # warm, healthy
    before = _counters(s)
    _assert_sums(q(), s._chaos_exp)
    after = _counters(s)
    assert _delta(after, before, "exchange.mesh") >= 1, \
        "query did not take the mesh path — test setup is wrong"

    # one gang failure: retry as a unit, still mesh, same results
    _set_faults(s, "kernel.dispatch=once@mesh_stage")
    before = _counters(s)
    _assert_sums(q(), s._chaos_exp)
    after = _counters(s)
    assert _delta(after, before, "exchange.mesh_gang_retries") == 1
    assert _delta(after, before, "exchange.mesh") >= 1
    assert _delta(after, before, "exchange.mesh_runtime_fallback") == 0

    # gang keeps dying: degrade to the host shuffle, same results
    _set_faults(s, "kernel.dispatch=first:2@mesh_stage", seed=8)
    before = _counters(s)
    _assert_sums(q(), s._chaos_exp)
    after = _counters(s)
    _clear_faults(s)
    assert _delta(after, before, "exchange.mesh_runtime_fallback") == 1
    assert _delta(after, before, "exchange.mesh") == 0
